"""Sharded round step: the protocol over a jax.sharding.Mesh.

Scale-out design (SURVEY.md §7.2.3): the per-edge state (fd_fail, alerted --
the [C, K] arrays that dominate memory and compute) is row-sharded over the
``nodes`` mesh axis by *observer*; the per-destination report table and the
small [C] masks are replicated. One round then is:

- local: every shard probes its own observers' edges and scatters the newly
  crossed edges into a full-width local report delta;
- collective: a single ``psum``(max) over ICI ORs the deltas into the
  replicated report table -- this is the batched "broadcast alerts to all
  members" of the real protocol (UnicastToAllBroadcaster fan-out);
- replicated: watermark cut detection, implicit invalidation and the
  fast-round vote tally run identically on every shard (cheap [C] ops), so no
  second collective is needed -- mirroring how every Rapid node independently
  evaluates the same alert stream.

The same step runs on an N-chip TPU mesh (ICI collectives) or a forced
multi-device CPU mesh for validation.

**Multi-host**: pass ``make_mesh(shape=(hosts, chips_per_host))`` to get a 2D
``("dcn", "ici")`` mesh. Per-edge state row-shards over *both* axes and the
single ``pmax`` reduction names both, which XLA decomposes into an intra-host
reduction riding ICI followed by a cross-host exchange on DCN -- the
hierarchy the scaling playbook prescribes, and the TPU-native equivalent of
the reference's one-transport-fits-all gRPC fan-out (SURVEY.md §5.8).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.jitwatch import make_jit
from ..sim.engine import (
    RoundInputs,
    SimConfig,
    SimState,
    route_and_tally,
    windowed_fd_phase,
)

NODES_AXIS = "nodes"


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: the top-level binding (and its
    ``check_vma`` knob) landed in 0.5.x; older jaxlibs ship it as
    ``jax.experimental.shard_map`` with the equivalent ``check_rep`` knob."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    n_devices: int | None = None,
    shape: Tuple[int, ...] | None = None,
    axis_names: Tuple[str, ...] | None = None,
) -> Mesh:
    """A 1D ``("nodes",)`` mesh by default; pass ``shape=(hosts, chips)`` for
    a 2D ``("dcn", "ici")`` multi-host layout (names overridable)."""
    devices = jax.devices()
    if shape is not None:
        assert n_devices is None, "pass either n_devices or shape, not both"
        total = int(np.prod(shape))
        assert total <= len(devices), (
            f"mesh shape {shape} needs {total} devices, have {len(devices)}"
        )
        if axis_names is None:
            assert len(shape) <= 2, "pass axis_names for meshes beyond 2D"
            names = ("dcn", "ici")[-len(shape):]
        else:
            names = axis_names
        assert len(names) == len(shape), f"{len(shape)} axes need {len(shape)} names"
        return Mesh(np.array(devices[:total]).reshape(shape), names)
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODES_AXIS,))


def make_multihost_mesh(
    chips_per_host: int | None = None,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Mesh:
    """The multi-host deployment entry: a global ``("dcn", "ici")`` mesh over
    every chip of every host.

    On a TPU pod slice, run one process per host and pass the coordinator's
    ``host:port`` plus this process's rank -- ``jax.distributed.initialize``
    wires the cross-host runtime, after which ``jax.devices()`` is the
    *global* device set and the returned mesh rows are hosts (DCN axis) and
    columns are each host's chips (ICI axis). The sharded round step then
    needs no further changes: its single ``pmax`` names both axes, and XLA
    decomposes it into an intra-host ICI reduction plus a cross-host DCN
    exchange (the hierarchy SURVEY.md §5.8 maps the reference's gRPC fan-out
    onto). Single-process callers (or tests on the forced CPU backend) get
    the degenerate 1-host mesh with identical program semantics.
    """
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    # group devices by owning process so mesh ROWS really are hosts -- a flat
    # prefix slice would put one host's chips across several "dcn" rows when
    # chips_per_host is smaller than the hosts' actual chip count
    by_process: dict = {}
    for d in jax.devices():
        by_process.setdefault(d.process_index, []).append(d)
    rows = []
    for proc in sorted(by_process):
        host_devices = sorted(by_process[proc], key=lambda d: d.id)
        per_host = (
            chips_per_host if chips_per_host is not None else len(host_devices)
        )
        assert per_host <= len(host_devices), (
            f"chips_per_host={per_host} exceeds process {proc}'s "
            f"{len(host_devices)} devices"
        )
        rows.append(host_devices[:per_host])
    widths = {len(r) for r in rows}
    if len(widths) != 1:
        # fail LOUDLY: np.array over ragged rows would otherwise surface as
        # an inscrutable dtype=object Mesh error far from the cause
        raise ValueError(
            "uneven devices per process: "
            + ", ".join(
                f"process {p}: {len(r)}"
                for p, r in zip(sorted(by_process), rows)
            )
            + " -- a ('dcn', 'ici') mesh needs identical host rows; pass "
            "chips_per_host to truncate every host to a common width"
        )
    return Mesh(np.array(rows), ("dcn", "ici"))


def state_shardings(mesh: Mesh) -> SimState:
    """The sharding pytree for SimState: per-edge arrays row-sharded by
    observer over every mesh axis, everything else replicated."""
    row = NamedSharding(mesh, P(mesh.axis_names, None))
    rep = NamedSharding(mesh, P())
    return SimState(
        active=rep,
        alive=rep,
        group_of=rep,
        subjects=row,
        observers=rep,  # gathered by destination in the implicit pass
        fd_fail=row,
        fd_hist=row,
        fd_seen=row,
        fd_streak=row,
        fd_ok=row,
        alerted=row,
        reports=rep,
        arrival_hist=rep,
        seen_down=rep,
        announced=rep,
        announced_round=rep,
        proposal=rep,
        auto_vote=rep,
        voted=rep,
        vote_prop=rep,
        vote_new=rep,
        vote_hist=rep,
        votes_recv=rep,
        classic_rnd=rep,
        classic_vrnd=rep,
        classic_vval=rep,
        decided=rep,
        decided_group=rep,
        decided_round=rep,
        round=rep,
        rng_key=rep,
    )


def input_shardings(mesh: Mesh) -> RoundInputs:
    row = NamedSharding(mesh, P(mesh.axis_names, None))
    rep = NamedSharding(mesh, P())
    return RoundInputs(alive=rep, probe_drop=row, drop_prob=rep,
                       join_reports=rep, down_reports=rep, deliver=rep,
                       deliver_delay=rep)


def place_state(state: SimState, mesh: Mesh) -> SimState:
    return jax.tree_util.tree_map(jax.device_put, state, state_shardings(mesh))


def place_inputs(inputs: RoundInputs, mesh: Mesh) -> RoundInputs:
    return jax.tree_util.tree_map(jax.device_put, inputs, input_shardings(mesh))


def _sharded_round(
    config: SimConfig,
    axes: Tuple[str, ...],
    axis_sizes: Tuple[int, ...],
    random_loss: bool,
    state: SimState,
    inputs: RoundInputs,
) -> SimState:
    """Body run inside shard_map: arrays arrive as per-shard blocks."""
    c, k = config.capacity, config.k
    halt = state.decided

    # linearized shard index over every mesh axis (row-major, matching the
    # row sharding's block order); distinct randomness per shard
    shard = jnp.int32(0)
    for name, size in zip(axes, axis_sizes):
        shard = shard * size + jax.lax.axis_index(name)
    key, probe_key = jax.random.split(state.rng_key)
    probe_key = jax.random.fold_in(probe_key, shard)

    active = state.active  # [C] replicated
    alive = inputs.alive & active
    subj = state.subjects  # [C/n, K] local observers' subjects (global ids)
    local_rows = subj.shape[0]
    row0 = shard * local_rows
    my_ids = row0 + jnp.arange(local_rows, dtype=jnp.int32)

    # --- probes over local observer edges ---------------------------------
    edge_live = active[my_ids][:, None] & active[subj]
    observer_up = alive[my_ids][:, None]
    if config.rounds_per_interval > 1:
        from ..sim.engine import probe_phases

        my_turn = probe_phases(config)[my_ids] == (
            state.round % config.rounds_per_interval
        )
        observer_up = observer_up & my_turn[:, None]
    target_up = alive[subj]
    if random_loss:
        rand_drop = (
            jax.random.uniform(probe_key, (local_rows, k)) < inputs.drop_prob[subj]
        )
    else:
        # statically elide the per-edge threefry draw when no lossy ingress
        # is active (mirrors the single-device step's random_loss flag)
        rand_drop = jnp.zeros((local_rows, k), bool)
    probe_ok = target_up & ~inputs.probe_drop & ~rand_drop
    probed = edge_live & observer_up
    fail_event = probed & ~probe_ok
    fd_fail, fd_hist, fd_seen = state.fd_fail, state.fd_hist, state.fd_seen
    fd_streak, fd_ok = state.fd_streak, state.fd_ok

    if config.fd_policy == "windowed":
        fd_hist, fd_seen, new_down = windowed_fd_phase(
            config, state, probed, fail_event
        )
    else:
        fd_fail = state.fd_fail + (
            fail_event & (state.fd_fail < jnp.uint8(255))
        ).astype(jnp.uint8)
        new_down = probed & (fd_fail >= config.fd_threshold) & ~state.alerted
        if config.fd_gray_confirm > 0:
            # gray streak mirror over the local observer rows (identical
            # math to sim.engine.step's cumulative branch)
            ok_event = probed & probe_ok
            fd_streak = state.fd_streak + (
                fail_event & (state.fd_streak < jnp.uint8(255))
            ).astype(jnp.uint8)
            fd_streak = jnp.where(ok_event, jnp.uint8(0), fd_streak)
            fd_ok = state.fd_ok + (
                ok_event & (state.fd_ok < jnp.uint8(255))
            ).astype(jnp.uint8)
            gray_down = (
                fail_event
                & (fd_streak >= config.fd_gray_confirm)
                & (state.fd_ok >= config.fd_gray_warmup)
                & ~state.alerted
            )
            new_down = new_down | gray_down
    alerted = state.alerted | new_down

    # --- alert fan-out: local scatter + psum(OR) over ICI ------------------
    delta = jnp.zeros((c, k), jnp.int32)
    rows = subj.reshape(-1)
    cols = jnp.tile(jnp.arange(k, dtype=jnp.int32), local_rows)
    delta = delta.at[rows, cols].max(new_down.reshape(-1).astype(jnp.int32))
    # on a ("dcn", "ici") mesh XLA splits this into an ICI reduction per host
    # followed by the cross-host DCN exchange
    delta = jax.lax.pmax(delta, axes)
    # dst-indexed DOWN alert arrivals [C, K]; down_reports are proactive
    # leave notifications (already dst-indexed, replicated)
    down_arrivals = (delta > 0) | (inputs.down_reports & active[:, None])

    # --- replicated delivery + cut detection + per-node vote tally
    # (identical on every shard -- cheap [C]/[G,C] ops, no second collective)
    tallied = route_and_tally(config, state, down_arrivals, inputs,
                              active, alive)

    new_state = dataclasses.replace(
        tallied,
        active=active,
        alive=inputs.alive,
        subjects=subj,
        fd_fail=fd_fail,
        fd_hist=fd_hist,
        fd_seen=fd_seen,
        fd_streak=fd_streak,
        fd_ok=fd_ok,
        alerted=alerted,
        round=state.round + 1,
        rng_key=key,
    )
    return jax.tree_util.tree_map(
        lambda old, new: jnp.where(halt, old, new), state, new_state
    )


def _mesh_specs(config: SimConfig, mesh: Mesh):
    n_dev = int(np.prod([mesh.shape[name] for name in mesh.axis_names]))
    assert config.capacity % n_dev == 0, (
        f"capacity {config.capacity} must divide evenly over {n_dev} devices"
    )
    state_specs = jax.tree_util.tree_map(lambda s: s.spec, state_shardings(mesh))
    input_specs = jax.tree_util.tree_map(lambda s: s.spec, input_shardings(mesh))
    axes = tuple(mesh.axis_names)
    axis_sizes = tuple(mesh.shape[name] for name in axes)
    return state_specs, input_specs, axes, axis_sizes


def make_sharded_run(
    config: SimConfig, mesh: Mesh, rounds: int, random_loss: bool = True
):
    """Build the jitted multi-device round loop: scan of shard_map'd rounds."""
    state_specs, input_specs, axes, axis_sizes = _mesh_specs(config, mesh)

    body = _shard_map(
        functools.partial(_sharded_round, config, axes, axis_sizes, random_loss),
        mesh=mesh,
        in_specs=(state_specs, input_specs),
        out_specs=state_specs,
        check_vma=False,
    )

    def run(state: SimState, inputs: RoundInputs) -> SimState:
        def scan_body(carry, _):
            return body(carry, inputs), ()

        final, _ = jax.lax.scan(scan_body, state, None, length=rounds)
        return final

    # a fresh jit per factory call by design: the caller (driver) caches the
    # returned runner per (rounds, random_loss)  # devlint: jit-cached
    return make_jit("shard.engine.sharded_run", run)


def make_sharded_run_until(
    config: SimConfig, mesh: Mesh, random_loss: bool = True,
    stop_when_announced: bool = False, donate: bool = False,
):
    """One-dispatch mesh decision loop: a while_loop of shard_map'd rounds.

    Multi-chip runs stop at the decision round exactly like the single-device
    closed-form dispatch (engine.run_until_decided_const's early-exit
    semantics) instead of paying full scan batches, and the round budget is a
    *dynamic* operand, so changing the batch size never re-jits. The loop
    condition reads the replicated ``decided`` scalar, so every shard takes
    the same trip count and the in-body ``pmax`` stays collective-safe. The
    body is the same per-round function the scan path runs, which makes the
    two paths bit-identical round for round (post-decision scan rounds are
    masked no-ops that preserve state, including ``rng_key``).
    """
    state_specs, input_specs, axes, axis_sizes = _mesh_specs(config, mesh)

    def run_until(
        state: SimState, inputs: RoundInputs, max_rounds: jax.Array
    ) -> SimState:
        def cond(carry):
            st, r = carry
            keep = (r < max_rounds) & ~st.decided
            if stop_when_announced:
                # pause at the announcement round (bridge phase A); the
                # announced latch is replicated, so the trip count stays
                # uniform across shards
                keep &= ~jnp.any(st.announced[: config.groups])
            return keep

        def body(carry):
            st, r = carry
            st = _sharded_round(config, axes, axis_sizes, random_loss, st, inputs)
            return st, r + 1

        final, _ = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
        return final

    sharded = _shard_map(
        run_until,
        mesh=mesh,
        in_specs=(state_specs, input_specs, P()),
        out_specs=state_specs,
        check_vma=False,
    )
    # fresh jit per factory call by design; the driver caches the runner per
    # (random_loss, stop_when_announced)  # devlint: jit-cached
    return make_jit(
        "shard.engine.sharded_run_until", sharded,
        # ``donate=True`` is the driver's carried-state loop: the input
        # state dies with the dispatch, so its shards are donated in place.
        # Differential callers that reuse the input keep the default.
        donate_argnums=(0,) if donate else (),
    )
