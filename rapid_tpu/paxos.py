"""Single-decree classic Paxos with the Fast Paxos coordinator value-pick rule.

Reference: Paxos.java. This is the fallback path when the fast round
(FastPaxos) cannot reach the 3/4 supermajority on identical cut proposals.
State per instance: acceptor (rnd, vrnd, vval) and coordinator (crnd, cval)
(Paxos.java:63-70). Ranks are (round, node_index) ordered lexicographically
(Paxos.java:331-337).

Divergence note: the reference derives a coordinator's node_index from the
protobuf Endpoint.hashCode() (Paxos.java:101) -- a JVM-internal value. We use
the low 32 signed bits of the endpoint's seed-0 xxHash instead; any
deterministic, (practically) unique per-node value preserves the protocol
(rank uniqueness + total order), and this one is reproducible across runs.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple, TYPE_CHECKING

from .hashing import endpoint_hash
from .messaging.base import IBroadcaster, IMessagingClient

if TYPE_CHECKING:  # pragma: no cover
    from .observability import Metrics, Tracer
from .types import (
    Endpoint,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    Rank,
)

Proposal = Tuple[Endpoint, ...]


def paxos_node_index(addr: Endpoint) -> int:
    """Deterministic 32-bit signed coordinator index for rank tie-breaking."""
    h = endpoint_hash(addr.hostname, addr.port, 0) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


class Paxos:  # guarded-by: protocol-executor
    def __init__(
        self,
        my_addr: Endpoint,
        configuration_id: int,
        membership_size: int,
        client: IMessagingClient,
        broadcaster: IBroadcaster,
        on_decide: Callable[[List[Endpoint]], None],
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        self._metrics = metrics
        self._tracer = tracer
        self._my_addr = my_addr
        self._configuration_id = configuration_id
        self._n = membership_size
        self._client = client
        self._broadcaster = broadcaster
        self._on_decide = on_decide

        self._crnd = Rank(0, 0)
        self._rnd = Rank(0, 0)
        self._vrnd = Rank(0, 0)
        self._vval: Proposal = ()
        self._cval: Proposal = ()
        # keyed by sender: a retried/duplicated promise must not double-count
        # toward the majority (the retrying IMessagingClient makes this real)
        self._phase1b_messages: Dict[Endpoint, Phase1bMessage] = {}
        self._accept_responses: Dict[Rank, Dict[Endpoint, Phase2bMessage]] = {}
        self._decided = False

    # -- coordinator --------------------------------------------------------

    def start_phase1a(self, round_: int) -> None:
        """Initiate a classic round as coordinator (Paxos.java:97-110)."""
        if self._crnd.round > round_:
            return
        self._crnd = Rank(round_, paxos_node_index(self._my_addr))
        self._broadcaster.broadcast(
            Phase1aMessage(
                sender=self._my_addr,
                configuration_id=self._configuration_id,
                rank=self._crnd,
            )
        )

    def handle_phase1a(self, msg: Phase1aMessage) -> None:
        """Acceptor: promise the highest rank seen (Paxos.java:117-146)."""
        if msg.configuration_id != self._configuration_id:
            return
        if self._rnd < msg.rank:
            self._rnd = msg.rank
        else:
            return  # reject prepare from lower rank
        self._client.send_message(
            msg.sender,
            Phase1bMessage(
                sender=self._my_addr,
                configuration_id=self._configuration_id,
                rnd=self._rnd,
                vrnd=self._vrnd,
                vval=self._vval,
            ),
        )

    def handle_phase1b(self, msg: Phase1bMessage) -> None:
        """Coordinator: collect promises; on majority, pick a value by the
        Fast-Paxos coordinator rule and send phase2a (Paxos.java:154-186)."""
        if msg.configuration_id != self._configuration_id:
            return
        if msg.rnd != self._crnd:
            return  # only handle responses for our current round
        self._phase1b_messages[msg.sender] = msg
        if len(self._phase1b_messages) > self._n // 2:
            chosen = self.select_proposal_using_coordinator_rule(
                list(self._phase1b_messages.values())
            )
            if msg.rnd == self._crnd and not self._cval and chosen:
                self._cval = chosen
                self._broadcaster.broadcast(
                    Phase2aMessage(
                        sender=self._my_addr,
                        configuration_id=self._configuration_id,
                        rnd=self._crnd,
                        vval=chosen,
                    )
                )

    # -- acceptor -----------------------------------------------------------

    def handle_phase2a(self, msg: Phase2aMessage) -> None:
        """Acceptor: accept the value unless promised higher (Paxos.java:193-214)."""
        if msg.configuration_id != self._configuration_id:
            return
        if self._rnd <= msg.rnd and self._vrnd != msg.rnd:
            self._rnd = msg.rnd
            self._vrnd = msg.rnd
            self._vval = msg.vval
            self._broadcaster.broadcast(
                Phase2bMessage(
                    sender=self._my_addr,
                    configuration_id=self._configuration_id,
                    rnd=msg.rnd,
                    endpoints=msg.vval,
                )
            )

    def handle_phase2b(self, msg: Phase2bMessage) -> None:
        """Learner: decide once a majority voted in a rank (Paxos.java:221-236)."""
        if msg.configuration_id != self._configuration_id:
            return
        in_rnd = self._accept_responses.setdefault(msg.rnd, {})
        in_rnd[msg.sender] = msg
        if len(in_rnd) > self._n // 2 and not self._decided:
            self._decided = True
            if self._metrics is not None:
                self._metrics.incr("consensus.classic_decisions")
            if self._tracer is not None:
                self._tracer.event(
                    "classic_decision", round=msg.rnd.round,
                    votes=len(in_rnd),
                )
            self._on_decide(list(msg.endpoints))

    def register_fast_round_vote(self, vote: Proposal) -> None:
        """Record our fast-round (round 1) vote so phase1b responses reflect it
        (Paxos.java:244-258). No-op if already in a classic round."""
        if self._rnd.round > 1:
            return
        self._rnd = Rank(1, 1)
        self._vrnd = self._rnd
        self._vval = tuple(vote)

    # -- the coordinator value-pick rule ------------------------------------

    def select_proposal_using_coordinator_rule(
        self, phase1b_messages: List[Phase1bMessage]
    ) -> Proposal:
        """Fig. 2 of the Fast Paxos paper (Paxos.java:269-326).

        Let k = max vrnd over the quorum; V = the non-empty vvals voted at k.
        - if V has a single distinct value, choose it;
        - else if some value in V has more than N/4 votes, choose it;
        - else choose any reported non-empty vval (may be empty => wait).
        """
        if not phase1b_messages:
            raise ValueError("phase1b_messages was empty")
        max_vrnd = max(m.vrnd for m in phase1b_messages)
        collected_vvals: List[Proposal] = [
            m.vval for m in phase1b_messages if m.vrnd == max_vrnd and len(m.vval) > 0
        ]
        chosen: Optional[Proposal] = None
        if len(set(collected_vvals)) == 1:
            chosen = collected_vvals[0]
        elif len(collected_vvals) > 1:
            counters: Dict[Proposal, int] = {}
            for value in collected_vvals:
                count = counters.setdefault(value, 0)
                if count + 1 > self._n // 4:
                    chosen = value
                    break
                counters[value] = count + 1
        if chosen is None:
            chosen = next((m.vval for m in phase1b_messages if len(m.vval) > 0), ())
        return chosen
