"""Client-side routing surface of the serving plane.

The serving plane has two routing layers. Inside the cluster, the
ServingEngine maps keys to partitions (``kv.partition_of``) and partitions
to replica rows through the placement map. OUTSIDE the cluster -- a
workload router, an edge proxy -- the natural surface is membership itself:
rendezvous (highest-random-weight) hashing over the live member list, so a
view change only remaps the keys owned by the members it removed.

``RendezvousRouter`` is that surface, factored out of
examples/load_balancer.py so the example and any other client share one
implementation. Routing is byte-identical to the original example: the
same ``rendezvous_route``/``weight_seed`` helpers over the same sorted
pool, rebalanced exactly at VIEW_CHANGE events (membership IS the health
signal -- no side-channel health checks).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..events import ClusterEvents, NodeStatusChange
from ..placement import rendezvous_route, weight_seed
from ..types import EdgeStatus, Endpoint


class RendezvousRouter:
    """Routes request keys over the live membership, rebalancing exactly at
    VIEW_CHANGE events (the reference app surface: Cluster.java:98-140's
    getters plus registerSubscription).

    Rendezvous hashing via the placement plane's helpers
    (rapid_tpu.placement.rendezvous_route): key k goes to the backend with
    the highest seeded hash of k. Removing a backend only remaps the keys
    that were on it -- the property that makes a single multi-node cut a
    single rebalance."""

    def __init__(self, cluster, self_address: Endpoint) -> None:
        self._self = self_address
        self._lock = threading.Lock()
        self._backends: List[Endpoint] = []
        self._weight_seed: Dict[Endpoint, int] = {}
        self.view_changes = 0
        self.last_down: List[NodeStatusChange] = []
        cluster.register_subscription(
            ClusterEvents.VIEW_CHANGE, self._on_view_change
        )
        # the initial pool comes from the join response's configuration
        self._set_backends(cluster.get_memberlist())

    def _set_backends(self, members: List[Endpoint]) -> None:
        backends = [m for m in members if m != self._self]
        with self._lock:
            self._backends = backends
            self._weight_seed = {b: weight_seed(b) for b in backends}

    def _on_view_change(self, config_id: int, changes) -> None:
        with self._lock:
            pool = {b for b in self._backends}
        for change in changes:
            if change.status == EdgeStatus.UP:
                pool.add(change.endpoint)
            else:
                pool.discard(change.endpoint)
        self.view_changes += 1
        self.last_down = [
            c for c in changes if c.status == EdgeStatus.DOWN
        ]
        self._set_backends(sorted(pool, key=lambda e: (e.hostname, e.port)))

    def backends(self) -> List[Endpoint]:
        with self._lock:
            return list(self._backends)

    def route(self, key: bytes) -> Optional[Endpoint]:
        """The backend owning this key under rendezvous hashing."""
        with self._lock:
            if not self._backends:
                return None
            return rendezvous_route(key, self._backends, self._weight_seed)
