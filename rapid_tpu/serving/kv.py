"""Key space and on-store format of the serving plane.

The serving plane stores a replicated key/value map *inside* the handoff
plane's :class:`~..handoff.store.PartitionStore`: every partition's live
keys are serialized to one deterministic blob, so the view-change state
transfer that already moves and fingerprint-verifies partition bytes
(handoff/engine.py) moves the KV data for free -- no second transfer
protocol, and replicas that hold the same keys at the same versions agree
byte-for-byte on the store fingerprint.

Determinism is the load-bearing property here: ``encode_kv`` sorts keys
and fixes the msgpack encoding, so two replicas that applied the same
writes (in any order -- replication is idempotent by per-key version)
produce identical blobs and therefore identical xxh64 fingerprints for
handoff verification and statusz cross-checks.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import msgpack

from ..hashing import xxh64

# Fixed hash seed for key -> partition routing. Distinct from placement's
# rendezvous seeds (which hash partitions onto members); every client and
# every member must agree on it or keys route to different leaders.
SERVING_SEED = 0x5E41


def partition_of(key: bytes, partitions: int) -> int:
    """The partition a key lives in: xxh64 under the fixed serving seed.

    Pure function of (key, partition count), so clients route without any
    metadata beyond the placement map's partition count."""
    if partitions <= 0:
        raise ValueError(f"partitions must be positive: {partitions}")
    return xxh64(key, SERVING_SEED) % partitions


def encode_kv(kv: Dict[bytes, Tuple[int, bytes]]) -> bytes:
    """Serialize one partition's ``key -> (version, value)`` map.

    Sorted by key with a canonical msgpack encoding: replicas holding the
    same logical content emit identical bytes (see module docstring)."""
    return msgpack.packb(
        [[key, version, value] for key, (version, value) in sorted(kv.items())],
        use_bin_type=True,
    )


def decode_kv(blob: Optional[bytes]) -> Dict[bytes, Tuple[int, bytes]]:
    """Inverse of :func:`encode_kv`; ``None``/empty decodes to an empty map
    (a partition nobody has written to has no blob in the store yet)."""
    if not blob:
        return {}
    return {
        bytes(key): (int(version), bytes(value))
        for key, version, value in msgpack.unpackb(blob, raw=False)
    }
