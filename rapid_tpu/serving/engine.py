"""The serving plane: a replicated Get/Put KV store over placement + handoff.

One :class:`ServingEngine` per member, wired by service.py next to the
handoff engine and fed the same placement maps. The engine is every role of
the protocol at once:

- *router* (``client_get``/``client_put``): hashes the key to a partition
  (kv.py), sends to that partition's leader -- the first replica in
  placement order, which is live by construction since placement rows only
  contain current members -- and follows NOT_LEADER hints / retries RETRY
  answers with a bounded budget, so requests issued mid-churn converge on
  the post-view leader instead of failing.
- *leader*: assigns each key's next monotonic version, applies locally,
  fans replication Puts to the other replicas and acks the client once a
  majority of the replica row (itself included) applied. Reads are served
  from local state (leader reads) except while the partition is *churned*
  (this member was just promoted and has not finished its snapshot sync),
  when they fall back to quorum reads: fan a quorum Get over the
  partition's PREVIOUS row and take the max-version answer among a
  majority of it -- which must intersect any acked write's majority,
  preserving read-your-writes through leader failover.
- *replica*: applies replication Puts idempotently (only if the version is
  newer than what it holds -- duplicated/reordered replication is a no-op)
  and answers quorum Gets and partition-snapshot Gets from local state.

Promotion protocol: when a new map makes this member leader of a partition
it did not lead before, the partition is flagged churned and the engine
pulls whole-partition snapshots (``Get.quorum == 2``) from the replicas of
the partition's *previous* row -- the row whose majority acked every
pre-view write -- merging per-key max-versions into its own state. Once a
majority of the OLD row (self included, if it was a member) contributed,
every write acked under the old leader is present by quorum intersection,
and the flag clears. Pulling from the new row would be unsound: a replica
that just acquired the partition holds nothing (or a single-source handoff
copy) and its empty answer must not count toward the majority. Members
that dropped the partition in the new map keep their final blob for one
view (``_retired``) so they can still answer these probes after the
handoff ack releases the store entry; members whose own acquisition is
still in flight answer RETRY instead of an empty snapshot. Writes during
the window answer RETRY (the sync is one round trip); reads take the
quorum-read fallback, which fans over the same old row with the same
old-majority count for the same intersection argument.

Map-install skew: replicas reject replication Puts stamped with a map
version other than their installed one, so a deposed leader that has not
yet installed the new map cannot assemble a quorum for writes the new
row would never inherit -- it answers RETRY to its client instead of a
false OK. (Versions are fingerprints, so equality is the only comparison;
a leader ahead of a lagging replica also collects RETRYs until the
replica installs, which client retries absorb.)

Known limitation: the merge makes the NEW LEADER complete, but does not
re-replicate the merged state across the new row. A sequence of view
changes that replaces a row's membership faster than writes refresh it
can leave pre-merge writes on fewer than a majority of the latest row;
the placement plane's incremental rendezvous moves make this window
narrow, and the statusz fingerprint cross-check surfaces divergence, but
a full reconfiguration protocol (ROADMAP) is the real fix.

Durability rides the handoff plane: every mutation re-serializes the
partition's KV map into the shared :class:`~..handoff.store.PartitionStore`
via the canonical encoding in kv.py, so view-change state transfer moves
serving data through the existing verified handoff sessions and replica
fingerprints stay comparable across members.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Set, Tuple

from ..observability import (
    SERVING_LATENCY_BUCKETS_MS,
    Metrics,
    NullMetrics,
)
from ..runtime.futures import Promise
from ..runtime.lockdep import make_rlock
from ..types import Endpoint, Get, Put, PutAck
from .kv import decode_kv, encode_kv, partition_of

DEFAULT_RETRY_LIMIT = 8
DEFAULT_RETRY_DELAY_MS = 10


def _bug_newrow_sync() -> bool:
    """Deliberately reintroduce the PR 11 sync-target bug when
    RAPID_BUG_NEWROW_SYNC=1: the promote-time sync pulls its quorum from
    the NEW row, counting just-acquired replicas whose handoff copy may
    descend from a stale survivor. This is the known-bug target the
    nemesis search must find, shrink, and pin (tests/test_search.py);
    read at call time so tests can monkeypatch the environment."""
    import os

    return os.environ.get("RAPID_BUG_NEWROW_SYNC", "") == "1"


class ServingEngine:
    """Router, leader and replica halves of the serving protocol.
    Thread-safe: handlers run on the protocol executor while replication
    and routing promises complete on transport threads."""

    def __init__(
        self,
        store,
        address: Endpoint,
        client,
        scheduler,
        *,
        metrics: Optional[Metrics] = None,
        tracer=None,
        recorder=None,
        retry_limit: int = DEFAULT_RETRY_LIMIT,
        retry_delay_ms: int = DEFAULT_RETRY_DELAY_MS,
    ) -> None:
        if retry_limit <= 0:
            raise ValueError(f"retry_limit must be positive: {retry_limit}")
        self.store = store
        self.address = address
        self._client = client
        self._scheduler = scheduler
        self.metrics = metrics if metrics is not None else NullMetrics()
        self._tracer = tracer
        self._recorder = recorder
        self.retry_limit = retry_limit
        self.retry_delay_ms = retry_delay_ms
        # reentrant: in-process transports complete send promises on the
        # calling thread, so a reply callback can land while the issuing
        # frame still holds the lock
        self._lock = make_rlock("ServingEngine._lock")
        self._map = None  # latest PlacementMap (None until first install)
        # guarded-by: _lock -- decoded per-partition KV caches; the store
        # blob stays authoritative (rewritten on every mutation)
        self._kv: Dict[int, Dict[bytes, Tuple[int, bytes]]] = {}
        # guarded-by: _lock -- partitions this member leads but has not
        # finished promote-time snapshot sync for, mapped to the sync
        # quorum: (old-row members to pull from, answers required)
        self._churned: Dict[int, Tuple[Tuple[Endpoint, ...], int]] = {}
        # guarded-by: _lock -- final blobs of partitions this member
        # dropped at the current map, kept one view so peers promoted over
        # the old row can still pull them after the handoff ack releases
        # the store entry: partition -> (map version at retirement, blob)
        self._retired: Dict[int, Tuple[int, bytes]] = {}
        # guarded-by: _lock -- partitions acquired at the current map whose
        # handoff delivery may still be in flight; until the store holds
        # bytes for them, this member has nothing authoritative to answer
        self._acquired: Set[int] = set()
        # guarded-by: _lock -- partitions acquired mid-stream (the row
        # existed before this member joined it): the handoff copy may
        # descend from ANY old-row survivor, stale ones included, so until
        # a majority of the pre-join row is merged in (the join-time pull)
        # this member abstains from snapshot and quorum answers. Counting
        # such a copy toward a peer's sync quorum is the chained-view
        # staleness the nemesis search pinned: partition -> (pre-join row
        # members to pull from, answers required)
        self._grafted: Dict[int, Tuple[Tuple[Endpoint, ...], int]] = {}
        self._next_request_id = 1
        self._gets = 0
        self._puts = 0
        self._put_acks = 0

    # -- introspection ---------------------------------------------------- #

    def status(self) -> Tuple[int, int, int]:
        """(gets served, puts served, replication acks received)."""
        with self._lock:
            return self._gets, self._puts, self._put_acks

    def leader_digest(self) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
        """Parallel (partition id, leader "host:port") arrays over the
        partitions this member replicates -- the statusz cross-check input:
        every member must name the same leader for a shared partition."""
        with self._lock:
            pmap = self._map
            if pmap is None:
                return (), ()
            partitions: List[int] = []
            leaders: List[str] = []
            for p, row in enumerate(pmap.assignments):
                if row and self.address in row:
                    partitions.append(p)
                    leaders.append(str(row[0]))
            return tuple(partitions), tuple(leaders)

    def churned_partitions(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._churned))

    def _now(self) -> Optional[int]:
        if self._scheduler is None:
            return None
        return self._scheduler.now_ms()

    # -- placement tracking ----------------------------------------------- #

    def update_map(self, pmap) -> None:
        """Adopt a just-installed placement map: recompute leadership,
        invalidate KV caches for partitions the handoff plane is about to
        (re)deliver, and launch promote-time snapshot syncs for partitions
        this member now leads. Runs on the protocol executor inside the
        view-change path, after the handoff sessions launch."""
        to_sync: List[Tuple[int, Tuple[Endpoint, ...], int, int]] = []
        to_graft: List[int] = []
        changes = 0
        with self._lock:
            old = self._map
            self._map = pmap
            # retired blobs outlive their partition by exactly one view:
            # entries saved at the map we are now replacing may still feed
            # peers whose promote-time sync runs against that map
            if old is not None:
                self._retired = {
                    q: entry for q, entry in self._retired.items()
                    if entry[0] == old.version
                }
            for p, row in enumerate(pmap.assignments):
                old_row: Tuple[Endpoint, ...] = ()
                if old is not None and p < len(old.assignments):
                    old_row = old.assignments[p]
                old_leader = old_row[0] if old_row else None
                if not row or self.address not in row:
                    if self.address in old_row and p not in self._grafted:
                        # retiring replica: the handoff ack path will
                        # release the store blob; keep the bytes one view
                        # so syncs against the old row can still pull them.
                        # A still-grafted leaver retires nothing: its copy
                        # was never reconciled, so it must not feed a
                        # peer's old-row majority
                        blob = self.store.get(p)
                        self._retired[p] = (
                            pmap.version, blob if blob is not None else b""
                        )
                    self._kv.pop(p, None)
                    self._churned.pop(p, None)
                    self._acquired.discard(p)
                    self._grafted.pop(p, None)
                    continue
                self._retired.pop(p, None)
                if old is None or self.address not in old_row:
                    # newly acquired replica: the bytes arrive via a
                    # verified handoff session into the store -- a stale
                    # decoded cache would shadow them, and until they land
                    # this member has nothing authoritative to answer
                    self._kv.pop(p, None)
                    self._acquired.add(p)
                    if old_row and not _bug_newrow_sync():
                        # mid-stream join: abstain until a majority of the
                        # pre-join row is merged in. If we were also just
                        # promoted, the promote-time sync below runs with
                        # the same (others, need) and clears the graft on
                        # completion; otherwise the join-time pull does.
                        self._grafted[p] = (
                            tuple(n for n in old_row if n != self.address),
                            len(old_row) // 2 + 1,
                        )
                        if row[0] != self.address:
                            to_graft.append(p)
                else:
                    self._acquired.discard(p)
                leader = row[0]
                if old is not None and old_leader != leader:
                    changes += 1
                if leader == self.address and (
                    old_leader != self.address or p in self._churned
                ):
                    # promoted (or still mid-sync from the previous
                    # promotion, whose pull this map just superseded):
                    # sync against the OLD row, whose majority acked every
                    # pre-view write. Pulling from the new row would count
                    # empty just-acquired replicas toward the quorum.
                    if old_row and not _bug_newrow_sync():
                        others = tuple(
                            n for n in old_row if n != self.address
                        )
                        # a grafted self does not count toward the old-row
                        # majority: its own copy is the unreconciled bytes
                        # the graft discipline exists to quarantine
                        need = (len(old_row) // 2 + 1) - (
                            1 if (
                                self.address in old_row
                                and p not in self._grafted
                            ) else 0
                        )
                    else:
                        # first map this member sees: the old row is
                        # unknowable, so best-effort sync against the new
                        # row -- responders still answer RETRY until their
                        # own acquisition lands, so empty co-acquirers
                        # cannot satisfy the count. (_bug_newrow_sync
                        # forces this branch even with an old row: a
                        # just-acquired replica then answers from its
                        # handoff copy, which may descend from a stale
                        # survivor -- the pinned-corpus regression)
                        others = tuple(n for n in row if n != self.address)
                        need = (len(row) // 2 + 1) - 1
                    if need <= 0 or not others:
                        self._churned.pop(p, None)
                        continue  # sole replica holds every acked write
                    self._churned[p] = (others, need)
                    to_sync.append((p, others, need, pmap.version))
                elif leader != self.address:
                    self._churned.pop(p, None)
        if changes:
            self.metrics.incr("serving.leader_changes", changes)
            if self._tracer is not None:
                self._tracer.event(
                    "serving_leader_change", virtual_ms=self._now(),
                    partitions=changes, version=pmap.version,
                )
            if self._recorder is not None:
                self._recorder.record(
                    "serving_leader_change", partitions=changes,
                    version=pmap.version, churned=len(to_sync),
                )
        # sends outside the lock: in-process transports complete inline
        for p, others, need, version in to_sync:
            self._start_sync(p, others, need, version)
        for p in to_graft:
            self._start_graft(p)

    def _start_sync(self, p: int, others: Tuple[Endpoint, ...], need: int,
                    version: int) -> None:
        """Pull whole-partition snapshots from the other replicas and merge
        per-key max-versions; the churn flag clears once a majority of the
        row (self included) contributed."""
        with self._lock:
            if (
                self._map is None or self._map.version != version
                or p not in self._churned
            ):
                return  # superseded by a newer map (its own sync runs)
        probe = Get(
            sender=self.address, key=p.to_bytes(8, "little"), quorum=2,
            map_version=version,
        )
        state = {"snaps": [], "replies": 0, "done": False}
        for node in others:
            promise = self._client.send_message(node, probe)
            promise.add_callback(
                lambda reply: self._on_snapshot(
                    p, others, need, version, state, reply
                )
            )

    def _on_snapshot(self, p: int, others: Tuple[Endpoint, ...], need: int,
                     version: int, state: dict, promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        retry = False
        with self._lock:
            if state["done"]:
                return
            if (
                self._map is None or self._map.version != version
                or p not in self._churned
            ):
                state["done"] = True
                return
            state["replies"] += 1
            if (
                exc is None and isinstance(reply, PutAck)
                and reply.status == PutAck.STATUS_OK
            ):
                state["snaps"].append(decode_kv(reply.value))
            if len(state["snaps"]) >= need:
                state["done"] = True
                kv = self._load_locked(p)
                for snap in state["snaps"]:
                    for key, (ver, val) in snap.items():
                        if ver > kv.get(key, (0, b""))[0]:
                            kv[key] = (ver, val)
                self._persist_locked(p)
                self._churned.pop(p, None)
                # the promote-time merge covers the join-time obligation:
                # when this member was grafted, need was a full old-row
                # majority (self uncounted), the same quorum the pull wants
                self._grafted.pop(p, None)
                if self._recorder is not None:
                    self._recorder.record(
                        "serving_sync", partition=p, version=version,
                        snapshots=len(state["snaps"]),
                    )
            elif state["replies"] >= len(others):
                # not enough live snapshot answers this round; re-pull
                # until a newer map supersedes this promotion
                state["done"] = True
                retry = True
        if retry:
            if self._scheduler is not None:
                self._scheduler.schedule(
                    self.retry_delay_ms,
                    lambda: self._start_sync(p, others, need, version),
                )
            else:
                # no scheduler to defer to: retry inline (mirroring
                # _on_routed_reply), otherwise the partition would stay
                # churned forever and every Put would answer RETRY
                self._start_sync(p, others, need, version)

    def _start_graft(self, p: int) -> None:
        """Join-time pull for a mid-stream acquirer (follower half of the
        graft discipline): merge a majority of the pre-join row, then
        start answering. The pull outlives map changes -- the obligation
        is about writes acked before this member joined, and the target
        row is fixed at join time -- and retries until it completes, the
        partition moves away, or a promotion's own sync subsumes it."""
        with self._lock:
            entry = self._grafted.get(p)
            pmap = self._map
            if entry is None or pmap is None:
                return
            others, need = entry
            version = pmap.version
        probe = Get(
            sender=self.address, key=p.to_bytes(8, "little"), quorum=2,
            map_version=version,
        )
        state = {"snaps": [], "replies": 0, "done": False}
        for node in others:
            promise = self._client.send_message(node, probe)
            promise.add_callback(
                lambda reply: self._on_graft_snapshot(
                    p, others, need, state, reply
                )
            )

    def _on_graft_snapshot(self, p: int, others: Tuple[Endpoint, ...],
                           need: int, state: dict, promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        retry = False
        with self._lock:
            if state["done"]:
                return
            if p not in self._grafted:
                state["done"] = True
                return
            state["replies"] += 1
            if (
                exc is None and isinstance(reply, PutAck)
                and reply.status == PutAck.STATUS_OK
            ):
                state["snaps"].append(decode_kv(reply.value))
            if len(state["snaps"]) >= need:
                state["done"] = True
                kv = self._kv.get(p)
                if kv is None:
                    blob = self.store.get(p)
                    kv = decode_kv(blob) if blob is not None else {}
                    self._kv[p] = kv
                for snap in state["snaps"]:
                    for key, (ver, val) in snap.items():
                        if ver > kv.get(key, (0, b""))[0]:
                            kv[key] = (ver, val)
                self._persist_locked(p)
                self._grafted.pop(p, None)
                self.metrics.incr("serving.reconciled_replicas")
                if self._recorder is not None:
                    self._recorder.record(
                        "serving_sync", partition=p, graft=True,
                        snapshots=len(state["snaps"]),
                    )
            elif state["replies"] >= len(others):
                state["done"] = True
                retry = True
        if retry and self._scheduler is not None:
            self._scheduler.schedule(
                self.retry_delay_ms, lambda: self._start_graft(p)
            )
        # without a scheduler a stuck graft just stays open: this member
        # keeps abstaining (safe), and a later promotion's sync or a map
        # move clears it -- unlike _on_snapshot there is no availability
        # cliff forcing an inline retry, and an inline loop could never
        # terminate against a RETRY-answering in-process peer

    # -- local state ------------------------------------------------------ #

    def _load_locked(self, p: int) -> Dict[bytes, Tuple[int, bytes]]:
        kv = self._kv.get(p)
        if kv is None:
            kv = decode_kv(self.store.get(p))
            self._kv[p] = kv
        return kv

    def _persist_locked(self, p: int) -> None:
        # every mutation re-serializes canonically so replica fingerprints
        # stay comparable and handoff always moves current bytes
        self.store.put(p, encode_kv(self._kv[p]))

    def _snapshot_blob_locked(self, p: int) -> Optional[bytes]:
        """Bytes this member may contribute to a peer's promote-time sync,
        or None when it has nothing authoritative: it never replicated the
        partition, or its own handoff acquisition is still in flight (an
        empty answer must not count toward the peer's old-row majority)."""
        pmap = self._map
        if pmap is None or not 0 <= p < len(pmap.assignments):
            return None
        row = pmap.assignments[p]
        if row and self.address in row:
            if p in self._acquired and self.store.get(p) is None:
                return None
            if p in self._grafted:
                return None  # handoff copy not yet reconciled (see graft)
            return encode_kv(self._load_locked(p))
        entry = self._retired.get(p)
        return entry[1] if entry is not None else None

    def _authoritative_kv_locked(
        self, p: int
    ) -> Optional[Dict[bytes, Tuple[int, bytes]]]:
        """Decoded state for quorum-read answers, under the same rules as
        _snapshot_blob_locked; retired state is decoded without caching
        (this member no longer owns the partition)."""
        pmap = self._map
        if pmap is None or not 0 <= p < len(pmap.assignments):
            return None
        row = pmap.assignments[p]
        if row and self.address in row:
            if p in self._acquired and self.store.get(p) is None:
                return None
            if p in self._grafted:
                return None  # handoff copy not yet reconciled (see graft)
            return self._load_locked(p)
        entry = self._retired.get(p)
        return decode_kv(entry[1]) if entry is not None else None

    # -- server half: Get ------------------------------------------------- #

    def handle_get(self, msg: Get) -> Promise:
        quorum_read: Optional[Tuple[int, Tuple[Endpoint, ...], int]] = None
        with self._lock:
            self._gets += 1
            self.metrics.incr("serving.gets")
            pmap = self._map
            if pmap is None:
                return Promise.completed(self._retry_ack(msg.key, 0))
            if msg.quorum == 2:
                # whole-partition snapshot (promote-time sync source half):
                # the key carries the partition id as 8 LE bytes. Answer
                # only what we are authoritative for -- bounds-checked,
                # replicated here (or just retired here), and not awaiting
                # our own handoff delivery -- so a stale or malformed probe
                # neither pollutes the KV cache nor contributes an empty
                # snapshot to a peer's old-row majority.
                if len(msg.key) < 8:
                    return Promise.completed(self._retry_ack(msg.key, 0))
                p = int.from_bytes(msg.key[:8], "little")
                blob = self._snapshot_blob_locked(p)
                if blob is None:
                    return Promise.completed(self._retry_ack(msg.key, 0))
                return Promise.completed(PutAck(
                    sender=self.address, status=PutAck.STATUS_OK,
                    key=msg.key, value=blob, map_version=pmap.version,
                ))
            p = partition_of(msg.key, pmap.config.partitions)
            if msg.quorum == 1:
                # quorum-read member half: answer from local state, but
                # only when authoritative (same gate as the snapshot path;
                # a churned leader's read quorum runs over the OLD row, so
                # retired state answers and in-flight acquirers abstain)
                akv = self._authoritative_kv_locked(p)
                if akv is None:
                    return Promise.completed(self._retry_ack(msg.key, 0))
                version, value = akv.get(msg.key, (0, b""))
                return Promise.completed(PutAck(
                    sender=self.address,
                    status=(PutAck.STATUS_OK if msg.key in akv
                            else PutAck.STATUS_NOT_FOUND),
                    key=msg.key, value=value, version=version,
                    map_version=pmap.version,
                ))
            row = pmap.assignments[p] if p < len(pmap.assignments) else ()
            if not row or row[0] != self.address:
                self.metrics.incr("serving.not_leader_redirects")
                return Promise.completed(PutAck(
                    sender=self.address, status=PutAck.STATUS_NOT_LEADER,
                    key=msg.key, leader=row[0] if row else None,
                    map_version=pmap.version,
                ))
            kv = self._load_locked(p)
            version, value = kv.get(msg.key, (0, b""))
            found = msg.key in kv
            if p in self._churned:
                # just promoted, snapshot sync still in flight: a local
                # answer could miss writes acked by the previous leader --
                # fall back to a quorum read over the same old row the
                # sync pulls from (the row whose majority acked them)
                others, need = self._churned[p]
                quorum_read = (p, others, need)
            else:
                self.metrics.incr("serving.leader_reads")
                return Promise.completed(PutAck(
                    sender=self.address,
                    status=(PutAck.STATUS_OK if found
                            else PutAck.STATUS_NOT_FOUND),
                    key=msg.key, value=value, version=version,
                    map_version=pmap.version,
                ))
        p, others, need = quorum_read
        return self._quorum_read(msg.key, others, need, version, value, found)

    def _quorum_read(self, key: bytes, others: Tuple[Endpoint, ...],
                     need: int, version: int, value: bytes,
                     found: bool) -> Promise:
        """Fan a quorum Get over the churned partition's old row; answer
        with the max-version value once a majority of that row (local
        answer included when this member was in it) responded. Any acked
        write's majority lives in the old row and intersects ours, so the
        max-version answer observes it. Responders answer only when
        authoritative (retired state counts; in-flight acquirers abstain
        with RETRY, which is not counted)."""
        self.metrics.incr("serving.quorum_reads")
        done: Promise = Promise()
        if need <= 0 or not others:
            done.set_result(self._read_ack(key, version, value, found))
            return done
        state = {
            "version": version, "value": value, "found": found,
            "answers": 0, "replies": 0, "done": False,
        }
        probe = Get(sender=self.address, key=key, quorum=1)
        for node in others:
            promise = self._client.send_message(node, probe)
            promise.add_callback(
                lambda reply: self._on_quorum_answer(
                    key, need, len(others), state, done, reply
                )
            )
        return done

    def _on_quorum_answer(self, key: bytes, need: int, total: int,
                          state: dict, done: Promise, promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        ack: Optional[PutAck] = None
        with self._lock:
            if state["done"]:
                return
            state["replies"] += 1
            if exc is None and isinstance(reply, PutAck) and reply.status in (
                PutAck.STATUS_OK, PutAck.STATUS_NOT_FOUND,
            ):
                state["answers"] += 1
                if (
                    reply.status == PutAck.STATUS_OK
                    and reply.version > state["version"]
                ):
                    state["version"] = reply.version
                    state["value"] = reply.value
                    state["found"] = True
            if state["answers"] >= need:
                state["done"] = True
                ack = self._read_ack(
                    key, state["version"], state["value"], state["found"]
                )
            elif state["replies"] >= total:
                # not enough replica answers for a majority: the client
                # retries against the (soon-synced) leader
                state["done"] = True
                ack = self._retry_ack(key, 0)
        if ack is not None:
            done.try_set_result(ack)

    def _read_ack(self, key: bytes, version: int, value: bytes,
                  found: bool) -> PutAck:
        return PutAck(
            sender=self.address,
            status=PutAck.STATUS_OK if found else PutAck.STATUS_NOT_FOUND,
            key=key, value=value, version=version,
            map_version=self._map.version if self._map is not None else 0,
        )

    def _retry_ack(self, key: bytes, request_id: int) -> PutAck:
        return PutAck(
            sender=self.address, status=PutAck.STATUS_RETRY, key=key,
            request_id=request_id,
            map_version=self._map.version if self._map is not None else 0,
        )

    # -- server half: Put ------------------------------------------------- #

    def handle_put(self, msg: Put) -> Promise:
        with self._lock:
            self._puts += 1
            self.metrics.incr("serving.puts")
            if msg.replicate:
                return Promise.completed(self._apply_replica_locked(msg))
            pmap = self._map
            if pmap is None:
                return Promise.completed(
                    self._retry_ack(msg.key, msg.request_id)
                )
            p = partition_of(msg.key, pmap.config.partitions)
            row = pmap.assignments[p] if p < len(pmap.assignments) else ()
            if not row or row[0] != self.address:
                self.metrics.incr("serving.not_leader_redirects")
                return Promise.completed(PutAck(
                    sender=self.address, status=PutAck.STATUS_NOT_LEADER,
                    key=msg.key, request_id=msg.request_id,
                    leader=row[0] if row else None,
                    map_version=pmap.version,
                ))
            if p in self._churned:
                # promote sync in flight: accepting the write now could
                # assign a version the previous leader already used
                return Promise.completed(
                    self._retry_ack(msg.key, msg.request_id)
                )
            kv = self._load_locked(p)
            version = kv.get(msg.key, (0, b""))[0] + 1
            kv[msg.key] = (version, msg.value)
            self._persist_locked(p)
            others = tuple(n for n in row if n != self.address)
            need = (len(row) // 2 + 1) - 1  # majority minus self-ack
            ack = PutAck(
                sender=self.address, status=PutAck.STATUS_OK, key=msg.key,
                version=version, request_id=msg.request_id,
                map_version=pmap.version,
            )
        if need <= 0:
            return Promise.completed(ack)
        done: Promise = Promise()
        state = {"acks": 0, "replies": 0, "done": False}
        replica_put = Put(
            sender=self.address, key=msg.key, value=msg.value,
            request_id=msg.request_id, replicate=1, version=ack.version,
            map_version=ack.map_version,
        )
        # sends outside the lock; replies can complete inline
        for node in others:
            self.metrics.incr("serving.replication_writes")
            promise = self._client.send_message(node, replica_put)
            promise.add_callback(
                lambda reply: self._on_replica_ack(
                    need, len(others), state, done, ack, reply
                )
            )
        return done

    def _apply_replica_locked(self, msg: Put) -> PutAck:
        """Replica half: apply iff the replicated version is newer than
        what we hold -- duplicated, reordered or nemesis-replayed
        replication converges to the same state.

        Applies only under the sender's exact installed map (versions are
        fingerprints; equality is the only comparison) and only for
        partitions this member replicates. A deposed leader racing a map
        install therefore cannot assemble a quorum here -- it collects
        RETRYs and reports RETRY to its client instead of acking a write
        the new row would never inherit -- and a delayed or duplicated
        replication Put cannot re-create a blob for a partition this
        member already dropped."""
        pmap = self._map
        if pmap is None or msg.map_version != pmap.version:
            return self._retry_ack(msg.key, msg.request_id)
        p = partition_of(msg.key, pmap.config.partitions)
        row = pmap.assignments[p] if p < len(pmap.assignments) else ()
        if not row or self.address not in row:
            return self._retry_ack(msg.key, msg.request_id)
        kv = self._load_locked(p)
        if msg.version > kv.get(msg.key, (0, b""))[0]:
            kv[msg.key] = (msg.version, msg.value)
            self._persist_locked(p)
        return PutAck(
            sender=self.address, status=PutAck.STATUS_OK, key=msg.key,
            version=msg.version, request_id=msg.request_id,
            map_version=pmap.version,
        )

    def _on_replica_ack(self, need: int, total: int, state: dict,
                        done: Promise, ack: PutAck, promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        final: Optional[PutAck] = None
        with self._lock:
            if state["done"]:
                return
            state["replies"] += 1
            if (
                exc is None and isinstance(reply, PutAck)
                and reply.status == PutAck.STATUS_OK
            ):
                state["acks"] += 1
                self._put_acks += 1
                self.metrics.incr("serving.put_acks")
            if state["acks"] >= need:
                state["done"] = True
                final = ack
            elif state["replies"] >= total:
                # quorum unreachable: the local apply stands but is not
                # acknowledged -- the client must re-issue (PutAck docs)
                state["done"] = True
                self.metrics.incr("serving.put_retries")
                final = replace(ack, status=PutAck.STATUS_RETRY)
        if final is not None:
            done.try_set_result(final)

    # -- router half ------------------------------------------------------ #

    def client_put(self, key: bytes, value: bytes) -> Promise:
        """Write ``key`` through the partition leader; completes with the
        final PutAck after routing redirects and bounded retries."""
        return self._routed("put", key, value)

    def client_get(self, key: bytes) -> Promise:
        """Read ``key`` from the partition leader (quorum-read fallback is
        the leader's, not the client's, decision)."""
        return self._routed("get", key, b"")

    def _routed(self, op: str, key: bytes, value: bytes) -> Promise:
        with self._lock:
            request_id = self._next_request_id
            self._next_request_id += 1
        done: Promise = Promise()
        t0 = self._now()
        span = None
        if self._tracer is not None:
            span = self._tracer.begin(
                "serving_request", virtual_ms=t0, op=op,
            )
        self._attempt(op, key, value, request_id, 0, None, done, span, t0)
        return done

    def _attempt(self, op: str, key: bytes, value: bytes, request_id: int,
                 attempt: int, hint: Optional[Endpoint], done: Promise,
                 span, t0: Optional[int]) -> None:
        with self._lock:
            pmap = self._map
            leader = hint
            map_version = pmap.version if pmap is not None else 0
            if leader is None and pmap is not None:
                p = partition_of(key, pmap.config.partitions)
                row = pmap.assignments[p] if p < len(pmap.assignments) else ()
                leader = row[0] if row else None
        if leader is None:
            self._finish(done, span, t0, self._retry_ack(key, request_id))
            return
        if op == "put":
            msg = Put(
                sender=self.address, key=key, value=value,
                request_id=request_id, map_version=map_version,
            )
        else:
            msg = Get(
                sender=self.address, key=key, quorum=0,
                map_version=map_version,
            )
        if leader == self.address:
            promise = (
                self.handle_put(msg) if op == "put" else self.handle_get(msg)
            )
        else:
            promise = self._client.send_message(leader, msg)
        promise.add_callback(
            lambda reply: self._on_routed_reply(
                op, key, value, request_id, attempt, done, span, t0, reply
            )
        )

    def _on_routed_reply(self, op: str, key: bytes, value: bytes,
                         request_id: int, attempt: int, done: Promise,
                         span, t0: Optional[int], promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        hint: Optional[Endpoint] = None
        retryable = (
            exc is not None
            or not isinstance(reply, PutAck)
            or reply.status in (
                PutAck.STATUS_NOT_LEADER, PutAck.STATUS_RETRY,
            )
        )
        if retryable and attempt + 1 < self.retry_limit:
            if (
                isinstance(reply, PutAck)
                and reply.status == PutAck.STATUS_NOT_LEADER
            ):
                hint = reply.leader  # follow once; next retry recomputes
            if op == "put":
                self.metrics.incr("serving.put_retries")
            retry = lambda: self._attempt(  # noqa: E731
                op, key, value, request_id, attempt + 1, hint, done, span, t0
            )
            if self._scheduler is not None:
                self._scheduler.schedule(self.retry_delay_ms, retry)
            else:
                retry()
            return
        final = (
            reply if isinstance(reply, PutAck)
            else self._retry_ack(key, request_id)
        )
        self._finish(done, span, t0, final)

    def _finish(self, done: Promise, span, t0: Optional[int],
                ack: PutAck) -> None:
        now = self._now()
        if t0 is not None and now is not None:
            self.metrics.observe(
                "serving.request_ms", max(0, now - t0),
                buckets=SERVING_LATENCY_BUCKETS_MS,
            )
        if self._tracer is not None and span is not None:
            span.attrs["status"] = ack.status
            self._tracer.end(span, virtual_ms=now)
        done.try_set_result(ack)
