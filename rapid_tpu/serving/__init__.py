"""Serving plane: a replicated Get/Put KV store over placement + handoff."""

from .engine import ServingEngine
from .kv import SERVING_SEED, decode_kv, encode_kv, partition_of
from .router import RendezvousRouter

__all__ = [
    "SERVING_SEED",
    "RendezvousRouter",
    "ServingEngine",
    "decode_kv",
    "encode_kv",
    "partition_of",
]
