"""The public API: build, start, or join a cluster node.

Reference: Cluster.java. ``Cluster.Builder(addr).start()`` bootstraps a seed;
``.join(seed)`` runs the two-phase join protocol with up to RETRIES attempts
(Cluster.java:303-344): phase 1 asks a seed for the configuration and the K
expected observers; phase 2 asks those observers to vouch for the joiner, and
the response arrives only after the resulting view change commits.

Protocol constants K=10, H=9, L=4, RETRIES=5 (Cluster.java:72-75).

The join client is a callback state machine (``join_async``) so the same code
drives both the real-time scheduler and the deterministic virtual-time one;
``join`` is the blocking wrapper for real-time mode.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from .cut_detector import MultiNodeCutDetector
from .events import ClusterEvents
from .forensics.bundle import install_exit_hooks, write_bundle
from .forensics.hlc import HlcClock, HlcStampingClient
from .handoff.store import PartitionStore
from .membership import MembershipView
from .messaging.base import IMessagingClient, IMessagingServer
from .metadata import FrozenMetadata
from .monitoring.base import IEdgeFailureDetectorFactory
from .monitoring.pingpong import PingPongFailureDetectorFactory
from .observability import FlightRecorder, Metrics, Tracer, global_metrics
from .placement.engine import DEFAULT_WEIGHT_KEY, PlacementConfig
from .runtime.futures import Promise, successful_as_list
from .runtime.lockdep import make_lock
from .runtime.resources import SharedResources
from .runtime.scheduler import Scheduler
from .service import MembershipService, SubscriptionCallback
from .settings import Settings
from .types import (
    Endpoint,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    PreJoinMessage,
)

K = 10
H = 9
L = 4
RETRIES = 5

# Join-health counters (regression guard for seed starvation: a seed that
# answers phase 1 within the deadline keeps ``join.phase1_no_response`` at
# zero; ``join.exhausted`` counts joins that burned all RETRIES attempts).
# Protocol-legal retries -- CONFIG_CHANGED, UUID redraws, phase-2 races --
# are deliberately NOT counted here. Promoted onto the telemetry plane: a
# builder with an injected registry (``use_metrics``) counts there (so tests
# stop leaking state into each other); otherwise counts land on the
# process-global registry, which this module-level alias re-exports for
# existing importers.
JOIN_METRICS = global_metrics()


class JoinException(RuntimeError):
    pass


class Cluster:
    def __init__(
        self,
        server: IMessagingServer,
        membership_service: MembershipService,
        resources: SharedResources,
        listen_address: Endpoint,
    ) -> None:
        self._server = server
        self._membership_service = membership_service
        self._resources = resources
        self._listen_address = listen_address
        self._shutdown_lock = make_lock("Cluster._shutdown_lock")
        self._has_shutdown = False  # guarded-by: _shutdown_lock

    @property
    def listen_address(self) -> Endpoint:
        return self._listen_address

    def get_memberlist(self) -> List[Endpoint]:
        self._check_running()
        return self._membership_service.get_membership_view()

    def get_membership_size(self) -> int:
        self._check_running()
        return self._membership_service.membership_size

    def get_cluster_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        self._check_running()
        return self._membership_service.get_metadata()

    def get_current_configuration_id(self) -> int:
        self._check_running()
        return self._membership_service.get_current_configuration_id()

    def get_cluster_status(self):
        """Local introspection snapshot (same shape the ClusterStatusRequest
        RPC returns): config id, view size, cut-detector watermark occupancy,
        consensus round state, metrics digest, and the journal tail."""
        self._check_running()
        return self._membership_service.cluster_status()

    @property
    def flight_recorder(self) -> FlightRecorder:
        """The node's event journal; deliberately NOT gated on running so a
        post-mortem can dump it after shutdown."""
        return self._membership_service.recorder

    @property
    def hierarchy(self):
        """The hierarchy plane (hierarchy/plane.py), or None when
        ``settings.hierarchy`` is off. Harnesses use it to seed parent
        bootstrap hints and to read the composed global view."""
        return self._membership_service.hierarchy

    def capture_bundle(self, path: Optional[str] = None, *,
                       trigger: str = "explicit",
                       detail: Optional[Dict[str, object]] = None,
                       ) -> Dict[str, object]:
        """Capture a cluster-wide incident evidence bundle (forensics
        plane): this node's full evidence plus a status-RPC sweep of every
        other member, each bounded by
        ``settings.forensics.bundle_member_timeout_ms`` -- unreachable
        members are named in the manifest, never waited on. When ``path``
        is given the bundle is also written atomically (tmp +
        ``os.replace``). Feed the file(s) to ``tools/forensics.py report``
        for the HLC-ordered timeline and anomaly-signature verdicts."""
        self._check_running()
        bundle = self._membership_service.capture_cluster_bundle(
            trigger, detail
        )
        if path is not None:
            write_bundle(bundle, path)
        return bundle

    def capture_bundle_async(self, *, trigger: str = "explicit",
                             detail: Optional[Dict[str, object]] = None,
                             ) -> Promise:
        """Non-blocking capture (virtual-time clusters drive this form and
        pump the scheduler until the promise completes)."""
        self._check_running()
        return self._membership_service.capture_cluster_bundle_async(
            trigger, detail
        )

    @property
    def last_bundle(self) -> Optional[Dict[str, object]]:
        """The most recent bundle an automatic trigger (e.g. a burn alert)
        pinned on this node; NOT gated on running, like the recorder."""
        return self._membership_service.last_bundle

    def register_subscription(
        self, event: ClusterEvents, callback: SubscriptionCallback
    ) -> None:
        self._membership_service.register_subscription(event, callback)

    def get_placement_map(self):
        """The current deterministic shard map (placement/engine.py), or
        None when the node was built without ``use_placement``. Identical
        bytes-for-bytes on every member of a configuration."""
        self._check_running()
        return self._membership_service.placement_map()

    def get_placement_diff(self):
        """The rebalance plan from the most recent view change (None before
        the first churn or without placement)."""
        self._check_running()
        return self._membership_service.placement_diff()

    def get_handoff_status(self) -> Tuple[int, int, int]:
        """(in-flight, completed, failed) handoff session counts, all zero
        when the node was built without ``use_handoff``."""
        self._check_running()
        engine = self._membership_service.handoff_engine()
        return engine.status() if engine is not None else (0, 0, 0)

    def get_partition_store(self):
        """The PartitionStore this node moves bytes through (None without
        ``use_handoff``)."""
        self._check_running()
        engine = self._membership_service.handoff_engine()
        return engine.store if engine is not None else None

    def serving_put(self, key: bytes, value: bytes) -> Promise:
        """Write ``key`` through the serving plane (use_serving); resolves
        with the final PutAck after routing redirects and quorum ack."""
        self._check_running()
        return self._membership_service.serving_put(key, value)

    def serving_get(self, key: bytes) -> Promise:
        """Read ``key`` through the serving plane; resolves with a PutAck."""
        self._check_running()
        return self._membership_service.serving_get(key)

    def get_serving_status(self) -> Tuple[int, int, int]:
        """(gets, puts, replication acks) served by this member, all zero
        when the node was built without ``use_serving``."""
        self._check_running()
        engine = self._membership_service.serving_engine()
        return engine.status() if engine is not None else (0, 0, 0)

    def leave_gracefully_async(self) -> Promise:
        """Inform observers of the intent to leave, then shut down
        (Cluster.java:145-149)."""
        done: Promise = Promise()

        def after_leave(_p: Promise) -> None:
            self.shutdown()
            done.set_result(None)

        self._membership_service.leave_async().add_callback(after_leave)
        return done

    def leave_gracefully(self, timeout: float = 10.0) -> None:
        self.leave_gracefully_async().result(timeout)

    def shutdown(self) -> None:
        # shutdown() races leave_gracefully_async's completion callback with a
        # user-thread call; flip the flag under a lock so exactly one caller
        # runs the teardown, and tear down outside it (it blocks on joins)
        with self._shutdown_lock:
            if self._has_shutdown:
                return
            self._has_shutdown = True
        self._server.shutdown()
        self._membership_service.shutdown()
        # Graceful-stop durability barrier: flush the WAL and write a
        # snapshot + marker so the next boot recovers with zero replayed
        # records. Without this, a clean shutdown left the tail in the log
        # and every restart paid a full replay -- and under FSYNC_NEVER the
        # page cache could still hold acked writes when the process exited
        # (pinned in tests/test_advice_regressions.py). Duck-typed so the
        # in-memory store (no checkpoint()) is untouched.
        engine = self._membership_service.handoff_engine()
        if engine is not None:
            checkpoint = getattr(engine.store, "checkpoint", None)
            if checkpoint is not None:
                checkpoint()
        self._resources.shutdown()

    def _check_running(self) -> None:
        if self._has_shutdown:
            raise RuntimeError("cluster instance has been shut down")

    def __str__(self) -> str:
        return f"Cluster:{self._listen_address}"


class ClusterBuilder:
    """Cluster.Builder (Cluster.java:162-248)."""

    def __init__(self, listen_address: Endpoint) -> None:
        self._listen_address = listen_address
        self._metadata: FrozenMetadata = ()
        self._settings = Settings()
        self._fd_factory: Optional[IEdgeFailureDetectorFactory] = None
        self._subscriptions: Dict[ClusterEvents, List[SubscriptionCallback]] = {}
        self._client: Optional[IMessagingClient] = None
        self._server: Optional[IMessagingServer] = None
        self._scheduler: Optional[Scheduler] = None
        self._rng: Optional[random.Random] = None
        self._broadcaster_factory = None
        self._metrics: Optional[Metrics] = None
        self._tracer: Optional[Tracer] = None
        self._placement: Optional[PlacementConfig] = None
        self._handoff_store: Optional[PartitionStore] = None
        self._serving = False
        self._tier_resolver: Optional[Callable[[Endpoint], str]] = None
        self._durability_dir: Optional[str] = None
        self._forensics_dump: Optional[str] = None

    def set_metadata(self, metadata: Dict[str, bytes]) -> "ClusterBuilder":
        self._metadata = tuple(sorted(metadata.items()))
        return self

    def set_edge_failure_detector_factory(
        self, factory: IEdgeFailureDetectorFactory
    ) -> "ClusterBuilder":
        self._fd_factory = factory
        return self

    def set_tier_resolver(
        self, tier_of: Callable[[Endpoint], str]
    ) -> "ClusterBuilder":
        """Topology tier label per monitored subject (rack/zone/region/wan)
        for the adaptive failure detector's peer grouping; ignored unless
        settings.adaptive_fd.enabled (see monitoring/adaptive.py)."""
        self._tier_resolver = tier_of
        return self

    def add_subscription(
        self, event: ClusterEvents, callback: SubscriptionCallback
    ) -> "ClusterBuilder":
        self._subscriptions.setdefault(event, []).append(callback)
        return self

    def use_settings(self, settings: Settings) -> "ClusterBuilder":
        self._settings = settings
        return self

    def set_messaging_client_and_server(
        self, client: IMessagingClient, server: IMessagingServer
    ) -> "ClusterBuilder":
        self._client = client
        self._server = server
        return self

    def use_scheduler(self, scheduler: Scheduler) -> "ClusterBuilder":
        """Share a scheduler across in-process nodes (virtual-time clusters)."""
        self._scheduler = scheduler
        return self

    def use_rng(self, rng: random.Random) -> "ClusterBuilder":
        """Seeded randomness for deterministic runs (node IDs, broadcast
        shuffles, consensus jitter)."""
        self._rng = rng
        return self

    def use_metrics(self, metrics: Metrics) -> "ClusterBuilder":
        """Inject the metrics registry for this node (join diagnostics,
        failure detectors, and the MembershipService all count there).
        Default: a per-node registry attached to ``global_metrics()``."""
        self._metrics = metrics
        return self

    def use_tracer(self, tracer: Tracer) -> "ClusterBuilder":
        """Inject the span tracer for this node. Default: a per-node tracer
        attached to ``global_tracer()``."""
        self._tracer = tracer
        return self

    def use_placement(
        self,
        partitions: int = 256,
        replicas: int = 3,
        seed: int = 0,
        weight_key: str = DEFAULT_WEIGHT_KEY,
        default_weight: int = 1,
    ) -> "ClusterBuilder":
        """Enable the placement plane: a deterministic P-partition, R-replica
        shard map recomputed locally at every view change (placement/). All
        members must be built with identical parameters -- they are part of
        the map function, like K/H/L are part of the protocol."""
        self._placement = PlacementConfig(
            partitions=partitions, replicas=replicas, seed=seed,
            weight_key=weight_key, default_weight=default_weight,
        )
        return self

    def use_handoff(self, store: PartitionStore) -> "ClusterBuilder":
        """Enable the handoff plane: every placement diff's moved partitions
        are pulled into ``store`` by this node when it becomes a new replica,
        and released from it once a verified new owner acks (handoff/).
        Requires ``use_placement`` with identical parameters cluster-wide."""
        self._handoff_store = store
        return self

    def use_serving(
        self, store: Optional[PartitionStore] = None
    ) -> "ClusterBuilder":
        """Enable the serving plane: a replicated Get/Put KV store routed by
        the placement map, with quorum-ack writes and leader reads
        (serving/). The serving engine persists into the handoff plane's
        PartitionStore so view-change state transfer moves serving data
        through verified handoff sessions; ``store`` configures the handoff
        plane when it is not configured yet. Requires ``use_placement`` and
        ``use_handoff`` (directly or via ``store``)."""
        if store is not None and self._handoff_store is None:
            self.use_handoff(store)
        self._serving = True
        return self

    def use_durability(self, directory: str) -> "ClusterBuilder":
        """Enable the durability plane: mount a write-ahead-logged
        DurablePartitionStore rooted at ``directory`` under the handoff
        seam (durability/). Construction is recovery -- a restarted node
        reopens with the state it acknowledged, reuses its persisted
        NodeId to rejoin, and catches up via verified handoff pulls.
        Gated on ``settings.durability.enabled`` (the kill switch): when
        off, the directory is ignored and the node runs the exact
        pre-durability in-memory path."""
        self._durability_dir = directory
        return self

    def _durable_store(self):
        """Build (and recover) the durable store when the plane is on;
        mounts it as the handoff store so every downstream plane
        (placement sizes, handoff pulls, serving persistence) rides it."""
        if self._durability_dir is None or not self._settings.durability.enabled:
            return None
        from .durability.store import DurablePartitionStore

        knobs = self._settings.durability
        store = DurablePartitionStore(
            self._durability_dir,
            segment_bytes=knobs.segment_bytes,
            fsync_policy=knobs.fsync_policy,
            snapshot_every_records=knobs.snapshot_every_records,
        )
        self._handoff_store = store
        return store

    def use_forensics_dump(self, journal_path: str) -> "ClusterBuilder":
        """Register crash/exit evidence hooks (forensics plane): an atexit
        dump of the flight-recorder journal to ``journal_path`` (atomic:
        tmp + ``os.replace``) plus a faulthandler traceback file beside it
        (``journal_path + ".crash"``) for hard crashes that never reach
        atexit. Inert unless ``settings.forensics.enabled``."""
        self._forensics_dump = journal_path
        return self

    def _forensics(
        self, resources: SharedResources, client: IMessagingClient,
        durable,
    ) -> Tuple[Optional[HlcClock], IMessagingClient,
               Optional[FlightRecorder]]:
        """Forensics-plane assembly, shared by ``start`` and ``join_async``.

        When ``settings.forensics.enabled``: mint this node's hybrid
        logical clock (physical axis = the node's scheduler clock, so
        virtual-time runs are deterministic and a nemesis clock-skew
        scheduler skews the HLC with the node; incarnation = the durable
        store's persisted boot count when one exists), wrap the messaging
        client so every outbound message carries a fresh stamp, and build
        the HLC-stamping flight recorder at the configured capacity. When
        off: (None, client, None) -- the exact pre-forensics path, byte
        for byte on the wire."""
        if not self._settings.forensics.enabled:
            return None, client, None
        incarnation = 1
        if durable is not None:
            bump = getattr(durable, "bump_incarnation", None)
            if bump is not None:
                incarnation = max(1, int(bump()))
        hlc = HlcClock(
            clock=resources.scheduler.now_ms, incarnation=incarnation
        )
        recorder = FlightRecorder(
            node=str(self._listen_address),
            clock=resources.scheduler.now_ms,
            capacity=self._settings.forensics.journal_capacity,
            hlc=hlc,
            metrics=self._metrics,
        )
        if self._forensics_dump:
            install_exit_hooks(recorder, self._forensics_dump)
        return hlc, HlcStampingClient(client, hlc), recorder

    def set_broadcaster_factory(self, factory) -> "ClusterBuilder":
        """Swap the dissemination strategy: ``factory(client, rng)`` returns
        the IBroadcaster this node's service uses (default:
        UnicastToAllBroadcaster; e.g. messaging.gossip.GossipBroadcaster for
        epidemic relay -- the alternative IBroadcaster.java:24-26 names)."""
        self._broadcaster_factory = factory
        return self

    def _broadcaster(self, client: IMessagingClient, rng: random.Random):
        if self._broadcaster_factory is None:
            return None  # service defaults to UnicastToAllBroadcaster
        broadcaster = self._broadcaster_factory(client, rng)
        if getattr(broadcaster, "receive", None) is not None:
            # gossip-style broadcasters wrap messages in GossipEnvelope,
            # which the JVM-wire-compatible gRPC transport cannot carry
            # (rapid.proto has no such message); best-effort sends would
            # fail silently and the cluster would never converge, so refuse
            # the pairing at build time
            try:
                from .messaging.grpc_transport import GrpcClient
            except Exception:  # noqa: BLE001 -- grpc extra not installed
                return broadcaster
            if isinstance(client, GrpcClient):
                raise JoinException(
                    "gossip-style broadcasters need a native-codec transport "
                    "(tcp / native-tcp / in-process); the gRPC wire has no "
                    "GossipEnvelope message"
                )
        return broadcaster

    # ------------------------------------------------------------------ #

    def _prepare(self) -> Tuple[SharedResources, IMessagingClient, IMessagingServer,
                                random.Random]:
        if self._client is None or self._server is None:
            raise JoinException(
                "no transport: call set_messaging_client_and_server(...) "
                "(e.g. InProcessClient/InProcessServer or the TCP transport)"
            )
        resources = SharedResources(self._scheduler, name=str(self._listen_address))
        rng = self._rng if self._rng is not None else random.Random()
        return resources, self._client, self._server, rng

    def _fd(self, client: IMessagingClient) -> IEdgeFailureDetectorFactory:
        if self._fd_factory is not None:
            return self._fd_factory
        # RTT estimates read the node's scheduler clock when one is set, so
        # virtual-time runs measure deterministic fd.rtt_ms and a nemesis
        # clock-skew scheduler drifts the estimates with the node
        clock = self._scheduler.now_ms if self._scheduler is not None else None
        if self._settings.adaptive_fd.enabled:
            from .monitoring.adaptive import AdaptivePingPongFactory

            return AdaptivePingPongFactory(
                self._listen_address, client,
                settings=self._settings,
                metrics=self._metrics,
                clock=clock,
                tier_of=self._tier_resolver,
            )
        if self._settings.fd_policy == "windowed":
            from .monitoring.pingpong import WindowedPingPongFailureDetectorFactory

            return WindowedPingPongFailureDetectorFactory(
                self._listen_address, client,
                window=self._settings.fd_window,
                threshold=self._settings.fd_window_threshold,
                metrics=self._metrics,
                clock=clock,
            )
        return PingPongFailureDetectorFactory(
            self._listen_address, client,
            failure_threshold=self._settings.fd_failure_threshold,
            metrics=self._metrics,
            clock=clock,
        )

    def start(self) -> Cluster:
        """Bootstrap a seed node (Cluster.java:255-280)."""
        resources, client, server, rng = self._prepare()
        durable = self._durable_store()
        # forensics plane (kill-switched): HLC-stamping client wrapper plus
        # the HLC-stamping recorder; (None, client, None) when off
        hlc, client, forensics_recorder = self._forensics(
            resources, client, durable
        )
        # restart-aware identity: a seed that persisted its NodeId boots
        # with the same identity it had before the restart
        node_id = durable.node_id if durable is not None else None
        if node_id is None:
            node_id = NodeId.random(rng)
        view = MembershipView(K, node_ids=[node_id], endpoints=[self._listen_address])
        cut_detector = MultiNodeCutDetector(K, H, L)
        metadata_map = (
            {self._listen_address: self._metadata} if self._metadata else {}
        )
        service = MembershipService(
            self._listen_address,
            cut_detector,
            view,
            resources,
            self._settings,
            client,
            self._fd(client),
            metadata_map=metadata_map,
            subscriptions=self._subscriptions,
            rng=rng,
            broadcaster=self._broadcaster(client, rng),
            metrics=self._metrics,
            tracer=self._tracer,
            recorder=(
                forensics_recorder
                if forensics_recorder is not None
                else FlightRecorder(
                    node=str(self._listen_address),
                    clock=resources.scheduler.now_ms,
                )
            ),
            placement=self._placement,
            handoff_store=self._handoff_store,
            serving=self._serving,
            hlc=hlc,
        )
        if durable is not None:
            durable.set_identity(node_id)
            durable.set_config_id(view.get_current_configuration_id())
        server.set_membership_service(service)
        server.start()
        return Cluster(server, service, resources, self._listen_address)

    def join(self, seed_address: Endpoint, timeout: float = 60.0) -> Cluster:
        """Blocking join for real-time mode."""
        return self.join_async(seed_address).result(timeout)

    def join_async(self, seed_address: Endpoint) -> Promise:
        """Two-phase join state machine (Cluster.java:303-401). Resolves with a
        Cluster or fails with JoinException after RETRIES attempts."""
        resources, client, server, rng = self._prepare()
        # The server starts before the join so observers can probe us; probes
        # are answered BOOTSTRAPPING until the service is wired
        # (Cluster.java:312, GrpcServer.java:83-95).
        server.start()
        result: Promise = Promise()
        durable = self._durable_store()
        # forensics plane (kill-switched): stamp the join traffic too, so
        # a seed's causal timeline includes the joiner's first messages
        hlc, client, forensics_recorder = self._forensics(
            resources, client, durable
        )
        # Restart-aware rejoin: reuse the persisted NodeId. A returning
        # host still present in the ring then gets HOSTNAME_ALREADY_IN_RING
        # in phase 1 and SAFE_TO_JOIN from observers that recognize the
        # (host, identity) pair -- the fast identity-preserving path; a
        # fresh random id on a still-present hostname would loop on
        # CONFIG_CHANGED until eviction. The identifier history is
        # append-only, so after eviction the old id is burned and the
        # UUID_ALREADY_IN_RING redraw below takes over.
        persisted = durable.node_id if durable is not None else None
        state = {
            "node_id": persisted if persisted is not None else NodeId.random(rng),
            "attempt": 0,
        }
        join_metrics = self._metrics if self._metrics is not None else JOIN_METRICS
        # the flight recorder outlives individual join attempts: created here
        # so retry exhaustion is journaled even when no service ever exists,
        # then handed to the MembershipService on success
        recorder = (
            forensics_recorder
            if forensics_recorder is not None
            else FlightRecorder(
                node=str(self._listen_address),
                clock=resources.scheduler.now_ms,
            )
        )

        def fail_all(reason: str) -> None:
            join_metrics.incr("join.exhausted")
            recorder.record(
                "join_exhausted", reason=reason, attempts=state["attempt"]
            )
            server.shutdown()
            client.shutdown()
            resources.shutdown()
            result.set_exception(
                JoinException(f"join attempt unsuccessful {self._listen_address}: {reason}")
            )

        def next_attempt(reason: str) -> None:
            state["attempt"] += 1
            if state["attempt"] >= RETRIES:
                fail_all(reason)
            else:
                attempt()

        def attempt() -> None:
            pre_join = PreJoinMessage(sender=self._listen_address, node_id=state["node_id"])
            client.send_message(seed_address, pre_join).add_callback(on_phase1)

        def on_phase1(p: Promise) -> None:
            if p.exception() is not None:
                # the seed never answered within the join deadline -- the
                # starvation signature, distinct from protocol-legal retries
                join_metrics.incr("join.phase1_no_response")
                next_attempt(f"phase 1 failed: {p.exception()}")
                return
            response = p.peek()
            if not isinstance(response, JoinResponse):
                next_attempt(f"unexpected phase 1 response {type(response).__name__}")
                return
            status = response.status_code
            if status not in (
                JoinStatusCode.SAFE_TO_JOIN,
                JoinStatusCode.HOSTNAME_ALREADY_IN_RING,
            ):
                # Error responses from the seed that warrant a retry
                # (Cluster.java:318-338)
                if status == JoinStatusCode.UUID_ALREADY_IN_RING:
                    state["node_id"] = NodeId.random(rng)
                next_attempt(f"phase 1 status {status.name}")
                return
            # HOSTNAME_ALREADY_IN_RING: a previous attempt's view change added
            # us; join with config id -1 so any SAFE_TO_JOIN response streams
            # the configuration (Cluster.java:374-381).
            config_to_join = (
                -1
                if status == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
                else response.configuration_id
            )
            send_phase2(response, config_to_join)

        def send_phase2(phase1_response: JoinResponse, config_to_join: int) -> None:
            # Batch ring numbers per distinct observer (Cluster.java:406-437)
            ring_numbers_per_observer: Dict[Endpoint, List[int]] = {}
            for ring_number, observer in enumerate(phase1_response.endpoints):
                ring_numbers_per_observer.setdefault(observer, []).append(ring_number)
            futures = []
            for observer, ring_numbers in ring_numbers_per_observer.items():
                msg = JoinMessage(
                    sender=self._listen_address,
                    node_id=state["node_id"],
                    ring_numbers=tuple(ring_numbers),
                    configuration_id=config_to_join,
                    metadata=self._metadata,
                )
                futures.append(client.send_message(observer, msg))
            successful_as_list(futures).add_callback(
                lambda p: on_phase2(p, config_to_join)
            )

        def on_phase2(p: Promise, config_to_join: int) -> None:
            responses = p.peek()
            # Accept the first response carrying a *different* configuration:
            # joining is itself a view change (Cluster.java:389-399).
            for response in responses:
                if (
                    isinstance(response, JoinResponse)
                    and response.status_code == JoinStatusCode.SAFE_TO_JOIN
                    and response.configuration_id != config_to_join
                ):
                    finish(response)
                    return
            next_attempt("phase 2 returned no valid configuration")

        def finish(response: JoinResponse) -> None:
            """createClusterFromJoinResponse (Cluster.java:442-474)."""
            view = MembershipView(
                K, node_ids=response.identifiers, endpoints=response.endpoints
            )
            cut_detector = MultiNodeCutDetector(K, H, L)
            metadata_map = dict(response.metadata)
            service = MembershipService(
                self._listen_address,
                cut_detector,
                view,
                resources,
                self._settings,
                client,
                self._fd(client),
                metadata_map=metadata_map,
                subscriptions=self._subscriptions,
                rng=rng,
                broadcaster=self._broadcaster(client, rng),
                metrics=self._metrics,
                tracer=self._tracer,
                recorder=recorder,
                placement=self._placement,
                handoff_store=self._handoff_store,
                serving=self._serving,
                hlc=hlc,
            )
            if durable is not None:
                durable.set_identity(state["node_id"])
                durable.set_config_id(response.configuration_id)
            server.set_membership_service(service)
            result.set_result(
                Cluster(server, service, resources, self._listen_address)
            )

        attempt()
        return result
