"""Runtime compile / host-transfer watchdog for the device plane.

The runtime half of the device-plane performance suite (the static half is
tools/devlint.py), built on the same seam discipline as lockdep.py: device
modules never call ``jax.jit`` / ``pl.pallas_call`` directly -- they go
through :func:`make_jit` / :func:`make_pallas_call`, which return the plain
jax objects when ``RAPID_JITWATCH`` is unset (zero overhead in production)
and instrumented wrappers when ``RAPID_JITWATCH=1`` (the tier-1 conftest
default).

What the wrapper records, per call-site *class* (the name passed to
``make_jit``):

- every compilation, detected from the jit object's executable-cache growth
  (``_cache_size``), so recompiles my own signature model would miss --
  donation, sharding or weak-type cache splits -- still count. Each event
  carries the *abstract signature* of the triggering call (shape / dtype /
  weak-type per traced leaf, values for statics), the wall time of that
  first call (trace + compile + execute -- the cost a steady-state caller
  would NOT have paid), and whether a timed window was open.
- a per-class compile budget (default ``RAPID_JITWATCH_BUDGET``, 512): a
  class that keeps compiling is leaking cache keys. Breaches record a
  violation *then* raise, so blanket ``except Exception`` handlers cannot
  swallow them silently -- the session-end conftest gate re-checks
  :func:`violations`.

Timed windows (:func:`timed_window`) declare a measured steady-state region:
any compilation inside one is a violation (warmup belongs outside), and
``jax.transfer_guard("disallow")`` is armed so implicit host transfers --
``int()`` on a traced value, numpy operands handed to a jitted call, python
scalars materialized per dispatch -- fail at the offending line. Deliberate
transfers route through the audited seams: :func:`fetch` (the one
device->host sync a protocol batch is allowed), :func:`drain` (a
block-until-ready barrier outside the measured region), and
:func:`host_transfer` (re-allows transfers for a labeled block, e.g. a
one-time scalar-constant upload). The guard is thread-local, so the
speculation worker's uploads never trip a window armed on the main thread.

Env vars:

- ``RAPID_JITWATCH=1``     enable (sampled at seam-creation time, like
                           lockdep; the wrapper also re-checks per call so
                           overhead A/B tests can toggle it)
- ``RAPID_JITWATCH_BUDGET`` per-class compile budget (default 512)
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax


class JitwatchViolation(RuntimeError):
    """A compile-budget breach or steady-state recompile."""


def enabled() -> bool:
    """Whether jitwatch is armed. Sampled at seam *creation* time to pick
    raw-vs-wrapped, and again per call so a wrapper created under
    ``RAPID_JITWATCH=1`` can be silenced for A/B overhead measurements."""
    return os.environ.get("RAPID_JITWATCH", "") == "1"


def _default_budget() -> int:
    return int(os.environ.get("RAPID_JITWATCH_BUDGET", "512"))


@dataclass(frozen=True)
class CompileEvent:
    """One recorded compilation (or pallas trace) of a watched class."""

    name: str  # call-site class (the make_jit name)
    signature: Tuple[Any, ...]  # abstract signature of the triggering call
    wall_s: float  # wall time of the compiling call (trace+compile+run)
    steady: bool  # a timed window was open on the calling thread
    kind: str  # "jit" | "pallas"


_LOCK = threading.Lock()
_EVENTS: List[CompileEvent] = []
_COUNTS: Dict[str, int] = {}
_SYNCS: Dict[str, int] = {}
_VIOLATIONS: List[str] = []
_TLS = threading.local()


def _windows() -> List[str]:
    stack = getattr(_TLS, "windows", None)
    if stack is None:
        stack = _TLS.windows = []
    return stack


def _fail(msg: str) -> None:
    """Record then raise, so a blanket handler around the call site cannot
    make the violation disappear -- the conftest session gate re-reads
    ``violations()`` (the lockdep precedent)."""
    with _LOCK:
        _VIOLATIONS.append(msg)
    raise JitwatchViolation(msg)


def _abstract_leaf(leaf: Any) -> Tuple[Any, ...]:
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return (
            tuple(shape),
            str(getattr(leaf, "dtype", "?")),
            bool(getattr(leaf, "weak_type", False)),
        )
    return ("py", type(leaf).__name__)


def _static_key(value: Any) -> Any:
    try:
        hash(value)
        return value
    except TypeError:
        return ("unhashable", repr(type(value)))


class _WatchedJit:
    """Instrumented stand-in for a ``jax.jit``-wrapped callable."""

    def __init__(
        self,
        name: str,
        jitted: Callable,
        static_argnums: Tuple[int, ...],
        static_argnames: Tuple[str, ...],
        compile_budget: Optional[int],
    ) -> None:
        self.name = name
        self._jitted = jitted
        self._static_argnums = static_argnums
        self._static_argnames = static_argnames
        self.compile_budget = (
            compile_budget if compile_budget is not None else _default_budget()
        )
        self._lock = threading.Lock()
        self._cache_size = getattr(jitted, "_cache_size", None)
        self._last_size = 0  # guarded-by: _lock
        # fallback compile detection when the jit object has no cache
        # counter: first sight of an abstract signature
        self._seen = set()  # guarded-by: _lock

    def signature_of(self, *args: Any, **kwargs: Any) -> Tuple[Any, ...]:
        """The abstract signature this wrapper classes calls by: static args
        by value, traced args by per-leaf (shape, dtype, weak_type)."""
        pos = []
        for i, a in enumerate(args):
            if i in self._static_argnums:
                pos.append(("static", _static_key(a)))
            else:
                pos.append(
                    ("traced", tuple(
                        _abstract_leaf(leaf)
                        for leaf in jax.tree_util.tree_leaves(a)
                    ))
                )
        kw = []
        for k in sorted(kwargs):
            if k in self._static_argnames:
                kw.append((k, "static", _static_key(kwargs[k])))
            else:
                kw.append(
                    (k, "traced", tuple(
                        _abstract_leaf(leaf)
                        for leaf in jax.tree_util.tree_leaves(kwargs[k])
                    ))
                )
        return (tuple(pos), tuple(kw))

    # -- underlying jax.jit API worth forwarding -------------------------- #

    def lower(self, *args: Any, **kwargs: Any):
        return self._jitted.lower(*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any):
        if not enabled():
            return self._jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        wall = time.perf_counter() - t0
        compiled = False
        if self._cache_size is not None:
            size = self._cache_size()
            with self._lock:
                if size != self._last_size:
                    self._last_size = size
                    compiled = True
        else:  # pragma: no cover - older jax without _cache_size
            sig = self.signature_of(*args, **kwargs)
            with self._lock:
                if sig not in self._seen:
                    self._seen.add(sig)
                    compiled = True
        if not compiled:
            return out
        signature = self.signature_of(*args, **kwargs)
        steady = bool(_windows())
        with _LOCK:
            _EVENTS.append(
                CompileEvent(self.name, signature, wall, steady, "jit")
            )
            count = _COUNTS[self.name] = _COUNTS.get(self.name, 0) + 1
        if steady:
            _fail(
                f"jitwatch: steady-state recompile of '{self.name}' inside "
                f"timed window '{_windows()[-1]}' (signature {signature!r}) "
                "-- warm this call class before the measured region"
            )
        if count > self.compile_budget:
            _fail(
                f"jitwatch: '{self.name}' compiled {count} times, over its "
                f"budget of {self.compile_budget} -- the call site is "
                "leaking jit cache keys (varying static values, shapes, or "
                "weak types)"
            )
        return out


def make_jit(
    name: str,
    fun: Optional[Callable] = None,
    *,
    static_argnums: Any = (),
    static_argnames: Any = (),
    donate_argnums: Any = (),
    compile_budget: Optional[int] = None,
) -> Callable:
    """The device plane's only route to ``jax.jit`` (seam, lockdep-style).

    ``name`` is the call-site class every compilation is recorded under.
    With ``fun`` omitted it curries, so the decorator form mirrors the old
    ``functools.partial(jax.jit, static_argnums=...)`` idiom::

        @functools.partial(make_jit, "sim.engine.step", static_argnums=0)
        def step(config, state): ...

    When jitwatch is disabled at creation time the plain ``jax.jit`` object
    is returned -- zero added overhead, and (like lockdep locks) the wrapper
    cannot be armed later.
    """
    if fun is None:
        def _bind(f: Callable) -> Callable:
            return make_jit(
                name, f, static_argnums=static_argnums,
                static_argnames=static_argnames,
                donate_argnums=donate_argnums,
                compile_budget=compile_budget,
            )
        return _bind
    nums = (
        (static_argnums,) if isinstance(static_argnums, int) else
        tuple(static_argnums)
    )
    names = (
        (static_argnames,) if isinstance(static_argnames, str) else
        tuple(static_argnames)
    )
    jitted = jax.jit(
        fun, static_argnums=nums, static_argnames=names,
        donate_argnums=donate_argnums,
    )
    if not enabled():
        return jitted
    return _WatchedJit(name, jitted, nums, names, compile_budget)


def make_pallas_call(name: str, kernel: Callable, **kwargs: Any) -> Callable:
    """Seam over ``pl.pallas_call``. The returned callable runs at trace
    time of the enclosing jit, so each invocation IS a (re)trace of the
    kernel class -- recorded as a pallas event; the enclosing ``make_jit``
    class carries the budget."""
    from jax.experimental import pallas as pl

    inner = pl.pallas_call(kernel, **kwargs)
    if not enabled():
        return inner

    def traced(*args: Any):
        if enabled():
            with _LOCK:
                _EVENTS.append(
                    CompileEvent(
                        name,
                        tuple(_abstract_leaf(a) for a in args),
                        0.0,
                        bool(_windows()),
                        "pallas",
                    )
                )
                _COUNTS[name] = _COUNTS.get(name, 0) + 1
        return inner(*args)

    return traced


# --------------------------------------------------------------------- #
# Declared timed windows + audited transfer seams
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def timed_window(name: str):
    """Declare a measured steady-state region: compiles on this thread
    become violations and ``jax.transfer_guard("disallow")`` is armed, so
    implicit host transfers fail at the offending line. A transfer-guard
    error propagating out is also recorded in ``violations()`` (in case an
    outer handler then swallows it)."""
    if not enabled():
        yield
        return
    stack = _windows()
    stack.append(name)
    try:
        with jax.transfer_guard("disallow"):
            yield
    except JitwatchViolation:
        raise
    except Exception as exc:
        text = str(exc)
        if "transfer" in text.lower():
            with _LOCK:
                _VIOLATIONS.append(
                    f"jitwatch: transfer-guard violation in timed window "
                    f"'{name}': {text.splitlines()[0]}"
                )
        raise
    finally:
        stack.pop()


@contextlib.contextmanager
def host_transfer(label: str):
    """Audited transfer seam: re-allows transfers for a labeled block
    inside a timed window (e.g. a one-time scalar-constant upload) and
    counts it, so 'zero unaudited transfers' stays checkable."""
    if not enabled():
        yield
        return
    with _LOCK:
        _SYNCS[label] = _SYNCS.get(label, 0) + 1
    with jax.transfer_guard("allow"):
        yield


def fetch(label: str, tree: Any) -> Any:
    """THE audited device->host sync: one explicit ``jax.device_get``,
    counted per label. Device modules route every fetch through here so
    devlint has a single annotated seam instead of ad-hoc call sites."""
    if enabled():
        with _LOCK:
            _SYNCS[label] = _SYNCS.get(label, 0) + 1
    return jax.device_get(tree)  # devlint: sync-point


def drain(label: str, *trees: Any) -> None:
    """Audited block-until-ready barrier (setup/teardown sync, not a data
    fetch): separates construction cost from measured protocol time."""
    if enabled():
        with _LOCK:
            _SYNCS[label] = _SYNCS.get(label, 0) + 1
    jax.block_until_ready(trees)  # devlint: sync-point


# --------------------------------------------------------------------- #
# Introspection
# --------------------------------------------------------------------- #


def compile_events() -> List[CompileEvent]:
    with _LOCK:
        return list(_EVENTS)


def compile_count(name: Optional[str] = None) -> int:
    with _LOCK:
        if name is not None:
            return _COUNTS.get(name, 0)
        return sum(_COUNTS.values())


def compile_wall_s(name: Optional[str] = None) -> float:
    with _LOCK:
        return sum(
            e.wall_s for e in _EVENTS if name is None or e.name == name
        )


def signatures(name: str) -> List[Tuple[Any, ...]]:
    """Distinct abstract signatures recorded for a class, in first-compile
    order -- the 'why did this recompile' forensic view."""
    with _LOCK:
        out, seen = [], set()
        for e in _EVENTS:
            if e.name == name and e.signature not in seen:
                seen.add(e.signature)
                out.append(e.signature)
        return out


def sync_counts() -> Dict[str, int]:
    with _LOCK:
        return dict(_SYNCS)


def stats() -> Dict[str, Any]:
    """Aggregate snapshot for bench records: total compiles and compile
    wall time so far (diff two snapshots to scope a phase)."""
    with _LOCK:
        return {
            "compiles": sum(_COUNTS.values()),
            "compile_wall_s": sum(e.wall_s for e in _EVENTS),
        }


def violations() -> List[str]:
    with _LOCK:
        return list(_VIOLATIONS)


def consume_violations() -> List[str]:
    global _VIOLATIONS
    with _LOCK:
        out = _VIOLATIONS
        _VIOLATIONS = []
        return out


def reset() -> None:
    """Clear the recorded log (events, counts, syncs, violations). Wrapper
    cache baselines persist -- jax's own caches do too."""
    global _EVENTS, _COUNTS, _SYNCS, _VIOLATIONS
    with _LOCK:
        _EVENTS = []
        _COUNTS = {}
        _SYNCS = {}
        _VIOLATIONS = []
