"""ctypes binding for the native framed-TCP reactor (native/rapid_io.cpp).

The runtime-IO analogue of the reference's shared Netty event-loop group
(SharedResources.java:48-67, NettyClientServer.java:65): a single epoll
thread in C++ multiplexes every accepted connection of a server, replacing
the Python transport's thread-per-connection readers. Frames cross the
boundary through a poll()-style event queue; payload parsing (request-no,
type tag, msgpack body) stays in rapid_tpu.messaging.codec.

``load()`` returns None when the shared library cannot be built/loaded
(no toolchain); callers fall back to the pure-Python FramedTcpServer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "librapid_io.so")

_lib: Optional[ctypes.CDLL] = None

# poll() event types (contract in rapid_io.cpp)
EV_NONE = 0
EV_FRAME = 1
EV_CLOSED = 2
EV_SHUTDOWN = -1


def _needs_build() -> bool:
    """True when the .so is absent or older than its source — a stale binary
    (e.g. built on another machine, or predating an edit to rapid_io.cpp)
    must never silently shadow the current source."""
    if not os.path.exists(_LIB_PATH):
        return True
    src = os.path.join(_NATIVE_DIR, "rapid_io.cpp")
    try:
        return os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return False


def _warn_if_stale() -> None:
    if os.path.exists(_LIB_PATH):
        import warnings

        warnings.warn(
            f"loading {_LIB_PATH} although its source is newer (rebuild "
            "unavailable); native results may not reflect source edits",
            RuntimeWarning,
            stacklevel=3,
        )


def load(auto_build: bool = True) -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if _needs_build():
        if not auto_build:
            # never build here: load the (possibly stale) binary if present
            if not os.path.exists(_LIB_PATH):
                return None
            _warn_if_stale()
        else:
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, "-B", "librapid_io.so"],
                    check=True, capture_output=True,
                )
            except Exception:  # noqa: BLE001 -- no toolchain: fallback
                if not os.path.exists(_LIB_PATH):
                    return None
                _warn_if_stale()
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64 = ctypes.c_int64
    lib.rapid_io_server_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rapid_io_server_create.restype = i64
    lib.rapid_io_server_port.argtypes = [i64]
    lib.rapid_io_server_port.restype = ctypes.c_int
    lib.rapid_io_server_poll.argtypes = [
        i64, ctypes.POINTER(i64), u8p, i64, ctypes.POINTER(i64), ctypes.c_int
    ]
    lib.rapid_io_server_poll.restype = ctypes.c_int
    lib.rapid_io_server_send.argtypes = [i64, i64, u8p, i64]
    lib.rapid_io_server_send.restype = ctypes.c_int
    lib.rapid_io_server_shutdown.argtypes = [i64]
    lib.rapid_io_server_shutdown.restype = None
    _lib = lib
    return lib


def available(auto_build: bool = True) -> bool:
    return load(auto_build) is not None


class NativeReactor:
    """One native server: epoll accept/read loop plus a framed send path.

    Events are drained with :meth:`poll`; replies go out with :meth:`send`.
    ``conn_id`` is the reactor's identity for an accepted connection and is
    the reply address for its frames.
    """

    def __init__(self, host: str, port: int) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native reactor unavailable (librapid_io.so)")
        self._lib = lib
        handle = lib.rapid_io_server_create(host.encode(), port)
        if handle < 0:
            raise OSError(-handle, os.strerror(-handle))
        self._handle = handle
        self.port = lib.rapid_io_server_port(handle)
        self._buf = np.empty(1 << 20, dtype=np.uint8)  # grows on demand

    def poll(self, timeout_ms: int = 500):
        """Next event as ``(type, conn_id, payload-or-None)``; type is one of
        the EV_* constants (EV_NONE on timeout, EV_SHUTDOWN after shutdown)."""
        conn_id = ctypes.c_int64()
        length = ctypes.c_int64()
        ev = self._lib.rapid_io_server_poll(
            self._handle, ctypes.byref(conn_id), self._buf,
            self._buf.shape[0], ctypes.byref(length), timeout_ms,
        )
        if ev == EV_FRAME:
            if length.value > self._buf.shape[0]:
                # frame larger than the buffer: the event stayed queued
                self._buf = np.empty(int(length.value), dtype=np.uint8)
                return self.poll(timeout_ms)
            payload = bytes(self._buf[: length.value])
            return EV_FRAME, conn_id.value, payload
        return ev, conn_id.value, None

    def send(self, conn_id: int, frame: bytes) -> bool:
        arr = np.frombuffer(frame, dtype=np.uint8)
        return (
            self._lib.rapid_io_server_send(
                self._handle, conn_id, arr, arr.shape[0]
            )
            == 0
        )

    def shutdown(self) -> None:
        self._lib.rapid_io_server_shutdown(self._handle)
