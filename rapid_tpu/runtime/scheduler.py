"""Pluggable clocks: a deterministic virtual-time scheduler and a real one.

The reference serializes all protocol logic through a single-threaded executor
per node and drives timers off one scheduled executor (SharedResources.java:48-67,
MembershipService.java:145-148,686-696). rapid-tpu abstracts that into a
Scheduler seam with two implementations:

- ``VirtualScheduler``: a discrete-event loop. All nodes of an in-process
  cluster share one instance; tasks run in deterministic (time, seq) order and
  "sleeping" is free. The reference's test battery needs minutes of wall clock
  for timers to tick (ClusterTest waits real seconds); under virtual time the
  same scenarios run in milliseconds and are bit-reproducible given a seed.
- ``RealScheduler``: one worker thread + heap with wall-clock deadlines, for
  actual deployments (the standalone agent / TCP transport).

Periodic jobs and cancellation mirror scheduleAtFixedRate/Future.cancel.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, List, Optional, Tuple

from .lockdep import make_condition


class ScheduledTask:
    """Cancellable handle, akin to java.util.concurrent.ScheduledFuture."""

    __slots__ = ("fn", "cancelled", "period_ms")

    def __init__(self, fn: Callable[[], None], period_ms: Optional[int] = None) -> None:
        self.fn = fn
        self.cancelled = False
        self.period_ms = period_ms

    def cancel(self) -> None:
        self.cancelled = True


class Scheduler:
    """Interface: current time + deferred/periodic execution."""

    def now_ms(self) -> int:
        raise NotImplementedError

    def schedule(self, delay_ms: int, fn: Callable[[], None]) -> ScheduledTask:
        raise NotImplementedError

    def schedule_at_fixed_rate(
        self, initial_delay_ms: int, period_ms: int, fn: Callable[[], None]
    ) -> ScheduledTask:
        raise NotImplementedError

    def execute(self, fn: Callable[[], None]) -> None:
        self.schedule(0, fn)

    def shutdown(self) -> None:
        pass


class VirtualScheduler(Scheduler):
    """Deterministic discrete-event scheduler; single-threaded."""

    def __init__(self) -> None:
        self._now = 0
        self._seq = itertools.count()
        self._heap: List[Tuple[int, int, ScheduledTask]] = []
        self._running = False

    def now_ms(self) -> int:
        return self._now

    def _push(self, when_ms: int, task: ScheduledTask) -> None:
        heapq.heappush(self._heap, (when_ms, next(self._seq), task))

    def schedule(self, delay_ms: int, fn: Callable[[], None]) -> ScheduledTask:
        task = ScheduledTask(fn)
        self._push(self._now + max(0, int(delay_ms)), task)
        return task

    def schedule_at_fixed_rate(
        self, initial_delay_ms: int, period_ms: int, fn: Callable[[], None]
    ) -> ScheduledTask:
        task = ScheduledTask(fn, period_ms=max(1, int(period_ms)))
        self._push(self._now + max(0, int(initial_delay_ms)), task)
        return task

    # -- driving the clock (test harness surface) ---------------------------

    def run_for(self, duration_ms: int) -> None:
        """Advance virtual time by ``duration_ms``, running every due task."""
        self.run_until_time(self._now + duration_ms)

    def run_until_time(self, deadline_ms: int) -> None:
        assert not self._running, "re-entrant scheduler drive"
        self._running = True
        try:
            while self._heap and self._heap[0][0] <= deadline_ms:
                when, _, task = heapq.heappop(self._heap)
                if task.cancelled:
                    continue
                self._now = max(self._now, when)
                if task.period_ms is not None:
                    self._push(self._now + task.period_ms, task)
                task.fn()
            self._now = max(self._now, deadline_ms)
        finally:
            self._running = False

    def run_until(
        self,
        predicate: Callable[[], bool],
        timeout_ms: int = 600_000,
        poll_ms: int = 10,
    ) -> bool:
        """Advance time until ``predicate()`` or virtual timeout. Returns success."""
        deadline = self._now + timeout_ms
        while self._now < deadline:
            if predicate():
                return True
            step_to = min(self._now + poll_ms, deadline)
            self.run_until_time(step_to)
        return predicate()


class RealScheduler(Scheduler):
    """Wall-clock scheduler: one timer thread draining a heap."""

    def __init__(self, name: str = "rapid-scheduler") -> None:
        self._heap: List[Tuple[float, int, ScheduledTask]] = []
        self._seq = itertools.count()
        self._cond = make_condition("RealScheduler._cond")
        self._shutdown = False
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def now_ms(self) -> int:
        return int(time.monotonic() * 1000)

    def schedule(self, delay_ms: int, fn: Callable[[], None]) -> ScheduledTask:
        task = ScheduledTask(fn)
        with self._cond:
            heapq.heappush(
                self._heap, (time.monotonic() + delay_ms / 1000.0, next(self._seq), task)
            )
            self._cond.notify()
        return task

    def schedule_at_fixed_rate(
        self, initial_delay_ms: int, period_ms: int, fn: Callable[[], None]
    ) -> ScheduledTask:
        task = ScheduledTask(fn, period_ms=max(1, int(period_ms)))
        with self._cond:
            heapq.heappush(
                self._heap,
                (time.monotonic() + initial_delay_ms / 1000.0, next(self._seq), task),
            )
            self._cond.notify()
        return task

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._shutdown and (
                    not self._heap or self._heap[0][0] > time.monotonic()
                ):
                    timeout = (
                        self._heap[0][0] - time.monotonic() if self._heap else None
                    )
                    self._cond.wait(timeout=timeout)
                if self._shutdown:
                    return
                _, _, task = heapq.heappop(self._heap)
                if task.cancelled:
                    continue
                if task.period_ms is not None:
                    heapq.heappush(
                        self._heap,
                        (time.monotonic() + task.period_ms / 1000.0, next(self._seq), task),
                    )
            try:
                task.fn()
            except Exception:  # noqa: BLE001 -- scheduler must survive task errors
                import logging

                logging.getLogger(__name__).exception("scheduled task failed")

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify()
        self._thread.join(timeout=5)
