"""A minimal promise usable under both real-threaded and virtual-time runtimes.

The reference uses Guava ListenableFuture/SettableFuture throughout
(e.g. MembershipService.java:171-193). This Promise provides the same surface:
set_result/set_exception once, callbacks fired on completion, and a blocking
``result(timeout)`` for real-time mode. Under the virtual-time scheduler tests
never block -- they drive the clock until ``done()``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, List, Optional, TypeVar

from .lockdep import make_lock

T = TypeVar("T")


class PromiseError(RuntimeError):
    pass


class Promise(Generic[T]):
    __slots__ = ("_event", "_result", "_exception", "_done", "_callbacks", "_lock")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._lock = make_lock("Promise._lock")
        self._result: Optional[T] = None
        self._exception: Optional[BaseException] = None
        self._done = False
        self._callbacks: List[Callable[["Promise[T]"], None]] = []

    def done(self) -> bool:
        return self._done

    def set_result(self, value: T) -> None:
        self._complete(result=value)

    def set_exception(self, exc: BaseException) -> None:
        self._complete(exception=exc)

    def try_set_result(self, value: T) -> bool:
        return self._complete(result=value, strict=False)

    def try_set_exception(self, exc: BaseException) -> bool:
        """Non-strict failure: False if already completed (for deadline
        timers racing a response that arrives at the same instant)."""
        return self._complete(exception=exc, strict=False)

    def _complete(self, result: Any = None, exception: Optional[BaseException] = None,
                  strict: bool = True) -> bool:
        with self._lock:
            if self._done:
                if strict:
                    raise PromiseError("promise already completed")
                return False
            self._result = result
            self._exception = exception
            self._done = True
            callbacks = self._callbacks
            self._callbacks = []
        self._event.set()
        for cb in callbacks:
            cb(self)
        return True

    def add_callback(self, cb: Callable[["Promise[T]"], None]) -> None:
        """Invoke ``cb(self)`` when complete (immediately if already complete)."""
        run_now = False
        with self._lock:
            if self._done:
                run_now = True
            else:
                self._callbacks.append(cb)
        if run_now:
            cb(self)

    def exception(self) -> Optional[BaseException]:
        return self._exception

    def result(self, timeout: Optional[float] = None) -> T:
        """Block for the result (real-time mode only)."""
        if not self._event.wait(timeout):
            raise TimeoutError("promise not completed within timeout")
        if self._exception is not None:
            raise self._exception
        return self._result  # type: ignore[return-value]

    def peek(self) -> T:
        """Non-blocking result access; raises if pending or failed."""
        if not self._done:
            raise PromiseError("promise not completed")
        if self._exception is not None:
            raise self._exception
        return self._result  # type: ignore[return-value]

    @staticmethod
    def completed(value: T) -> "Promise[T]":
        p: Promise[T] = Promise()
        p.set_result(value)
        return p

    @staticmethod
    def failed(exc: BaseException) -> "Promise[T]":
        p: Promise[T] = Promise()
        p.set_exception(exc)
        return p


def successful_as_list(promises: List[Promise[T]]) -> Promise[List[Optional[T]]]:
    """Complete with the list of results, None for failures
    (Futures.successfulAsList, Cluster.java:436)."""
    out: Promise[List[Optional[T]]] = Promise()
    if not promises:
        out.set_result([])
        return out
    remaining = [len(promises)]
    results: List[Optional[T]] = [None] * len(promises)
    lock = make_lock("futures.successful_as_list.lock")

    def make_cb(i: int) -> Callable[[Promise[T]], None]:
        def cb(p: Promise[T]) -> None:
            results[i] = None if p.exception() is not None else p._result
            with lock:
                remaining[0] -= 1
                fire = remaining[0] == 0
            if fire:
                out.set_result(results)

        return cb

    for i, p in enumerate(promises):
        p.add_callback(make_cb(i))
    return out
