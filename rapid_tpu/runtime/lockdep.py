"""Runtime lock-order checking (lockdep) for the protocol plane.

Linux lockdep's core idea, ported to the repo's threading surface: locks are
grouped into *classes* by creation site (``"Metrics._lock"``,
``"codec._enc_memo_lock"``, ...), every acquisition records *held-class ->
acquired-class* edges into one process-global order graph, and the first
acquisition that would close a cycle in that graph fails fast with the exact
two chains -- at the moment the inversion is *possible*, not the rare run
where two threads actually interleave into the deadlock.

The seam is :func:`make_lock` / :func:`make_rlock` / :func:`make_condition`:
every lock in ``rapid_tpu/`` is created through them. With ``RAPID_LOCKDEP``
unset (or ``0``) they return plain ``threading`` primitives -- zero overhead,
nothing imported beyond the stdlib. With ``RAPID_LOCKDEP=1`` they return
instrumented wrappers that

- fail fast (``LockOrderViolation``) when acquiring a lock whose class can
  already reach a currently-held class in the order graph (a cycle);
- fail fast on same-instance re-entry of a non-reentrant lock (guaranteed
  self-deadlock);
- additionally append every violation to a process-global list
  (:func:`violations`), because protocol threads run under blanket
  exception handlers that must survive anything -- the conftest fixture
  asserts the list is empty at session end so a swallowed raise still
  fails the suite.

Two instances of the same class may nest (e.g. a parent registry iterating
children that share its class): same-class edges are ignored for cycle
purposes; only same-*instance* re-entry is fatal.

Conditions are deliberately returned uninstrumented: ``Condition.wait``
releases and reacquires its lock internally, and the repo's discipline
(enforced statically by ``tools/concur.py``) is that condition locks are
leaves -- nothing else is acquired under them.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the global order graph, or a
    non-reentrant lock was re-entered by its holder."""


def enabled() -> bool:
    """Sampled at lock *creation* time: locks made while RAPID_LOCKDEP=1 are
    instrumented for their lifetime, locks made while it is unset are plain."""
    return os.environ.get("RAPID_LOCKDEP", "") == "1"


# class name -> classes ever acquired while it was held (process-global,
# across every test in a session: lock *order* is a global invariant, so
# edges observed in different runs legitimately compose into cycles)
_graph: Dict[str, Set[str]] = {}
# guards _graph; a plain lock, never instrumented (it is always a leaf)
_graph_lock = threading.Lock()
_violations: List[str] = []
_tls = threading.local()


def _stack() -> List["_InstrumentedLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def violations() -> List[str]:
    """Violations recorded so far (survives raises swallowed by blanket
    executor handlers; checked by the tier-1 conftest at session end)."""
    return list(_violations)


def consume_violations() -> List[str]:
    """Return and clear recorded violations. For tests that *intentionally*
    provoke one: consume it so the session-end gate stays green."""
    out = list(_violations)
    del _violations[:]
    return out


def reset() -> None:
    """Clear the order graph and violation log (test isolation helper)."""
    with _graph_lock:
        _graph.clear()
    del _violations[:]


def _reaches(src: str, dst: str) -> bool:
    """True if dst is reachable from src in the order graph. Caller holds
    _graph_lock."""
    seen: Set[str] = set()
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        frontier.extend(_graph.get(node, ()))
    return False


def _fail(msg: str) -> None:
    _violations.append(msg)
    raise LockOrderViolation(msg)


class _InstrumentedLock:
    """threading.Lock/RLock lookalike recording acquisition order."""

    def __init__(self, name: str, reentrant: bool) -> None:
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    # -- ordering ----------------------------------------------------------

    def _note_acquire(self) -> None:
        stack = _stack()
        for held in stack:
            if held is self:
                # re-entry of an RLock adds no new ordering information;
                # non-reentrant re-entry is caught in acquire() BEFORE the
                # inner lock blocks
                stack.append(self)
                return
        with _graph_lock:
            for held in stack:
                if held.name == self.name:
                    continue  # same-class nesting across instances: allowed
                if _reaches(self.name, held.name):
                    _fail(
                        f"lockdep: acquiring {self.name!r} while holding "
                        f"{held.name!r} closes a cycle: the order graph "
                        f"already shows {self.name!r} ... -> {held.name!r}"
                    )
                _graph.setdefault(held.name, set()).add(self.name)
        stack.append(self)

    def _note_release(self) -> None:
        stack = _stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return

    # -- threading.Lock surface --------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not self._reentrant and any(h is self for h in _stack()):
            # must fail BEFORE self._inner.acquire: the inner Lock would
            # deadlock this thread instead of reporting
            _fail(
                f"lockdep: same-instance re-entry of non-reentrant lock "
                f"{self.name!r} (guaranteed self-deadlock)"
            )
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquire()
        return got

    def release(self) -> None:
        self._note_release()
        self._inner.release()

    def locked(self) -> bool:
        if self._reentrant:
            # RLock has no locked(); approximate via non-blocking acquire
            if self._inner.acquire(blocking=False):
                self._inner.release()
                return False
            return True
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockdep {'RLock' if self._reentrant else 'Lock'} {self.name!r}>"


def make_lock(name: str) -> "threading.Lock | _InstrumentedLock":
    """A non-reentrant lock, instrumented when RAPID_LOCKDEP=1."""
    if enabled():
        return _InstrumentedLock(name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str) -> "threading.RLock | _InstrumentedLock":
    """A reentrant lock, instrumented when RAPID_LOCKDEP=1."""
    if enabled():
        return _InstrumentedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str, lock: Optional[threading.Lock] = None):
    """A condition variable. Never instrumented (wait() releases/reacquires
    internally); named for symmetry and future use. Condition locks must be
    leaves -- tools/concur.py enforces that statically."""
    del name
    return threading.Condition(lock)
