"""Per-node shared runtime resources.

Reference: SharedResources.java:48-67 -- per instance: a single-threaded
protocol executor that serializes ALL protocol logic, a scheduled background
executor for timers, and transport event loops. rapid-tpu collapses these onto
the Scheduler seam:

- virtual mode: one VirtualScheduler shared by every in-process node; the
  protocol executor is `schedule(0, fn)` -- globally serialized and
  deterministic, which is strictly stronger than the reference's per-node
  serialization.
- real mode: a RealScheduler for timers plus a dedicated single worker thread
  per node for protocol serialization.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from .scheduler import RealScheduler, Scheduler, VirtualScheduler


class ProtocolExecutor:
    """Serialized executor for a node's protocol logic."""

    def execute(self, fn: Callable[[], None]) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class _SchedulerExecutor(ProtocolExecutor):
    def __init__(self, scheduler: Scheduler) -> None:
        self._scheduler = scheduler

    def execute(self, fn: Callable[[], None]) -> None:
        self._scheduler.schedule(0, fn)


class _ThreadExecutor(ProtocolExecutor):
    def __init__(self, name: str) -> None:
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            fn = self._queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 -- executor must survive task errors
                import logging

                logging.getLogger(__name__).exception("protocol task failed")

    def execute(self, fn: Callable[[], None]) -> None:
        self._queue.put(fn)

    def shutdown(self) -> None:
        self._queue.put(None)
        self._thread.join(timeout=5)


def _is_virtual(scheduler: Scheduler) -> bool:
    """True when the scheduler is (or wraps, via an ``inner`` chain) a
    VirtualScheduler -- e.g. a nemesis SkewedScheduler around the shared
    virtual clock. Such a node must serialize protocol tasks through the
    scheduler, not a real thread: a thread races the virtual clock, which
    jumps past RPC deadlines before the thread completes the response."""
    seen = 0
    while scheduler is not None and seen < 8:
        if isinstance(scheduler, VirtualScheduler):
            return True
        scheduler = getattr(scheduler, "inner", None)
        seen += 1
    return False


class SharedResources:
    def __init__(self, scheduler: Optional[Scheduler] = None, name: str = "node") -> None:
        self.scheduler: Scheduler = scheduler if scheduler is not None else RealScheduler()
        self._owns_scheduler = scheduler is None
        if _is_virtual(self.scheduler):
            self.protocol_executor: ProtocolExecutor = _SchedulerExecutor(self.scheduler)
        else:
            self.protocol_executor = _ThreadExecutor(f"{name}-protocol")

    def shutdown(self) -> None:
        self.protocol_executor.shutdown()
        if self._owns_scheduler:
            self.scheduler.shutdown()
