"""Cluster event subscriptions.

Reference: ClusterEvents.java:19-24, NodeStatusChange.java:24-52. Callbacks
receive (configuration_id, [NodeStatusChange]).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Tuple

from .types import EdgeStatus, Endpoint


class ClusterEvents(enum.Enum):
    VIEW_CHANGE_PROPOSAL = "VIEW_CHANGE_PROPOSAL"
    VIEW_CHANGE = "VIEW_CHANGE"
    VIEW_CHANGE_ONE_STEP_FAILED = "VIEW_CHANGE_ONE_STEP_FAILED"
    KICKED = "KICKED"


@dataclass(frozen=True)
class NodeStatusChange:
    endpoint: Endpoint
    status: EdgeStatus
    metadata: Tuple[Tuple[str, bytes], ...] = ()

    def __str__(self) -> str:
        return f"{self.endpoint}:{self.status.name}:{dict(self.metadata)}"
