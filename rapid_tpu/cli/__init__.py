"""Command-line entry points: the standalone agent and the swarm gateway
(the runnable analogues of the reference's examples/ shaded jars,
StandaloneAgent.java:94-116, examples/pom.xml:60-89)."""
