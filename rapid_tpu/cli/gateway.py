"""Socket-hosted TPU swarm gateway.

Hosts N virtual nodes (their rings, failure detectors, cut detection, and
fast-round tallies living as device arrays in the TPU simulator) behind one
real TCP socket. External agent processes join through any virtual seed
endpoint using ``standalone_agent.py --gateway-address`` (the reference's
plugin-seam design hosted on a real wire: IMessagingServer.java:24-41).

    python examples/swarm_gateway.py --listen-address 127.0.0.1:4000 \
        --n-virtual 1000

Prints the seed endpoint on startup and one status line per second:
``swarm size=N config=C`` plus a line per decided view change.
"""

import argparse
import logging
import time


def main() -> None:
    parser = argparse.ArgumentParser(description="rapid-tpu swarm gateway")
    parser.add_argument("--listen-address", required=True, help="host:port to bind")
    parser.add_argument("--n-virtual", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0, help="simulator RNG seed")
    parser.add_argument("--pump-interval-ms", type=int, default=100)
    parser.add_argument("--platform", help="force a jax platform (e.g. cpu)")
    parser.add_argument(
        "--restore-from", help="resume from a swarm snapshot (same config id)"
    )
    parser.add_argument(
        "--snapshot", help="checkpoint the swarm to this path on Ctrl-C"
    )
    parser.add_argument(
        "--native-server", action="store_true",
        help="accept routed frames on the C++ epoll reactor "
        "(native/rapid_io.cpp) instead of the Python accept loop",
    )
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    log = logging.getLogger("gateway")

    from rapid_tpu import Endpoint, Settings
    from rapid_tpu.messaging.gateway import SwarmGateway

    listen = Endpoint.from_string(args.listen_address)
    if args.restore_from:
        # identity/config come from the snapshot; n_virtual/seed must not be
        # passed alongside (SwarmGateway rejects the combination)
        gateway = SwarmGateway(
            listen,
            settings=Settings(),
            pump_interval_ms=args.pump_interval_ms,
            restore_from=args.restore_from,
            native_server=args.native_server,
        )
    else:
        gateway = SwarmGateway(
            listen,
            n_virtual=args.n_virtual,
            seed=args.seed,
            settings=Settings(),
            pump_interval_ms=args.pump_interval_ms,
            native_server=args.native_server,
        )
    gateway.start()
    log.info("warming the swarm engine (first jit compile)...")
    gateway.warm()
    seed_ep = gateway.seed_endpoint()
    log.info(
        "gateway up at %s hosting %d members (%s); seed endpoint %s",
        listen,
        gateway.membership_size(),
        f"restored from {args.restore_from}" if args.restore_from else "fresh",
        seed_ep,
    )
    print(f"SEED {seed_ep}", flush=True)  # noqa: print-in-lib

    seen_decisions = 0
    try:
        while True:
            time.sleep(1)
            decisions = gateway.decisions()
            for rec in decisions[seen_decisions:]:
                log.info(
                    "view change: cut=%d added=%d removed=%d",
                    len(rec.cut),
                    len(rec.added),
                    len(rec.removed),
                )
            seen_decisions = len(decisions)
            log.info(
                "swarm size=%d config=%d",
                gateway.membership_size(),
                gateway.configuration_id(),
            )
    except KeyboardInterrupt:
        if args.snapshot:
            gateway.save(args.snapshot)
            log.info("snapshot written to %s", args.snapshot)
        gateway.shutdown()


if __name__ == "__main__":
    main()
