"""Standalone cluster agent over the real TCP transport.

Equivalent of the reference's CLI agent (StandaloneAgent.java:94-116): start a
seed with --listen-address only, or join via --seed-address; subscribes to the
cluster events and prints the membership once per second.

    python examples/standalone_agent.py --listen-address 127.0.0.1:1234
    python examples/standalone_agent.py --listen-address 127.0.0.1:1235 \
        --seed-address 127.0.0.1:1234
"""

import argparse
import json
import logging
import os
import sys
import tempfile
import time

from rapid_tpu import ClusterBuilder, ClusterEvents, Endpoint, Settings
from rapid_tpu.messaging.tcp import TcpClientServer


def _write_prometheus_atomic(path: str) -> None:
    """Rewrite the exposition file atomically: a scraper that reads during a
    tick sees either the previous complete file or the new complete file,
    never a truncated one."""
    from rapid_tpu.observability import prometheus_text

    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=".prom-", dir=directory)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(prometheus_text())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _print_status(target_raw: str, timeout_s: float) -> int:
    """--status mode: one-shot ClusterStatusRequest against a live agent."""
    from rapid_tpu.types import ClusterStatusRequest, ClusterStatusResponse

    target = Endpoint.from_string(target_raw)
    client = TcpClientServer(Endpoint(b"127.0.0.1", 0), Settings())
    try:
        reply = client.send_message(
            target, ClusterStatusRequest(sender=client.address)
        ).result(timeout_s)
    finally:
        client.shutdown()
    if not isinstance(reply, ClusterStatusResponse):
        sys.stdout.write(
            f"{target_raw}: unexpected reply {type(reply).__name__}\n"
        )
        return 1
    lines = [
        f"{reply.sender}  config={reply.configuration_id}"
        f"  members={reply.membership_size}",
        f"  cut-detector: tracked={reply.reports_tracked}"
        f" pre-proposal={reply.pre_proposal_size}"
        f" proposal={reply.proposal_size}"
        f" in-progress={reply.updates_in_progress}",
        f"  consensus: decided={reply.consensus_decided}"
        f" votes={reply.consensus_votes}",
    ]
    for name, value in zip(reply.metric_names, reply.metric_values):
        lines.append(f"  metric {name} = {value}")
    for raw in reply.journal:
        try:
            entry = json.loads(raw)
            lines.append(
                f"  journal [{entry.get('seq')}] {entry.get('kind')}"
                f" @{entry.get('virtual_ms')}ms {entry.get('detail', {})}"
            )
        except (ValueError, TypeError):
            lines.append(f"  journal {raw}")
    sys.stdout.write("\n".join(lines) + "\n")
    return 0


def main() -> None:
    parser = argparse.ArgumentParser(description="rapid-tpu standalone agent")
    parser.add_argument(
        "--status", metavar="ADDR",
        help="client-only mode: query ADDR's cluster-status RPC (config id, "
        "view size, cut-detector occupancy, consensus state, metrics digest, "
        "journal tail), print it, and exit",
    )
    parser.add_argument("--listen-address", help="host:port to listen on")
    parser.add_argument("--seed-address", help="host:port of a seed to join")
    parser.add_argument(
        "--gateway-address",
        help="host:port of a SwarmGateway; destinations whose hostname is not "
        "in the direct set (the swarm's virtual endpoints) ride this connection",
    )
    parser.add_argument(
        "--direct-host",
        action="append",
        default=[],
        help="additional hostname reached directly rather than via the "
        "gateway (repeatable; loopback and this agent's own hostname are "
        "always direct). Required for multi-host deployments so peer agents "
        "on other machines are not misrouted to the gateway",
    )
    parser.add_argument("--fd-interval-ms", type=int, default=1000)
    parser.add_argument(
        "--fd-policy", choices=("cumulative", "windowed"), default="cumulative",
        help="cumulative = reference parity (never-reset counter); "
        "windowed = the paper's '40%% of last N probes' policy",
    )
    parser.add_argument("--fd-window", type=int, default=10)
    parser.add_argument("--fd-window-threshold", type=float, default=0.4)
    parser.add_argument(
        "--transport", choices=("tcp", "native-tcp", "grpc"), default="tcp",
        help="tcp = framed-TCP transport; native-tcp = same wire format with "
        "the C++ epoll server half (native/rapid_io.cpp); grpc = "
        "wire-compatible with JVM Rapid",
    )
    parser.add_argument(
        "--broadcaster", choices=("unicast", "gossip"), default="unicast",
        help="unicast = reference-parity unicast-to-all; gossip = epidemic "
        "relay (needs a native-codec transport, not grpc)",
    )
    parser.add_argument("--gossip-fanout", type=int, default=4)
    parser.add_argument(
        "--join-timeout", type=float, default=60.0,
        help="seconds to wait for the two-phase join (bootstrapping into a "
        "very large view takes longer: the full configuration must be "
        "shipped and the member's rings built)",
    )
    parser.add_argument(
        "--metrics-out",
        help="path rewritten once per status tick with the Prometheus text "
        "exposition of this agent's metrics (point node_exporter's textfile "
        "collector or a file-based scraper at it)",
    )
    parser.add_argument(
        "--trace-out",
        help="path written on shutdown with a Chrome trace_event JSON of the "
        "agent's spans (load in Perfetto / chrome://tracing)",
    )
    parser.add_argument(
        "--journal-out",
        help="path written on shutdown with the flight-recorder journal "
        "(JSON lines, newest last): the last N membership-relevant events "
        "this node saw",
    )
    parser.add_argument(
        "--forensics", action="store_true",
        help="enable the forensics plane: HLC stamps on every message and "
        "journal entry, burn-alert evidence capture, and crash/exit "
        "journal hooks (with --journal-out, the dump also happens via "
        "atexit + a faulthandler traceback file for hard crashes)",
    )
    parser.add_argument(
        "--bundle-out",
        help="path written on shutdown with a cluster-wide incident "
        "evidence bundle (implies --forensics): this agent's evidence plus "
        "a status sweep of every reachable member; feed the file to "
        "tools/forensics.py report",
    )
    parser.add_argument(
        "--serving", action="store_true",
        help="demo mode: enable the serving plane (replicated Get/Put KV "
        "over placement + handoff) on this agent; every status tick writes "
        "a per-agent demo key through the quorum path, reads it back, and "
        "logs the serving counters",
    )
    parser.add_argument(
        "--serving-partitions", type=int, default=64,
        help="placement partition count for --serving mode",
    )
    parser.add_argument("--status-timeout", type=float, default=5.0,
                        help="seconds to wait in --status mode")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args()

    if args.status:
        raise SystemExit(_print_status(args.status, args.status_timeout))
    if not args.listen_address:
        parser.error("--listen-address is required (except in --status mode)")

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    log = logging.getLogger("agent")

    listen = Endpoint.from_string(args.listen_address)
    settings = Settings(
        failure_detector_interval_ms=args.fd_interval_ms,
        fd_policy=args.fd_policy,
        fd_window=args.fd_window,
        fd_window_threshold=args.fd_window_threshold,
    )
    if args.forensics or args.bundle_out:
        import dataclasses

        from rapid_tpu.settings import ForensicsSettings

        settings = dataclasses.replace(
            settings, forensics=ForensicsSettings(enabled=True)
        )
    if args.transport == "grpc":
        if args.gateway_address:
            parser.error(
                "--gateway-address requires the tcp transport: the gateway "
                "delivers swarm traffic over framed TCP to the agent's server"
            )
        from rapid_tpu.messaging.grpc_transport import GrpcClient, GrpcServer

        client, server = GrpcClient(listen, settings), GrpcServer(listen)
    elif args.transport == "native-tcp":
        from rapid_tpu.messaging.native_tcp import NativeTcpClientServer

        client = server = NativeTcpClientServer(listen, settings)
    else:
        client = server = TcpClientServer(listen, settings)
    if args.gateway_address:
        if args.broadcaster == "gossip":
            parser.error(
                "--broadcaster gossip cannot ride a gateway (the swarm has "
                "no gossip relay); gateway mode uses the swarm broadcaster"
            )
        from rapid_tpu.messaging.gateway import (
            DEFAULT_DIRECT_HOSTS,
            GatewayRoutedClient,
        )

        direct = set(DEFAULT_DIRECT_HOSTS)
        direct.update(h.encode() for h in args.direct_host)
        client = GatewayRoutedClient(
            listen, Endpoint.from_string(args.gateway_address), client, settings,
            direct_hosts=direct,
        )

    def on_event(name):
        def callback(configuration_id, changes):
            log.info("%s config=%d changes=%s", name, configuration_id,
                     [str(c) for c in changes])

        return callback

    builder = (
        ClusterBuilder(listen)
        .use_settings(settings)
        .set_messaging_client_and_server(client, server)
        .add_subscription(ClusterEvents.VIEW_CHANGE_PROPOSAL, on_event("VIEW_CHANGE_PROPOSAL"))
        .add_subscription(ClusterEvents.VIEW_CHANGE, on_event("VIEW_CHANGE"))
        .add_subscription(ClusterEvents.KICKED, on_event("KICKED"))
    )
    if settings.forensics.enabled and args.journal_out:
        # crash/exit evidence: atexit journal dump + faulthandler traceback
        # file beside it, in addition to the explicit dump on shutdown below
        builder.use_forensics_dump(args.journal_out)
    if args.serving:
        from rapid_tpu.handoff.store import InMemoryPartitionStore

        builder.use_placement(partitions=args.serving_partitions)
        builder.use_serving(InMemoryPartitionStore())
    if args.broadcaster == "gossip":
        if args.gossip_fanout < 1:
            parser.error("--gossip-fanout must be >= 1")
        from rapid_tpu.messaging.gossip import GossipBroadcaster

        builder.set_broadcaster_factory(
            lambda c, rng: GossipBroadcaster(
                c, listen, fanout=args.gossip_fanout, rng=rng
            )
        )
    elif args.gateway_address:
        # swarm-bound broadcast fan-out collapses to one wildcard frame;
        # unicast-to-all through one socket does not scale to large swarms
        from rapid_tpu.messaging.gateway import GatewaySwarmBroadcaster

        builder.set_broadcaster_factory(
            lambda c, rng, routed=client: GatewaySwarmBroadcaster(routed)
        )
    if args.seed_address:
        cluster = builder.join(
            Endpoint.from_string(args.seed_address), timeout=args.join_timeout
        )
    else:
        cluster = builder.start()
    log.info("agent started at %s", listen)

    demo_key = b"agent-demo:" + args.listen_address.encode()
    try:
        while True:
            time.sleep(1)
            members = cluster.get_memberlist()
            log.info(
                "membership size=%d config=%d members=%s",
                len(members),
                cluster.get_current_configuration_id(),
                [str(m) for m in members] if len(members) <= 32 else "...",
            )
            if args.serving:
                # the demo loop: one quorum write + one routed read per
                # tick, so a multi-agent deployment visibly replicates
                try:
                    value = b"tick-%d" % int(time.time())
                    cluster.serving_put(demo_key, value).result(5.0)
                    back = cluster.serving_get(demo_key).result(5.0)
                    gets, puts, put_acks = cluster.get_serving_status()
                    log.info(
                        "serving key=%s value=%s gets=%d puts=%d acks=%d",
                        demo_key.decode(), back.value.decode(),
                        gets, puts, put_acks,
                    )
                except Exception as exc:  # noqa: BLE001 -- demo, keep ticking
                    log.warning("serving demo op failed: %s", exc)
            if args.metrics_out:
                _write_prometheus_atomic(args.metrics_out)
    except KeyboardInterrupt:
        if args.bundle_out:
            # capture while the cluster is still a member: the sweep needs
            # live peers, so it runs before the graceful leave
            try:
                cluster.capture_bundle(args.bundle_out)
                log.info("wrote evidence bundle to %s", args.bundle_out)
            except Exception as exc:  # noqa: BLE001 -- still leave cleanly
                log.warning("bundle capture failed: %s", exc)
        cluster.leave_gracefully()
    finally:
        if args.trace_out:
            from rapid_tpu.observability import write_chrome_trace

            write_chrome_trace(args.trace_out)
            log.info("wrote Chrome trace to %s", args.trace_out)
        if args.journal_out:
            cluster.flight_recorder.dump(args.journal_out)
            log.info("wrote flight-recorder journal to %s", args.journal_out)


if __name__ == "__main__":
    main()
