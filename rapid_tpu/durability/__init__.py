"""Durability plane: per-node write-ahead log + snapshot crash recovery.

Mounted under the handoff :class:`~rapid_tpu.handoff.store.PartitionStore`
seam, so the serving/handoff planes gain durability without learning any
new interface: :class:`DurablePartitionStore` is a drop-in for the
in-memory reference store whose mutations survive the process.
"""

from .store import DurablePartitionStore
from .wal import (
    FSYNC_ALWAYS,
    FSYNC_BATCH,
    FSYNC_NEVER,
    WriteAheadLog,
    tear_wal_tail,
)

__all__ = [
    "DurablePartitionStore",
    "WriteAheadLog",
    "tear_wal_tail",
    "FSYNC_NEVER",
    "FSYNC_BATCH",
    "FSYNC_ALWAYS",
]
