"""Durable :class:`PartitionStore`: WAL-over-snapshot crash recovery.

:class:`DurablePartitionStore` mirrors the in-memory reference store's
surface exactly (the handoff engine, placement planner, and statusz all
duck-type against it), but every mutation is appended to a per-node
write-ahead log before it lands in memory, and checkpoints serialize the
partition blobs -- the same xxh64-fingerprinted bytes handoff verifies
over the wire -- into an atomically renamed snapshot file. Recovery loads
the newest complete snapshot and replays the log from its marker, so a
restarted node resumes with exactly the state it acknowledged, and the
handoff fingerprint cross-check against its replica row comes for free.

The store also persists the node's membership identity (NodeId + last
installed configuration id) as META records: Rapid's strongly consistent
view makes identity-preserving rejoin safe, but only if the identity
actually survives the process.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Callable, Dict, Optional, Tuple

from ..handoff.plan import content_fingerprint
from ..handoff.store import PartitionStore
from ..runtime.lockdep import make_lock
from ..types import NodeId
from . import wal as _wal

_NODE_ID = struct.Struct("<qq")
_CONFIG_ID = struct.Struct("<q")

_SNAP_PREFIX = "snap-"
_SNAP_SUFFIX = ".bin"

META_NODE_ID = "node_id"
META_CONFIG_ID = "config_id"
META_INCARNATION = "incarnation"


class DurablePartitionStore(PartitionStore):
    """Write-ahead-logged partition store with snapshot checkpoints.

    Construction *is* recovery: the newest complete snapshot is loaded,
    the log's torn tail (if any) is truncated at the first bad record, and
    surviving records after the snapshot marker are replayed into memory.
    """

    def __init__(self, directory: str, *, segment_bytes: int = 1 << 20,
                 fsync_policy: int = _wal.FSYNC_BATCH,
                 snapshot_every_records: int = 4096,
                 fsync_hook: Optional[Callable[[], None]] = None) -> None:
        self._lock = make_lock("DurablePartitionStore._lock")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.snapshot_every_records = int(snapshot_every_records)
        self._data: Dict[int, bytes] = {}
        self._fingerprints: Dict[int, int] = {}
        self._meta: Dict[str, bytes] = {}
        self._crashed = False
        self._metrics = None
        self._recorder = None
        self._fsyncs_reported = 0
        self._records_since_snapshot = 0
        self._snapshot_version = 0
        self._replayed_records = 0
        self._recovery_ms = 0.0
        started = time.monotonic()
        snap_version, snap_data, snap_meta = self._load_newest_snapshot()
        self._wal = _wal.WriteAheadLog(
            directory, segment_bytes=segment_bytes, fsync_policy=fsync_policy,
            fsync_hook=fsync_hook,
        )
        self._recover(snap_version, snap_data, snap_meta)
        self._recovery_ms = (time.monotonic() - started) * 1000.0

    # -- recovery -------------------------------------------------------------

    def _snap_path(self, version: int) -> str:
        return os.path.join(
            self.directory, f"{_SNAP_PREFIX}{version:016d}{_SNAP_SUFFIX}"
        )

    def _snapshot_versions(self) -> Tuple[int, ...]:
        versions = []
        for name in os.listdir(self.directory):
            if name.startswith(_SNAP_PREFIX) and name.endswith(_SNAP_SUFFIX):
                try:
                    versions.append(int(name[len(_SNAP_PREFIX):-len(_SNAP_SUFFIX)]))
                except ValueError:
                    continue
        return tuple(sorted(versions))

    def _load_newest_snapshot(self):
        """Newest snapshot with a completeness witness; torn snapshot files
        read as absent, never as an empty store."""
        for version in reversed(self._snapshot_versions()):
            loaded = _wal.load_snapshot(self._snap_path(version))
            if loaded is not None:
                return version, loaded[0], loaded[1]
        return 0, {}, {}

    def _recover(self, snap_version: int, snap_data: Dict[int, bytes],
                 snap_meta: Dict[str, bytes]) -> None:
        for partition, data in snap_data.items():
            self._data[partition] = data
            self._fingerprints[partition] = content_fingerprint(partition, data)
        self._meta.update(snap_meta)
        self._snapshot_version = snap_version
        records = self._wal.recovered_records()
        # log-over-snapshot: skip records up to (and including) the marker
        # matching the loaded snapshot, replay everything after it. If the
        # marker is missing (retention raced a crash), replay the whole
        # retained log -- PUT records carry full content, so re-applying
        # pre-snapshot records is harmless, merely slower.
        start = 0
        if snap_version:
            for index, (_seq, payload) in enumerate(records):
                decoded = _wal.parse_record(payload)
                if decoded and decoded[0] == _wal.KIND_SNAPSHOT \
                        and decoded[1][0] == snap_version:
                    start = index + 1
                    break
        for _seq, payload in records[start:]:
            decoded = _wal.parse_record(payload)
            if decoded is None:
                continue  # unknown kind from a newer writer: skip, not fatal
            kind, args = decoded
            if kind == _wal.KIND_PUT:
                partition, data = args
                self._data[partition] = data
                self._fingerprints[partition] = content_fingerprint(
                    partition, data
                )
            elif kind == _wal.KIND_DELETE:
                self._data.pop(args[0], None)
                self._fingerprints.pop(args[0], None)
            elif kind == _wal.KIND_META:
                self._meta[args[0]] = args[1]
            elif kind == _wal.KIND_SNAPSHOT:
                continue  # stale marker inside the replay range
            self._replayed_records += 1
        self._records_since_snapshot = self._replayed_records

    # -- telemetry ------------------------------------------------------------

    def bind_telemetry(self, metrics, recorder=None) -> None:
        """Attach the node's metrics registry / flight recorder. Called
        after construction (the service owns both), so recovery's counters
        are emitted retroactively here."""
        self._metrics = metrics
        self._recorder = recorder
        if metrics is not None:
            if self._replayed_records:
                metrics.incr(
                    "durability.replayed_records", self._replayed_records
                )
            if self._wal.torn_truncations:
                metrics.incr(
                    "durability.torn_truncations", self._wal.torn_truncations
                )
            metrics.set_gauge(
                "durability.segments", float(len(self._wal.segment_seqs()))
            )
        if recorder is not None:
            recorder.record(
                "durability_recovered",
                snapshot_version=self._snapshot_version,
                replayed_records=self._replayed_records,
                torn_truncations=self._wal.torn_truncations,
                partitions=len(self._data),
            )

    def _note_io(self) -> None:
        """Fold the WAL's internal fsync counter into the metric stream."""
        if self._metrics is None:
            return
        delta = self._wal.fsyncs - self._fsyncs_reported
        if delta:
            self._metrics.incr("durability.fsyncs", delta)
            self._fsyncs_reported = self._wal.fsyncs

    # -- PartitionStore surface ----------------------------------------------

    def get(self, partition: int) -> Optional[bytes]:
        with self._lock:
            return self._data.get(partition)

    def put(self, partition: int, data: bytes) -> None:
        data = bytes(data)
        fp = content_fingerprint(partition, data)
        with self._lock:
            if self._crashed:
                return
            self._wal.append(_wal.put_record(partition, data))
            self._data[partition] = data
            self._fingerprints[partition] = fp
            self._bump_locked()
        if self._metrics is not None:
            self._metrics.incr("durability.appends")
            self._note_io()

    def delete(self, partition: int) -> None:
        with self._lock:
            if self._crashed:
                return
            if partition not in self._data:
                return
            self._wal.append(_wal.delete_record(partition))
            self._data.pop(partition, None)
            self._fingerprints.pop(partition, None)
            self._bump_locked()
        if self._metrics is not None:
            self._metrics.incr("durability.appends")
            self._note_io()

    def partitions(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._data))

    def fingerprint(self, partition: int) -> Optional[int]:
        with self._lock:
            return self._fingerprints.get(partition)

    def sizes(self) -> Dict[int, int]:
        """Partition id -> content length (planner input)."""
        with self._lock:
            return {p: len(d) for p, d in self._data.items()}

    def digest(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Parallel (partition ids, fingerprints) arrays, id-sorted -- the
        shape ClusterStatusResponse carries for cross-replica checks."""
        with self._lock:
            ids = tuple(sorted(self._data))
            return ids, tuple(self._fingerprints[p] for p in ids)

    # -- identity persistence -------------------------------------------------

    def set_identity(self, node_id: NodeId) -> None:
        self._set_meta(META_NODE_ID, _NODE_ID.pack(node_id.high, node_id.low))

    @property
    def node_id(self) -> Optional[NodeId]:
        raw = self._meta.get(META_NODE_ID)
        if raw is None or len(raw) != _NODE_ID.size:
            return None
        high, low = _NODE_ID.unpack(raw)
        return NodeId(high, low)

    def set_config_id(self, config_id: int) -> None:
        self._set_meta(META_CONFIG_ID, _CONFIG_ID.pack(config_id))

    @property
    def incarnation(self) -> int:
        """Boot count persisted in the WAL meta (0 before the first
        ``bump_incarnation``). The forensics HLC stamps it so a restarted
        member's fresh clock is never mistaken for a regression of its
        previous life (PR 17 incarnation-seq discipline)."""
        raw = self._meta.get(META_INCARNATION)
        if raw is None or len(raw) != _CONFIG_ID.size:
            return 0
        return int(_CONFIG_ID.unpack(raw)[0])

    def bump_incarnation(self) -> int:
        """Advance and persist the boot count; returns the new value."""
        nxt = self.incarnation + 1
        self._set_meta(META_INCARNATION, _CONFIG_ID.pack(nxt))
        return nxt

    @property
    def config_id(self) -> Optional[int]:
        raw = self._meta.get(META_CONFIG_ID)
        if raw is None or len(raw) != _CONFIG_ID.size:
            return None
        return _CONFIG_ID.unpack(raw)[0]

    def _set_meta(self, key: str, value: bytes) -> None:
        with self._lock:
            if self._crashed:
                return
            if self._meta.get(key) == value:
                return
            self._wal.append(_wal.meta_record(key, value))
            self._meta[key] = value
            # identity records must never outrun the ack that carries them:
            # the join/view path reads them back on the next boot
            self._wal.sync()
        self._note_io()

    # -- durability control plane ---------------------------------------------

    def _bump_locked(self) -> None:
        self._records_since_snapshot += 1
        if (
            self.snapshot_every_records > 0
            and self._records_since_snapshot >= self.snapshot_every_records
        ):
            self._checkpoint_locked()

    def sync(self) -> None:
        """Durability barrier: every accepted mutation survives a crash."""
        with self._lock:
            if self._crashed:
                return
            self._wal.sync()
        self._note_io()

    def checkpoint(self) -> None:
        """Snapshot + marker + retention: a graceful stop leaves a log that
        recovers with zero replayed records."""
        with self._lock:
            if self._crashed:
                return
            self._checkpoint_locked()
        self._note_io()

    def _checkpoint_locked(self) -> None:
        version = self._snapshot_version = self._next_version_locked()
        _wal.write_snapshot(
            self._snap_path(version), dict(self._data), dict(self._meta)
        )
        self._wal.mark_snapshot(version)
        for old in self._snapshot_versions():
            if old < version:
                os.remove(self._snap_path(old))
        self._records_since_snapshot = 0
        if self._metrics is not None:
            self._metrics.incr("durability.snapshots")
            self._metrics.set_gauge(
                "durability.segments", float(len(self._wal.segment_seqs()))
            )
        if self._recorder is not None:
            self._recorder.record(
                "durability_checkpoint", snapshot_version=version,
                partitions=len(self._data),
            )

    def _next_version_locked(self) -> int:
        versions = self._snapshot_versions()
        return max(versions[-1] if versions else 0, self._snapshot_version) + 1

    def close(self) -> None:
        with self._lock:
            if not self._crashed:
                self._wal.close()
                self._crashed = True

    def crash(self) -> None:
        """Simulate process death: close handles without any barrier and
        refuse all further mutation, so a harness's graceful ``shutdown``
        path cannot quietly rescue state the crash should have stranded."""
        with self._lock:
            self._wal.crash()
            self._crashed = True

    # -- introspection ---------------------------------------------------------

    def durability_stats(self) -> Dict[str, int]:
        """The status-RPC digest: segment count, last snapshot version, and
        how many log records the last recovery replayed."""
        with self._lock:
            return {
                "segments": len(self._wal.segment_seqs()),
                "snapshot_version": self._snapshot_version,
                "replayed_records": self._replayed_records,
                "appends": self._wal.appends,
                "fsyncs": self._wal.fsyncs,
                "torn_truncations": self._wal.torn_truncations,
                "recovery_ms": self._recovery_ms,
            }
