"""Append-only write-ahead log: fsync'd segments with CRC'd length framing.

Every record is ``[u32 length][u32 crc32][payload]`` (little-endian), the
same self-describing discipline as the wire schema: a reader that trusts
the frame never trusts the bytes inside it, and a payload whose first byte
names an unknown record kind is skipped rather than fatal, so old replayers
tolerate frames appended by newer writers. A torn tail -- a short header, a
short payload, or a CRC mismatch from a crash mid-append -- truncates the
log at the first bad record: everything before it was durable, everything
after it was never acknowledged.

Segments are ``wal-<seq>.log`` files rotated at a size threshold; rotation
happens immediately *before* a snapshot marker is appended, so the marker
is always the first record of its segment and retention can simply delete
every segment numbered below it.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Callable, Dict, List, Optional, Tuple

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
_PART = struct.Struct("<I")
_VERSION = struct.Struct("<q")
_KEYLEN = struct.Struct("<H")

# Record kinds (first payload byte). Unknown kinds are skipped on replay --
# the old-frame-tolerance seam mirroring the codec's "__"-key stripping.
KIND_PUT = 1  # u32 partition + content bytes (full replacement)
KIND_DELETE = 2  # u32 partition
KIND_SNAPSHOT = 3  # i64 snapshot version (marker: state below is on disk)
KIND_META = 4  # u16 key length + utf-8 key + value bytes

# fsync policies (int-coded so the settings catalog can bound them)
FSYNC_NEVER = 0  # leave durability to the OS page cache
FSYNC_BATCH = 1  # fsync on explicit sync()/checkpoint barriers
FSYNC_ALWAYS = 2  # fsync after every append

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".log"


def frame(payload: bytes) -> bytes:
    """Wrap ``payload`` in the length+CRC header."""
    return _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def iter_frames(blob: bytes):
    """Yield ``(payload, end_offset)`` for every intact frame in ``blob``.

    Stops at the first short or corrupt frame; the last yielded
    ``end_offset`` is the byte length of the trustworthy prefix.
    """
    offset = 0
    total = len(blob)
    while offset + _HEADER.size <= total:
        length, crc = _HEADER.unpack_from(blob, offset)
        start = offset + _HEADER.size
        end = start + length
        if end > total:
            return  # short payload: torn mid-append
        payload = blob[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return  # corrupt record: torn or bit-flipped
        yield payload, end
        offset = end


def put_record(partition: int, data: bytes) -> bytes:
    return bytes([KIND_PUT]) + _PART.pack(partition) + data


def delete_record(partition: int) -> bytes:
    return bytes([KIND_DELETE]) + _PART.pack(partition)


def snapshot_record(version: int) -> bytes:
    return bytes([KIND_SNAPSHOT]) + _VERSION.pack(version)


def meta_record(key: str, value: bytes) -> bytes:
    encoded = key.encode("utf-8")
    return bytes([KIND_META]) + _KEYLEN.pack(len(encoded)) + encoded + value


def parse_record(payload: bytes) -> Optional[Tuple[int, tuple]]:
    """Decode one record payload to ``(kind, args)``; None for unknown or
    malformed kinds (skipped on replay, never fatal)."""
    if not payload:
        return None
    kind = payload[0]
    body = payload[1:]
    try:
        if kind == KIND_PUT:
            (partition,) = _PART.unpack_from(body)
            return kind, (partition, body[_PART.size:])
        if kind == KIND_DELETE:
            (partition,) = _PART.unpack_from(body)
            return kind, (partition,)
        if kind == KIND_SNAPSHOT:
            (version,) = _VERSION.unpack_from(body)
            return kind, (version,)
        if kind == KIND_META:
            (key_len,) = _KEYLEN.unpack_from(body)
            key = body[_KEYLEN.size:_KEYLEN.size + key_len].decode("utf-8")
            return kind, (key, body[_KEYLEN.size + key_len:])
    except (struct.error, UnicodeDecodeError):
        return None
    return None  # unknown kind: a newer writer's record, skip it


class WriteAheadLog:
    """Segmented append-only log under one directory.

    Construction scans existing segments in order, truncates the torn tail
    (if any) at the first bad record, and exposes the surviving payloads as
    :meth:`recovered_records`; the handle then reopens the last segment for
    appending so the log continues where the previous process stopped.
    """

    def __init__(self, directory: str, *, segment_bytes: int = 1 << 20,
                 fsync_policy: int = FSYNC_BATCH,
                 fsync_hook: Optional[Callable[[], None]] = None) -> None:
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.segment_bytes = max(int(segment_bytes), _HEADER.size + 1)
        self.fsync_policy = int(fsync_policy)
        # test/bench seam for disk_stall fault injection: called before every
        # physical fsync so the harness can bill or sleep the stall
        self.fsync_hook = fsync_hook
        self.appends = 0
        self.fsyncs = 0
        self.torn_truncations = 0
        self._dirty = False
        self._records: List[Tuple[int, bytes]] = []
        self._scan_and_truncate()
        seqs = self.segment_seqs()
        self._seq = seqs[-1] if seqs else 0
        path = self._path(self._seq)
        self._fh = open(path, "ab", buffering=0)
        self._size = os.path.getsize(path)

    # -- layout ---------------------------------------------------------------

    def _path(self, seq: int) -> str:
        return os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}{seq:08d}{_SEGMENT_SUFFIX}"
        )

    def segment_seqs(self) -> List[int]:
        seqs = []
        for name in os.listdir(self.directory):
            if name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX):
                try:
                    seqs.append(int(name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(seqs)

    # -- recovery -------------------------------------------------------------

    def _scan_and_truncate(self) -> None:
        """Collect every intact record across segments in seq order; the
        first torn record truncates its file and discards all later
        segments (a tear is only ever at the active tail)."""
        seqs = self.segment_seqs()
        for index, seq in enumerate(seqs):
            path = self._path(seq)
            with open(path, "rb") as fh:
                blob = fh.read()
            good = 0
            for payload, end in iter_frames(blob):
                self._records.append((seq, payload))
                good = end
            if good < len(blob):
                self.torn_truncations += 1
                with open(path, "r+b") as fh:
                    fh.truncate(good)
                for later in seqs[index + 1:]:
                    os.remove(self._path(later))
                return

    def recovered_records(self) -> List[Tuple[int, bytes]]:
        """``(segment seq, payload)`` for every record that survived the
        tail truncation, in append order."""
        return list(self._records)

    # -- append path ----------------------------------------------------------

    def append(self, payload: bytes) -> None:
        record = frame(payload)
        if self._size and self._size + len(record) > self.segment_bytes:
            self.rotate()
        self._fh.write(record)
        self._size += len(record)
        self.appends += 1
        if self.fsync_policy >= FSYNC_ALWAYS:
            self._fsync()
        else:
            self._dirty = True

    def _fsync(self) -> None:
        if self.fsync_hook is not None:
            self.fsync_hook()
        if self.fsync_policy > FSYNC_NEVER:
            os.fsync(self._fh.fileno())
            self.fsyncs += 1
        self._dirty = False

    def sync(self) -> None:
        """Durability barrier: everything appended so far survives a crash
        (no-op under FSYNC_NEVER beyond the OS page cache)."""
        if self._dirty:
            self._fsync()

    def rotate(self) -> int:
        """Close the active segment and open the next one; returns the new
        segment's seq."""
        self.sync()
        self._fh.close()
        self._seq += 1
        self._fh = open(self._path(self._seq), "ab", buffering=0)
        self._size = 0
        return self._seq

    def mark_snapshot(self, version: int) -> int:
        """Rotate, then write the snapshot marker as the *first* record of
        the fresh segment (always fsync'd -- the marker gates retention),
        then delete every segment below it. Returns the marker's seq."""
        seq = self.rotate()
        self._fh.write(frame(snapshot_record(version)))
        self._size += _HEADER.size + 1 + _VERSION.size
        self.appends += 1
        if self.fsync_hook is not None:
            self.fsync_hook()
        os.fsync(self._fh.fileno())
        self.fsyncs += 1
        self._dirty = False
        for old in self.segment_seqs():
            if old < seq:
                os.remove(self._path(old))
        return seq

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self.sync()
            self._fh.close()

    def crash(self) -> None:
        """Abrupt close: no barrier, whatever the OS buffered is whatever
        survives -- the test seam for process-death simulation."""
        if self._fh is not None and not self._fh.closed:
            self._fh.close()


def tear_wal_tail(directory: str, drop_bytes: int = 3,
                  corrupt: bool = False) -> Optional[int]:
    """Damage the last WAL segment in ``directory``: truncate ``drop_bytes``
    off its end, or (``corrupt=True``) flip a byte inside its final record
    so the CRC fails. Returns the damaged segment's seq, or None if there
    was nothing to tear. Test/nemesis helper for the ``torn_write`` family.
    """
    seqs = []
    for name in os.listdir(directory):
        if name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX):
            seqs.append(int(name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]))
    for seq in sorted(seqs, reverse=True):
        path = os.path.join(
            directory, f"{_SEGMENT_PREFIX}{seq:08d}{_SEGMENT_SUFFIX}"
        )
        size = os.path.getsize(path)
        if size == 0:
            continue
        if corrupt:
            with open(path, "r+b") as fh:
                fh.seek(size - 1)
                last = fh.read(1)
                fh.seek(size - 1)
                fh.write(bytes([last[0] ^ 0xFF]))
        else:
            with open(path, "r+b") as fh:
                fh.truncate(max(0, size - drop_bytes))
        return seq
    return None


def load_snapshot(path: str) -> Optional[Tuple[Dict[int, bytes], Dict[str, bytes]]]:
    """Parse a snapshot file written by :func:`write_snapshot`.

    Returns ``(partition data, meta)`` or None when the file is torn or
    missing its completeness witness (an interrupted snapshot write that
    never got renamed into place should be impossible, but a truncated one
    must read as absent, not as an empty store).
    """
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError:
        return None
    data: Dict[int, bytes] = {}
    meta: Dict[str, bytes] = {}
    complete = False
    good = 0
    for payload, end in iter_frames(blob):
        good = end
        decoded = parse_record(payload)
        if decoded is None:
            continue
        kind, args = decoded
        if kind == KIND_PUT:
            data[args[0]] = args[1]
        elif kind == KIND_META:
            if args[0] == "complete":
                complete = True
            else:
                meta[args[0]] = args[1]
    if not complete or good < len(blob):
        return None
    return data, meta


def write_snapshot(path: str, data: Dict[int, bytes],
                   meta: Dict[str, bytes]) -> None:
    """Write a snapshot atomically: framed PUT records, framed META records,
    and a terminal ``complete`` witness, to a temp file renamed into place.
    """
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        for partition in sorted(data):
            fh.write(frame(put_record(partition, data[partition])))
        for key in sorted(meta):
            fh.write(frame(meta_record(key, meta[key])))
        fh.write(frame(meta_record("complete", b"")))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
