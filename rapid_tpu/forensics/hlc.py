"""Hybrid logical clocks (Kulkarni et al., "Logical Physical Clocks").

An HLC stamp is a ``(physical_ms, logical)`` pair: the physical half tracks
the node's own clock, the logical half breaks ties and absorbs skew. The
update rules guarantee that if event *a* happened-before event *b* (same
node, or a message from *a*'s node received before *b*), then
``stamp(a) < stamp(b)`` -- even when the receiving node's wall clock runs
*behind* the sender's. That is the property the forensic timeline leans on:
journal entries from deliberately skewed nodes (``clock_skew`` faults)
merge into one causally-consistent order.

Wire carriage mirrors the trace-context sidecar exactly (PR 13): the stamp
rides as an out-of-band attribute on the frozen message dataclass, the
msgpack codec emits it under the reserved ``__hlc`` key, and the proto
transport carries it in an append-only field outside the request oneof.
Old peers strip the key / skip the field; with the forensics kill switch
off no stamp is ever attached, so the wire bytes are byte-identical to the
pre-forensics build (the PR 3 golden criterion).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from ..runtime.lockdep import make_lock


@dataclass(frozen=True)
class HlcStamp:
    """One hybrid-logical-clock reading. Totally ordered as the pair
    ``(physical_ms, logical)``; ``incarnation`` disambiguates a restarted
    node whose physical clock regressed below its pre-crash stamps (the
    PR 17 incarnation-seq pattern: compare incarnation first when ordering
    events of ONE node, but never across nodes)."""

    physical_ms: int
    logical: int
    incarnation: int = 1

    def pair(self) -> Tuple[int, int]:
        return (int(self.physical_ms), int(self.logical))

    def to_wire(self) -> list:
        # list, not tuple: msgpack round-trips lists; the proto transport
        # maps the fields explicitly
        return [int(self.physical_ms), int(self.logical), int(self.incarnation)]

    @classmethod
    def from_wire(cls, raw: object) -> Optional["HlcStamp"]:
        """None on anything malformed -- a bad stamp from a hostile or
        half-upgraded peer must never take the receive path down."""
        if not isinstance(raw, (list, tuple)) or len(raw) < 2:
            return None
        try:
            physical = int(raw[0])
            logical = int(raw[1])
            incarnation = int(raw[2]) if len(raw) > 2 else 1
        except (TypeError, ValueError):
            return None
        if physical < 0 or logical < 0 or incarnation < 1:
            return None
        return cls(physical, logical, incarnation)


class HlcClock:
    """The per-node clock: ``now()`` for send/local events, ``merge()`` on
    receive. Thread-safe; tolerant of a dying physical clock (falls back to
    the last known physical time, logical half keeps events ordered)."""

    def __init__(self, clock: Optional[Callable[[], int]] = None,
                 incarnation: int = 1) -> None:
        # default physical source is wall milliseconds; the sim passes its
        # virtual clock so engine/sim timelines stay comparable
        self._clock = clock if clock is not None else (
            lambda: int(time.time() * 1000)
        )
        self.incarnation = max(1, int(incarnation))
        self._lock = make_lock("HlcClock._lock")
        # guarded-by: _lock
        self._physical_ms = 0
        self._logical = 0

    def _physical_now(self) -> int:
        try:
            return int(self._clock())
        except Exception:  # noqa: BLE001 -- clock failure never loses the stamp
            return self._physical_ms

    def now(self) -> HlcStamp:
        """Advance for a send or local event (HLC rule: l' = max(l, pt))."""
        pt = self._physical_now()
        with self._lock:
            if pt > self._physical_ms:
                self._physical_ms = pt
                self._logical = 0
            else:
                self._logical += 1
            return HlcStamp(self._physical_ms, self._logical, self.incarnation)

    def merge(self, remote: HlcStamp) -> HlcStamp:
        """Advance past a received stamp (HLC receive rule): the returned
        stamp is strictly greater than both the local clock and ``remote``,
        which is exactly the happened-before edge the timeline needs."""
        pt = self._physical_now()
        with self._lock:
            local = self._physical_ms
            physical = max(local, int(remote.physical_ms), pt)
            if physical == local and physical == remote.physical_ms:
                logical = max(self._logical, int(remote.logical)) + 1
            elif physical == local:
                logical = self._logical + 1
            elif physical == remote.physical_ms:
                logical = int(remote.logical) + 1
            else:
                logical = 0
            self._physical_ms = physical
            self._logical = logical
            return HlcStamp(physical, logical, self.incarnation)

    def peek(self) -> HlcStamp:
        """Current reading without advancing (status reporting)."""
        with self._lock:
            return HlcStamp(self._physical_ms, self._logical, self.incarnation)


# --------------------------------------------------------------------------- #
# Message sidecar (the trace-context pattern, observability.py)
# --------------------------------------------------------------------------- #

_HLC_ATTR = "hlc_stamp"


def stamp_hlc(msg: object, stamp: HlcStamp) -> None:
    """Attach a stamp to a (frozen) message out-of-band. Degrades to a
    no-op on slotted/odd message objects -- forensics never breaks send."""
    try:
        object.__setattr__(msg, _HLC_ATTR, stamp)
    except (AttributeError, TypeError):
        pass


def hlc_of(msg: object) -> Optional[HlcStamp]:
    return getattr(msg, _HLC_ATTR, None)


class HlcStampingClient:
    """IMessagingClient decorator: stamps ``clock.now()`` on every outbound
    message. Installed by ClusterBuilder when ``settings.forensics.enabled``
    -- one seam covers unicast, gossip, batching, and the join pipeline,
    because every path funnels through the node's messaging client."""

    def __init__(self, inner, clock: HlcClock) -> None:
        self._inner = inner
        self._clock = clock

    def send_message(self, remote, msg):
        stamp_hlc(msg, self._clock.now())
        return self._inner.send_message(remote, msg)

    def send_message_best_effort(self, remote, msg):
        stamp_hlc(msg, self._clock.now())
        return self._inner.send_message_best_effort(remote, msg)

    def shutdown(self) -> None:
        self._inner.shutdown()

    def __getattr__(self, name):
        # transports expose extras (settings, stats); delegate transparently
        return getattr(self._inner, name)
