"""Incident evidence bundles: everything a post-mortem needs, in one file.

A bundle is a JSON document with one record per cluster member: journal
tail (HLC-stamped entries), metric digest, metric-history ring tail, SLO
digest, durability stats, trace spans, and the config/view coordinates --
plus a manifest whose fingerprint covers the member records, so a bundle
quoted in an incident review can be checked against the original bytes.

Capture never blocks and never throws into the triggering path: members
that miss the per-member status deadline are recorded as unreachable (with
the error string) and the capture proceeds. Writes are atomic (tmp +
``os.replace``, the agent's Prometheus-rewrite pattern) so a crash mid-
capture never leaves a torn bundle on disk.

Triggers (the ``trigger`` field): ``slo_burn`` (a burn alert fired),
``invariant_violation`` (search-plane checker tripped), ``crash`` (exit
hook), ``dump`` (operator journal dump), ``explicit``
(``Cluster.capture_bundle()`` / ``agent --bundle-out``), ``hunt_witness``
(a shrunken hunt witness was pinned).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence

BUNDLE_SCHEMA_VERSION = 1

TRIGGERS = (
    "explicit", "slo_burn", "invariant_violation", "crash", "dump",
    "hunt_witness",
)


def _canonical(doc: object) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"), default=str)


def bundle_fingerprint(members: Sequence[Dict[str, object]]) -> str:
    """sha256 over the canonical JSON of the member records -- the manifest
    fingerprint a review can recompute to authenticate a quoted bundle."""
    return hashlib.sha256(_canonical(list(members)).encode()).hexdigest()


def member_record(node: str, *, reachable: bool = True,
                  hlc: Optional[list] = None,
                  journal: Sequence[Dict[str, object]] = (),
                  journal_dropped: int = 0, journal_capacity: int = 0,
                  configuration_id: int = 0, membership_size: int = 0,
                  metrics: Optional[Dict[str, int]] = None,
                  history: Sequence[str] = (),
                  spans: Sequence[Dict[str, object]] = (),
                  slo: Optional[Dict[str, object]] = None,
                  durability: Optional[Dict[str, int]] = None,
                  error: str = "") -> Dict[str, object]:
    """One member's evidence, normalized. Unreachable members carry only
    ``node``/``reachable``/``error`` -- the bundle says who was missing."""
    record: Dict[str, object] = {
        "node": str(node),
        "reachable": bool(reachable),
        "hlc": list(hlc) if hlc else None,
        "journal": list(journal),
        "journal_dropped": int(journal_dropped),
        "journal_capacity": int(journal_capacity),
        "configuration_id": int(configuration_id),
        "membership_size": int(membership_size),
        "metrics": dict(metrics or {}),
        "history": list(history),
        "spans": list(spans),
        "slo": dict(slo or {}),
        "durability": dict(durability or {}),
    }
    if error:
        record["error"] = str(error)
    return record


def _parse_journal_lines(lines: Sequence[str]) -> List[Dict[str, object]]:
    entries: List[Dict[str, object]] = []
    for line in lines:
        try:
            entry = json.loads(line)
        except (TypeError, ValueError):
            continue
        if isinstance(entry, dict) and "kind" in entry:
            entries.append(entry)
    return entries


def status_to_record(status) -> Dict[str, object]:
    """A member record from a ``ClusterStatusResponse`` (duck-typed: any
    object carrying the status fields works, including old-dialect
    responses whose forensics fields default to zero)."""
    hlc = None
    if int(getattr(status, "hlc_incarnation", 0) or 0) > 0:
        hlc = [
            int(status.hlc_physical_ms), int(status.hlc_logical),
            int(status.hlc_incarnation),
        ]
    slo: Dict[str, object] = {}
    names = tuple(getattr(status, "slo_names", ()) or ())
    if names:
        slo = {
            "names": list(names),
            "burn_milli": list(getattr(status, "slo_burn_milli", ()) or ()),
            "firing": list(getattr(status, "slo_firing", ()) or ()),
            "attributed_trace": list(
                getattr(status, "slo_attributed_trace", ()) or ()
            ),
        }
    return member_record(
        str(getattr(status, "sender", "")),
        hlc=hlc,
        journal=_parse_journal_lines(getattr(status, "journal", ()) or ()),
        journal_dropped=int(getattr(status, "journal_dropped", 0) or 0),
        journal_capacity=int(getattr(status, "journal_capacity", 0) or 0),
        configuration_id=int(getattr(status, "configuration_id", 0) or 0),
        membership_size=int(getattr(status, "membership_size", 0) or 0),
        metrics=dict(zip(
            getattr(status, "metric_names", ()) or (),
            (int(v) for v in getattr(status, "metric_values", ()) or ()),
        )),
        history=tuple(getattr(status, "history", ()) or ()),
        slo=slo,
        durability={
            "segments": int(getattr(status, "durability_segments", 0) or 0),
            "snapshot_version": int(
                getattr(status, "durability_snapshot_version", 0) or 0
            ),
            "replayed": int(getattr(status, "durability_replayed", 0) or 0),
        },
    )


def unreachable_record(node: str, error: str) -> Dict[str, object]:
    return member_record(str(node), reachable=False, error=error)


def _span_dict(span) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for key in ("name", "span_id", "parent_id", "start_ms", "end_ms",
                "virtual_start_ms", "virtual_end_ms", "plane", "track"):
        value = getattr(span, key, None)
        if value is not None:
            out[key] = value
    attrs = getattr(span, "attrs", None)
    if attrs:
        out["attrs"] = dict(attrs)
    return out


def capture_local_evidence(*, node: str, recorder=None, metrics=None,
                           tracer=None, slo=None, hlc=None,
                           configuration_id: int = 0,
                           membership_size: int = 0,
                           durability: Optional[Dict[str, int]] = None,
                           history=None,
                           journal_tail: int = 128,
                           history_tail: int = 32) -> Dict[str, object]:
    """The local node's full evidence record, assembled straight from the
    plane objects (NOT via the status RPC, so a capture triggered from
    inside the status/SLO path cannot recurse). Every accessor degrades
    independently: a dying subsystem costs its own section, never the
    bundle."""
    journal: Sequence[Dict[str, object]] = ()
    dropped = capacity = 0
    if recorder is not None:
        try:
            journal = recorder.tail(journal_tail)
            dropped = recorder.dropped
            capacity = recorder.capacity
        except Exception:  # noqa: BLE001
            journal = ()
    stamp = None
    if hlc is not None:
        try:
            stamp = hlc.peek().to_wire()
        except Exception:  # noqa: BLE001
            stamp = None
    snapshot: Dict[str, int] = {}
    if metrics is not None:
        try:
            snapshot = dict(metrics.snapshot())
        except Exception:  # noqa: BLE001
            snapshot = {}
    spans: List[Dict[str, object]] = []
    if tracer is not None:
        try:
            spans = [_span_dict(s) for s in tracer.collect_spans()]
        except Exception:  # noqa: BLE001
            spans = []
    digest: Dict[str, object] = {}
    if slo is not None:
        try:
            names, burn, firing, attributed = slo.status_digest()
            digest = {
                "names": [str(n) for n in names],
                "burn_milli": [int(v) for v in burn],
                "firing": [int(v) for v in firing],
                "attributed_trace": [int(v) for v in attributed],
            }
        except Exception:  # noqa: BLE001
            digest = {}
    lines: Sequence[str] = ()
    if history is not None and history_tail > 0:
        try:
            lines = history.to_wire(history_tail)
        except Exception:  # noqa: BLE001
            lines = ()
    return member_record(
        node, hlc=stamp, journal=journal, journal_dropped=dropped,
        journal_capacity=capacity, configuration_id=configuration_id,
        membership_size=membership_size, metrics=snapshot, history=lines,
        spans=spans, slo=digest, durability=durability,
    )


def build_bundle(trigger: str, local: Dict[str, object],
                 members: Sequence[Dict[str, object]] = (),
                 detail: Optional[Dict[str, object]] = None
                 ) -> Dict[str, object]:
    """Assemble the bundle document. ``local`` is the capturing node's
    record (always first); ``members`` are the fan-out records (reachable
    or not). The manifest fingerprint covers every member record."""
    records = [local] + [
        m for m in members if m.get("node") != local.get("node")
    ]
    events = sum(
        len(m.get("journal", ())) for m in records  # type: ignore[arg-type]
    )
    unreachable = sorted(
        str(m["node"]) for m in records if not m.get("reachable", True)
    )
    return {
        "schema": BUNDLE_SCHEMA_VERSION,
        "trigger": str(trigger),
        "captured_by": str(local.get("node", "")),
        "captured_wall_s": time.time(),
        "detail": dict(detail or {}),
        "members": records,
        "manifest": {
            "fingerprint": bundle_fingerprint(records),
            "members": len(records),
            "unreachable": unreachable,
            "events": events,
        },
    }


def write_bundle(bundle: Dict[str, object], path: str) -> str:
    """Atomic write (tmp + ``os.replace``): readers never see a torn
    bundle, and a crash mid-write leaves the previous file intact."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=".bundle-", dir=directory)
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(bundle, fh, sort_keys=True, default=str)
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_bundle(path: str) -> Dict[str, object]:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "members" not in doc:
        raise ValueError(f"{path}: not an evidence bundle")
    return doc


def verify_bundle(bundle: Dict[str, object]) -> bool:
    """Recompute the manifest fingerprint over the member records."""
    manifest = bundle.get("manifest")
    if not isinstance(manifest, dict):
        return False
    members = bundle.get("members", [])
    return manifest.get("fingerprint") == bundle_fingerprint(members)  # type: ignore[arg-type]


# --------------------------------------------------------------------------- #
# Crash/exit hooks (behind the forensics kill switch; see ClusterBuilder)
# --------------------------------------------------------------------------- #

_EXIT_HOOKS: Dict[int, str] = {}  # id(recorder) -> path (idempotence guard)


def install_exit_hooks(recorder, journal_path: str) -> bool:
    """Register an atexit journal dump (atomic, via FlightRecorder.dump)
    and enable faulthandler tracebacks next to it, so even an uncaught
    crash leaves evidence on disk. Idempotent per (recorder, path); only
    ever called when ``settings.forensics.enabled``."""
    key = id(recorder)
    if _EXIT_HOOKS.get(key) == journal_path:
        return False
    _EXIT_HOOKS[key] = journal_path

    def _dump() -> None:
        try:
            recorder.dump(journal_path)
        except Exception:  # noqa: BLE001 -- exiting anyway; never mask the exit
            pass

    atexit.register(_dump)
    try:
        import faulthandler

        if not faulthandler.is_enabled():
            # hard crashes (segfault/abort) cannot run Python atexit hooks;
            # the faulthandler traceback file is the evidence of last resort
            crash_file = open(journal_path + ".crash", "w")  # noqa: SIM115
            faulthandler.enable(file=crash_file)
    except Exception:  # noqa: BLE001 -- faulthandler is best-effort
        pass
    return True
