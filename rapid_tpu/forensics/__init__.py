"""Forensics plane: hybrid logical clocks, incident evidence bundles, and
causal cluster timelines.

Three layers, each usable on its own:

``hlc``
    Hybrid logical clocks (physical-ms, logical counter). Stamped on every
    outbound message next to the ``__tc`` trace sidecar and merged on
    receive, so journal events from wall-clock-skewed nodes still order
    causally. The whole layer rides the same reserved-key / append-only
    proto-field pattern the trace context uses: with the forensics kill
    switch off the wire bytes are unchanged.

``bundle``
    Incident evidence capture: journal tails, metric-history rings, SLO
    digest, trace spans, and config/view ids from every reachable member,
    written atomically with a manifest fingerprint. Triggered by SLO burn
    alerts, search-plane invariant violations, crash/dump paths, or an
    explicit ``Cluster.capture_bundle()`` / ``agent --bundle-out``.

``timeline``
    Merge one or more bundles into a single HLC-ordered cluster timeline
    and run the anomaly-signature detectors over it (``tools/forensics.py``
    is the CLI face).
"""

from .hlc import HlcClock, HlcStamp, hlc_of, stamp_hlc
from .bundle import (
    BUNDLE_SCHEMA_VERSION,
    bundle_fingerprint,
    capture_local_evidence,
    write_bundle,
)
from .timeline import (
    SIGNATURE_CATALOG,
    TimelineEvent,
    detect_signatures,
    merge_timeline,
)

__all__ = [
    "HlcClock",
    "HlcStamp",
    "hlc_of",
    "stamp_hlc",
    "BUNDLE_SCHEMA_VERSION",
    "bundle_fingerprint",
    "capture_local_evidence",
    "write_bundle",
    "SIGNATURE_CATALOG",
    "TimelineEvent",
    "detect_signatures",
    "merge_timeline",
]
