"""Causal cluster timelines and anomaly-signature detection.

``merge_timeline`` folds the member journals of one or more evidence
bundles into a single event stream ordered by hybrid logical clock -- the
order that survives ``clock_skew`` faults, where wall-clock merge provably
does not (tests/test_forensics.py pins a run whose wall order is wrong).
Events that predate the forensics plane (no ``hlc`` coordinate) fall back
to wall milliseconds, so mixed bundles still merge.

``detect_signatures`` runs every cataloged anomaly detector over the
merged timeline. Detectors are pure functions -- timeline in, finding
dicts out -- so the same code judges a live capture, a bundle file, or a
hand-built test fixture. SIGNATURE_CATALOG is the closed set of signature
names (linted two-sidedly by tools/check.py, the METRIC_CATALOG
discipline): every catalog row has a detector, every finding a detector
emits is cataloged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..slo.attrib import attribute_burn, describe, episodes_from_journal

# Signature name -> documentation (pure module literal: tools/check.py
# loads this standalone for the signature-catalog lint, like RULE_CATALOG)
SIGNATURE_CATALOG = {
    "view_divergence": {
        "doc": "two members held different configuration ids for longer "
               "than the propagation grace window (HLC-overlapping view "
               "intervals with different ids)",
    },
    "stuck_handoff": {
        "doc": "a member launched handoff sessions that never reached "
               "handoff_complete or handoff_failed before the capture",
    },
    "deposed_leader_write": {
        "doc": "a member kept acting on a stale placement-map version "
               "causally after another member announced a newer one, and "
               "never caught up before the capture",
    },
    "alert_storm_burn": {
        "doc": "a burn alert fired inside a membership episode that also "
               "carried an alert storm (the churn -> alert flood -> burn "
               "chain, attributed via slo/attrib.py episodes)",
    },
}

# events counted as "alert traffic" by the alert_storm_burn detector
_STORM_KINDS = ("fd_signal", "alert_enqueued", "alert_in", "alert_out")

# grace windows (physical-ms on the HLC axis): normal propagation after a
# churn wave must not read as divergence or deposal
DEFAULT_DIVERGENCE_GRACE_MS = 2000
DEFAULT_STORM_MIN_EVENTS = 5


@dataclass(frozen=True)
class TimelineEvent:
    """One journal entry on the merged cluster timeline."""

    node: str
    kind: str
    seq: int
    wall_s: float
    virtual_ms: Optional[int]
    hlc: Optional[Tuple[int, int, int]]  # (physical_ms, logical, incarnation)
    detail: Dict[str, object] = field(default_factory=dict)

    @property
    def hlc_key(self) -> Tuple[int, int, str, int]:
        """The merge key: HLC coordinate when stamped, wall-ms fallback
        otherwise; node + seq break exact ties deterministically."""
        if self.hlc is not None:
            return (int(self.hlc[0]), int(self.hlc[1]), self.node, self.seq)
        return (int(self.wall_s * 1000), 0, self.node, self.seq)

    @property
    def wall_key(self) -> Tuple[int, int, str, int]:
        """The naive wall-clock merge key -- kept so tests (and the report)
        can show exactly where wall order betrays causality under skew."""
        return (int(self.wall_s * 1000), 0, self.node, self.seq)

    def to_journal_entry(self) -> Dict[str, object]:
        """Back to the FlightRecorder entry dict shape (what
        slo/attrib.py's episode folding consumes)."""
        entry: Dict[str, object] = {
            "seq": self.seq, "kind": self.kind, "wall_s": self.wall_s,
            "virtual_ms": self.virtual_ms, "node": self.node,
            "detail": dict(self.detail),
        }
        if self.hlc is not None:
            entry["hlc"] = list(self.hlc)
        return entry


def _event_from_entry(node: str, entry: Dict[str, object]
                      ) -> Optional[TimelineEvent]:
    kind = entry.get("kind")
    if not isinstance(kind, str):
        return None
    hlc = entry.get("hlc")
    stamp: Optional[Tuple[int, int, int]] = None
    if isinstance(hlc, (list, tuple)) and len(hlc) >= 2:
        try:
            stamp = (
                int(hlc[0]), int(hlc[1]),
                int(hlc[2]) if len(hlc) > 2 else 1,
            )
        except (TypeError, ValueError):
            stamp = None
    try:
        wall_s = float(entry.get("wall_s", 0.0))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        wall_s = 0.0
    virtual = entry.get("virtual_ms")
    try:
        virtual_ms = int(virtual) if virtual is not None else None  # type: ignore[arg-type]
    except (TypeError, ValueError):
        virtual_ms = None
    try:
        seq = int(entry.get("seq", 0))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        seq = 0
    detail = entry.get("detail")
    return TimelineEvent(
        node=str(entry.get("node") or node),
        kind=kind, seq=seq, wall_s=wall_s, virtual_ms=virtual_ms,
        hlc=stamp, detail=dict(detail) if isinstance(detail, dict) else {},
    )


def merge_timeline(bundles: Sequence[Dict[str, object]]
                   ) -> List[TimelineEvent]:
    """One HLC-ordered stream from every member journal of every bundle.

    The same node's journal may appear in several records (its own local
    capture plus other members' status fan-outs): entries dedupe on
    ``(node, incarnation, seq)``, the per-recorder identity the PR 17
    incarnation-seq pattern guarantees unique."""
    events: List[TimelineEvent] = []
    seen = set()
    for bundle in bundles:
        members = bundle.get("members", [])
        if not isinstance(members, list):
            continue
        for member in members:
            if not isinstance(member, dict):
                continue
            node = str(member.get("node", ""))
            journal = member.get("journal", [])
            if not isinstance(journal, list):
                continue
            for entry in journal:
                if not isinstance(entry, dict):
                    continue
                event = _event_from_entry(node, entry)
                if event is None:
                    continue
                incarnation = event.hlc[2] if event.hlc is not None else 0
                key = (event.node, incarnation, event.seq, event.kind)
                if key in seen:
                    continue
                seen.add(key)
                events.append(event)
    events.sort(key=lambda e: e.hlc_key)
    return events


# --------------------------------------------------------------------------- #
# Anomaly signatures (pure functions: timeline in, finding dicts out)
# --------------------------------------------------------------------------- #


def _finding(signature: str, **fields: object) -> Dict[str, object]:
    assert signature in SIGNATURE_CATALOG, signature
    return {"signature": signature, **fields}


def _detail_int(event: TimelineEvent, key: str) -> int:
    try:
        return int(event.detail.get(key, 0))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return 0


def _axis_ms(event: TimelineEvent) -> int:
    """The event's position on the merge axis in milliseconds."""
    return event.hlc_key[0]


def detect_view_divergence(
    events: Sequence[TimelineEvent],
    grace_ms: int = DEFAULT_DIVERGENCE_GRACE_MS,
) -> List[Dict[str, object]]:
    """Overlapping per-node view intervals with different configuration
    ids, lasting longer than the propagation grace window. Each node's
    interval for a config runs from its install to its next install (or to
    its last journal entry -- a kicked node's stale view stops counting
    when its journal does)."""
    last_event_ms: Dict[str, int] = {}
    installs: Dict[str, List[Tuple[int, int]]] = {}  # node -> [(ms, config)]
    for event in events:
        ms = _axis_ms(event)
        last_event_ms[event.node] = max(last_event_ms.get(event.node, 0), ms)
        if event.kind == "view_install":
            installs.setdefault(event.node, []).append(
                (ms, _detail_int(event, "configuration_id"))
            )
    intervals: Dict[str, List[Tuple[int, int, int]]] = {}
    for node, items in installs.items():
        rows: List[Tuple[int, int, int]] = []
        for i, (start, config) in enumerate(items):
            end = (
                items[i + 1][0] if i + 1 < len(items)
                else last_event_ms.get(node, start)
            )
            rows.append((start, max(end, start), config))
        intervals[node] = rows
    findings: List[Dict[str, object]] = []
    nodes = sorted(intervals)
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            for a_start, a_end, a_cfg in intervals[a]:
                for b_start, b_end, b_cfg in intervals[b]:
                    if a_cfg == b_cfg:
                        continue
                    lo, hi = max(a_start, b_start), min(a_end, b_end)
                    if hi - lo > grace_ms:
                        findings.append(_finding(
                            "view_divergence",
                            nodes=[a, b], configs=[a_cfg, b_cfg],
                            window_ms=hi - lo, start_ms=lo, end_ms=hi,
                        ))
    return findings


def detect_stuck_handoff(
    events: Sequence[TimelineEvent],
) -> List[Dict[str, object]]:
    """Per node: sessions launched (``handoff_started`` carries the count)
    minus sessions that reached a terminal event. A positive balance at
    capture time is a transfer the cluster is still waiting on."""
    started: Dict[str, int] = {}
    resolved: Dict[str, int] = {}
    last_start: Dict[str, TimelineEvent] = {}
    for event in events:
        if event.kind == "handoff_started":
            launched = _detail_int(event, "sessions") or 1
            started[event.node] = started.get(event.node, 0) + launched
            last_start[event.node] = event
        elif event.kind in ("handoff_complete", "handoff_failed"):
            resolved[event.node] = resolved.get(event.node, 0) + 1
    findings: List[Dict[str, object]] = []
    for node in sorted(started):
        stuck = started[node] - resolved.get(node, 0)
        if stuck > 0:
            anchor = last_start[node]
            findings.append(_finding(
                "stuck_handoff",
                node=node, stuck=stuck, started=started[node],
                resolved=resolved.get(node, 0),
                since_ms=_axis_ms(anchor),
                version=_detail_int(anchor, "version"),
            ))
    return findings


def detect_deposed_leader_writes(
    events: Sequence[TimelineEvent],
) -> List[Dict[str, object]]:
    """A member that kept acting on a stale placement-map version causally
    *after* another member announced a newer one, and never announced the
    newer version itself before the capture. Transient staleness during
    propagation does not trip this: the stale member must end the timeline
    still behind."""
    versioned = [
        e for e in events
        if e.kind in ("serving_leader_change", "serving_sync")
        and _detail_int(e, "version") > 0
    ]
    if not versioned:
        return []
    last_version: Dict[str, int] = {}
    first_announce: Dict[int, TimelineEvent] = {}
    for event in versioned:
        version = _detail_int(event, "version")
        last_version[event.node] = version
        if version not in first_announce:
            first_announce[version] = event
    vmax = max(last_version.values())
    findings: List[Dict[str, object]] = []
    for node in sorted(last_version):
        stale = last_version[node]
        if stale >= vmax:
            continue
        newer = [
            v for v, e in first_announce.items()
            if v > stale and e.node != node
        ]
        if not newer:
            continue
        deposed_at = min(first_announce[v].hlc_key for v in newer)
        stale_after = [
            e for e in versioned
            if e.node == node and _detail_int(e, "version") <= stale
            and e.hlc_key > deposed_at
        ]
        if stale_after:
            findings.append(_finding(
                "deposed_leader_write",
                node=node, stale_version=stale, newer_version=vmax,
                write_attempts=len(stale_after),
                first_stale_ms=_axis_ms(stale_after[0]),
            ))
    return findings


def detect_alert_storm_burn(
    events: Sequence[TimelineEvent],
    storm_min_events: int = DEFAULT_STORM_MIN_EVENTS,
) -> List[Dict[str, object]]:
    """The churn -> alert storm -> burn chain: a ``slo_alert_fired`` whose
    attributed membership episode (slo/attrib.py, over the merged journal)
    also carried at least ``storm_min_events`` of alert traffic."""
    entries = [e.to_journal_entry() for e in events]
    episodes = episodes_from_journal(entries)
    findings: List[Dict[str, object]] = []
    for event in events:
        if event.kind != "slo_alert_fired":
            continue
        fired_ms = (
            event.virtual_ms if event.virtual_ms is not None
            else _axis_ms(event)
        )
        episode = attribute_burn(episodes, fired_ms - 1, fired_ms)
        if episode is None:
            continue
        storm = [
            e for e in events
            if e.kind in _STORM_KINDS
            and e.virtual_ms is not None
            and episode.start_ms <= e.virtual_ms <= max(
                episode.end_ms, fired_ms
            )
        ]
        if len(storm) >= storm_min_events:
            findings.append(_finding(
                "alert_storm_burn",
                node=event.node,
                slo=str(event.detail.get("slo", "")),
                window=str(event.detail.get("window", "")),
                storm_events=len(storm),
                episode=describe(episode),
                episode_start_ms=episode.start_ms,
                fired_ms=fired_ms,
            ))
    return findings


def detect_signatures(
    events: Sequence[TimelineEvent],
    grace_ms: int = DEFAULT_DIVERGENCE_GRACE_MS,
    storm_min_events: int = DEFAULT_STORM_MIN_EVENTS,
) -> List[Dict[str, object]]:
    """Every cataloged detector over one merged timeline."""
    findings: List[Dict[str, object]] = []
    findings.extend(detect_view_divergence(events, grace_ms=grace_ms))
    findings.extend(detect_stuck_handoff(events))
    findings.extend(detect_deposed_leader_writes(events))
    findings.extend(
        detect_alert_storm_burn(events, storm_min_events=storm_min_events)
    )
    return findings


# --------------------------------------------------------------------------- #
# Rendering
# --------------------------------------------------------------------------- #


def timeline_chrome_trace(events: Sequence[TimelineEvent]) -> Dict[str, object]:
    """Chrome-trace instants on the HLC axis: ``ts`` is the HLC physical
    half in microseconds plus the logical half as sub-microsecond ticks,
    one track per node -- load in Perfetto next to any device trace."""
    trace_events: List[Dict[str, object]] = []
    tids = {node: i for i, node in enumerate(
        sorted({e.node for e in events})
    )}
    for event in events:
        physical, logical = event.hlc_key[0], event.hlc_key[1]
        trace_events.append({
            "name": event.kind, "ph": "i", "s": "g",
            "pid": 0, "tid": tids[event.node],
            "ts": physical * 1000 + logical,
            "cat": "forensics",
            "args": {"node": event.node, "seq": event.seq,
                     "hlc": list(event.hlc) if event.hlc else None,
                     **event.detail},
        })
    trace_events.extend(
        {
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
            "args": {"name": node},
        }
        for node, tid in tids.items()
    )
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def report_text(events: Sequence[TimelineEvent],
                findings: Sequence[Dict[str, object]],
                bundles: Sequence[Dict[str, object]] = ()) -> str:
    """The operator report: bundle manifests, the merged timeline, and the
    signature verdicts."""
    lines: List[str] = []
    for bundle in bundles:
        manifest = bundle.get("manifest", {})
        if isinstance(manifest, dict):
            unreachable = manifest.get("unreachable") or []
            suffix = (
                f", unreachable: {', '.join(map(str, unreachable))}"
                if unreachable else ""
            )
            lines.append(
                f"bundle[{bundle.get('trigger', '?')}] by "
                f"{bundle.get('captured_by', '?')}: "
                f"{manifest.get('members', 0)} members, "
                f"{manifest.get('events', 0)} events, fingerprint "
                f"{str(manifest.get('fingerprint', ''))[:12]}{suffix}"
            )
    nodes = sorted({e.node for e in events})
    lines.append(
        f"merged timeline: {len(events)} events across {len(nodes)} nodes"
    )
    dropped = sum(
        int(m.get("journal_dropped", 0) or 0)  # type: ignore[arg-type]
        for bundle in bundles
        for m in bundle.get("members", [])  # type: ignore[union-attr]
        if isinstance(m, dict)
    )
    if dropped:
        lines.append(
            f"  (journals truncated: {dropped} events dropped before "
            f"capture -- raise forensics.journal_capacity)"
        )
    for event in events:
        physical, logical = event.hlc_key[0], event.hlc_key[1]
        hlc_txt = (
            f"{physical}.{logical:03d}" if event.hlc is not None
            else f"~{physical} (wall)"
        )
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(event.detail.items())
        )
        lines.append(
            f"  {hlc_txt:>18}  {event.node:<18} {event.kind}"
            + (f"  [{detail}]" if detail else "")
        )
    if findings:
        lines.append(f"signatures detected: {len(findings)}")
        for finding in findings:
            fields = ", ".join(
                f"{k}={v}" for k, v in sorted(finding.items())
                if k != "signature"
            )
            lines.append(f"  {finding['signature']}: {fields}")
    else:
        lines.append("signatures detected: none")
    return "\n".join(lines)
