"""Deterministic cell assignment: pure functions, no state, no RNG.

Two disciplines, consulted in this order:

- **Topology cells** -- when a :class:`~..sim.topology.LatencyTopology`
  places the member (``cell_of_slot``), the zone IS the cell: the zone
  tier is the aggregation-fabric boundary, so keeping a cell inside one
  zone keeps the cell's alert/vote hot path off the regional backbone.
  Device-plane slots are topology indices already; the protocol plane
  maps endpoints to indices the same way the fault plane does
  (``FaultPlan.topology_slots``).
- **Rendezvous cells** -- topology-less clusters fall back to
  highest-random-weight hashing (``cell_of_endpoint``): each endpoint
  scores every cell with the seeded endpoint hash the rings already use
  (hashing.endpoint_hash) and joins the argmax. Rendezvous, not modulo,
  so growing the cell count moves only ~1/cells of the members -- and
  every plane (routing, fault rules, statusz) recomputes the same
  assignment from the endpoint alone, with no shared table.

Both are pure functions of (identity, cell count), so any two members
that agree on the member list agree on the whole cell partition -- the
property leader election (parent.py) builds on.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..hashing import endpoint_hash
from ..types import Endpoint

# seed base for rendezvous scoring, disjoint from the K ring seeds (which
# are small ring indices) so cell placement never correlates with ring
# adjacency
_CELL_SEED_BASE = 0x43454C4C  # "CELL"


def cell_of_endpoint(endpoint: Endpoint, cells: int) -> int:
    """Rendezvous (highest-random-weight) cell of ``endpoint`` among
    ``cells`` cells. Deterministic everywhere the endpoint is known."""
    if cells <= 1:
        return 0
    best_cell = 0
    best_score = -1
    for cell in range(cells):
        score = endpoint_hash(
            endpoint.hostname, endpoint.port, _CELL_SEED_BASE + cell
        )
        if score > best_score:
            best_score = score
            best_cell = cell
    return best_cell


def cell_of_slot(slot: int, topology) -> int:
    """Topology cell of device slot / topology index ``slot``: the zone
    (LatencyTopology.zone_of -- a pure function of the index)."""
    return topology.zone_of(int(slot))


def cell_count(cells: int, topology=None) -> int:
    """Resolve the configured cell count: an explicit ``cells > 0`` wins;
    otherwise the topology's zone count; otherwise one cell (which makes
    the hierarchy a flat cluster plus a trivial parent of one leader)."""
    if cells > 0:
        return int(cells)
    if topology is not None:
        return int(topology.zones)
    return 1


def cell_of(
    endpoint: Endpoint,
    cells: int,
    topology=None,
    slots: Optional[Dict[Endpoint, int]] = None,
) -> int:
    """The one assignment function every plane shares: topology zone when
    the endpoint is placed (``slots`` maps endpoints to topology indices),
    rendezvous hash otherwise."""
    if topology is not None and slots is not None:
        index = slots.get(endpoint)
        if index is not None:
            return cell_of_slot(index, topology)
    return cell_of_endpoint(endpoint, cell_count(cells, topology))


def cell_members(
    members: Iterable[Endpoint],
    cells: int,
    topology=None,
    slots: Optional[Dict[Endpoint, int]] = None,
) -> Dict[int, List[Endpoint]]:
    """Partition ``members`` into cells, preserving input order inside
    each cell (callers pass ring-0 order, so per-cell order is itself the
    ring order every member agrees on)."""
    resolved = cell_count(cells, topology)
    out: Dict[int, List[Endpoint]] = {}
    for member in members:
        out.setdefault(
            cell_of(member, resolved, topology=topology, slots=slots), []
        ).append(member)
    return out


def cell_sizes(
    members: Iterable[Endpoint],
    cells: int,
    topology=None,
    slots: Optional[Dict[Endpoint, int]] = None,
) -> Tuple[Tuple[int, int], ...]:
    """Sorted ``(cell, size)`` rows -- the statusz/bench digest shape."""
    grouped = cell_members(members, cells, topology=topology, slots=slots)
    return tuple((cell, len(grouped[cell])) for cell in sorted(grouped))
