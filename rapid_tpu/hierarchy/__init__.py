"""Hierarchy plane: two-level cell-based membership (ROADMAP item 1).

Cells of ~1-10k members each run Rapid internally -- the cut detector and
Fast Paxos are untouched -- while each cell's deterministic leader set
participates in a parent configuration that agrees on the composed global
view, so cross-cell churn costs O(cells) instead of O(members).

- :mod:`.cells`   -- deterministic cell assignment (topology zones when a
  :class:`~..sim.topology.LatencyTopology` is attached, rendezvous hash
  otherwise); shared verbatim by the protocol plane, the device plane,
  and the fault plane's cell-scoped rules.
- :mod:`.parent`  -- leader election as a pure function of the cell's
  view, per-cell config-id epochs, and the composed global fingerprint.
- :mod:`.routing` -- cell-aware routing on the broadcaster seam (intra-
  cell alerts never leave the cell) and the leader's batched parent
  channel.
- :mod:`.plane`   -- the per-node engine MembershipService drives at view
  installs and message dispatch.

``Settings.hierarchy.enabled`` is the kill switch: off (the default)
attaches nothing and reproduces the exact flat-path wire bytes.
"""

from .cells import cell_count, cell_members, cell_of_endpoint, cell_of_slot
from .parent import (
    CellState,
    GlobalView,
    cell_leaders,
    compose_fingerprint,
    parent_configuration_id,
)
from .plane import HierarchyPlane
from .routing import CellRouter, ParentChannel

__all__ = [
    "CellRouter",
    "CellState",
    "GlobalView",
    "HierarchyPlane",
    "ParentChannel",
    "cell_count",
    "cell_leaders",
    "cell_members",
    "cell_of_endpoint",
    "cell_of_slot",
    "compose_fingerprint",
    "parent_configuration_id",
]
