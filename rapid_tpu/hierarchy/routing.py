"""Cell-aware routing on the broadcaster seam + the leader's parent channel.

Two pieces, both hung on existing seams rather than new transports:

- :class:`CellRouter` wraps any :class:`~..messaging.base.IBroadcaster`
  (unicast-to-all or gossip alike) and narrows its membership to the
  local member's cell, so every protocol broadcast -- alerts, fast-round
  votes, classical Paxos rounds -- stays inside the cell. This is the
  whole O(members) -> O(cell) reduction: the cut detector and Fast Paxos
  are untouched, they just see a cell-sized cluster.
- :class:`ParentChannel` is the leader's high-fan-in fabric for parent
  traffic: cell digests to the other cells' leaders, composed global
  views back into the local cell. It reuses the PR 13 flush-window
  discipline (:class:`~..messaging.unicast.BatchingSink`) so a churn
  wave's digests leave as one ``MessageBatch`` per peer leader; with
  ``hierarchy.parent_flush_ms == 0`` it degrades to bare best-effort
  sends (and exact virtual-time timing), mirroring the broadcaster's own
  window semantics.

Neither class knows how cells are assigned or who leads them -- the
:class:`~.plane.HierarchyPlane` computes both from the installed view and
feeds this module plain endpoint lists.
"""

from __future__ import annotations

from typing import List, Sequence

from ..runtime.futures import Promise
from ..types import Endpoint, RapidMessage
from ..messaging.base import IBroadcaster, IMessagingClient
from .cells import cell_of


class CellRouter(IBroadcaster):  # guarded-by: protocol-executor
    """Broadcaster decorator: same ``broadcast``, cell-filtered membership.

    ``set_membership`` receives the full ring-0 recipient list exactly as
    the flat path would, partitions it with the shared assignment function
    (:func:`~.cells.cell_of`), and forwards only the local cell's members
    to the wrapped broadcaster. The wrapped broadcaster keeps all its own
    behavior (shuffle, flush windows, gossip fan-out) -- it simply serves
    a smaller cluster."""

    def __init__(
        self,
        inner: IBroadcaster,
        my_addr: Endpoint,
        cells: int,
        topology=None,
        slots=None,
    ) -> None:
        self._inner = inner
        self._my_addr = my_addr
        self._cells = cells
        self._topology = topology
        self._slots = slots
        self._my_cell = cell_of(
            my_addr, cells, topology=topology, slots=slots
        )
        self._cell_recipients: List[Endpoint] = []

    @property
    def my_cell(self) -> int:
        return self._my_cell

    @property
    def cell_recipients(self) -> List[Endpoint]:
        """The current cell-local recipient list (ring-0 order)."""
        return list(self._cell_recipients)

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        return self._inner.broadcast(msg)

    def set_membership(self, recipients: List[Endpoint]) -> None:
        self._cell_recipients = [
            ep
            for ep in recipients
            if cell_of(
                ep, self._cells, topology=self._topology, slots=self._slots
            )
            == self._my_cell
        ]
        self._inner.set_membership(self._cell_recipients)


class ParentChannel:
    """The leader's fabric for cross-cell traffic.

    ``send_to_leaders`` fans a message out to peer leaders (parent plane);
    ``send_to_cell`` fans the composed global view back into the local
    cell. Both coalesce through one shared ``BatchingSink`` when
    ``parent_flush_ms > 0`` -- the high-fan-in case this exists for is a
    multi-cell churn wave, where a leader's digests to every peer leader
    ride one ``MessageBatch`` per peer per window."""

    def __init__(
        self,
        client: IMessagingClient,
        my_addr: Endpoint,
        scheduler=None,
        flush_ms: int = 0,
    ) -> None:
        self._client = client
        self._my_addr = my_addr
        self._sink = None
        if flush_ms > 0 and scheduler is not None:
            from ..messaging.unicast import BatchingSink

            self._sink = BatchingSink(client, my_addr, scheduler, flush_ms)

    def _send(self, recipient: Endpoint, msg: RapidMessage) -> None:
        if self._sink is not None:
            self._sink.offer(recipient, msg)
        else:
            self._client.send_message_best_effort(recipient, msg)

    def send_to_leaders(
        self, leaders: Sequence[Endpoint], msg: RapidMessage
    ) -> int:
        """Best-effort fan-out to every peer leader except self; returns
        the number of sends offered."""
        sent = 0
        for leader in leaders:
            if leader == self._my_addr:
                continue
            self._send(leader, msg)
            sent += 1
        return sent

    def send_to_cell(
        self, members: Sequence[Endpoint], msg: RapidMessage
    ) -> int:
        """Fan the composed view back into the local cell (skip self --
        the plane installs locally without a loopback hop)."""
        sent = 0
        for member in members:
            if member == self._my_addr:
                continue
            self._send(member, msg)
            sent += 1
        return sent

    def flush(self) -> None:
        """Force out any window-pending parent traffic (shutdown path)."""
        if self._sink is not None:
            self._sink.flush()
