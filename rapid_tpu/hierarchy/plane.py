"""HierarchyPlane: the per-node two-level composition engine.

Each cell is an ordinary Rapid cluster -- unchanged cut detector, unchanged
Fast Paxos -- whose local configuration id doubles as the cell's *epoch* in
the composed global view. This plane is everything above that: after every
intra-cell view install the node recomputes its cell's deterministic leader
set (parent.cell_leaders); if it leads, it announces the cell's row to the
other cells' leaders (CellDigestMessage, tag 26) and fans the composed
global view back into its own cell (GlobalViewMessage, tag 27) whenever the
composition moved. Followers just install what their leader announces.

Leader failover is a non-event by construction: a leader eviction is an
ordinary intra-cell view change, after which `cell_leaders` of the new view
simply names the next member in leader order -- no election protocol, no
parent-level churn beyond one digest with a higher epoch.

Whole-cell eviction is the one place liveness enters: a cell that lost every
member (leader included) can never announce its own departure. Each leader
keeps a parent-round counter, incremented at every announce edge, and stamps
each foreign cell's row with the round it last refreshed in; a row idle for
``eviction_rounds`` parent rounds is dropped from the composition -- O(1)
parent rounds after the loss, independent of member count. Rounds advance on
view-change edges and on the periodic leader heartbeat (``tick``, driven by
``hierarchy.parent_round_ms`` on the service's scheduler): the heartbeat
re-announces the leader's digest so peers' idle stamps stay fresh, which is
what lets survivors that see no churn of their own still evict a lost cell.
On the deterministic scheduler (harness/sim) heartbeats are virtual-time
events, so the whole discipline stays reproducible per seed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..types import CellDigestMessage, Endpoint, GlobalViewMessage
from .cells import cell_of
from .parent import (
    CellState,
    GlobalView,
    cell_fingerprint,
    cell_leaders,
    parent_configuration_id,
)
from .routing import ParentChannel


class HierarchyPlane:  # guarded-by: protocol-executor
    """Drives the two-level composition for one node.

    Not thread-safe by itself: the service calls every entry point from
    its single protocol executor (the same guarded-by discipline as the
    cut detector), and the sim driver is single-threaded."""

    def __init__(
        self,
        my_addr: Endpoint,
        channel: Optional[ParentChannel] = None,
        cells: int = 0,
        leaders_per_cell: int = 1,
        topology=None,
        slots=None,
        eviction_rounds: int = 3,
    ) -> None:
        self._my_addr = my_addr
        self._channel = channel
        self._cells = cells
        self._leaders_per_cell = leaders_per_cell
        self._topology = topology
        self._slots = slots
        self._eviction_rounds = eviction_rounds
        self.my_cell = cell_of(my_addr, cells, topology=topology, slots=slots)
        self.global_view = GlobalView()
        self._cell_members: Tuple[Endpoint, ...] = ()
        self._leaders: Tuple[Endpoint, ...] = ()
        self._parent_round = 0
        # parent-round stamp each foreign cell's row last refreshed in
        self._last_seen: Dict[int, int] = {}
        # reorder gates: epochs are configuration-id hashes (unordered),
        # so stale frames are rejected by each SENDER's monotonic parent
        # round instead -- per-cell for digests, one for the global-view
        # stream from our own leader; a changed leader resets the gate
        # (leadership is recomputed deterministically from the new view)
        self._digest_gate: Dict[int, Tuple[str, int]] = {}
        self._view_gate: Tuple[str, int] = ("", -1)

    # ------------------------------------------------------------------ #
    # Derived state
    # ------------------------------------------------------------------ #

    @property
    def is_leader(self) -> bool:
        return self._my_addr in self._leaders

    @property
    def leaders(self) -> Tuple[Endpoint, ...]:
        return self._leaders

    @property
    def parent_round(self) -> int:
        return self._parent_round

    def parent_configuration_id(self) -> int:
        """Config id of the parent configuration: the fold over the sorted
        leader endpoints named by the composed view's rows."""
        return parent_configuration_id(
            Endpoint.from_string(leader)
            for leader in self.global_view.leaders()
        )

    def peer_leaders(self) -> List[Endpoint]:
        """Rank-0 leaders of every *other* cell the composition knows."""
        return [
            Endpoint.from_string(state.leader)
            for cell, state in sorted(self.global_view.cells.items())
            if cell != self.my_cell
        ]

    # ------------------------------------------------------------------ #
    # Entry points
    # ------------------------------------------------------------------ #

    def seed_parent(self, leaders: Sequence[Endpoint]) -> None:
        """Bootstrap hint: endpoints believed to lead other cells (the
        hierarchy's analogue of the join seed). Rows are installed at epoch
        0 with unknown size, so the first real digest from each replaces
        them; wrong hints age out like any dead cell."""
        hinted = []
        for leader in leaders:
            cell = cell_of(
                leader,
                self._cells,
                topology=self._topology,
                slots=self._slots,
            )
            if cell == self.my_cell:
                continue
            hinted.append(leader)
            self.global_view.install(
                CellState(cell=cell, epoch=0, size=0, leader=str(leader))
            )
            self._last_seen.setdefault(cell, self._parent_round)
        if (
            hinted
            and self.is_leader
            and self._channel is not None
            and self.my_cell in self.global_view.cells
        ):
            # introduce ourselves: the hint endpoints need not be their
            # cells' actual leaders -- a non-leader receiver relays one
            # hop to its own rank-0 leader (handle_digest), and the reply
            # carries the real rows back
            self._channel.send_to_leaders(hinted, self._own_digest())

    def on_view_installed(
        self, members: Sequence[Endpoint], configuration_id: int
    ) -> None:
        """The one hook the membership layer calls, right after an
        intra-cell view install: recompute leadership from the new view,
        refresh our own row, advance the parent round, age out dead cells,
        and (if leading) announce."""
        self._cell_members = tuple(members)
        self._leaders = cell_leaders(members, self._leaders_per_cell)
        own = CellState(
            cell=self.my_cell,
            epoch=configuration_id,
            size=len(members),
            leader=str(self._leaders[0]) if self._leaders else "",
            fingerprint=cell_fingerprint(members),
        )
        moved = self.global_view.install(own)
        if not self.is_leader:
            return
        self._parent_round += 1
        self._last_seen[self.my_cell] = self._parent_round
        evicted = self._age_out()
        if moved or evicted:
            self._announce()

    def tick(self) -> None:
        """Parent heartbeat (leaders only): advance the round, refresh our
        own stamp, age out idle cells, and re-announce our digest so peer
        leaders' stamps for us stay fresh. A quiet follower's rounds never
        advance -- eviction authority stays with leaders, and followers
        adopt removals from the fanned view."""
        if not self.is_leader or self.my_cell not in self.global_view.cells:
            return
        self._parent_round += 1
        self._last_seen[self.my_cell] = self._parent_round
        evicted = self._age_out()
        if self._channel is not None:
            self._channel.send_to_leaders(
                self.peer_leaders(), self._own_digest()
            )
            if evicted:
                self._fan_into_cell()

    def handle_message(self, msg) -> bool:
        """Dispatch seam for the service: returns True iff consumed."""
        if isinstance(msg, CellDigestMessage):
            self.handle_digest(msg)
            return True
        if isinstance(msg, GlobalViewMessage):
            self.handle_global_view(msg)
            return True
        return False

    def handle_digest(self, msg: CellDigestMessage) -> None:
        """A peer leader's announcement of its cell's row."""
        if msg.cell == self.my_cell:
            # our own cell's row is locally derived, never adopted from
            # the wire -- a partitioned stale leader cannot regress us
            return
        gate = self._digest_gate.get(msg.cell)
        if (
            gate is not None
            and gate[0] == msg.leader
            and msg.parent_round < gate[1]
        ):
            return  # reordered stale frame from the same leader
        self._digest_gate[msg.cell] = (msg.leader, msg.parent_round)
        first_contact = msg.cell not in self.global_view.cells
        moved = self.global_view.install(
            CellState(
                cell=msg.cell,
                epoch=msg.configuration_id,
                size=msg.membership_size,
                leader=msg.leader,
                fingerprint=msg.fingerprint,
            )
        )
        self._last_seen[msg.cell] = self._parent_round
        if not self.is_leader:
            # one-hop relay to our own rank-0 leader: parent traffic
            # addressed on a stale leader table (bootstrap hints, or a
            # sender that missed our leader failover) still reaches the
            # parent plane; leaders never relay, so no loops
            if self._channel is not None and self._leaders:
                self._channel.send_to_leaders([self._leaders[0]], msg)
            return
        if (moved or first_contact) and self._channel is not None:
            # symmetric introduction: the sender's row moved ours, so ours
            # (or its real leader) is likely news to the sender too --
            # reply with our own row; converges because install() is a
            # no-op once both sides agree
            self._channel.send_to_leaders([msg.sender], self._own_digest())
        if moved:
            self._fan_into_cell()

    def handle_global_view(self, msg: GlobalViewMessage) -> None:
        """Our own leader's composed view, fanned into the cell. Adopt
        every foreign row; our own cell's row stays locally derived.
        Reordered frames from the same leader are gated by its monotonic
        parent round."""
        sender = str(msg.sender)
        if sender == self._view_gate[0] and msg.parent_round < self._view_gate[1]:
            return
        self._view_gate = (sender, msg.parent_round)
        announced = set()
        for cell, epoch, size, leader, fingerprint in zip(
            msg.cells, msg.epochs, msg.sizes, msg.leaders, msg.fingerprints
        ):
            announced.add(cell)
            if cell == self.my_cell:
                continue
            if self.global_view.install(
                CellState(
                    cell=cell,
                    epoch=epoch,
                    size=size,
                    leader=leader,
                    fingerprint=fingerprint,
                )
            ):
                self._last_seen[cell] = self._parent_round
        # rows the leader no longer composes are evictions (e.g. a whole
        # cell aged out at the leader): adopt the removal too, or the
        # composed fingerprints would diverge leader-vs-followers forever
        for cell in list(self.global_view.cells):
            if cell != self.my_cell and cell not in announced:
                self.global_view.evict_cell(cell)
                self._last_seen.pop(cell, None)
                self._digest_gate.pop(cell, None)

    # ------------------------------------------------------------------ #
    # Announce path (leaders only)
    # ------------------------------------------------------------------ #

    def _own_digest(self) -> CellDigestMessage:
        own = self.global_view.cells[self.my_cell]
        return CellDigestMessage(
            sender=self._my_addr,
            cell=own.cell,
            configuration_id=own.epoch,
            membership_size=own.size,
            leader=own.leader,
            fingerprint=own.fingerprint,
            parent_round=self._parent_round,
        )

    def _announce(self) -> None:
        if self._channel is None:
            return
        self._channel.send_to_leaders(self.peer_leaders(), self._own_digest())
        self._fan_into_cell()

    def _fan_into_cell(self) -> None:
        if self._channel is None:
            return
        cells, epochs, sizes, leaders, fingerprints = self.global_view.digest()
        self._channel.send_to_cell(
            self._cell_members,
            GlobalViewMessage(
                sender=self._my_addr,
                parent_configuration_id=self.parent_configuration_id(),
                global_fingerprint=self.global_view.fingerprint(),
                cells=cells,
                epochs=epochs,
                sizes=sizes,
                leaders=leaders,
                fingerprints=fingerprints,
                parent_round=self._parent_round,
            ),
        )

    def _age_out(self) -> bool:
        """Drop foreign cells idle for ``eviction_rounds`` parent rounds.
        Only meaningful on leaders (followers' rounds don't advance)."""
        evicted = False
        for cell in list(self.global_view.cells):
            if cell == self.my_cell:
                continue
            seen = self._last_seen.get(cell, self._parent_round)
            if self._parent_round - seen >= self._eviction_rounds:
                self.global_view.evict_cell(cell)
                self._last_seen.pop(cell, None)
                self._digest_gate.pop(cell, None)
                evicted = True
        return evicted

    # ------------------------------------------------------------------ #
    # Status digest (cluster_status carriage)
    # ------------------------------------------------------------------ #

    def status_fields(self) -> Dict[str, object]:
        """The hierarchy fields of ClusterStatusResponse, ready to splat."""
        cells, epochs, sizes, leaders, _ = self.global_view.digest()
        return {
            "cell_id": self.my_cell,
            "cell_size": len(self._cell_members),
            "parent_configuration_id": self.parent_configuration_id(),
            "global_fingerprint": self.global_view.fingerprint(),
            "global_cells": cells,
            "global_epochs": epochs,
            "global_sizes": sizes,
            "global_leaders": leaders,
        }
