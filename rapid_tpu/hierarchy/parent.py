"""Parent configuration: leader election, per-cell epochs, composed view.

**Leader election is a pure function of the cell's view.** The leader set
of a cell is the first ``leaders_per_cell`` members of the cell in leader
order: ascending seeded endpoint hash (hashing.endpoint_hash with the
leader seed), endpoint as the tie-break -- the same deterministic-order
trick the K rings use, so leadership spreads uniformly instead of biasing
toward lexicographically small addresses. There is no leader *election
protocol*: any member that knows the cell's membership knows its leaders,
and a leader eviction is an ordinary intra-cell view change after which
everyone recomputes and the next member in leader order simply IS the
leader. Failover is a non-event by construction.

**The parent configuration** is the union of every cell's leader set. Its
configuration id is the chained ``h = h*37 + x`` fold (the exact
MembershipView.java:535-547 discipline, shared with
sim/topology.config_fold) over the sorted leader endpoints' hashes --
again a pure function of the composed state, so two members agree on the
parent configuration id iff they agree on who leads every cell.

**The composed global view** is one row per cell -- (cell id, config-id
epoch, membership size, leader) -- folded into a single global
fingerprint with the same chained hash. A cell's local configuration id
is its epoch: every intra-cell view change advances it, so the composed
fingerprint moves whenever any cell's membership moves and
``check_hierarchy_agreement`` can compare whole cluster states as single
integers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Sequence, Tuple

from ..hashing import endpoint_hash, xxh64, xxh64_long
from ..types import Endpoint

_MASK = (1 << 64) - 1
# seed for the leader ordering, disjoint from ring seeds and the cell
# rendezvous seeds so leader rank never correlates with either
_LEADER_SEED = 0x4C454144  # "LEAD"


def leader_key(endpoint: Endpoint) -> Tuple[int, bytes, int]:
    """Sort key of the deterministic leader order within a cell."""
    return (
        endpoint_hash(endpoint.hostname, endpoint.port, _LEADER_SEED),
        endpoint.hostname,
        endpoint.port,
    )


def cell_leaders(
    members: Sequence[Endpoint], leaders_per_cell: int = 1
) -> Tuple[Endpoint, ...]:
    """The cell's leader set: first ``leaders_per_cell`` members in leader
    order. Pure function of the membership -- no messages, no state."""
    ordered = sorted(members, key=leader_key)
    return tuple(ordered[: max(1, leaders_per_cell)])


def _fold(values: Iterable[int]) -> int:
    """Chained configuration fold (MembershipView.java:535-547): Java
    ``h = h * 37 + x`` over already-hashed 64-bit elements, returned as a
    signed 64-bit int (the configuration-id convention everywhere)."""
    h = 1
    for value in values:
        h = (h * 37 + (value & _MASK)) & _MASK
    return h - (1 << 64) if h >= (1 << 63) else h


def parent_configuration_id(leaders: Iterable[Endpoint]) -> int:
    """Configuration id of the parent (leader-set) configuration: the
    chained fold over the sorted leaders' endpoint hashes."""
    keys = sorted(
        endpoint_hash(ep.hostname, ep.port, 0) for ep in set(leaders)
    )
    return _fold(keys)


@dataclass(frozen=True)
class CellState:
    """One cell's row in the composed global view, as last reported by
    its leader (or derived locally for the member's own cell)."""

    cell: int
    epoch: int            # the cell's local configuration id
    size: int             # the cell's membership size
    leader: str           # "host:port" of the cell's rank-0 leader
    fingerprint: int = 0  # fold over the cell's sorted member hashes

    def row_hash(self) -> int:
        seed = self.cell & 0xFFFFFFFF
        return (
            xxh64_long(self.epoch, seed)
            ^ xxh64_long(self.size, seed + 1)
            ^ xxh64(self.leader.encode("utf-8"), seed + 2)
            ^ xxh64_long(self.fingerprint, seed + 3)
        )


def cell_fingerprint(members: Sequence[Endpoint]) -> int:
    """Fold over a cell's sorted member hashes -- the membership identity
    a digest carries so two leaders disagreeing about who is in the cell
    produce different composed fingerprints even at equal sizes."""
    return _fold(
        sorted(endpoint_hash(ep.hostname, ep.port, 0) for ep in members)
    )


def compose_fingerprint(rows: Iterable[CellState]) -> int:
    """The composed global fingerprint: chained fold over the per-cell
    row hashes in cell order. Single-integer equality == whole-cluster
    agreement on every cell's (epoch, size, leader, membership)."""
    ordered = sorted(rows, key=lambda r: r.cell)
    return _fold(r.row_hash() for r in ordered)


@dataclass
class GlobalView:  # guarded-by: protocol-executor
    """The composed two-level view: one CellState per known cell.

    Mutated only through :meth:`install`, which returns whether the
    composition actually moved -- the edge the plane uses to decide
    whether to re-announce to its cell."""

    cells: Dict[int, CellState] = field(default_factory=dict)

    def install(self, state: CellState) -> bool:
        """Adopt ``state`` for its cell; a row identical to the known one
        is a no-op (a leader restating the same view). Epochs are Rapid
        configuration ids -- chained hashes, NOT ordered -- so staleness
        cannot be judged here: the plane gates reordered frames by each
        sender's monotonic parent round before calling install
        (hierarchy/plane.py)."""
        known = self.cells.get(state.cell)
        if known == state:
            return False
        self.cells[state.cell] = state
        return True

    def evict_cell(self, cell: int) -> bool:
        """Drop a cell's row (the parent agreed the whole cell is gone)."""
        return self.cells.pop(cell, None) is not None

    def fingerprint(self) -> int:
        return compose_fingerprint(self.cells.values())

    def member_count(self) -> int:
        return sum(state.size for state in self.cells.values())

    def leaders(self) -> Tuple[str, ...]:
        return tuple(
            self.cells[cell].leader for cell in sorted(self.cells)
        )

    def rows(self) -> Tuple[CellState, ...]:
        return tuple(self.cells[cell] for cell in sorted(self.cells))

    def digest(self) -> Tuple[Tuple[int, ...], Tuple[int, ...],
                              Tuple[int, ...], Tuple[str, ...],
                              Tuple[int, ...]]:
        """Parallel (cells, epochs, sizes, leaders, fingerprints) arrays --
        the wire and statusz carriage shape."""
        rows = self.rows()
        return (
            tuple(r.cell for r in rows),
            tuple(r.epoch for r in rows),
            tuple(r.size for r in rows),
            tuple(r.leader for r in rows),
            tuple(r.fingerprint for r in rows),
        )
