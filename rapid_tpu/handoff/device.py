"""Vectorized transfer planning: the device-plane mirror of handoff/plan.py.

Same discipline as placement/device.py vs placement/engine.py: the object
plane plans transfers from ``PlacementMap`` rows; this module plans them
from the device plane's ``[P, R]`` assignment arrays, and both land on
bit-identical output for the same inputs (pinned in tests/test_handoff.py
and the golden vectors). The heavy work -- the moved-row mask, the row-wise
old/new membership masks, and the batched session-id hashes -- is numpy
over the whole map at once; only the per-moved-row donor/recipient pairing
walks Python, exactly like the engine's own diff loop walks only moved
partitions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..hashing import xxh64_batch_auto
from .plan import chunk_spans

__all__ = ["DeviceTransferPlan", "device_transfer_plans", "session_keys_batch"]

_MASK64 = (1 << 64) - 1


@dataclass(frozen=True)
class DeviceTransferPlan:
    """Slot-index form of :class:`~.plan.TransferPlan`: ``recipient`` and
    ``sources`` are candidate-slot indices into the device placement's
    universe instead of endpoints."""

    partition: int
    recipient: int
    sources: Tuple[int, ...]
    size: int
    chunks: Tuple[Tuple[int, int], ...]
    session_id: int


def session_keys_batch(
    new_version: int,
    partitions: np.ndarray,
    recipient_keys64: np.ndarray,
    seed: int,
) -> np.ndarray:
    """plan.session_key for many (partition, recipient) pairs at once:
    batched xxh64 over the packed 24-byte ``<QQQ`` blobs. Returns signed
    int64, bit-identical to the scalar path."""
    n = int(partitions.shape[0])
    blob = np.zeros((n, 24), dtype=np.uint8)
    version = np.full(n, new_version & _MASK64, dtype=np.uint64)
    shifts = (8 * np.arange(8, dtype=np.uint64))[None, :]
    blob[:, 0:8] = ((version[:, None] >> shifts) & np.uint64(0xFF)).astype(np.uint8)
    parts = partitions.astype(np.uint64)
    blob[:, 8:16] = ((parts[:, None] >> shifts) & np.uint64(0xFF)).astype(np.uint8)
    keys = recipient_keys64.astype(np.uint64)
    blob[:, 16:24] = ((keys[:, None] >> shifts) & np.uint64(0xFF)).astype(np.uint8)
    lengths = np.full(n, 24, dtype=np.int64)
    h = xxh64_batch_auto(blob, lengths, seed)
    return h.astype(np.uint64).view(np.int64)


def device_transfer_plans(
    old_assign: np.ndarray,
    new_assign: np.ndarray,
    new_active: np.ndarray,
    keys64: np.ndarray,
    new_version: int,
    seed: int,
    sizes: np.ndarray,
    chunk_size: int = 1 << 16,
) -> List[DeviceTransferPlan]:
    """Every transfer implied by old_assign -> new_assign, in the same
    (partition, new-row recipient) order as ``plan.plan_transfers``.

    ``old_assign`` / ``new_assign`` are ``[P, R]`` int32 slot ids (-1 for
    empty), ``new_active`` the new map's membership mask over the slot
    universe, ``sizes`` an int64[P] of partition byte sizes."""
    if old_assign.shape != new_assign.shape:
        raise ValueError("assignment shapes differ")
    # row-wise membership masks in one broadcast each: old slot i of row p
    # survives iff it appears anywhere in the new row, and vice versa
    valid_old = old_assign >= 0
    valid_new = new_assign >= 0
    eq = old_assign[:, :, None] == new_assign[:, None, :]  # [P, R, R]
    eq &= valid_old[:, :, None] & valid_new[:, None, :]
    old_in_new = eq.any(axis=2)
    new_in_old = eq.any(axis=1)
    moved_rows = np.flatnonzero((old_assign != new_assign).any(axis=1))

    # first pass: collect (partition, recipient slot) pairs so the session
    # ids hash in one batch, then assemble plans in the same order
    partitions: List[int] = []
    recipients: List[int] = []
    sources_per: List[Tuple[int, ...]] = []
    for p in moved_rows:
        p = int(p)
        donors = [
            int(s)
            for i, s in enumerate(old_assign[p])
            if s >= 0 and not old_in_new[p, i]
        ]
        row_recipients = [
            int(s)
            for j, s in enumerate(new_assign[p])
            if s >= 0 and not new_in_old[p, j]
        ]
        survivors = [
            int(s)
            for i, s in enumerate(old_assign[p])
            if s >= 0 and old_in_new[p, i]
        ]
        for i, recipient in enumerate(row_recipients):
            if i < len(donors):
                donor = donors[i]
            elif survivors:
                donor = survivors[0]
            else:
                donor = -1
            sources: List[int] = []
            if donor >= 0 and bool(new_active[donor]):
                sources.append(donor)
            for s in survivors:
                if s not in sources:
                    sources.append(s)
            partitions.append(p)
            recipients.append(recipient)
            sources_per.append(tuple(sources))
    if not partitions:
        return []
    part_arr = np.asarray(partitions, dtype=np.int64)
    rec_arr = np.asarray(recipients, dtype=np.int64)
    session_ids = session_keys_batch(
        new_version, part_arr, keys64[rec_arr], seed
    )
    plans: List[DeviceTransferPlan] = []
    for idx, (p, recipient, sources) in enumerate(
        zip(partitions, recipients, sources_per)
    ):
        size = int(sizes[p])
        plans.append(DeviceTransferPlan(
            partition=p,
            recipient=recipient,
            sources=sources,
            size=size,
            chunks=chunk_spans(size, chunk_size),
            session_id=int(session_ids[idx]),
        ))
    return plans
