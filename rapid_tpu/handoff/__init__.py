"""Handoff plane: partition state transfer driven by placement diffs.

The placement plane (placement/) makes every member derive a bit-identical
partition map from the strongly consistent view; this package moves the
bytes that map implies. A view change produces a :class:`PlacementDiff`;
the :class:`HandoffEngine` turns each moved partition into a versioned,
pull-based transfer *session* -- the new owner fetches chunks from a
surviving old replica, bounded in flight, resumable by (session id, chunk
offset), idempotent on duplicate delivery, and verified by an xxh64 content
fingerprint before it is acked. A corrupt or torn transfer is retried, a
dead source fails over to the next surviving replica.

Layout mirrors placement/: ``store.py`` is the application seam
(:class:`PartitionStore`), ``plan.py`` the pure object-plane planner whose
output is pinned in the golden vectors, ``device.py`` the vectorized mirror
of the planner, and ``engine.py`` the live session machinery wired into
service.py via ``ClusterBuilder.use_handoff``.
"""

from .engine import DEFAULT_CHUNK_SIZE, HandoffEngine
from .plan import TransferPlan, chunk_spans, content_fingerprint, plan_transfers, session_key
from .store import InMemoryPartitionStore, PartitionStore

__all__ = [
    "DEFAULT_CHUNK_SIZE",
    "HandoffEngine",
    "InMemoryPartitionStore",
    "PartitionStore",
    "TransferPlan",
    "chunk_spans",
    "content_fingerprint",
    "plan_transfers",
    "session_key",
]
