"""Live handoff sessions: the recipient-driven pull machinery.

One :class:`HandoffEngine` per member, wired by service.py. The engine is
both halves of the protocol:

- *source half*: answers ``HandoffRequest`` with a ``HandoffChunk`` sliced
  from the local :class:`~.store.PartitionStore` (stateless per request --
  resume costs the source nothing), and releases a partition on a verified
  ``HandoffAck`` once the new map no longer assigns it a replica.
- *recipient half*: ``start_sessions`` turns a placement diff into sessions
  (one per partition this member must acquire) and pulls chunks with a
  bounded in-flight window. Duplicate deliveries are dropped by offset
  (idempotent), a failed source advances to the next surviving replica with
  the already-received offsets kept (resumable), and completion is gated on
  the assembled content's xxh64 fingerprint matching the source's -- a
  corrupt transfer re-pulls instead of acking.

Transport-level retry/backoff/deadline discipline rides the messaging
clients themselves (messaging/retries.py: GrpcClient and the nemesis
decorator wrap ``send_message`` in ``call_with_retries`` with
``Settings.deadline_for``), so by the time a promise fails here the retry
budget for that source is spent and failover is the right response.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..runtime.lockdep import make_rlock
from ..observability import (
    HANDOFF_BYTES_BUCKETS,
    HANDOFF_CHUNKS_BUCKETS,
    Metrics,
    NullMetrics,
)
from ..placement.engine import node_key64
from ..types import Endpoint, HandoffAck, HandoffChunk, HandoffRequest
from .plan import (
    TransferPlan,
    chunk_spans,
    content_fingerprint,
    plan_transfers,
    session_key,
)
from .store import PartitionStore

DEFAULT_CHUNK_SIZE = 1 << 16
DEFAULT_MAX_INFLIGHT = 4
DEFAULT_VERIFY_ATTEMPTS = 3


class _Session:
    """One partition's in-progress pull. All mutation happens under the
    engine lock; ``done`` flips exactly once."""

    __slots__ = (
        "plan", "map_version", "source_idx", "received", "inflight",
        "total_size", "expected_fp", "schedule", "verify_attempts",
        "not_found_sources", "done", "failed", "span",
    )

    def __init__(self, plan: TransferPlan, map_version: int, span) -> None:
        self.plan = plan
        self.map_version = map_version
        self.source_idx = 0
        self.received: Dict[int, bytes] = {}
        self.inflight: set = set()
        self.total_size: Optional[int] = None
        self.expected_fp: Optional[int] = None
        self.schedule: Optional[Tuple[Tuple[int, int], ...]] = None
        self.verify_attempts = 0
        self.not_found_sources = 0
        self.done = False
        self.failed = False
        self.span = span

    def source(self) -> Endpoint:
        return self.plan.sources[self.source_idx]

    def reset_progress(self) -> None:
        """Drop assembled state for a fresh pull (verify retry / failover
        after a metadata conflict). In-flight offsets stay tracked; their
        late replies are reconciled against the new metadata on arrival."""
        self.received.clear()
        self.total_size = None
        self.expected_fp = None
        self.schedule = None


class HandoffEngine:
    """Session bookkeeping plus both protocol halves. Thread-safe: chunk
    promises complete on transport threads."""

    def __init__(
        self,
        store: PartitionStore,
        address: Endpoint,
        client,
        scheduler,
        *,
        metrics: Optional[Metrics] = None,
        tracer=None,
        recorder=None,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        max_inflight: int = DEFAULT_MAX_INFLIGHT,
        verify_attempts: int = DEFAULT_VERIFY_ATTEMPTS,
    ) -> None:
        if chunk_size <= 0:
            raise ValueError(f"chunk_size must be positive: {chunk_size}")
        if max_inflight <= 0:
            raise ValueError(f"max_inflight must be positive: {max_inflight}")
        self.store = store
        self.address = address
        self._client = client
        self._scheduler = scheduler
        self.metrics = metrics if metrics is not None else NullMetrics()
        self._tracer = tracer
        self._recorder = recorder
        self.chunk_size = chunk_size
        self.max_inflight = max_inflight
        self.verify_attempts = verify_attempts
        self._lock = make_rlock("HandoffEngine._lock")
        self._sessions: Dict[int, _Session] = {}
        self._completed = 0
        self._failed = 0

    # -- introspection ---------------------------------------------------- #

    def status(self) -> Tuple[int, int, int]:
        """(in-flight, completed, failed) session counts."""
        with self._lock:
            in_flight = sum(1 for s in self._sessions.values() if not s.done)
            return in_flight, self._completed, self._failed

    def idle(self) -> bool:
        with self._lock:
            return all(s.done for s in self._sessions.values())

    # -- source half ------------------------------------------------------ #

    def handle_request(self, msg: HandoffRequest) -> HandoffChunk:
        """Slice one chunk out of the local store. Stateless: the recipient
        owns all session state, so duplicated or replayed requests are
        answered identically (idempotent by construction)."""
        data = self.store.get(msg.partition)
        if data is None:
            return HandoffChunk(
                sender=self.address, session_id=msg.session_id,
                partition=msg.partition, offset=msg.offset,
                status=HandoffChunk.STATUS_NOT_FOUND,
            )
        fingerprint = self.store.fingerprint(msg.partition)
        if fingerprint is None:
            fingerprint = content_fingerprint(msg.partition, data)
        chunk = data[msg.offset : msg.offset + max(0, msg.length)]
        self.metrics.incr("handoff.chunks_sent")
        return HandoffChunk(
            sender=self.address, session_id=msg.session_id,
            partition=msg.partition, offset=msg.offset, data=chunk,
            total_size=len(data), fingerprint=fingerprint,
            status=HandoffChunk.STATUS_OK,
        )

    def handle_ack(self, msg: HandoffAck, still_replica: bool) -> None:
        """A recipient verified its copy. If the current map no longer
        assigns this member a replica of the partition, release the local
        copy -- completing the ownership move."""
        if still_replica:
            return
        if self.store.get(msg.partition) is None:
            return
        # Durability barrier BEFORE the release: the source copy is the
        # last line of defense for this partition until the recipient's
        # copy is stable, so a durable store must not discard it while its
        # own WAL still holds unfsynced records -- a crash straight after
        # the delete would otherwise recover to a state that neither holds
        # the partition nor can prove who does (pinned in
        # tests/test_advice_regressions.py). Duck-typed: the in-memory
        # store has no sync() and is untouched.
        sync = getattr(self.store, "sync", None)
        if sync is not None:
            sync()
        self.store.delete(msg.partition)
        self.metrics.incr("handoff.releases")
        if self._recorder is not None:
            self._recorder.record(
                "handoff_release", virtual_ms=self._now(),
                partition=msg.partition, session=msg.session_id,
                to=str(msg.sender),
            )

    # -- recipient half --------------------------------------------------- #

    def start_sessions(self, old_map, new_map) -> int:
        """Launch a session for every plan that names this member as the
        recipient. Duplicate launches for the same (map version, partition)
        are no-ops -- the deterministic session id dedups them."""
        plans = plan_transfers(old_map, new_map, chunk_size=self.chunk_size)
        return self._launch(
            [p for p in plans if p.recipient == self.address],
            new_map.version,
        )

    def bootstrap_sessions(self, new_map) -> int:
        """Launch pulls for every partition the map assigns this member but
        the local store lacks. This is the joiner path: a fresh member's
        first map has no predecessor, so it never sees the diff that names
        it the recipient -- yet pull-based transfer means only the recipient
        can launch. The failover chain is the partition's other current
        replicas (the likely holders) followed by every remaining member,
        so a row whose old replicas all rotated out still finds the bytes;
        if genuinely nobody holds the partition the session completes
        vacuously. Session ids match what the survivors' diffs would have
        planned for this recipient, keeping launches idempotent."""
        seed = new_map.config.seed
        rkey = node_key64(self.address, seed)
        plans: List[TransferPlan] = []
        for p, row in enumerate(new_map.assignments):
            if self.address not in row:
                continue
            if self.store.get(p) is not None:
                continue
            sources = [node for node in row if node != self.address]
            for node in new_map.members:
                if node != self.address and node not in sources:
                    sources.append(node)
            if not sources:
                continue
            plans.append(TransferPlan(
                partition=p, recipient=self.address,
                sources=tuple(sources), size=0, chunks=(),
                session_id=session_key(new_map.version, p, rkey, seed),
            ))
        return self._launch(plans, new_map.version)

    def _launch(self, plans: List[TransferPlan], map_version: int) -> int:
        started: List[_Session] = []
        with self._lock:
            for plan in plans:
                if plan.session_id in self._sessions:
                    continue
                span = None
                if self._tracer is not None:
                    span = self._tracer.begin(
                        "handoff_session", virtual_ms=self._now(),
                        partition=plan.partition, session=plan.session_id,
                        sources=len(plan.sources),
                    )
                session = _Session(plan, map_version, span)
                self._sessions[plan.session_id] = session
                started.append(session)
                self.metrics.incr("handoff.sessions_started")
        for session in started:
            if not session.plan.sources:
                with self._lock:
                    self._fail_locked(session)
            else:
                self._pump(session)
        return len(started)

    # -- session machinery ------------------------------------------------ #

    def _now(self) -> Optional[int]:
        if self._scheduler is None:
            return None
        return self._scheduler.now_ms()

    def _pump(self, session: _Session) -> None:
        """Issue chunk requests up to the in-flight window. Sends happen
        outside the lock: in-process transports can complete the promise on
        the calling thread, re-entering the engine."""
        to_send: List[Tuple[int, int]] = []
        with self._lock:
            if session.done:
                return
            if session.schedule is None:
                # size/fingerprint unknown (fresh session, or a failover
                # dropped the dead source's metadata): a single probe pull
                # for the first chunk carries the metadata on its reply
                if not session.inflight:
                    session.inflight.add(0)
                    to_send.append((0, self.chunk_size))
            else:
                for offset, length in session.schedule:
                    if len(session.inflight) >= self.max_inflight:
                        break
                    if offset in session.received or offset in session.inflight:
                        continue
                    session.inflight.add(offset)
                    to_send.append((offset, length))
                if (
                    not to_send and not session.inflight
                    and self._assembled_locked(session)
                ):
                    self._verify_locked(session)
                    return
        for offset, length in to_send:
            self._fetch(session, offset, length)

    def _fetch(self, session: _Session, offset: int, length: int) -> None:
        with self._lock:
            if session.done:
                session.inflight.discard(offset)
                return
            source = session.source()
            source_idx = session.source_idx
        request = HandoffRequest(
            sender=self.address, session_id=session.plan.session_id,
            partition=session.plan.partition, offset=offset, length=length,
            map_version=session.map_version,
        )
        promise = self._client.send_message(source, request)
        promise.add_callback(
            lambda p: self._on_reply(session, offset, source_idx, p)
        )

    def _on_reply(self, session: _Session, offset: int, source_idx: int,
                  promise) -> None:
        exc = promise.exception()
        reply = None if exc is not None else promise._result  # noqa: SLF001
        with self._lock:
            if session.done:
                return
            session.inflight.discard(offset)
            if exc is not None or not isinstance(reply, HandoffChunk):
                self._failover_locked(session, source_idx, not_found=False)
                return
            if reply.status != HandoffChunk.STATUS_OK:
                self._failover_locked(session, source_idx, not_found=True)
                return
            self.metrics.incr("handoff.chunks_received")
            self.metrics.incr("handoff.bytes_moved", len(reply.data))
            if session.expected_fp is None:
                session.expected_fp = reply.fingerprint
                session.total_size = reply.total_size
                session.schedule = chunk_spans(
                    reply.total_size, self.chunk_size
                )
            elif (
                reply.fingerprint != session.expected_fp
                or reply.total_size != session.total_size
            ):
                # the source's content changed under us (or a failover
                # landed on a replica with different bytes): what we have
                # assembled so far is unverifiable -- restart the pull
                # against the newly reported content
                self.metrics.incr("handoff.retries")
                session.reset_progress()
                session.expected_fp = reply.fingerprint
                session.total_size = reply.total_size
                session.schedule = chunk_spans(
                    reply.total_size, self.chunk_size
                )
            if offset in session.received:
                self.metrics.incr("handoff.chunks_duplicate")
            elif any(offset == o for o, _ in session.schedule):
                session.received[offset] = bytes(reply.data)
            if self._assembled_locked(session) and not session.inflight:
                self._verify_locked(session)
                return
        self._pump(session)

    def _assembled_locked(self, session: _Session) -> bool:
        return session.schedule is not None and all(
            offset in session.received for offset, _ in session.schedule
        )

    def _verify_locked(self, session: _Session) -> None:
        plan = session.plan
        data = b"".join(
            session.received[offset] for offset, _ in session.schedule
        )
        fingerprint = content_fingerprint(plan.partition, data)
        if fingerprint != session.expected_fp:
            self.metrics.incr("handoff.fingerprint_mismatches")
            session.verify_attempts += 1
            if session.verify_attempts >= self.verify_attempts:
                session.verify_attempts = 0
                self._failover_locked(
                    session, session.source_idx, not_found=False
                )
                return
            self.metrics.incr("handoff.retries")
            session.reset_progress()
            self._schedule_pump(session)
            return
        self.store.put(plan.partition, data)
        session.done = True
        self._completed += 1
        self.metrics.incr("handoff.sessions_completed")
        self.metrics.observe(
            "handoff.session_bytes", len(data), buckets=HANDOFF_BYTES_BUCKETS
        )
        self.metrics.observe(
            "handoff.session_chunks", len(session.schedule),
            buckets=HANDOFF_CHUNKS_BUCKETS,
        )
        if self._recorder is not None:
            self._recorder.record(
                "handoff_complete", virtual_ms=self._now(),
                partition=plan.partition, session=plan.session_id,
                bytes=len(data), source=str(session.source()),
            )
        if self._tracer is not None and session.span is not None:
            session.span.attrs["bytes"] = len(data)
            self._tracer.end(session.span, virtual_ms=self._now())
        # the ack below authorizes the source to discard its copy, so this
        # recipient's copy must be durable before the ack leaves: sync the
        # store (no-op on the in-memory reference store) ahead of the send
        sync = getattr(self.store, "sync", None)
        if sync is not None:
            sync()
        ack = HandoffAck(
            sender=self.address, session_id=plan.session_id,
            partition=plan.partition, fingerprint=fingerprint,
            map_version=session.map_version,
        )
        source = session.source()
        # best-effort: a lost ack only delays the source's release until
        # the next rebalance touches the partition
        self._client.send_message_best_effort(source, ack)

    def _failover_locked(self, session: _Session, source_idx: int,
                         not_found: bool) -> None:
        if session.done or source_idx != session.source_idx:
            # a stale failure from a source we already abandoned; the
            # offset was returned to the pool, just keep pulling
            self._schedule_pump(session)
            return
        if not_found:
            session.not_found_sources += 1
        session.source_idx += 1
        if session.source_idx >= len(session.plan.sources):
            if (
                session.not_found_sources == len(session.plan.sources)
                and len(session.plan.sources) > 0
            ):
                # every source is alive and none holds the partition:
                # there is genuinely no state to move
                session.done = True
                self._completed += 1
                self.metrics.incr("handoff.sessions_completed")
                if self._tracer is not None and session.span is not None:
                    session.span.attrs["empty"] = True
                    self._tracer.end(session.span, virtual_ms=self._now())
                return
            self._fail_locked(session)
            return
        self.metrics.incr("handoff.failovers")
        # the new source may hold different bytes than the dead one
        # reported; drop unverifiable metadata but KEEP received chunks --
        # replicas are normally identical, so the pull resumes from the
        # offsets already landed, and the metadata reconciliation in
        # _on_reply restarts it if the new source disagrees
        session.expected_fp = None
        session.total_size = None
        session.schedule = None
        self._schedule_pump(session)

    def _fail_locked(self, session: _Session) -> None:
        session.done = True
        session.failed = True
        self._failed += 1
        self.metrics.incr("handoff.sessions_failed")
        if self._recorder is not None:
            self._recorder.record(
                "handoff_failed", virtual_ms=self._now(),
                partition=session.plan.partition,
                session=session.plan.session_id,
                sources=len(session.plan.sources),
            )
        if self._tracer is not None and session.span is not None:
            session.span.attrs["failed"] = True
            self._tracer.end(session.span, virtual_ms=self._now())

    def _schedule_pump(self, session: _Session) -> None:
        """Re-enter _pump off the current stack: failovers can fire from a
        promise callback while _pump's send loop is still on the stack."""
        if self._scheduler is not None:
            self._scheduler.schedule(0, lambda: self._pump(session))
        else:
            self._pump(session)
