"""Pure transfer planning: PlacementDiff semantics -> per-partition sessions.

Everything here is a deterministic function of two placement maps plus the
partition sizes, with no I/O -- the same discipline as placement/engine.py.
The vectorized mirror in ``handoff/device.py`` reproduces these plans
bit-identically from the device plane's assignment arrays, and the golden
vectors pin both (tests/golden/).

Source-selection rule (mirrors ``engine.diff_maps`` pairing): for each moved
partition, departing old replicas (donors) are paired positionally with the
arriving new replicas (recipients); a recipient beyond the donor list pulls
from the partition's first surviving replica. The session's failover chain
is the paired donor (if it is still a member of the new map -- a crashed
donor is gone from the view and pointless to dial) followed by every
surviving replica in old-row order.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Tuple

from ..hashing import to_signed, xxh64
from ..placement.engine import PlacementMap, node_key64
from ..types import Endpoint

_MASK64 = (1 << 64) - 1

__all__ = [
    "TransferPlan",
    "chunk_spans",
    "content_fingerprint",
    "plan_transfers",
    "session_key",
]


def content_fingerprint(partition: int, data: bytes) -> int:
    """Signed xxh64 of a partition's content, seeded by the partition id so
    identical bytes in different partitions fingerprint differently."""
    return to_signed(xxh64(data, partition & 0x7FFFFFFF))


def session_key(new_version: int, partition: int, recipient_key64: int,
                seed: int) -> int:
    """Deterministic session id: signed xxh64 over (new map version,
    partition, recipient node key). Every member -- and the device plane --
    derives the same id without coordination, which is what makes duplicate
    session launches and duplicate chunk deliveries idempotent."""
    blob = struct.pack(
        "<QQQ", new_version & _MASK64, partition & _MASK64,
        recipient_key64 & _MASK64,
    )
    return to_signed(xxh64(blob, seed))


def chunk_spans(size: int, chunk_size: int) -> Tuple[Tuple[int, int], ...]:
    """The (offset, length) schedule for a partition of ``size`` bytes.
    Empty content needs no chunks -- the session completes on the first
    (metadata-only) chunk reply."""
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive: {chunk_size}")
    return tuple(
        (offset, min(chunk_size, size - offset))
        for offset in range(0, size, chunk_size)
    )


@dataclass(frozen=True)
class TransferPlan:
    """One partition's planned movement to one new replica.

    ``sources`` is the failover chain in preference order; ``chunks`` the
    (offset, length) pull schedule for the planned ``size`` (the live engine
    re-derives it from the source-reported size, same arithmetic)."""

    partition: int
    recipient: Endpoint
    sources: Tuple[Endpoint, ...]
    size: int
    chunks: Tuple[Tuple[int, int], ...]
    session_id: int


def plan_transfers(
    old_map: PlacementMap,
    new_map: PlacementMap,
    sizes: Optional[Mapping[int, int]] = None,
    chunk_size: int = 1 << 16,
) -> Tuple[TransferPlan, ...]:
    """Every transfer implied by the old->new map change, in (partition,
    new-row recipient order). Must stay in lockstep with
    ``placement.engine.diff_maps`` -- same moved set, same donor/recipient
    pairing -- and with ``handoff.device.device_transfer_plans``."""
    if old_map.config != new_map.config:
        raise ValueError("cannot plan across different placement configs")
    sizes = sizes if sizes is not None else {}
    members = set(new_map.members)
    seed = new_map.config.seed
    key_cache: Dict[Endpoint, int] = {}
    plans: List[TransferPlan] = []
    for p, (old_row, new_row) in enumerate(
        zip(old_map.assignments, new_map.assignments)
    ):
        if old_row == new_row:
            continue
        donors = [node for node in old_row if node not in new_row]
        recipients = [node for node in new_row if node not in old_row]
        survivors = [node for node in old_row if node in new_row]
        for i, recipient in enumerate(recipients):
            donor: Optional[Endpoint] = (
                donors[i] if i < len(donors)
                else (survivors[0] if survivors else None)
            )
            sources: List[Endpoint] = []
            if donor is not None and donor in members:
                sources.append(donor)
            for node in survivors:
                if node not in sources:
                    sources.append(node)
            size = int(sizes.get(p, 0))
            rkey = key_cache.get(recipient)
            if rkey is None:
                rkey = key_cache[recipient] = node_key64(recipient, seed)
            plans.append(TransferPlan(
                partition=p,
                recipient=recipient,
                sources=tuple(sources),
                size=size,
                chunks=chunk_spans(size, chunk_size),
                session_id=session_key(new_map.version, p, rkey, seed),
            ))
    return tuple(plans)
