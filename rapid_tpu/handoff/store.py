"""The partition-store seam between the handoff plane and the application.

The handoff engine never interprets partition content -- it moves opaque
bytes and verifies their xxh64 fingerprint. Applications plug in whatever
storage they have by implementing :class:`PartitionStore`;
:class:`InMemoryPartitionStore` is the reference implementation used by the
tests, the simulator, and the examples.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, Optional, Tuple

from ..runtime.lockdep import make_lock
from .plan import content_fingerprint


class PartitionStore(ABC):
    """Opaque per-partition byte storage keyed by partition id.

    Implementations must be safe to call from transport callback threads;
    ``fingerprint`` must equal ``content_fingerprint(partition, get(...))``
    for stored partitions, since replicas cross-check it over the wire."""

    @abstractmethod
    def get(self, partition: int) -> Optional[bytes]:
        """Full content of ``partition``, or None if not stored here."""

    @abstractmethod
    def put(self, partition: int, data: bytes) -> None:
        """Store (replacing) the full content of ``partition``."""

    @abstractmethod
    def delete(self, partition: int) -> None:
        """Drop ``partition`` if present (no-op otherwise)."""

    @abstractmethod
    def partitions(self) -> Tuple[int, ...]:
        """Sorted ids of every partition stored here."""

    def fingerprint(self, partition: int) -> Optional[int]:
        """Signed xxh64 of the partition's content (None if not stored)."""
        data = self.get(partition)
        if data is None:
            return None
        return content_fingerprint(partition, data)


class InMemoryPartitionStore(PartitionStore):
    """Reference store: a locked dict of partition id -> bytes, with the
    fingerprint maintained on write so status digests are O(partitions)
    lookups rather than O(bytes) rehashes."""

    def __init__(self) -> None:
        self._lock = make_lock("InMemoryPartitionStore._lock")
        self._data: Dict[int, bytes] = {}
        self._fingerprints: Dict[int, int] = {}

    def get(self, partition: int) -> Optional[bytes]:
        with self._lock:
            return self._data.get(partition)

    def put(self, partition: int, data: bytes) -> None:
        fp = content_fingerprint(partition, data)
        with self._lock:
            self._data[partition] = bytes(data)
            self._fingerprints[partition] = fp

    def delete(self, partition: int) -> None:
        with self._lock:
            self._data.pop(partition, None)
            self._fingerprints.pop(partition, None)

    def partitions(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._data))

    def fingerprint(self, partition: int) -> Optional[int]:
        with self._lock:
            return self._fingerprints.get(partition)

    def sizes(self) -> Dict[int, int]:
        """Partition id -> content length (planner input)."""
        with self._lock:
            return {p: len(d) for p, d in self._data.items()}

    def digest(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Parallel (partition ids, fingerprints) arrays, id-sorted -- the
        shape ClusterStatusResponse carries for cross-replica checks."""
        with self._lock:
            ids = tuple(sorted(self._data))
            return ids, tuple(self._fingerprints[p] for p in ids)
