"""Pallas TPU kernel for the fused failure-detection phase of the round step.

The round step splits into (a) an elementwise chain over the per-edge [C, K]
state -- probe outcome, cumulative FD counter update, threshold crossing,
alert latch -- and (b) permutation gathers along the ring adjacency (alert
routing, flux lookups). The gathers are exactly the access pattern XLA's TPU
gather lowering is built for and stay in stock jax; the elementwise chain is
the Pallas fit: one VMEM-resident kernel producing all four outputs per tile,
with no intermediate HBM round-trips between them.

Layout notes: the [C, K] per-edge arrays are processed in row tiles of
``block_rows`` x K with K padded to the 128-lane boundary by the caller's
choice of tile (K=10 << 128, so rows are the parallel axis; int32/bool lanes
vectorize on the VPU's 8x128 shape).

Validated in interpret mode against the stock-jax formulation
(tests/test_pallas_kernels.py) and bit-identical on real TPU hardware
(v5 lite, tests/test_pallas_kernels.py::test_hardware_kernel_matches_stock,
opt-in via RAPID_TPU_PALLAS_HW=1).

**Verdict: NOT wired into the engine.** Both halves of the question were
measured on a real v5e chip:

1. Elementwise-only kernel (this file): stock XLA is FASTER (1.6 ms vs
   2.4 ms per call at [100k, 10]). K=10 occupies 10 of 128 VPU lanes per
   row tile, so the hand-written kernel wastes lane parallelism that XLA's
   layout assignment recovers by reshaping.
2. The hypothesized win -- fusing the dst-indexed arrival gather
   (``take_along_axis(new_down, observers, axis=0)``) into the same
   VMEM-resident kernel -- does not lower: Mosaic rejects the dynamic
   cross-row gather (MosaicError, v5e toolchain, 2026-07). The gather must
   stay in stock jax, where XLA's TPU gather lowering already fuses the
   producing elementwise chain into it.

With neither path winning, the engine runs pure stock jax (the former
``SimConfig.pallas_fd`` flag is deleted); this module remains as the
measured exemplar of the Pallas seam, kept compiling and bit-identical by
its tests. It would only be worth rewiring for K padded near the 128-lane
width, or if a future Mosaic supports in-kernel row gathers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.jitwatch import make_jit, make_pallas_call


def _fd_phase_kernel(
    edge_live_ref,  # bool[B, K] edge exists (active obs & active subj)
    observer_up_ref,  # bool[B, K] observer alive this round
    probe_ok_ref,  # bool[B, K] probe would succeed (target up & not dropped)
    fd_fail_ref,  # int32[B, K] cumulative failures (input)
    alerted_ref,  # bool[B, K] already-alerted latch (input)
    threshold_ref,  # int32[1, 1] FD threshold (SMEM)
    fd_fail_out_ref,  # int32[B, K]
    alerted_out_ref,  # bool[B, K]
    new_down_out_ref,  # bool[B, K]
):
    edge_live = edge_live_ref[:]
    observer_up = observer_up_ref[:]
    fail_event = edge_live & observer_up & ~probe_ok_ref[:]
    fd_fail = fd_fail_ref[:] + fail_event.astype(jnp.int32)
    new_down = (
        edge_live
        & observer_up
        & (fd_fail >= threshold_ref[0, 0])
        & ~alerted_ref[:]
    )
    fd_fail_out_ref[:] = fd_fail
    alerted_out_ref[:] = alerted_ref[:] | new_down
    new_down_out_ref[:] = new_down


# ``block_rows`` is a compile-time tile-size knob (a handful of values per
# process), not a per-call-varying shape.  # devlint: static-shape
@functools.partial(make_jit, "sim.pallas.fd_phase",
                   static_argnames=("threshold", "block_rows", "interpret"))
def fd_phase(
    edge_live: jax.Array,
    observer_up: jax.Array,
    probe_ok: jax.Array,
    fd_fail: jax.Array,
    alerted: jax.Array,
    threshold: int,
    block_rows: int = 1024,
    interpret: bool = False,
):
    """Fused probe/counter/alert phase. Returns (fd_fail, alerted, new_down).

    Semantics (must stay in lockstep with engine.step's stock-jax fallback):
      fail_event = edge_live & observer_up & ~probe_ok
      fd_fail   += fail_event                       (cumulative, never reset:
                                                     PingPongFailureDetector.java:116-118)
      new_down   = edge_live & observer_up & fd_fail>=threshold & ~alerted
      alerted   |= new_down
    """
    c, k = fd_fail.shape
    block_rows = min(block_rows, c)
    if c % block_rows != 0:
        # fall back to one whole-array block for awkward capacities
        block_rows = c
    grid = (c // block_rows,)

    def row_spec():
        return pl.BlockSpec((block_rows, k), lambda i: (i, 0), memory_space=pltpu.VMEM)

    out = make_pallas_call(
        "sim.pallas.fd_phase_kernel",
        _fd_phase_kernel,
        grid=grid,
        in_specs=[
            row_spec(),  # edge_live
            row_spec(),  # observer_up
            row_spec(),  # probe_ok
            row_spec(),  # fd_fail
            row_spec(),  # alerted
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[row_spec(), row_spec(), row_spec()],
        out_shape=[
            jax.ShapeDtypeStruct((c, k), jnp.int32),
            jax.ShapeDtypeStruct((c, k), jnp.bool_),
            jax.ShapeDtypeStruct((c, k), jnp.bool_),
        ],
        interpret=interpret,
    )(
        edge_live,
        observer_up,
        probe_ok,
        fd_fail,
        alerted,
        jnp.full((1, 1), threshold, jnp.int32),
    )
    return tuple(out)
