"""Host-side control plane: vectorized ring/adjacency construction.

The sim's equivalent of MembershipView for N up to 100k: all K ring orderings
are computed at once with the batched xxHash64 (rapid_tpu.hashing.xxh64_batch)
and numpy argsorts -- bit-identical ordering to the JVM reference's seeded
TreeSets (Utils.java:211-230), so the observer/subject adjacency and the
configuration identity of the simulated cluster match what real Rapid nodes
would compute.

Ring construction happens only at configuration changes (rare); the per-round
protocol work stays on device (rapid_tpu.sim.engine).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..hashing import endpoint_hash_batch, pack_hostnames, xxh64_batch

_U64 = np.uint64


@dataclass
class VirtualCluster:
    """Identity of up to ``capacity`` virtual nodes; row index == node id."""

    hostnames: np.ndarray  # [C, max_len] uint8
    host_lengths: np.ndarray  # [C] int64
    ports: np.ndarray  # [C] int64
    id_high: np.ndarray  # [C] int64  (NodeId.high, Java signed)
    id_low: np.ndarray  # [C] int64
    # per-ring endpoint hashes, computed once: [K, C] uint64
    ring_hashes: np.ndarray

    @property
    def capacity(self) -> int:
        return len(self.ports)

    @staticmethod
    def synthesize(capacity: int, k: int, seed: int = 0) -> "VirtualCluster":
        """Synthetic but *realistic* identities: distinct host:port strings and
        UUID-style node ids, hashed exactly as the JVM would."""
        rng = np.random.default_rng(seed)
        hostnames = [
            f"10.{i >> 16 & 0xFF}.{i >> 8 & 0xFF}.{i & 0xFF}".encode()
            for i in range(capacity)
        ]
        data, lengths = pack_hostnames(hostnames)
        ports = np.full(capacity, 5000, dtype=np.int64) + (
            np.arange(capacity, dtype=np.int64) % 1000
        )
        id_high = rng.integers(-(2**63), 2**63, size=capacity, dtype=np.int64)
        id_low = rng.integers(-(2**63), 2**63, size=capacity, dtype=np.int64)
        from .. import native

        ring_hashes = native.ring_hashes(data, lengths, ports, k)
        if ring_hashes is None:
            ring_hashes = np.stack(
                [endpoint_hash_batch(data, lengths, ports, ring) for ring in range(k)]
            )
        return VirtualCluster(
            hostnames=data,
            host_lengths=lengths,
            ports=ports,
            id_high=id_high,
            id_low=id_low,
            ring_hashes=ring_hashes,
        )


def build_adjacency(
    cluster: VirtualCluster, active: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """subjects[C, K] and observers[C, K] over the active membership.

    subjects[i, k] is the ring-k predecessor of node i (the node i monitors,
    MembershipView.java:309-323); observers[i, k] the ring-k successor
    (MembershipView.java:235-258). Inactive rows are set to the node itself.
    """
    from .. import native

    native_result = native.build_adjacency(cluster.ring_hashes, active)
    if native_result is not None:
        return native_result

    k_rings, capacity = cluster.ring_hashes.shape
    subjects = np.tile(np.arange(capacity, dtype=np.int32)[:, None], (1, k_rings))
    observers = subjects.copy()
    active_idx = np.flatnonzero(active)
    n = len(active_idx)
    if n <= 1:
        return subjects, observers
    signed = cluster.ring_hashes[:, active_idx].view(np.int64)
    for ring in range(k_rings):
        order = np.argsort(signed[ring], kind="stable")  # ring order, signed-hash domain
        ring_nodes = active_idx[order]
        preds = np.roll(ring_nodes, 1)
        succs = np.roll(ring_nodes, -1)
        subjects[ring_nodes, ring] = preds
        observers[ring_nodes, ring] = succs
    return subjects, observers


def ring_order(cluster: VirtualCluster, active: np.ndarray, ring: int = 0) -> np.ndarray:
    """Active node ids in ring-``ring`` order (the reference's getRing)."""
    active_idx = np.flatnonzero(active)
    signed = cluster.ring_hashes[ring, active_idx].view(np.int64)
    return active_idx[np.argsort(signed, kind="stable")]


def configuration_id_vectorized(
    id_high: np.ndarray,
    id_low: np.ndarray,
    hostnames: np.ndarray,
    host_lengths: np.ndarray,
    ports: np.ndarray,
) -> int:
    """Chained configuration hash (MembershipView.java:535-547), vectorized.

    The fold h = h*37 + x_i over m elements equals
    ``37^m + sum_i x_i * 37^(m-1-i)`` (mod 2^64); with precomputed power
    ladders this is O(m) vector ops instead of an O(m) Python loop.
    Inputs must already be ordered: identifiers by NodeId order, endpoints in
    ring-0 order.
    """
    with np.errstate(over="ignore"):
        id_high_h = xxh64_batch(
            id_high.astype(np.int64).view(np.uint64)[:, None].view(np.uint8).reshape(-1, 8),
            np.full(len(id_high), 8, dtype=np.int64),
            0,
        )
        id_low_h = xxh64_batch(
            id_low.astype(np.int64).view(np.uint64)[:, None].view(np.uint8).reshape(-1, 8),
            np.full(len(id_low), 8, dtype=np.int64),
            0,
        )
        host_h = xxh64_batch(hostnames, host_lengths, 0)
        port_bytes = np.zeros((len(ports), 4), dtype=np.uint8)
        p = ports.astype(np.uint32)
        for i in range(4):
            port_bytes[:, i] = ((p >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(np.uint8)
        port_h = xxh64_batch(port_bytes, np.full(len(ports), 4, dtype=np.int64), 0)

        # interleave: id_high_0, id_low_0, id_high_1, ... then host_0, port_0, ...
        ids = np.empty(2 * len(id_high), dtype=_U64)
        ids[0::2] = id_high_h
        ids[1::2] = id_low_h
        eps = np.empty(2 * len(ports), dtype=_U64)
        eps[0::2] = host_h
        eps[1::2] = port_h
        xs = np.concatenate([ids, eps])
        m = len(xs)
        # pw[t] = 37^t mod 2^64 (uint64 cumprod wraps modulo 2^64)
        pw = np.ones(m + 1, dtype=_U64)
        if m:
            pw[1:] = np.cumprod(np.full(m, 37, dtype=_U64))
        powers = pw[:m][::-1]  # [37^(m-1), ..., 37^0]
        # h = 1*37^m + sum x_j * 37^(m-1-j)
        total = pw[m] + (xs * powers).sum(dtype=_U64)
    as_signed = int(total.astype(np.int64))
    return as_signed
