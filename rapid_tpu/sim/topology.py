"""Host-side control plane: vectorized ring/adjacency construction.

The sim's equivalent of MembershipView for N up to 100k: all K ring orderings
are computed at once with the batched xxHash64 (rapid_tpu.hashing.xxh64_batch)
and numpy argsorts -- bit-identical ordering to the JVM reference's seeded
TreeSets (Utils.java:211-230), so the observer/subject adjacency and the
configuration identity of the simulated cluster match what real Rapid nodes
would compute.

Ring construction happens only at configuration changes (rare); the per-round
protocol work stays on device (rapid_tpu.sim.engine).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..hashing import endpoint_hash_batch, xxh64_batch

_U64 = np.uint64


def _int64_le_bytes(values: np.ndarray) -> np.ndarray:
    """[N] int64 -> [N, 8] uint8 little-endian rows (hashLong input layout)."""
    return (
        values.astype(np.int64).view(np.uint64)[:, None]
        .view(np.uint8).reshape(-1, 8)
    )


def _port_le_bytes(ports: np.ndarray) -> np.ndarray:
    """[N] ports -> [N, 4] uint8 little-endian rows (hashInt input layout)."""
    out = np.zeros((len(ports), 4), dtype=np.uint8)
    p = ports.astype(np.uint32)
    for i in range(4):
        out[:, i] = ((p >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(np.uint8)
    return out


@dataclass
class VirtualCluster:
    """Identity of up to ``capacity`` virtual nodes; row index == node id."""

    hostnames: np.ndarray  # [C, max_len] uint8
    host_lengths: np.ndarray  # [C] int64
    ports: np.ndarray  # [C] int64
    id_high: np.ndarray  # [C] int64  (NodeId.high, Java signed)
    id_low: np.ndarray  # [C] int64
    # per-ring endpoint hashes, computed once: [K, C] uint64
    ring_hashes: np.ndarray
    # lazy caches (identities are immutable, so these never invalidate)
    _full_order: Optional[np.ndarray] = None  # [K, C] stable argsort per ring
    _ring_rank: Optional[np.ndarray] = None  # [K, C] inverse of _full_order
    _node_hashes: Optional[Tuple[np.ndarray, ...]] = None  # config-id inputs

    @property
    def capacity(self) -> int:
        return len(self.ports)

    def full_ring_order(self) -> np.ndarray:
        """Stable argsort of every ring over the full capacity, cached.

        The ring order of any active subset is the stable filter of this
        order (a subsequence of a sorted sequence is sorted; stable ties
        resolve by node id in both), so adjacency rebuilds at view changes
        are O(C) masking instead of O(C log C) sorting.
        """
        if self._full_order is None:
            signed = self.ring_hashes.view(np.int64)
            self._full_order = np.argsort(
                signed, axis=1, kind="stable"
            ).astype(np.int32)
        return self._full_order

    def ring_rank(self) -> np.ndarray:
        """Each node's position in the full-capacity ring order, per ring
        ([K, C] int32, the inverse permutation of full_ring_order). Ranks are
        distinct and order-equivalent to the signed hashes, so devices can
        rebuild adjacency by sorting int32 ranks instead of 64-bit keys."""
        if self._ring_rank is None:
            order = self.full_ring_order()
            k, c = order.shape
            rank = np.empty((k, c), dtype=np.int32)
            cols = np.arange(c, dtype=np.int32)
            for ring in range(k):
                rank[ring, order[ring]] = cols
            self._ring_rank = rank
        return self._ring_rank

    def node_hashes(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-node xxHash64 inputs to the configuration-id fold, cached:
        (id_high_h, id_low_h, host_h, port_h), each uint64[C]. The chained
        fold (MembershipView.java:535-547) hashes each element independently
        before folding, so per-node hashes are membership-invariant."""
        if self._node_hashes is None:
            from ..hashing import xxh64_batch_auto

            n = self.capacity
            eight = np.full(n, 8, dtype=np.int64)
            self._node_hashes = (
                xxh64_batch_auto(_int64_le_bytes(self.id_high), eight),
                xxh64_batch_auto(_int64_le_bytes(self.id_low), eight),
                xxh64_batch_auto(self.hostnames, self.host_lengths),
                xxh64_batch_auto(
                    _port_le_bytes(self.ports), np.full(n, 4, dtype=np.int64)
                ),
            )
        return self._node_hashes

    def assign_identity(
        self, slot: int, hostname: bytes, port: int, id_high: int, id_low: int
    ) -> None:
        """Replace a slot's identity (endpoint + NodeId) -- used by the
        messaging bridge to seat a *real* process in a spare virtual slot so
        it participates in ring construction and configuration identity
        exactly like a synthesized node. Only the slot's column of the ring
        hashes and element hashes is recomputed; order caches rebuild lazily."""
        from ..hashing import endpoint_hash, xxh64

        if len(hostname) > self.hostnames.shape[1]:
            grown = np.zeros(
                (self.capacity, len(hostname)), dtype=np.uint8
            )
            grown[:, : self.hostnames.shape[1]] = self.hostnames
            self.hostnames = grown
        self.hostnames[slot, :] = 0
        self.hostnames[slot, : len(hostname)] = np.frombuffer(hostname, np.uint8)
        self.host_lengths[slot] = len(hostname)
        self.ports[slot] = port
        self.id_high[slot] = id_high
        self.id_low[slot] = id_low
        for ring in range(self.ring_hashes.shape[0]):
            self.ring_hashes[ring, slot] = np.uint64(
                endpoint_hash(hostname, port, ring)
            )
        if self._node_hashes is not None:
            high_h, low_h, host_h, port_h = self._node_hashes
            high_h[slot] = np.uint64(xxh64(_int64_le_bytes(
                np.array([id_high], dtype=np.int64))[0].tobytes()))
            low_h[slot] = np.uint64(xxh64(_int64_le_bytes(
                np.array([id_low], dtype=np.int64))[0].tobytes()))
            host_h[slot] = np.uint64(xxh64(hostname))
            port_h[slot] = np.uint64(xxh64(_port_le_bytes(
                np.array([port], dtype=np.int64))[0].tobytes()))
        self._full_order = None
        self._ring_rank = None

    @staticmethod
    def synthesize(capacity: int, k: int, seed: int = 0) -> "VirtualCluster":
        """Synthetic but *realistic* identities: distinct host:port strings and
        UUID-style node ids, hashed exactly as the JVM would."""
        rng = np.random.default_rng(seed)
        # vectorized "10.a.b.c" construction (np.char.mod is a C-level
        # sprintf; a Python f-string loop over 1M rows costs whole seconds):
        # the <S14 bytes view is zero-padded exactly like pack_hostnames
        idx = np.arange(capacity, dtype=np.int64)
        octet = [np.char.mod("%d", (idx >> s) & 0xFF) for s in (16, 8, 0)]
        dotted = np.char.add("10", np.char.add(".", octet[0]))
        for part in octet[1:]:
            dotted = np.char.add(dotted, np.char.add(".", part))
        packed = dotted.astype("S")
        lengths = np.char.str_len(packed).astype(np.int64)
        data = np.ascontiguousarray(packed.view(np.uint8)).reshape(
            capacity, packed.dtype.itemsize
        )
        ports = np.full(capacity, 5000, dtype=np.int64) + (
            np.arange(capacity, dtype=np.int64) % 1000
        )
        id_high = rng.integers(-(2**63), 2**63, size=capacity, dtype=np.int64)
        id_low = rng.integers(-(2**63), 2**63, size=capacity, dtype=np.int64)
        from .. import native

        ring_hashes = native.ring_hashes(data, lengths, ports, k)
        if ring_hashes is None:
            ring_hashes = np.stack(
                [endpoint_hash_batch(data, lengths, ports, ring) for ring in range(k)]
            )
        return VirtualCluster(
            hostnames=data,
            host_lengths=lengths,
            ports=ports,
            id_high=id_high,
            id_low=id_low,
            ring_hashes=ring_hashes,
        )


@dataclass(frozen=True)
class LatencyTopology:
    """Deterministic rack/zone/region placement with a tiered RTT model.

    Node ``i`` lives in rack ``i % racks``, zone ``rack % zones``, region
    ``zone % regions`` -- pure functions of the index, so the same topology
    object describes the protocol plane (endpoints mapped to indices by the
    fault plane) and the device plane (slots ARE indices) with no shared
    state. The RTT between two nodes is the widest tier that separates them:

        same rack    -> rack_rtt_ms      (ToR switch hop)
        same zone    -> zone_rtt_ms      (aggregation fabric)
        same region  -> region_rtt_ms    (inter-zone backbone)
        cross-region -> inter_region_rtt_ms  (WAN)

    Everything derives from these five integers; there is no RNG anywhere,
    so a topology is replayable bit-identically wherever it is consulted.
    """

    racks: int = 4
    zones: int = 2
    regions: int = 1
    rack_rtt_ms: int = 0
    zone_rtt_ms: int = 1
    region_rtt_ms: int = 2
    inter_region_rtt_ms: int = 150

    def __post_init__(self) -> None:
        if not (self.racks >= self.zones >= self.regions >= 1):
            raise ValueError(
                f"need racks >= zones >= regions >= 1, got "
                f"{self.racks}/{self.zones}/{self.regions}"
            )
        if not (0 <= self.rack_rtt_ms <= self.zone_rtt_ms
                <= self.region_rtt_ms <= self.inter_region_rtt_ms):
            raise ValueError("tier RTTs must be non-decreasing outward")

    # -- placement (pure functions of the node index) -----------------------

    def rack_of(self, i: int) -> int:
        return i % self.racks

    def zone_of(self, i: int) -> int:
        return self.rack_of(i) % self.zones

    def region_of(self, i: int) -> int:
        return self.zone_of(i) % self.regions

    # -- latency -------------------------------------------------------------

    def rtt_ms(self, i: int, j: int) -> int:
        if i == j:
            return 0
        if self.region_of(i) != self.region_of(j):
            return self.inter_region_rtt_ms
        if self.zone_of(i) != self.zone_of(j):
            return self.region_rtt_ms
        if self.rack_of(i) != self.rack_of(j):
            return self.zone_rtt_ms
        return self.rack_rtt_ms

    def one_way_ms(self, i: int, j: int) -> int:
        return self.rtt_ms(i, j) // 2

    def rtt_matrix(self, n: int) -> np.ndarray:
        """[n, n] int32 RTT matrix, vectorized over the tier comparisons."""
        idx = np.arange(n, dtype=np.int64)
        rack = idx % self.racks
        zone = rack % self.zones
        region = zone % self.regions
        out = np.full((n, n), self.rack_rtt_ms, dtype=np.int32)
        out[rack[:, None] != rack[None, :]] = self.zone_rtt_ms
        out[zone[:, None] != zone[None, :]] = self.region_rtt_ms
        out[region[:, None] != region[None, :]] = self.inter_region_rtt_ms
        np.fill_diagonal(out, 0)
        return out

    # -- device-plane compilation helpers ------------------------------------

    def group_assignment(self, capacity: int) -> np.ndarray:
        """Per-slot delivery group (= zone id) for
        ``Simulator.set_delivery_groups``: zones are the unit of broadcast
        heterogeneity on the device plane."""
        idx = np.arange(capacity, dtype=np.int64)
        return ((idx % self.racks) % self.zones).astype(np.int32)

    def delay_rounds(self, zone_a: int, zone_b: int, round_ms: int) -> int:
        """One-way broadcast latency between two zones in whole device
        rounds (floor: sub-round latency is absorbed by the round model,
        mirroring the fault plane's DelayRule compilation rule)."""
        if zone_a == zone_b:
            return 0
        if zone_a % self.regions != zone_b % self.regions:
            return (self.inter_region_rtt_ms // 2) // round_ms
        return (self.region_rtt_ms // 2) // round_ms


def build_adjacency(
    cluster: VirtualCluster, active: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """subjects[C, K] and observers[C, K] over the active membership.

    subjects[i, k] is the ring-k predecessor of node i (the node i monitors,
    MembershipView.java:309-323); observers[i, k] the ring-k successor
    (MembershipView.java:235-258). Inactive rows are set to the node itself.

    Rebuilds filter the cached full-capacity ring order (O(C·K) masking)
    rather than re-sorting per configuration.
    """
    full_order = cluster.full_ring_order()
    k_rings, capacity = cluster.ring_hashes.shape
    subjects = np.tile(np.arange(capacity, dtype=np.int32)[:, None], (1, k_rings))
    observers = subjects.copy()
    if int(active.sum()) <= 1:
        return subjects, observers
    for ring in range(k_rings):
        fo = full_order[ring]
        ring_nodes = fo[active[fo]]
        preds = np.roll(ring_nodes, 1)
        succs = np.roll(ring_nodes, -1)
        subjects[ring_nodes, ring] = preds
        observers[ring_nodes, ring] = succs
    return subjects, observers


def ring_order(cluster: VirtualCluster, active: np.ndarray, ring: int = 0) -> np.ndarray:
    """Active node ids in ring-``ring`` order (the reference's getRing)."""
    fo = cluster.full_ring_order()[ring]
    return fo[active[fo]]


def configuration_id_vectorized(
    id_high: np.ndarray,
    id_low: np.ndarray,
    hostnames: np.ndarray,
    host_lengths: np.ndarray,
    ports: np.ndarray,
) -> int:
    """Chained configuration hash (MembershipView.java:535-547), vectorized.

    The fold h = h*37 + x_i over m elements equals
    ``37^m + sum_i x_i * 37^(m-1-i)`` (mod 2^64); with precomputed power
    ladders this is O(m) vector ops instead of an O(m) Python loop.
    Inputs must already be ordered: identifiers by NodeId order, endpoints in
    ring-0 order.
    """
    with np.errstate(over="ignore"):
        id_high_h = xxh64_batch(
            _int64_le_bytes(id_high), np.full(len(id_high), 8, dtype=np.int64), 0
        )
        id_low_h = xxh64_batch(
            _int64_le_bytes(id_low), np.full(len(id_low), 8, dtype=np.int64), 0
        )
        host_h = xxh64_batch(hostnames, host_lengths, 0)
        port_bytes = _port_le_bytes(ports)
        port_h = xxh64_batch(port_bytes, np.full(len(ports), 4, dtype=np.int64), 0)

    return config_fold(id_high_h, id_low_h, host_h, port_h)


_POWER_LADDER = np.ones(1, dtype=_U64)  # [37^0, 37^1, ...], grown on demand


def _powers_of_37(m: int) -> np.ndarray:
    """[37^0 .. 37^m] mod 2^64, served from a module-level ladder cache (the
    fold runs on every view change; the ladder only depends on length)."""
    global _POWER_LADDER
    if len(_POWER_LADDER) <= m:
        n = len(_POWER_LADDER)
        grown = np.empty(m + 1, dtype=_U64)
        grown[:n] = _POWER_LADDER
        with np.errstate(over="ignore"):
            grown[n:] = _POWER_LADDER[n - 1] * np.cumprod(
                np.full(m + 1 - n, 37, dtype=_U64)
            )
        _POWER_LADDER = grown
    return _POWER_LADDER[: m + 1]


def config_fold(
    id_high_h: np.ndarray,
    id_low_h: np.ndarray,
    host_h: np.ndarray,
    port_h: np.ndarray,
) -> int:
    """Fold already-hashed elements into the chained configuration identity.

    Inputs are the per-element xxHash64 values, identifiers ordered by NodeId,
    endpoints in ring-0 order (e.g. gathered from VirtualCluster.node_hashes).
    """
    with np.errstate(over="ignore"):
        # interleave: id_high_0, id_low_0, id_high_1, ... then host_0, port_0, ...
        ids = np.empty(2 * len(id_high_h), dtype=_U64)
        ids[0::2] = id_high_h
        ids[1::2] = id_low_h
        eps = np.empty(2 * len(port_h), dtype=_U64)
        eps[0::2] = host_h
        eps[1::2] = port_h
        xs = np.concatenate([ids, eps])
        from .. import native

        native_total = native.config_fold(xs)
        if native_total is not None:
            return native_total
        m = len(xs)
        pw = _powers_of_37(m)
        powers = pw[:m][::-1]  # [37^(m-1), ..., 37^0]
        # h = 1*37^m + sum x_j * 37^(m-1-j)
        total = pw[m] + (xs * powers).sum(dtype=_U64)
    as_signed = int(total.astype(np.int64))
    return as_signed
