"""TpuSimMessaging: real protocol-plane nodes against TPU-hosted virtual peers.

This is the bridge the reference's plugin seams exist for: the untouched
``Cluster``/``MembershipService`` stack (rapid_tpu.cluster, the analogue of
the untouched Java API) runs against a swarm of N *virtual* nodes whose rings,
failure detection, cut detection, and fast-round vote tallies live as device
arrays in the TPU simulator (rapid_tpu.sim). The bridge crosses exactly the
two seams the reference defines -- messaging (IMessagingClient/Server,
IMessagingClient.java:25-48) and edge failure detection -- and nothing else:
real nodes join through the standard two-phase protocol (Cluster.java:303-474),
probe their virtual subjects, broadcast alerts, receive fast-round votes, and
apply view changes through their own untouched consensus path.

How each protocol interaction crosses the bridge:

- **Join** (real node -> swarm): phase 1 seats the joiner's identity in a
  spare virtual slot (so ring order and configuration identity include it
  bit-exactly); phase 2 parks the per-observer responses and registers the
  join with the simulator; when the simulated cut decides, the parked
  responses complete with the full configuration -- the same
  park-until-view-change-commits flow as MembershipService.java:229-286.
- **Probes** (real node -> virtual subject): answered from the simulator's
  liveness plane; a crashed virtual node fails the probe promise, driving the
  real node's own PingPong counters.
- **Alerts** (real node -> all): DOWN alerts about virtual nodes are injected
  into the simulated report tables (Simulator.inject_down_report), so a real
  observer's evidence counts toward the swarm's H/L watermarks.
- **Votes** (real node -> swarm): a real member's fast-round vote counts.
  Its slot's ``auto_vote`` is cleared when the identity is seated, so the
  engine never casts a vote on its behalf; when a proposal is announced but
  undecided, the bridge broadcasts the proposed cut to real members *before*
  the decision (``pump`` phase B), their own cut detectors propose, and the
  FastRoundPhase2bMessages they broadcast back are registered into the
  device tally (Simulator.register_extern_vote) -- interned as extern
  proposal rows that pool with identical group proposals. A real member can
  therefore complete a quorum the virtual members alone cannot reach, or
  block it by voting a conflicting value (forcing the classic fallback).
- **Decisions** (swarm -> real members): when the simulator decides a cut,
  every real member of the pre-decision configuration receives (a) one
  batched alert carrying the joiner UUIDs/metadata the view change will need
  and (b) fast-round votes (FastRoundPhase2bMessage) from live virtual
  members; the real node's own FastPaxos then reaches the 3/4 supermajority
  and applies the view change itself -- including firing KICKED if it was cut.
- **Leave** (real node -> observers): converted to the simulator's proactive
  leave, deciding in ~2 rounds (alert hop + vote hop).
- **Real-node liveness** (swarm side): a real node is sensed alive while its
  server is registered on the network; when it disappears (crash or
  shutdown), the swarm marks its slot dead and the *simulated* failure
  detectors remove it through the normal 10-round threshold cut.
"""

from __future__ import annotations

import dataclasses
import logging
import pickle
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.lockdep import make_lock
from ..runtime.futures import Promise
from ..service import address_comparator_key
from ..types import (
    AlertMessage,
    BatchedAlertMessage,
    ConsensusResponse,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    FastRoundVoteBatch,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidMessage,
    Response,
)
from .driver import Simulator, ViewChangeRecord
from .engine import SimConfig
from .topology import ring_order

LOG = logging.getLogger(__name__)

_CONSENSUS_TYPES = (
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)


def _failed(exc: BaseException) -> Promise:
    p: Promise = Promise()
    p.set_exception(exc)
    return p


class TpuSimMessaging:  # guarded-by: sim-loop
    """A multi-endpoint handler on an InProcessNetwork hosting N virtual
    nodes in the TPU simulator (the BASELINE.json north star's plugin)."""

    def __init__(
        self,
        network,
        n_virtual: int,
        capacity: Optional[int] = None,
        config: Optional[SimConfig] = None,
        seed: int = 0,
        mesh=None,
    ) -> None:
        """``mesh``: a jax.sharding.Mesh to host the swarm sharded over
        multiple devices (shard.engine) -- the full composition: external
        protocol-plane members against a mesh-sharded device swarm. The
        capacity must divide evenly over the mesh's devices."""
        if capacity is None:
            capacity = config.capacity if config is not None else n_virtual + 16
        if mesh is not None:
            # row-sharded state must divide evenly over the mesh's devices
            n_dev = int(np.prod(list(mesh.shape.values())))
            capacity = ((capacity + n_dev - 1) // n_dev) * n_dev
        if config is None:
            config = SimConfig(capacity=capacity)
        elif config.capacity != capacity:
            config = dataclasses.replace(config, capacity=capacity)
        if config.extern_proposals == 0:
            # extern rows so real members' votes can be interned as proposal
            # values (register_extern_vote); 4 covers the common regimes --
            # real members agreeing with the swarm pool into one row
            config = dataclasses.replace(config, extern_proposals=4)
        self.sim = Simulator(
            n_virtual, capacity=capacity, config=config, seed=seed, mesh=mesh
        )
        self.network = network
        network.attach_handler(self)
        self._init_caches()
        self._slot_of: Dict[Endpoint, int] = {}
        for slot in range(n_virtual):
            self._slot_of[self._endpoint(slot)] = slot
        self._free_slots: Deque[int] = deque(range(n_virtual, capacity))
        self._real: Dict[Endpoint, int] = {}
        # joiner endpoint -> [(observer endpoint, parked promise)]
        self._parked: Dict[Endpoint, List[Tuple[Endpoint, Promise]]] = {}
        self._metadata: Dict[Endpoint, tuple] = {}
        # configuration id whose announced proposal was already broadcast to
        # real members (pump phase B runs once per configuration)
        self._informed_config: Optional[int] = None
        # last decision packet, for catching up members whose delivery was
        # lost (they reveal themselves by sending traffic stamped with the
        # pre-decision configuration id); _replay_counts bounds replays per
        # member per decision; _prior_configs identifies members stale beyond
        # what a replay can fix (they get cut, like any faulty member)
        self._last_decision: Optional[tuple] = None
        self._replay_counts: Dict[Endpoint, int] = {}
        self._prior_configs: Deque[int] = deque(maxlen=8)
        # stale-cut tolerance: repeated sightings of one stale config before
        # a member is declared beyond repair (a single occurrence can be an
        # in-flight frame racing a pair of quick decisions)
        self._stale_counts: Dict[Tuple[Endpoint, int], int] = {}

    # ------------------------------------------------------------------ #
    # checkpoint / resume (SURVEY.md section 5.4, extended to the bridge)
    # ------------------------------------------------------------------ #

    def save(self, path: str) -> None:
        """Persist the swarm configuration plus the bridge's real-member
        plane (which slots are owned by external processes, and their
        metadata). Parked join responses are deliberately NOT persisted -- a
        restarted gateway, like a restarted Rapid process, makes in-flight
        joiners retry (Cluster.java:313-344's retry loop handles it)."""
        import pickle

        real_slots = np.array(sorted(self._real.values()), dtype=np.int64)
        blob = pickle.dumps({"metadata": dict(self._metadata)})
        self.sim.save_configuration(
            path,
            extra={
                "real_slots": real_slots,
                "bridge_blob": np.frombuffer(blob, dtype=np.uint8),
            },
        )

    @classmethod
    def restore(
        cls,
        network,
        path: str,
        config_overrides: Optional[dict] = None,
        mesh=None,
    ) -> "TpuSimMessaging":
        """Rebuild a bridge swarm from a snapshot: same configuration id,
        same real-member slot ownership. Live real members keep their seats
        (their processes sense nothing but a transport blip); dead ones are
        detected and cut by the restored simulated FDs as usual.

        SimConfig fields the snapshot does not persist (fd_policy/fd_window,
        rounds_per_interval, delivery-group faults, ...) reset to defaults;
        pass ``config_overrides`` to re-apply them. extern_proposals defaults
        to 4 (the bridge needs extern rows for real members' votes)."""
        overrides = {"extern_proposals": 4}
        overrides.update(config_overrides or {})
        sim = Simulator.from_configuration(
            path, mesh=mesh, config_overrides=overrides
        )
        with np.load(path) as data:
            real_slots = [int(s) for s in data["extra_real_slots"]]
            blob = pickle.loads(data["extra_bridge_blob"].tobytes())

        bridge = cls.__new__(cls)
        bridge.sim = sim
        bridge.network = network
        network.attach_handler(bridge)
        bridge._init_caches()
        capacity = sim.config.capacity
        # map ONLY currently-seated endpoints: active slots plus real
        # members' seats. Mapping every capacity slot would resurrect stale
        # endpoint->slot entries for previously-cut members and never-seated
        # spares -- a rejoining agent would then be found "already seated"
        # and never re-enter _real (votes dropped, liveness unmonitored),
        # while its slot simultaneously sat in the free list
        real_set = {int(s) for s in real_slots}
        bridge._slot_of = {}
        for slot in range(capacity):
            if sim.active[slot] or slot in real_set:
                host, port = sim.endpoint_of(slot)
                bridge._slot_of[Endpoint(host, port)] = slot
        bridge._real = {
            bridge._endpoint(slot): slot for slot in real_slots
        }
        for slot in real_slots:
            sim.set_auto_vote(slot, False)
        bridge._free_slots = deque(
            s for s in range(capacity)
            if not sim.active[s] and s not in real_set
        )
        bridge._parked = {}
        bridge._metadata = dict(blob["metadata"])
        bridge._informed_config = None
        bridge._last_decision = None
        bridge._replay_counts = {}
        bridge._stale_counts = {}
        bridge._prior_configs = deque(maxlen=8)
        return bridge

    # ------------------------------------------------------------------ #
    # identity helpers
    # ------------------------------------------------------------------ #

    def _init_caches(self) -> None:
        """Identity/configuration caches (shared by __init__ and restore).

        At 100k virtual nodes a single join otherwise rebuilds ~1M Endpoint
        objects (K observers each stream the full configuration,
        Cluster.java:442-474 / rapid.proto:74-83) -- the dominant cost
        VERDICT r3 item 5 measured at 50-90 s per joiner. Slot endpoints are
        immutable between identity re-seatings, and the configuration
        content is immutable within a configuration id, so both cache
        exactly."""
        self._ep_cache: Dict[int, Endpoint] = {}
        # config id -> (endpoints, identifiers, metadata) of the full
        # JoinResponse; serialized content is bit-identical to the uncached
        # build, so parity is untouched
        self._config_content: Optional[Tuple[int, tuple, tuple, tuple]] = None
        self._config_responses: Dict[Endpoint, JoinResponse] = {}
        # joiners already streamed the full configuration this join attempt
        # (sender -> configuration id): the sibling phase-2 responses of the
        # SAME attempt answer CONFIG_CHANGED instead of re-streaming the
        # multi-megabyte configuration K times through one socket; a fresh
        # PreJoin (every retry starts with one, Cluster.java:313-344) clears
        # the mark, so a lost full-config response self-heals via retry
        self._streamed: Dict[Endpoint, int] = {}
        # the decision's two wire messages, built ONCE (identity-stable so
        # the codec's packed-body memo makes every delivery reuse one
        # encode): (config id, alert batch, vote batch, src endpoint)
        self._decision_packet: Optional[tuple] = None
        # pre-decision config id -> packet, newest last (bounded like
        # _prior_configs): lets a lagging member be walked FORWARD packet by
        # packet instead of being cut the moment one decision supersedes
        # another mid-chain
        self._packet_history: "OrderedDict[int, tuple]" = OrderedDict()
        # members whose decision chain failed (member -> missed config id):
        # the pump actively re-drives these -- probes carry no configuration
        # id, so passive stale-sighting repair alone can strand a quiescent
        # member. Mutated from delivery-callback threads.
        self._undelivered: Dict[Endpoint, int] = {}
        self._chain_inflight: set = set()
        self._undelivered_lock = make_lock("SwarmBridge._undelivered_lock")

    def _endpoint(self, slot: int) -> Endpoint:
        ep = self._ep_cache.get(slot)
        if ep is None:
            host, port = self.sim.endpoint_of(slot)
            ep = self._ep_cache[slot] = Endpoint(host, port)
        return ep

    def _node_id(self, slot: int) -> NodeId:
        return NodeId(
            int(self.sim.cluster.id_high[slot]), int(self.sim.cluster.id_low[slot])
        )

    def endpoint(self, slot: int) -> Endpoint:
        """A virtual node's address (e.g. a join seed for real nodes)."""
        return self._endpoint(slot)

    def virtual_members(self) -> List[Endpoint]:
        return [
            self._endpoint(s)
            for s in self.sim.members()
            if self._endpoint(s) not in self._real
        ]

    # ------------------------------------------------------------------ #
    # network handler interface
    # ------------------------------------------------------------------ #

    def owns(self, address: Endpoint) -> bool:
        return address in self._slot_of and address not in self._real

    def handle(self, dst: Endpoint, msg: RapidMessage) -> Promise:
        broadcastable = self._handle_broadcastable(msg)
        if broadcastable is not None:
            return broadcastable
        slot = self._slot_of[dst]
        if isinstance(msg, ProbeMessage):
            if self.sim.active[slot] and self.sim.alive[slot]:
                return Promise.completed(ProbeResponse())
            return _failed(ConnectionError(f"virtual node {dst} is down"))
        if isinstance(msg, PreJoinMessage):
            return Promise.completed(self._handle_pre_join(dst, msg))
        if isinstance(msg, JoinMessage):
            return self._handle_join(dst, msg)
        return _failed(TypeError(f"unexpected message {type(msg).__name__}"))

    def handle_broadcast(self, msg: RapidMessage) -> Promise:
        """A real member's broadcast collapsed to one frame (the gateway's
        wildcard destination): ingest the dst-independent traffic exactly
        once. Semantically identical to the N unicast copies -- alert
        batches and votes are absorbed per *sender* (the device delivers
        them to every virtual member as array work), so the copies were
        redundant. Unicast-only messages (probes, joins) are refused."""
        broadcastable = self._handle_broadcastable(msg)
        if broadcastable is not None:
            return broadcastable
        return _failed(
            TypeError(f"{type(msg).__name__} cannot be swarm-broadcast")
        )

    def _handle_broadcastable(self, msg: RapidMessage) -> Optional[Promise]:
        """The destination-independent message types (None = not one)."""
        if isinstance(msg, BatchedAlertMessage):
            if msg.messages:
                self._maybe_catch_up(
                    msg.sender, msg.messages[0].configuration_id
                )
            self._absorb_alerts(msg)
            return Promise.completed(Response())
        if isinstance(msg, FastRoundPhase2bMessage):
            self._maybe_catch_up(msg.sender, msg.configuration_id)
            self._register_real_vote(msg)
            return Promise.completed(ConsensusResponse())
        if isinstance(msg, _CONSENSUS_TYPES):
            # classic-round traffic from real members is acknowledged; the
            # swarm's recovery exchange (Simulator._run_classic_round over
            # sim/classic.py's device acceptor state) represents their slots
            # as acceptors, with their registered fast votes as vvals
            return Promise.completed(ConsensusResponse())
        if isinstance(msg, LeaveMessage):
            sender_slot = self._slot_of.get(msg.sender)
            if (
                sender_slot is not None
                and self.sim.active[sender_slot]
                and self.sim.alive[sender_slot]
                and sender_slot not in self.sim.pending_leavers
            ):
                self.sim.leave(np.array([sender_slot]))
            return Promise.completed(Response())
        return None

    def warm_compile(self) -> None:
        """Compile every executable the steady-state pump can hit, BEFORE
        agents arrive: at large capacities a 20-40 s jit compile landing on
        the protocol thread mid-join-wave starves every joiner past its
        phase-1 retry budget (the r4 50-joiner failure). Covers the no-op
        probe variants (plain + announcement-stop), the full decision path
        (_apply_view_change / ring rebuild / speculation -- only compiled at
        the FIRST decision, which warm-by-probe never reaches), and the
        classic-fallback phases. The decision path runs on a throwaway twin
        simulator: the jit cache is keyed by shapes + statics, so the twin's
        executables ARE the real ones, and the real sim's protocol state
        (membership, configuration id, clock) is untouched."""
        import jax.numpy as jnp

        from . import classic

        sim = self.sim
        sim.run_until_decision(max_rounds=1, batch=1)
        sim.run_until_decision(max_rounds=1, batch=1, stop_when_announced=True)
        spare = Simulator(
            sim.config.capacity, config=sim.config, seed=104729,
            mesh=sim.mesh,
        )
        spare.crash(np.array([0]))
        rec = spare.run_until_decision(max_rounds=32, batch=8)
        assert rec is not None, "warm twin failed to decide"
        deliver = spare._deliver  # noqa: SLF001
        group_of = spare.group_of
        hears = jnp.asarray(deliver[group_of, 0])
        coord_hears = jnp.asarray(deliver[group_of[0], :])
        resp = jnp.full(sim.config.capacity, 2, dtype=jnp.int32)
        rank = jnp.int32(classic.make_rank(2, 0))
        state1, _ = classic.phase1(
            spare.config, spare.state, rank, hears, coord_hears, resp
        )
        classic.phase2(
            spare.config, state1, rank, jnp.int32(0), hears, coord_hears,
            resp,
        )
        sim.ready()

    # ------------------------------------------------------------------ #
    # join protocol (swarm side)
    # ------------------------------------------------------------------ #

    def _handle_pre_join(self, dst: Endpoint, msg: PreJoinMessage) -> JoinResponse:
        """Phase-1 gatekeeping at a virtual seed (MembershipService.java:200-221)."""
        # a new attempt begins: its phase 2 may stream the full config once
        self._streamed.pop(msg.sender, None)
        slot = self._slot_of.get(msg.sender)
        if slot is not None and self.sim.active[slot]:
            status = JoinStatusCode.HOSTNAME_ALREADY_IN_RING
        elif self.sim.is_identifier_seen(msg.node_id.high, msg.node_id.low):
            return JoinResponse(
                sender=dst,
                status_code=JoinStatusCode.UUID_ALREADY_IN_RING,
                configuration_id=self.sim.configuration_id(),
            )
        else:
            status = JoinStatusCode.SAFE_TO_JOIN
            if slot is None:
                if not self._free_slots:
                    return JoinResponse(
                        sender=dst,
                        status_code=JoinStatusCode.MEMBERSHIP_REJECTED,
                        configuration_id=self.sim.configuration_id(),
                    )
                slot = self._free_slots.popleft()
                self._slot_of[msg.sender] = slot
                self._real[msg.sender] = slot
            # a retry -- or a rejoin after removal -- re-seats the same slot
            # with the fresh UUID; the identifier history is value-based, so
            # the slot's past identities stay in the configuration-id fold.
            # While a phase-2 join is pending the identity is already seated
            # (the client retries phase 1 with the same UUID, Cluster.java:313-344).
            if slot not in self.sim.pending_joiners:
                self._ep_cache.pop(slot, None)  # slot re-seated: new identity
                self.sim.assign_identity(
                    slot,
                    msg.sender.hostname,
                    msg.sender.port,
                    msg.node_id.high,
                    msg.node_id.low,
                )
                # the engine must not cast votes for a real member's slot:
                # only its actually-received votes count (_register_real_vote)
                self.sim.set_auto_vote(slot, False)
        # expected observers = ring predecessors, for present members too
        # (MembershipView.java:293-304; service._handle_pre_join returns them
        # for HOSTNAME_ALREADY_IN_RING as well)
        observer_slots, _ = self.sim.expected_observers(slot)
        return JoinResponse(
            sender=dst,
            status_code=status,
            configuration_id=self.sim.configuration_id(),
            endpoints=tuple(self._endpoint(int(s)) for s in observer_slots),
        )

    def _handle_join(self, dst: Endpoint, msg: JoinMessage) -> Promise:
        """Phase-2 at a virtual observer: park until the simulated view change
        commits (MembershipService.java:229-286)."""
        slot = self._slot_of.get(msg.sender)
        current = self.sim.configuration_id()
        if slot is None:
            return Promise.completed(
                JoinResponse(
                    sender=dst,
                    status_code=JoinStatusCode.CONFIG_CHANGED,
                    configuration_id=current,
                )
            )
        if msg.configuration_id != current:
            if self.sim.active[slot]:
                # the cut already admitted this joiner; stream the config --
                # to the FIRST of this attempt's K observer messages only
                # (the joiner accepts one response; re-streaming the
                # multi-MB configuration K times through one socket starved
                # the gateway at scale). Siblings answer CONFIG_CHANGED,
                # which the join client ignores when a valid response
                # exists, and a lost full response heals via retry: the
                # next attempt's PreJoin clears the mark.
                if self._streamed.get(msg.sender) == current:
                    return Promise.completed(
                        JoinResponse(
                            sender=dst,
                            status_code=JoinStatusCode.CONFIG_CHANGED,
                            configuration_id=current,
                        )
                    )
                self._streamed[msg.sender] = current
                return Promise.completed(self._full_config_response(dst))
            return Promise.completed(
                JoinResponse(
                    sender=dst,
                    status_code=JoinStatusCode.CONFIG_CHANGED,
                    configuration_id=current,
                )
            )
        parked: Promise = Promise()
        self._parked.setdefault(msg.sender, []).append((dst, parked))
        if msg.metadata:
            self._metadata[msg.sender] = msg.metadata
        if slot not in self.sim.pending_joiners:
            self.sim.request_joins(np.array([slot]))
        return parked

    def _full_config_response(self, sender: Endpoint) -> JoinResponse:
        """The SAFE_TO_JOIN response streaming the full configuration. The
        content (endpoints in ring-0 order, identifier history, metadata) is
        a pure function of the configuration id, and every one of a joiner's
        K observers -- and every joiner of the same configuration -- streams
        the same one (Cluster.java:442-474), so it is built once per
        configuration and reused; only the per-observer ``sender`` field
        varies. Sharing the same tuple objects also lets the wire codec
        reuse its encoding of them (codec._enc tuple memo)."""
        sim = self.sim
        config_id = sim.configuration_id()
        cached = self._config_content
        if cached is None or cached[0] != config_id:
            order0 = ring_order(sim.cluster, sim.active, 0)
            endpoints = tuple(self._endpoint(int(s)) for s in order0)
            identifiers = tuple(
                NodeId(int(h), int(l)) for h, l in sim.sorted_identifiers()
            )
            metadata = tuple(
                (ep, md)
                for ep, md in self._metadata.items()
                if sim.active[self._slot_of[ep]]
            )
            cached = self._config_content = (
                config_id, endpoints, identifiers, metadata
            )
            self._config_responses = {}
        # one response OBJECT per (configuration, sender): the codec's
        # packed-body memo is identity-keyed, so reusing the object makes
        # msgpack run once per configuration instead of once per send
        response = self._config_responses.get(sender)
        if response is None:
            response = JoinResponse(
                sender=sender,
                status_code=JoinStatusCode.SAFE_TO_JOIN,
                configuration_id=config_id,
                endpoints=cached[1],
                identifiers=cached[2],
                metadata=cached[3],
            )
            self._config_responses[sender] = response
        return response

    # ------------------------------------------------------------------ #
    # votes from real members
    # ------------------------------------------------------------------ #

    def _register_real_vote(self, msg: FastRoundPhase2bMessage) -> None:
        """Count a real member's fast-round vote in the device tally. The
        message's endpoint list is its proposed cut; unknown endpoints (not
        hosted by this swarm) make the value unrepresentable and the vote is
        dropped, like any best-effort loss."""
        sender_slot = self._slot_of.get(msg.sender)
        if (
            sender_slot is None
            or msg.sender not in self._real
            or not self.sim.active[sender_slot]
            or msg.configuration_id != self.sim.configuration_id()
        ):
            return
        cut_slots = [
            self._slot_of[ep] for ep in msg.endpoints if ep in self._slot_of
        ]
        if len(cut_slots) != len(msg.endpoints):
            LOG.warning(
                "vote from %s names endpoints outside the swarm; dropped",
                msg.sender,
            )
            return
        self.sim.register_extern_vote(sender_slot, np.array(cut_slots))

    _MAX_REPLAYS = 3
    _STALE_STRIKES_TO_CUT = 3  # repeated sightings of one stale config

    def _maybe_catch_up(self, sender: Endpoint, config_id: int) -> None:
        """Keep lagging members from being stranded. A member stuck exactly
        one decision behind (its delivery was lost) gets the decision packet
        replayed -- up to _MAX_REPLAYS times per decision, since a replay can
        be lost too; the replay is idempotent on the member's side (votes
        dedup per sender, FastPaxos.java:134-141, stale alerts are filtered).
        A member stale beyond the last decision cannot be repaired by votes
        (each FastPaxos instance is per-configuration), so it is cut like any
        faulty member -- Rapid's answer to a node that falls behind is
        removal and rejoin."""
        packet = self._last_decision
        if packet is None or sender not in self._real:
            return
        if config_id in self._packet_history:
            count = self._replay_counts.get(sender, 0)
            if count >= self._MAX_REPLAYS:
                return
            self._replay_counts[sender] = count + 1
            LOG.info(
                "replaying decision %d to lagging member %s (attempt %d)",
                config_id, sender, count + 1,
            )
            self._deliver_decision_chain(
                sender, self._packet_history[config_id]
            )
        elif config_id in self._prior_configs:
            # a single old-config frame can be an in-flight race against two
            # quick decisions (a join wave); only REPEATED sightings of the
            # same stale configuration mean the member is truly stranded
            strikes = self._stale_counts.get((sender, config_id), 0) + 1
            self._stale_counts[(sender, config_id)] = strikes
            if strikes < self._STALE_STRIKES_TO_CUT:
                return
            slot = self._real[sender]
            if self.sim.active[slot] and self.sim.alive[slot]:
                LOG.warning(
                    "member %s is stale beyond the last decision; cutting it "
                    "(rejoin required)",
                    sender,
                )
                self.sim.crash(np.array([slot]))

    def _deliver_decision_chain(
        self, member: Endpoint, packet: Optional[tuple] = None
    ) -> None:
        """Deliver one decision to one member: the UUID-carrying alert batch
        first, the quorum-completing vote batch ONLY after the alerts
        succeed. Delivering votes to a member whose alert leg was lost would
        make it decide a proposal whose joiner identities it never saw --
        the reference's disabled-assert NPE path
        (MembershipService.java:396).

        On success, if newer decisions committed meanwhile, the member is
        walked FORWARD through the packet history one decision at a time
        (FastPaxos is per-configuration: each packet only applies to a
        member sitting exactly at its pre-decision configuration). On
        failure the member is recorded in ``_undelivered`` and the pump
        re-drives the chain: FD probes carry no configuration id, so a
        quiescent lagging member emits nothing stale and passive
        sighting-based repair alone would strand it."""
        if packet is None:
            packet = self._decision_packet
        if packet is None:
            return
        config_id, alert_msg, votes_msg, src, after_id = packet
        with self._undelivered_lock:
            if member in self._chain_inflight:
                # a chain for an earlier decision is still in flight; its
                # settle() walks forward from the then-current history, so
                # this newer decision is NOT lost (dropping it here was the
                # staircase bug: members stuck at their join-era
                # configuration once decisions outpaced their chains)
                return
            self._chain_inflight.add(member)

        def settle(ok: bool) -> None:
            with self._undelivered_lock:
                self._chain_inflight.discard(member)
                if ok:
                    self._undelivered.pop(member, None)
                else:
                    self._undelivered[member] = config_id
            if not ok:
                return
            nxt = self._packet_history.get(after_id)
            if nxt is not None:
                # the member now sits at after_id and the decision taken
                # FROM there is in history: keep walking
                self._deliver_decision_chain(member, nxt)

        def after_votes(p: Promise) -> None:
            settle(p.exception() is None)

        def after_alerts(p: Promise) -> None:
            if p.exception() is None:
                self._deliver(src, member, votes_msg).add_callback(after_votes)
            else:
                LOG.warning(
                    "alert delivery to %s failed (%s); withholding votes -- "
                    "the pump will re-drive the chain",
                    member, p.exception(),
                )
                settle(False)

        self._deliver(src, member, alert_msg).add_callback(after_alerts)

    def _reconcile_lagging(self) -> None:
        """Active repair of members whose decision chain failed (runs at the
        top of every pump): re-drive the missed packet from history so the
        member can be walked forward. Only a member whose needed packet has
        aged OUT of the history (>= 8 decisions behind) is beyond repair
        and is cut for rejoin -- Rapid's answer to a node that falls behind
        is removal and rejoin."""
        if self._decision_packet is None:
            return
        with self._undelivered_lock:
            lagging = dict(self._undelivered)
        for member, missed in lagging.items():
            slot = self._real.get(member)
            if slot is None or not self.sim.active[slot]:
                with self._undelivered_lock:
                    self._undelivered.pop(member, None)
                continue
            packet = self._packet_history.get(missed)
            if packet is not None:
                self._deliver_decision_chain(member, packet)
            else:
                LOG.warning(
                    "member %s missed decision %d and its packet has aged "
                    "out of the replay history; cutting it (rejoin "
                    "required)",
                    member, missed,
                )
                with self._undelivered_lock:
                    self._undelivered.pop(member, None)
                if self.sim.alive[slot]:
                    self.sim.crash(np.array([slot]))

    # ------------------------------------------------------------------ #
    # alerts from real members
    # ------------------------------------------------------------------ #

    def _absorb_alerts(self, batch: BatchedAlertMessage) -> None:
        """A real member's broadcast: DOWN evidence joins the simulated report
        tables; UP metadata is stashed for the joiner's admission."""
        current = self.sim.configuration_id()
        for alert in batch.messages:
            if alert.configuration_id != current:
                continue
            slot = self._slot_of.get(alert.edge_dst)
            if slot is None:
                continue
            if alert.edge_status == EdgeStatus.DOWN and self.sim.active[slot]:
                self.sim.inject_down_report(slot, alert.ring_numbers)
            elif alert.edge_status == EdgeStatus.UP and alert.metadata:
                self._metadata[alert.edge_dst] = alert.metadata

    # ------------------------------------------------------------------ #
    # the pump: device rounds + decision delivery
    # ------------------------------------------------------------------ #

    def pump(
        self, max_rounds: int = 32, batch: int = 8,
        classic_fallback_after_rounds: Optional[int] = 8,
    ) -> Optional[ViewChangeRecord]:
        """Sense real-node liveness, run simulated rounds until a decision,
        then make that decision real: alerts + votes to every real member of
        the pre-decision configuration, full configurations to admitted
        joiners.

        When live real members exist, the run pauses at the first proposal
        announcement of each configuration (phase B): the proposed cut is
        broadcast to the real members *before* the decision, the virtual
        clock advances so their cut detectors propose and their
        FastRoundPhase2bMessages flow back into the device tally, and only
        then does the fast round resume -- so a real member's vote can
        complete a quorum the virtual members alone cannot reach, or block
        one by voting a conflicting value."""
        self._sense_real_liveness()
        self._reconcile_lagging()
        sim = self.sim
        if self._quiescent():
            # nothing can decide: no pending membership work, every member
            # alive, no fault knob armed. Skip the device dispatches
            # entirely -- a periodic pump (the gateway drives one every
            # pump_interval) would otherwise burn a full no-op round batch
            # on the protocol thread, starving joins and probes behind it
            # at large capacities. Liveness was still sensed above, so a
            # member death re-arms real work for the next pump.
            return None
        config_before = sim.configuration_id()
        n_before = sim.membership_size
        members_before = [
            ep
            for ep, slot in self._real.items()
            if sim.active[slot] and self.network.is_listening(ep)
        ]
        # fast-round votes are cast by the pre-decision configuration's live
        # members; the cut-set members that are *leaving* voted too
        voters = [
            ep
            for ep in (
                self._endpoint(int(s))
                for s in np.flatnonzero(sim.active & sim.alive)
            )
            if ep not in self._real
        ]
        rec = None
        rounds_before = sim.metrics.get("rounds")
        if members_before and self._informed_config != config_before:
            # phase A: run only to the announcement, so real members can
            # vote. On the deterministic (const/mesh) planes the engine's
            # while_loop pauses at the announcement round in ONE dispatch;
            # batch=1 covers the scan path, where the announcement must be
            # observed the round it happens (a wider scan batch could run
            # announcement and decision inside one dispatch and skip the
            # pre-decision broadcast)
            rec = sim.run_until_decision(
                max_rounds=max_rounds, batch=1,
                classic_fallback_after_rounds=classic_fallback_after_rounds,
                stop_when_announced=True,
            )
            announced = sim.last_announcement
            if (
                rec is None
                and announced is not None
                and announced[0][: sim.config.groups].any()
                and voters
            ):
                # phase B: pre-decision broadcast of the proposed cut; the
                # clock advance lets the real members' protocol stacks
                # process it and broadcast their votes back to the swarm
                self._informed_config = config_before
                self._broadcast_announced_proposal(
                    config_before, members_before, voters[0]
                )
                self._advance_clock(100)
        # phases A and resume share one round budget per pump call
        remaining = max_rounds - (sim.metrics.get("rounds") - rounds_before)
        if rec is None and remaining > 0:
            rec = sim.run_until_decision(
                max_rounds=remaining, batch=batch,
                classic_fallback_after_rounds=classic_fallback_after_rounds,
            )
        if rec is None:
            return None
        cut_eps = sorted(
            (self._endpoint(int(s)) for s in rec.cut), key=address_comparator_key
        )
        added = {int(s) for s in rec.added}
        if members_before and not voters:
            LOG.warning(
                "no live virtual voters; real members cannot learn this decision"
            )
        if members_before and voters:
            alerts = tuple(
                AlertMessage(
                    edge_src=voters[0],
                    edge_dst=ep,
                    edge_status=(
                        EdgeStatus.UP
                        if self._slot_of[ep] in added
                        else EdgeStatus.DOWN
                    ),
                    configuration_id=config_before,
                    ring_numbers=(0,),
                    node_id=(
                        self._node_id(self._slot_of[ep])
                        if self._slot_of[ep] in added
                        else None
                    ),
                    metadata=self._metadata.get(ep, ()),
                )
                for ep in cut_eps
            )
            quorum = n_before - (n_before - 1) // 4
            if len(voters) + 1 < quorum:  # each member also tallies its own vote
                LOG.warning(
                    "only %d live virtual voters for quorum %d; real members "
                    "may need the classic fallback to learn this decision",
                    len(voters),
                    quorum,
                )
            # one alert batch + ONE vote-batch frame per member: the quorum
            # of identical-value votes (~3N/4 protocol messages) is
            # transport-batched (FastRoundVoteBatch), or a 10k-member swarm
            # would grind thousands of frames through the delivery worker
            # per member per decision and members would fall behind
            votes_msg = FastRoundVoteBatch(
                senders=tuple(voters[:quorum]),
                configuration_id=config_before,
                endpoints=tuple(cut_eps),
            )
            # keep the packet BEFORE delivering: a failed chain records the
            # member in _undelivered against this decision
            self._last_decision = (
                config_before, alerts, tuple(cut_eps), tuple(voters[:quorum])
            )
            self._decision_packet = (
                config_before,
                BatchedAlertMessage(voters[0], alerts),
                votes_msg,
                voters[0],
                # post-decision id: a later packet applies to a member only
                # if that member is exactly here (chains walk off this)
                sim.configuration_id(),
            )
            self._packet_history[config_before] = self._decision_packet
            while len(self._packet_history) > 8:
                self._packet_history.popitem(last=False)
            with self._undelivered_lock:
                lagging_now = set(self._undelivered)
            for member in members_before:
                if member in lagging_now:
                    # it provably missed the PREVIOUS decision and is now
                    # beyond vote repair (FastPaxos is per-configuration);
                    # delivering the new chain would "succeed" at the
                    # transport and mask the miss -- the next pump's
                    # reconciliation cuts it for rejoin instead
                    continue
                self._deliver_decision_chain(member)
            self._replay_counts = {}
            self._prior_configs.append(config_before)
            # prune strikes whose config fell out of the stale window; keep
            # live ones -- wiping wholesale would let a member stranded many
            # configs behind linger forever under sustained churn (1-2
            # sightings per epoch, reset each decision, never reaching the
            # cut threshold)
            self._stale_counts = {
                key: strikes
                for key, strikes in self._stale_counts.items()
                if key[1] in self._prior_configs
            }
        # unblock admitted joiners (respondToJoiners, MembershipService.java:708-733);
        # the full configuration streams once per joiner -- the first parked
        # observer response carries it, siblings answer CONFIG_CHANGED (the
        # join client needs exactly one valid response; K full copies of a
        # multi-MB configuration through one socket starved the gateway)
        config_now = sim.configuration_id()
        for joiner in list(self._parked):
            slot = self._slot_of.get(joiner)
            if slot is not None and sim.active[slot]:
                first = self._streamed.get(joiner) != config_now
                # newest parked entry first: a slow decision can span several
                # join attempts, and the earlier attempts' requests have
                # expired client-side -- streaming the one full configuration
                # to the oldest entry hands it to a dead request while the
                # live retry gets CONFIG_CHANGED
                for observer_ep, parked in reversed(self._parked.pop(joiner)):
                    if first:
                        self._streamed[joiner] = config_now
                        first = False
                        parked.set_result(
                            self._full_config_response(observer_ep)
                        )
                    else:
                        parked.set_result(
                            JoinResponse(
                                sender=observer_ep,
                                status_code=JoinStatusCode.CONFIG_CHANGED,
                                configuration_id=config_now,
                            )
                        )
        # recycle removed real nodes' slots: the identifier history is
        # value-based, so a slot can be re-seated for a future joiner
        for slot in (int(s) for s in rec.removed):
            ep = self._endpoint(slot)
            if self._real.get(ep) == slot:
                del self._real[ep]
                del self._slot_of[ep]
                self._metadata.pop(ep, None)
                self._streamed.pop(ep, None)
                self.sim.set_auto_vote(slot, True)
                self._free_slots.append(slot)
        return rec

    def _broadcast_announced_proposal(
        self,
        config_id: int,
        members: List[Endpoint],
        src: Endpoint,
    ) -> None:
        """Send real members the alert evidence behind the announced (still
        undecided) proposal, so their own cut detectors cross H and they cast
        genuine fast-round votes. Ring numbers 0..K-1 stand for the K
        observers whose reports the swarm aggregated -- one report per
        (dst, ring), exactly what the cut detector dedups on
        (MultiNodeCutDetector.java:97-101)."""
        announced, proposals = self.sim.last_announcement
        # group rows only: extern rows are real members' own votes
        row = int(np.flatnonzero(announced[: self.sim.config.groups])[0])
        cut_slots = np.flatnonzero(proposals[row])
        cut_eps = sorted(
            (self._endpoint(int(s)) for s in cut_slots),
            key=address_comparator_key,
        )
        rings = tuple(range(self.sim.config.k))
        alerts = tuple(
            AlertMessage(
                edge_src=src,
                edge_dst=ep,
                edge_status=(
                    EdgeStatus.UP
                    if not self.sim.active[self._slot_of[ep]]
                    else EdgeStatus.DOWN
                ),
                configuration_id=config_id,
                ring_numbers=rings,
                node_id=(
                    self._node_id(self._slot_of[ep])
                    if not self.sim.active[self._slot_of[ep]]
                    else None
                ),
                metadata=self._metadata.get(ep, ()),
            )
            for ep in cut_eps
        )
        for member in members:
            self._deliver(src, member, BatchedAlertMessage(src, alerts))

    def _advance_clock(self, ms: int) -> None:
        """Let the object plane process in-flight messages: drive the shared
        virtual clock when there is one, otherwise wait out wall time."""
        run_for = getattr(self.network.scheduler, "run_for", None)
        if run_for is not None:
            run_for(ms)
        else:  # pragma: no cover - real-scheduler deployments
            import time

            time.sleep(ms / 1000.0)

    def _deliver(self, src: Endpoint, dst: Endpoint, msg: RapidMessage):
        # join-class deadline, not the 1 s default: decision packets straddle
        # a view change, and the receiving member may be mid-bootstrap of its
        # new N-member view when the packet lands -- the same reason the
        # reference gives joins 5x the default RPC deadline
        # (GrpcClient.java:55-59). A short deadline here made the bridge
        # declare deliveries failed against members that were merely busy,
        # stranding them a configuration behind for the replay path to fix.
        return self.network.deliver(src, dst, msg, timeout_ms=5000)

    def _quiescent(self) -> bool:
        """True when no protocol progress is possible: no membership work
        pending (joins/leaves/crashes/injected evidence/extern votes), no
        announcement awaiting a decision, and no fault knob armed that could
        make a probe of a live member fail (lossy ingress / one-way
        partitions / delivery faults can cut LIVE members, so any of them
        armed means rounds must run)."""
        sim = self.sim
        return (
            not sim.pending_joiners
            and not sim.pending_leavers
            and not sim._extern_voted  # noqa: SLF001
            and sim.last_announcement is None
            and not sim._injected_down.any()  # noqa: SLF001
            and bool((sim.alive | ~sim.active).all())
            and not sim._ingress_partitioned  # noqa: SLF001
            and not (sim._drop_prob > 0).any()  # noqa: SLF001
            and bool(sim._deliver.all())  # noqa: SLF001
        )

    def _sense_real_liveness(self) -> None:
        """A real node is alive while its server listens on the network; when
        it disappears, its slot dies and the simulated FDs take over. A node
        that dies *before* admission has its pending join withdrawn and its
        spare slot reclaimed."""
        for ep, slot in list(self._real.items()):
            if self.network.is_listening(ep):
                continue
            if self.sim.active[slot]:
                if self.sim.alive[slot]:
                    self.sim.crash(np.array([slot]))
            else:
                self.sim.cancel_join(slot)
                del self._real[ep]
                del self._slot_of[ep]
                self._metadata.pop(ep, None)
                self._parked.pop(ep, None)  # the dead joiner can't hear replies
                self.sim.set_auto_vote(slot, True)
                self._free_slots.append(slot)
