"""Device-side data plane: the jitted protocol round step.

This is the TPU execution backend for Rapid's steady-state loop
(SURVEY.md §3.3, MembershipService.java:297-348): each simulated round
1. evaluates every monitoring edge's probe (PingPongFailureDetector semantics:
   cumulative failure counter, threshold 10 -- PingPongFailureDetector.java:40,69-77),
2. scatters newly-crossed edges as DOWN alerts along the observer->subject
   adjacency (alert fan-out, MembershipService.java:602-626),
3. updates the per-destination H/L watermark report table and applies one
   implicit-invalidation pass (MultiNodeCutDetector.java:76-164),
4. tallies fast-round votes and decides at the 3/4 supermajority
   (FastPaxos.java:145-150).

All state lives in capacity-padded arrays (static shapes; membership churn is
an active-mask update + host-side adjacency rebuild). ``run_rounds`` scans R
rounds per device dispatch; once ``decided`` latches the remaining rounds are
masked no-ops, so the host can run large batches without losing the decision
round. Everything here is elementwise/gather/scatter arithmetic on [C, K]
arrays -- HBM-bandwidth bound, which is exactly what the TPU vector units eat.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .topology import VirtualCluster, build_adjacency


@dataclass(frozen=True)
class SimConfig:
    """Static protocol parameters (hashable; part of the jit cache key)."""

    capacity: int
    k: int = 10
    h: int = 9
    l: int = 4
    fd_threshold: int = 10  # PingPongFailureDetector.FAILURE_THRESHOLD
    fd_interval_ms: int = 1000  # MembershipService.java:77
    batching_window_ms: int = 100  # MembershipService.java:75
    # Fuse the probe/counter/alert elementwise phase into one Pallas kernel
    # (sim/pallas_kernels.py). "off" = stock jax; "tpu" = hardware kernel;
    # "interpret" = Pallas interpreter (CPU-testable).
    pallas_fd: str = "off"


@jax.tree_util.register_dataclass
@dataclass
class SimState:
    """Per-round mutable protocol state (a pytree of device arrays)."""

    active: jax.Array  # bool[C] current membership
    alive: jax.Array  # bool[C] fault-model liveness (crashed => False)
    subjects: jax.Array  # int32[C, K] monitored node per ring
    observers: jax.Array  # int32[C, K] monitoring node per ring
    fd_fail: jax.Array  # int32[C, K] cumulative failed probes per edge
    alerted: jax.Array  # bool[C, K] edge already reported DOWN
    reports: jax.Array  # bool[C, K] cut-detector report table (dst, ring)
    seen_down: jax.Array  # bool[] any DOWN alert this configuration
    announced: jax.Array  # bool[] proposal announced (consensus started)
    proposal: jax.Array  # bool[C] latched proposal mask
    decided: jax.Array  # bool[] consensus reached
    decided_round: jax.Array  # int32[] round at which decision happened
    round: jax.Array  # int32[] rounds elapsed in this configuration
    rng_key: jax.Array


@jax.tree_util.register_dataclass
@dataclass
class RoundInputs:
    """Per-round fault-plane inputs (leading axis = rounds when scanned)."""

    alive: jax.Array  # bool[C] liveness this round
    probe_drop: jax.Array  # bool[C, K] deterministic probe drops (one-way loss)
    drop_prob: jax.Array  # float32[C] random ingress-loss probability per dst
    join_reports: jax.Array  # bool[C, K] UP-alert reports for joining slots


def initial_state(
    config: SimConfig,
    cluster: VirtualCluster,
    active: np.ndarray,
    seed: int = 0,
) -> SimState:
    subjects, observers = build_adjacency(cluster, active)
    c, k = config.capacity, config.k
    return SimState(
        active=jnp.asarray(active),
        alive=jnp.asarray(active),
        subjects=jnp.asarray(subjects),
        observers=jnp.asarray(observers),
        fd_fail=jnp.zeros((c, k), jnp.int32),
        alerted=jnp.zeros((c, k), bool),
        reports=jnp.zeros((c, k), bool),
        seen_down=jnp.asarray(False),
        announced=jnp.asarray(False),
        proposal=jnp.zeros(c, bool),
        decided=jnp.asarray(False),
        decided_round=jnp.asarray(0, jnp.int32),
        round=jnp.asarray(0, jnp.int32),
        rng_key=jax.random.PRNGKey(seed),
    )


def _gather_alerts(
    reports: jax.Array, observers: jax.Array, new_alerts: jax.Array,
    active: jax.Array,
) -> jax.Array:
    """OR each observer-edge alert into its (dst, ring) report slot.

    On ring k the subject map (i -> subjects[i,k]) and the observer map
    (d -> observers[d,k]) are inverse permutations over the active set, so the
    scatter "alert from observer i lands at (subjects[i,k], k)" is exactly the
    gather ``reports[d,k] |= new_alerts[observers[d,k], k]`` -- and gathers
    are far cheaper than scatters on TPU. The gather is masked to active
    destinations: inactive rows' observers entries are either self-loops or
    (for pending joiners) their *expected* observers, whose DOWN alerts are
    about different destinations entirely.
    """
    k = reports.shape[1]
    cols = jnp.arange(k, dtype=jnp.int32)[None, :]
    return reports | (new_alerts[observers, cols] & active[:, None])


def cut_and_tally(
    config: SimConfig,
    state: SimState,
    reports: jax.Array,
    seen_down: jax.Array,
    active: jax.Array,
    alive: jax.Array,
):
    """The replicated protocol phase, shared by the single-device and sharded
    steps: H/L watermark cut detection, one implicit-invalidation pass,
    proposal emission, and the fast-round vote tally.

    Returns (reports, announced, proposal, decided, decided_round).
    """
    # --- cut detection: H/L watermarks ------------------------------------
    counts = reports.sum(axis=1)
    in_flux = (counts >= config.l) & (counts < config.h)
    stable = counts >= config.h

    # One implicit-invalidation pass (per-batch call in the reference,
    # MembershipService.java:327): edges from observers that are themselves
    # in flux or stable count as implicit reports. Applies to failing members
    # (DOWN edges, via their successors) AND to joining slots (UP edges, via
    # their expected observers -- MultiNodeCutDetector.java:146-158); the
    # driver writes each joiner's expected observers into its observers row.
    obs_in_flux = (in_flux | stable)[state.observers]  # [C, K]
    implicit = seen_down & in_flux[:, None] & obs_in_flux & ~reports
    reports = reports | implicit
    counts = reports.sum(axis=1)
    in_flux = (counts >= config.l) & (counts < config.h)
    stable = counts >= config.h

    # --- proposal emission (almost-everywhere agreement) -------------------
    emit = jnp.any(stable) & ~jnp.any(in_flux) & ~state.announced
    announced = state.announced | emit
    proposal = jnp.where(emit, stable, state.proposal)

    # --- fast-round vote tally --------------------------------------------
    # Under uniform alert delivery every live member proposes the same cut, so
    # the tally is the live-member count; quorum is N - floor((N-1)/4)
    # (FastPaxos.java:145-150).
    n = active.sum()
    voters = (active & alive).sum()
    quorum = n - (n - 1) // 4
    decide_now = announced & ~state.decided & (voters >= quorum)
    decided = state.decided | decide_now
    decided_round = jnp.where(decide_now, state.round + 1, state.decided_round)
    return reports, announced, proposal, decided, decided_round


def step(config: SimConfig, state: SimState, inputs: RoundInputs,
         random_loss: bool = True) -> SimState:
    """One protocol round. Pure; jit/scan-friendly.

    ``random_loss`` statically elides the per-edge RNG draw when no lossy
    ingress fault is active (the common case) -- the threefry generation over
    [C, K] per round is otherwise a real bandwidth cost at C=100k.
    """
    c, k = config.capacity, config.k
    halt = state.decided

    key, probe_key = jax.random.split(state.rng_key)
    active = state.active
    alive = inputs.alive & active  # membership ∩ fault-model liveness

    # --- failure detection (one probe per monitoring edge per round) -------
    subj = state.subjects
    edge_live = active[:, None] & active[subj]  # edge exists in this config
    observer_up = alive[:, None]
    target_up = alive[subj]
    if random_loss:
        rand_drop = jax.random.uniform(probe_key, (c, k)) < inputs.drop_prob[subj]
    else:
        rand_drop = jnp.zeros((c, k), bool)
    probe_ok = target_up & ~inputs.probe_drop & ~rand_drop

    if config.pallas_fd != "off":
        from .pallas_kernels import fd_phase

        fd_fail, alerted, new_down = fd_phase(
            edge_live,
            jnp.broadcast_to(observer_up, (c, k)),
            probe_ok,
            state.fd_fail,
            state.alerted,
            threshold=config.fd_threshold,
            interpret=config.pallas_fd == "interpret",
        )
    else:
        fail_event = edge_live & observer_up & ~probe_ok
        fd_fail = state.fd_fail + fail_event.astype(jnp.int32)
        # --- alert generation --------------------------------------------
        new_down = (
            edge_live
            & observer_up
            & (fd_fail >= config.fd_threshold)
            & ~state.alerted
        )
        alerted = state.alerted | new_down
    reports = _gather_alerts(state.reports, state.observers, new_down, active)
    reports = reports | inputs.join_reports
    seen_down = state.seen_down | jnp.any(new_down)

    reports, announced, proposal, decided, decided_round = cut_and_tally(
        config, state, reports, seen_down, active, alive
    )

    new_state = SimState(
        active=active,
        alive=inputs.alive,
        subjects=state.subjects,
        observers=state.observers,
        fd_fail=fd_fail,
        alerted=alerted,
        reports=reports,
        seen_down=seen_down,
        announced=announced,
        proposal=proposal,
        decided=decided,
        decided_round=decided_round,
        round=state.round + 1,
        rng_key=key,
    )
    # After a decision the configuration is frozen until the host applies the
    # view change: all updates become no-ops.
    return jax.tree_util.tree_map(
        lambda old, new: jnp.where(halt, old, new), state, new_state
    )


@functools.partial(jax.jit, static_argnums=0)
def run_rounds(config: SimConfig, state: SimState, inputs: RoundInputs) -> SimState:
    """Scan ``step`` over stacked per-round inputs (leading axis = rounds)."""

    def body(carry: SimState, per_round: RoundInputs):
        return step(config, carry, per_round), ()

    final, _ = jax.lax.scan(body, state, inputs)
    return final


@functools.partial(jax.jit, static_argnums=(0, 3, 4))
def run_rounds_const(
    config: SimConfig, state: SimState, inputs: RoundInputs, rounds: int,
    random_loss: bool = True,
) -> SimState:
    """Scan ``rounds`` rounds under a constant fault plane (inputs without a
    leading rounds axis). Avoids materializing [R, C, K] fault arrays -- the
    path used for large-capacity runs."""

    def body(carry: SimState, _):
        return step(config, carry, inputs, random_loss), ()

    final, _ = jax.lax.scan(body, state, None, length=rounds)
    return final


def const_inputs(
    config: SimConfig,
    alive: np.ndarray,
    probe_drop: Optional[np.ndarray] = None,
    drop_prob: Optional[np.ndarray] = None,
    join_reports: Optional[np.ndarray] = None,
) -> RoundInputs:
    """A single-round fault plane (for run_rounds_const)."""
    c, k = config.capacity, config.k
    return RoundInputs(
        alive=jnp.asarray(alive),
        probe_drop=jnp.zeros((c, k), bool) if probe_drop is None else jnp.asarray(probe_drop),
        drop_prob=jnp.zeros(c, jnp.float32) if drop_prob is None else jnp.asarray(drop_prob),
        join_reports=jnp.zeros((c, k), bool) if join_reports is None else jnp.asarray(join_reports),
    )
