"""Device-side data plane: the jitted protocol round step.

This is the TPU execution backend for Rapid's steady-state loop
(SURVEY.md §3.3, MembershipService.java:297-348): each simulated round
1. evaluates every monitoring edge's probe (PingPongFailureDetector semantics:
   cumulative failure counter, threshold 10 -- PingPongFailureDetector.java:40,69-77),
2. routes newly-crossed edges as DOWN alerts along the observer->subject
   adjacency (the batched equivalent of the unicast-to-all broadcast,
   MembershipService.java:602-626),
3. updates per-destination H/L watermark report tables and applies one
   implicit-invalidation pass (MultiNodeCutDetector.java:76-164),
4. tallies fast-round votes and decides at the 3/4 supermajority
   (FastPaxos.java:145-150).

**Delivery groups** make almost-everywhere agreement real rather than assumed:
nodes are partitioned into G delivery classes; the fault plane can drop
broadcast traffic per (receiving group, sender), so groups can see different
alert subsets, hold *different* cut-detector states, and propose different
cuts. G=1 reduces to uniform delivery.

**Consensus is per-node** (FastPaxos.java:125-156): every live node casts one
fast-round vote -- for its own cut detector's proposal, i.e. its delivery
group's -- the round that proposal is announced, guarded by a per-sender
dedup latch (``voted``, the votesReceived set of FastPaxos.java:134-141). The
vote broadcast is itself a delivery hop: votes cast in round t are in flight
(``vote_new``) and arrive in round t+1 (plus the per-(group, sender)
``deliver_delay`` under heterogeneous latency -- one fabric carries alerts
and votes alike), gated per receiving group by the same ``deliver`` fault
mask as alert broadcasts (a dropped vote is lost, exactly like the
reference's best-effort unicast). Each group tallies the votes it
received (``votes_recv``); identical proposals pool their votes; a cut decides
when some group's tally holds N - floor((N-1)/4) votes for one value
(FastPaxos.java:145-150). ``decided_round`` therefore always bills at least
one round between announcement and decision -- vote propagation is simulated,
not assumed. Proposal rows beyond the first G (``extern_proposals``) carry
values proposed by *bridged real nodes*; the host registers their actual
votes into the same per-node state, so a real member can swing or block a
simulated quorum.

All state lives in capacity-padded arrays (static shapes; membership churn is
an active-mask update + host-side adjacency rebuild). ``run_rounds*`` scans R
rounds per device dispatch; once ``decided`` latches the remaining rounds are
masked no-ops, so the host can run large batches without losing the decision
round. Everything here is elementwise/gather arithmetic on [C,K] / [G,C,K]
arrays -- HBM-bandwidth bound, which is exactly what the TPU vector units eat.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.jitwatch import make_jit
from .topology import VirtualCluster, build_adjacency


@dataclass(frozen=True)
class SimConfig:
    """Static protocol parameters (hashable; part of the jit cache key)."""

    capacity: int
    k: int = 10
    h: int = 9
    l: int = 4
    fd_threshold: int = 10  # PingPongFailureDetector.FAILURE_THRESHOLD
    fd_interval_ms: int = 1000  # MembershipService.java:77
    batching_window_ms: int = 100  # MembershipService.java:75
    # Asynchrony model (SURVEY.md §7.4): with rounds_per_interval > 1 a round
    # is a fraction of the FD interval and each node probes only in its own
    # phase of the interval (a fixed pseudo-random offset) -- alerts from
    # different observers then arrive staggered across the batching timeline
    # instead of quantized to whole intervals, exercising the H/L flux window
    # in time. 1 = the reference-aligned synchronous model.
    rounds_per_interval: int = 1
    groups: int = 1  # delivery classes (heterogeneous broadcast delivery)
    # Failure-detection policy. "cumulative" = the reference code's
    # never-reset counter (PingPongFailureDetector.java:116-118, the parity
    # default); "windowed" = the paper's policy (atc-2018 paper section 6):
    # an edge is faulty when >= fd_window_threshold of its last fd_window
    # probes failed, so recovered edges shed old evidence. Windowed runs on
    # the scan path (no closed-form fast path).
    fd_policy: str = "cumulative"
    fd_window: int = 10
    fd_window_threshold: float = 0.4
    # Adaptive gray-aware FD mirror (monitoring/adaptive.py). When
    # fd_gray_confirm > 0, an edge with an established healthy history
    # (>= fd_gray_warmup successful probes) also alerts after
    # fd_gray_confirm CONSECUTIVE failed probes -- the sim-plane analogue
    # of the adaptive detector's miss-streak suspicion (device probes
    # carry no RTT, and a gray node past the probe timeout is exactly a
    # consecutive-miss streak). 0 disables the gray path entirely (the
    # static parity default; fd_streak/fd_ok are then never touched).
    # Cumulative policy only: the windowed policy is already streak-like.
    fd_gray_confirm: int = 0
    fd_gray_warmup: int = 3
    # Extra proposal rows past the G group rows, reserved for values proposed
    # by bridged real nodes (sim/bridge.py registers their actual fast-round
    # votes into these rows). 0 = all-simulated cluster.
    extern_proposals: int = 0
    # Forensics mirror (forensics/hlc.py): when True the sim's flight
    # recorder stamps every journal entry with an HLC driven by the VIRTUAL
    # clock, so sim journals merge into the same causal timelines as real
    # members' (tools/forensics.py). Off = the exact pre-forensics entries.
    forensics: bool = False
    # Heterogeneous broadcast LATENCY (the paper's Fig.-11 conflict regime):
    # a broadcast from sender s reaches group g ``deliver_delay[g, s]``
    # EXTRA rounds late (0..max_delivery_delay). Nothing is lost -- but
    # groups see different interleavings, so with staggered FD phases they
    # can cross H at different times holding different report snapshots and
    # propose *different* cuts, purely from timing. 0 disables the delay
    # buffers entirely (static). One fabric carries every message type
    # (UnicastToAllBroadcaster.java:46-52: alerts, votes, and recovery all
    # ride the same sendRequest RPC), so the delay applies uniformly: DOWN
    # alerts arrive at fire + delay, fast-round votes arrive at cast + 1 +
    # delay (the base one-round vote hop, skewed like any broadcast), and
    # the classic recovery exchange's per-acceptor hop times carry the same
    # per-edge delays (sim/classic.py via driver._run_classic_round). Join
    # reports stay delay-0: the experiment axis is failure timing.
    max_delivery_delay: int = 0

    def __post_init__(self) -> None:
        assert self.fd_policy in ("cumulative", "windowed"), (
            f"fd_policy must be 'cumulative' or 'windowed', got "
            f"{self.fd_policy!r}"
        )
        assert 1 <= self.fd_window <= 16, (
            f"fd_window must be in [1, 16] (window bitmask is uint16), got "
            f"{self.fd_window}"
        )
        assert 1 <= self.fd_threshold <= 255, (
            f"fd_threshold must be in [1, 255] (the per-edge failure counter "
            f"is uint8 and saturates at 255, so a larger threshold would "
            f"never fire), got {self.fd_threshold}"
        )
        assert 0 <= self.fd_gray_confirm <= 255, (
            f"fd_gray_confirm must be in [0, 255] (uint8 streak counter; "
            f"0 disables), got {self.fd_gray_confirm}"
        )
        assert 1 <= self.fd_gray_warmup <= 255, (
            f"fd_gray_warmup must be in [1, 255] (uint8 success counter), "
            f"got {self.fd_gray_warmup}"
        )
        assert self.fd_gray_confirm == 0 or self.fd_policy == "cumulative", (
            "the gray streak path mirrors the adaptive detector on top of "
            "the cumulative policy only"
        )

    @property
    def proposal_rows(self) -> int:
        return self.groups + self.extern_proposals


# Classic-Paxos rank packing, shared with sim.classic: rank =
# round << RANK_BITS | node; the fast round is rank (1, 1)
# (registerFastRoundVote, Paxos.java:244-258), so every classic rank
# outranks it. Defined here so the engine's fast-vote gate and the classic
# recovery layer agree without a circular import.
RANK_BITS = 21
FAST_RANK = (1 << RANK_BITS) | 1


@jax.tree_util.register_dataclass
@dataclass
class SimState:
    """Per-round mutable protocol state (a pytree of device arrays)."""

    active: jax.Array  # bool[C] current membership
    alive: jax.Array  # bool[C] fault-model liveness (crashed => False)
    group_of: jax.Array  # int32[C] delivery group of each node
    subjects: jax.Array  # int32[C, K] monitored node per ring
    observers: jax.Array  # int32[C, K] monitoring node per ring
    fd_fail: jax.Array  # uint8[C, K] cumulative failed probes per edge,
    # saturating at 255 (only >= fd_threshold comparisons and the
    # threshold-distance are ever read; uint8 quarters the FD plane's
    # per-round HBM traffic at 1M nodes vs int32)
    fd_hist: jax.Array  # uint16[C, K] last-W probe outcomes (windowed policy)
    fd_seen: jax.Array  # uint8[C, K] probes recorded, saturating at W (<=16)
    fd_streak: jax.Array  # uint8[C, K] consecutive failed probes (gray path;
    # resets to 0 on any successful probe, saturates at 255)
    fd_ok: jax.Array  # uint8[C, K] successful probes observed, saturating at
    # 255 (>= fd_gray_warmup establishes the healthy history the gray
    # streak alert requires)
    alerted: jax.Array  # bool[C, K] edge already reported DOWN
    reports: jax.Array  # bool[G, C, K] per-group report tables (dst, ring)
    arrival_hist: jax.Array  # bool[Dmax, C, K] DOWN alerts aged 1..Dmax rounds
    seen_down: jax.Array  # bool[G] group saw a DOWN alert this configuration
    announced: jax.Array  # bool[P] proposal row holds an announced value
    announced_round: jax.Array  # int32[] round of the first announcement
    proposal: jax.Array  # bool[P, C] latched proposal masks (G group + extern)
    auto_vote: jax.Array  # bool[C] slot casts its own votes (False = bridged)
    voted: jax.Array  # bool[C] fast-round per-sender dedup latch
    vote_prop: jax.Array  # int32[C] proposal row each voter voted for
    vote_new: jax.Array  # bool[C] votes cast this round, arriving next round
    vote_hist: jax.Array  # bool[Dmax, C] votes in flight, cast 2+d rounds ago
    votes_recv: jax.Array  # bool[G, C] votes received per (group, sender)
    # Classic-Paxos acceptor state (sim/classic.py; Paxos.java:63-70). Ranks
    # are (round, node) pairs packed into int32 (round << RANK_BITS | node);
    # 0 = never participated. The fast round's implicit rank/vote is derived
    # from voted/vote_prop, so the hot path never writes these.
    classic_rnd: jax.Array  # int32[C] highest rank promised (phase1a)
    classic_vrnd: jax.Array  # int32[C] rank last accepted at (phase2a)
    classic_vval: jax.Array  # int32[C] accepted proposal row (-1 = none)
    decided: jax.Array  # bool[] consensus reached
    decided_group: jax.Array  # int32[] proposal row whose value won
    decided_round: jax.Array  # int32[] round at which decision happened
    round: jax.Array  # int32[] rounds elapsed in this configuration
    rng_key: jax.Array


@jax.tree_util.register_dataclass
@dataclass
class RoundInputs:
    """Per-round fault-plane inputs (leading axis = rounds when scanned)."""

    alive: jax.Array  # bool[C] liveness this round
    probe_drop: jax.Array  # bool[C, K] deterministic probe drops (one-way loss)
    drop_prob: jax.Array  # float32[C] random ingress-loss probability per dst
    join_reports: jax.Array  # bool[C, K] UP-alert reports for joining slots
    down_reports: jax.Array  # bool[C, K] proactive DOWN reports (graceful leave)
    deliver: jax.Array  # bool[G, C] does group g hear broadcasts from node i
    deliver_delay: jax.Array  # int32[G, C] broadcast latency (rounds) per edge


def initial_state(
    config: SimConfig,
    cluster: VirtualCluster,
    active: np.ndarray,
    seed: int = 0,
    group_of: Optional[np.ndarray] = None,
) -> SimState:
    subjects, observers = build_adjacency(cluster, active)
    c, k, g = config.capacity, config.k, config.groups
    p = config.proposal_rows
    if group_of is None:
        group_of = np.zeros(c, dtype=np.int32)
    return SimState(
        active=jnp.asarray(active),
        alive=jnp.asarray(active),
        group_of=jnp.asarray(group_of, dtype=jnp.int32),
        subjects=jnp.asarray(subjects),
        observers=jnp.asarray(observers),
        fd_fail=jnp.zeros((c, k), jnp.uint8),
        fd_hist=jnp.zeros((c, k), jnp.uint16),
        fd_seen=jnp.zeros((c, k), jnp.uint8),
        fd_streak=jnp.zeros((c, k), jnp.uint8),
        fd_ok=jnp.zeros((c, k), jnp.uint8),
        alerted=jnp.zeros((c, k), bool),
        reports=jnp.zeros((g, c, k), bool),
        arrival_hist=jnp.zeros((config.max_delivery_delay, c, k), bool),
        seen_down=jnp.zeros(g, bool),
        announced=jnp.zeros(p, bool),
        announced_round=jnp.asarray(0, jnp.int32),
        proposal=jnp.zeros((p, c), bool),
        auto_vote=jnp.ones(c, bool),
        voted=jnp.zeros(c, bool),
        vote_prop=jnp.zeros(c, jnp.int32),
        vote_new=jnp.zeros(c, bool),
        vote_hist=jnp.zeros((config.max_delivery_delay, c), bool),
        votes_recv=jnp.zeros((g, c), bool),
        classic_rnd=jnp.zeros(c, jnp.int32),
        classic_vrnd=jnp.zeros(c, jnp.int32),
        classic_vval=jnp.full(c, -1, jnp.int32),
        decided=jnp.asarray(False),
        decided_group=jnp.asarray(0, jnp.int32),
        decided_round=jnp.asarray(0, jnp.int32),
        round=jnp.asarray(0, jnp.int32),
        rng_key=jax.random.PRNGKey(seed),
    )


def route_and_tally(
    config: SimConfig,
    state: SimState,
    down_arrivals: jax.Array,  # bool[C, K] dst-indexed DOWN alert arrivals
    inputs: RoundInputs,
    active: jax.Array,
    alive: jax.Array,
    *,
    uniform_delivery: bool = False,
    gate_implicit: bool = False,
    stop_after_cut: bool = False,
) -> SimState:
    """Alert delivery, per-group cut detection, per-node vote casting, the
    vote delivery hop, and the fast-round tally -- shared by the
    single-device and sharded steps.

    ``down_arrivals[d, k]`` is the (dst, ring)-indexed view of this round's
    DOWN alerts; the sender of the (d, k) alert is ``observers[d, k]`` (the
    unique observer of d on ring k). Join UP alerts arrive via
    ``inputs.join_reports`` with the joiner's expected observer in the same
    observers row. Each delivery group receives an alert iff its
    ``deliver[g, sender]`` entry is set.

    ``uniform_delivery`` (static) elides the [G, C, K] deliver gather when the
    fault plane delivers every broadcast to every group (the common case).
    ``gate_implicit`` (static) wraps the implicit-invalidation pass in a
    ``lax.cond`` so its [G, C, K] gather only runs in rounds where some group
    both saw a DOWN alert and has a node in flux -- it is the identity
    otherwise, so gating is exact.

    ``stop_after_cut`` (static) returns right after proposal emission with
    the vote/tally fields untouched -- the cut-detector phase boundary the
    profiling plane's shadow attribution times against (profiling/phases.py);
    never used on a production dispatch path.

    Returns ``state`` with the tally-owned fields replaced (reports,
    seen_down, announced, proposal, voted, vote_prop, vote_new, vote_hist,
    votes_recv,
    decided, decided_group, decided_round); the caller layers the FD fields
    and the round increment on top.
    """
    sender = state.observers  # [C, K]
    arrival_hist = state.arrival_hist
    if config.max_delivery_delay > 0:
        # Heterogeneous latency: an alert fired d rounds ago sits in
        # hist[d]; group g reads the slot its (group, sender) delay names,
        # so each alert reaches each group exactly once, at fire + delay.
        # Join reports stay delay-0 (the experiment axis is DOWN timing).
        hist = jnp.concatenate(
            [down_arrivals[None], arrival_hist], axis=0
        )  # [Dmax+1, C, K]
        arrival_hist = hist[: config.max_delivery_delay]
        delay_gck = inputs.deliver_delay[:, sender]  # [G, C, K]
        c_idx = jnp.arange(config.capacity, dtype=jnp.int32)[None, :, None]
        k_idx = jnp.arange(config.k, dtype=jnp.int32)[None, None, :]
        arrived = hist[delay_gck, c_idx, k_idx]  # [G, C, K]
        joins = inputs.join_reports[None, :, :]
        if not uniform_delivery:
            deliver = inputs.deliver[:, sender]  # [G, C, K]
            arrived = arrived & deliver
            joins = joins & deliver  # drop masks gate UP reports here too
        reports = state.reports | arrived | joins
        seen_down = state.seen_down | jnp.any(arrived, axis=(1, 2))
    elif uniform_delivery:
        arrivals = down_arrivals | inputs.join_reports  # [C, K]
        reports = state.reports | arrivals[None, :, :]
        seen_down = state.seen_down | jnp.any(down_arrivals)
    else:
        arrivals = down_arrivals | inputs.join_reports  # [C, K]
        deliver = inputs.deliver[:, sender]  # [G, C, K]
        reports = state.reports | (arrivals[None, :, :] & deliver)
        seen_down = state.seen_down | jnp.any(
            down_arrivals[None, :, :] & deliver, axis=(1, 2)
        )

    # --- per-group cut detection: H/L watermarks ---------------------------
    counts = reports.sum(axis=2)  # [G, C]
    in_flux = (counts >= config.l) & (counts < config.h)
    stable = counts >= config.h

    # One implicit-invalidation pass per round (the per-batch call in the
    # reference, MembershipService.java:327): edges from observers that are
    # themselves in flux or stable count as implicit reports
    # (MultiNodeCutDetector.java:137-164). Covers failing members (their
    # successors) and joiners (their expected observers, written into the
    # observers row by the driver).
    def _implicit_pass(reports: jax.Array) -> jax.Array:
        fs = in_flux | stable  # [G, C]
        obs_fs = fs[:, state.observers]  # [G, C, K]
        implicit = (
            seen_down[:, None, None] & in_flux[:, :, None] & obs_fs & ~reports
        )
        return reports | implicit

    if gate_implicit:
        reports = jax.lax.cond(
            jnp.any(seen_down[:, None] & in_flux),
            _implicit_pass,
            lambda r: r,
            reports,
        )
    else:
        reports = _implicit_pass(reports)
    counts = reports.sum(axis=2)
    in_flux = (counts >= config.l) & (counts < config.h)
    stable = counts >= config.h

    # --- proposal emission per group ---------------------------------------
    # Group rows are the first G of the [P, C] proposal table; extern rows are
    # written only by the host (bridged real proposers, sim/bridge.py).
    g = config.groups
    p_rows = config.proposal_rows
    announced_g = state.announced[:g]
    emit = jnp.any(stable, axis=1) & ~jnp.any(in_flux, axis=1) & ~announced_g
    announced = state.announced.at[:g].set(announced_g | emit)
    proposal = state.proposal.at[:g].set(
        jnp.where(emit[:, None], stable, state.proposal[:g])
    )
    # the round at which the first value was proposed -- the anchor for the
    # host's classic-fallback timer (the reference schedules its fallback
    # relative to propose(), FastPaxos.java:105-107). Latched when no
    # announcement round is recorded yet (0 = none; rounds are 1-based), so a
    # host-written extern-row announcement between dispatches is stamped with
    # the first round the device processes it.
    announced_round = jnp.where(
        (state.announced_round == 0) & jnp.any(announced),
        state.round + 1,
        state.announced_round,
    )

    if stop_after_cut:
        return dataclasses.replace(
            state,
            reports=reports,
            arrival_hist=arrival_hist,
            seen_down=seen_down,
            announced=announced,
            announced_round=announced_round,
            proposal=proposal,
        )

    # --- per-node fast-round votes (FastPaxos.java:125-156) ----------------
    # A node casts its vote -- for its own group's proposal -- the round that
    # proposal is announced, once per configuration (the per-sender dedup of
    # FastPaxos.java:134-141). Bridged real slots (auto_vote=False) vote only
    # when the host registers their actual message.
    live = active & alive
    # a node that already joined a classic round (promised or accepted at a
    # classic rank) must not have a fast vote counted toward a fast quorum --
    # registerFastRoundVote refuses once rnd.round > 1 (Paxos.java:246-248);
    # without this gate the fast/classic quorum-intersection argument weakens
    # under concurrent coordinators
    new_voters = (
        live & state.auto_vote & announced[state.group_of] & ~state.voted
        & (state.classic_rnd < FAST_RANK)
    )
    voted = state.voted | new_voters
    vote_prop = jnp.where(new_voters, state.group_of, state.vote_prop)

    # The vote broadcast is a delivery hop: votes cast last round
    # (state.vote_new) arrive now, gated per receiving group by the same
    # fault mask as any broadcast. A vote dropped on its delivery round is
    # lost for good (best-effort unicast, UnicastToAllBroadcaster.java:46-52).
    # With heterogeneous latency the vote rides the same per-(group, sender)
    # delay as every other broadcast: group g hears sender s's vote
    # deliver_delay[g, s] rounds after the base one-round hop, read from the
    # same aged-history mechanism as alerts.
    vote_hist = state.vote_hist
    if config.max_delivery_delay > 0:
        vhist = jnp.concatenate(
            [state.vote_new[None], vote_hist], axis=0
        )  # [Dmax+1, C]; vhist[d] = votes of age 1+d rounds
        vote_hist = vhist[: config.max_delivery_delay]
        c_idx = jnp.arange(config.capacity, dtype=jnp.int32)[None, :]
        arrived_votes = vhist[inputs.deliver_delay, c_idx]  # [G, C]
        if not uniform_delivery:
            arrived_votes = arrived_votes & inputs.deliver
        votes_recv = state.votes_recv | arrived_votes
    elif uniform_delivery:
        votes_recv = state.votes_recv | state.vote_new[None, :]
    else:
        votes_recv = state.votes_recv | (
            state.vote_new[None, :] & inputs.deliver
        )

    # --- tally, per receiving group ----------------------------------------
    # counts[g, q] = votes group g has received for proposal row q; identical
    # rows pool via the [P, P] equality matrix; decision when some group sees
    # N - floor((N-1)/4) votes for one value (FastPaxos.java:145-150).
    onehot = (
        (vote_prop[:, None] == jnp.arange(p_rows, dtype=jnp.int32)[None, :])
        & voted[:, None]
    )  # [C, P]
    counts = votes_recv.astype(jnp.int32) @ onehot.astype(jnp.int32)  # [G, P]
    eq = jnp.all(
        proposal[:, None, :] == proposal[None, :, :], axis=2
    )  # [P, P]
    pooled = counts @ (eq & announced[:, None]).astype(jnp.int32)  # [G, P]
    n = active.sum()
    quorum = n - (n - 1) // 4
    qualifies = announced[None, :] & (pooled >= quorum)  # [G, P]
    decide_now = jnp.any(qualifies) & ~state.decided
    best = jnp.max(jnp.where(qualifies, pooled, -1), axis=0)  # [P]
    winner = jnp.argmax(best).astype(jnp.int32)
    decided = state.decided | decide_now
    decided_group = jnp.where(decide_now, winner, state.decided_group)
    decided_round = jnp.where(decide_now, state.round + 1, state.decided_round)
    return dataclasses.replace(
        state,
        reports=reports,
        arrival_hist=arrival_hist,
        seen_down=seen_down,
        announced=announced,
        announced_round=announced_round,
        proposal=proposal,
        voted=voted,
        vote_prop=vote_prop,
        vote_new=new_voters,
        vote_hist=vote_hist,
        votes_recv=votes_recv,
        decided=decided,
        decided_group=decided_group,
        decided_round=decided_round,
    )


def probe_phases(config: SimConfig) -> jnp.ndarray:
    """Each node's fixed probe phase within the FD interval ([C] int32 in
    [0, rounds_per_interval)): a Knuth multiplicative hash of the node index,
    so phases are deterministic, seed-free, and identical across the scan,
    closed-form, and sharded lowerings."""
    rpi = config.rounds_per_interval
    idx = jnp.arange(config.capacity, dtype=jnp.uint32)
    return ((idx * jnp.uint32(2654435761)) % jnp.uint32(rpi)).astype(jnp.int32)


def _window_params(config: SimConfig) -> Tuple[int, int, jnp.ndarray]:
    """(window size W, firing threshold t, uint16 bitmask) for the windowed
    policy -- the single source of the rounding and mask rules."""
    w = config.fd_window
    t = int(np.ceil(config.fd_window_threshold * w))
    return w, t, jnp.uint16((1 << w) - 1)


def window_step(
    config: SimConfig,
    hist: jax.Array,  # uint16[., K] last-W probe outcomes
    seen: jax.Array,  # uint8[., K] probes recorded, saturating at W
    probed: jax.Array,  # bool[., K] a probe was recorded on this edge
    fail_event: jax.Array,  # bool[., K] the recorded probe failed
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One recorded-probe update of the sliding window, returning
    (hist, seen, crossed). This is THE definition of the paper policy's
    firing rule (atc-2018 paper section 6: an edge is faulty when >=
    fd_window_threshold of its last fd_window recorded probes failed, once a
    full window has been recorded) -- shared by the scan step, the sharded
    step, and the closed-form fast path, so the semantics cannot drift
    between lowerings."""
    w, t, mask = _window_params(config)
    shifted = ((hist << 1) | fail_event.astype(jnp.uint16)) & mask
    hist = jnp.where(probed, shifted, hist)
    seen = jnp.where(
        probed, jnp.minimum(seen + jnp.uint8(1), jnp.uint8(w)), seen
    )
    crossed = (
        probed
        & (seen >= w)
        & (jax.lax.population_count(hist).astype(jnp.int32) >= t)
    )
    return hist, seen, crossed


def windowed_fd_phase(
    config: SimConfig,
    state: SimState,
    probed: jax.Array,  # bool[., K] a probe was recorded on this edge
    fail_event: jax.Array,  # bool[., K] the recorded probe failed
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The per-round windowed-FD phase over SimState: ``window_step`` plus
    the one-shot alert latch. The cumulative fd_fail counter is not touched
    (windowed detection never reads it). Returns (fd_hist, fd_seen,
    new_down)."""
    fd_hist, fd_seen, crossed = window_step(
        config, state.fd_hist, state.fd_seen, probed, fail_event
    )
    return fd_hist, fd_seen, crossed & ~state.alerted


def _fd_phase(
    config: SimConfig, state: SimState, inputs: RoundInputs,
    random_loss: bool,
) -> Tuple[jax.Array, ...]:
    """Probe evaluation + alert routing: the leading FD-scan phase of
    ``step``, shared with the profiling prefixes so the shadow-measured
    phase is the production computation, not a re-derivation. Returns
    ``(rng_key, active, alive, fd_fail, fd_hist, fd_seen, fd_streak,
    fd_ok, alerted, down_arrivals)``."""
    c, k = config.capacity, config.k
    key, probe_key = jax.random.split(state.rng_key)
    active = state.active
    alive = inputs.alive & active  # membership ∩ fault-model liveness

    # --- failure detection (one probe per monitoring edge per round) -------
    subj = state.subjects
    edge_live = active[:, None] & active[subj]  # edge exists in this config
    observer_up = alive[:, None]
    target_up = alive[subj]
    if random_loss:
        rand_drop = jax.random.uniform(probe_key, (c, k)) < inputs.drop_prob[subj]
    else:
        rand_drop = jnp.zeros((c, k), bool)
    probe_ok = target_up & ~inputs.probe_drop & ~rand_drop

    if config.rounds_per_interval > 1:
        # staggered FD phases: a node probes only in its own sub-interval
        # round (0-based round t probes nodes with phase == t mod rpi)
        my_turn = probe_phases(config) == (
            state.round % config.rounds_per_interval
        )
        observer_up = observer_up & my_turn[:, None]

    fd_fail, fd_hist, fd_seen = state.fd_fail, state.fd_hist, state.fd_seen
    fd_streak, fd_ok = state.fd_streak, state.fd_ok
    if config.fd_policy == "windowed":
        probed = edge_live & observer_up
        fd_hist, fd_seen, new_down = windowed_fd_phase(
            config, state, probed, probed & ~probe_ok
        )
        alerted = state.alerted | new_down
    else:
        fail_event = edge_live & observer_up & ~probe_ok
        # saturating add: the counter is only ever compared against the
        # (<=255) threshold, so clamping at 255 preserves semantics
        fd_fail = state.fd_fail + (
            fail_event & (state.fd_fail < jnp.uint8(255))
        ).astype(jnp.uint8)
        new_down = (
            edge_live
            & observer_up
            & (fd_fail >= config.fd_threshold)
            & ~state.alerted
        )
        if config.fd_gray_confirm > 0:
            # gray streak path (statically elided when disabled): a probe
            # that succeeds resets the streak; one that fails extends it,
            # and a streak of fd_gray_confirm on an edge with >=
            # fd_gray_warmup past successes fires like a hard failure
            ok_event = edge_live & observer_up & probe_ok
            fd_streak = state.fd_streak + (
                fail_event & (state.fd_streak < jnp.uint8(255))
            ).astype(jnp.uint8)
            fd_streak = jnp.where(ok_event, jnp.uint8(0), fd_streak)
            fd_ok = state.fd_ok + (
                ok_event & (state.fd_ok < jnp.uint8(255))
            ).astype(jnp.uint8)
            gray_down = (
                fail_event
                & (fd_streak >= config.fd_gray_confirm)
                & (state.fd_ok >= config.fd_gray_warmup)
                & ~state.alerted
            )
            new_down = new_down | gray_down
        alerted = state.alerted | new_down

    # --- alert routing (dst-indexed): on ring k the subject and observer
    # maps are inverse permutations over the active set, so the scatter
    # "alert from observer i lands at (subjects[i,k], k)" is exactly the
    # gather ``down_arrivals[d,k] = new_down[observers[d,k], k]`` -- and
    # gathers are far cheaper than scatters on TPU. Masked to active
    # destinations (joiner rows hold *expected* observers). ``down_reports``
    # are proactive DOWN alerts -- a graceful leave is just an eagerly
    # triggered edge failure (MembershipService.java:366-371) that skips the
    # FD threshold wait.
    cols = jnp.arange(k, dtype=jnp.int32)[None, :]
    down_arrivals = (
        new_down[state.observers, cols] | inputs.down_reports
    ) & active[:, None]
    return (key, active, alive, fd_fail, fd_hist, fd_seen, fd_streak,
            fd_ok, alerted, down_arrivals)


def step(config: SimConfig, state: SimState, inputs: RoundInputs,
         random_loss: bool = True) -> SimState:
    """One protocol round. Pure; jit/scan-friendly.

    ``random_loss`` statically elides the per-edge RNG draw when no lossy
    ingress fault is active (the common case) -- the threefry generation over
    [C, K] per round is otherwise a real bandwidth cost at C=100k.
    """
    halt = state.decided
    (key, active, alive, fd_fail, fd_hist, fd_seen, fd_streak, fd_ok,
     alerted, down_arrivals) = _fd_phase(config, state, inputs, random_loss)

    tallied = route_and_tally(config, state, down_arrivals, inputs,
                              active, alive)

    new_state = dataclasses.replace(
        tallied,
        active=active,
        alive=inputs.alive,
        fd_fail=fd_fail,
        fd_hist=fd_hist,
        fd_seen=fd_seen,
        fd_streak=fd_streak,
        fd_ok=fd_ok,
        alerted=alerted,
        round=state.round + 1,
        rng_key=key,
    )
    # After a decision the configuration is frozen until the host applies the
    # view change: all updates become no-ops.
    return jax.tree_util.tree_map(
        lambda old, new: jnp.where(halt, old, new), state, new_state
    )


# --------------------------------------------------------------------- #
# Profiling phase prefixes (profiling/phases.py)
# --------------------------------------------------------------------- #
# Each entry point executes only the leading phases of one round, so the
# shadow profiler can time consecutive prefixes and difference them:
# per-phase wall time then sums to the full step by construction. Never
# called on a production dispatch path; outputs exist only so XLA cannot
# dead-code the phase's work.


def step_fd_scan(
    config: SimConfig, state: SimState, inputs: RoundInputs,
    random_loss: bool = True,
) -> Tuple[SimState, jax.Array]:
    """FD-scan prefix: probe evaluation + alert routing only. Returns the
    partially-updated state and the ``down_arrivals`` gather (a live output,
    so the routing cost is measured, not eliminated)."""
    (key, active, _alive, fd_fail, fd_hist, fd_seen, fd_streak, fd_ok,
     alerted, down_arrivals) = _fd_phase(config, state, inputs, random_loss)
    partial = dataclasses.replace(
        state,
        active=active,
        alive=inputs.alive,
        fd_fail=fd_fail,
        fd_hist=fd_hist,
        fd_seen=fd_seen,
        fd_streak=fd_streak,
        fd_ok=fd_ok,
        alerted=alerted,
        rng_key=key,
    )
    return partial, down_arrivals


def step_cut_detector(
    config: SimConfig, state: SimState, inputs: RoundInputs,
    random_loss: bool = True,
) -> SimState:
    """FD-scan + cut-detector prefix: everything in ``step`` through
    proposal emission; vote casting and the fast-round tally are skipped
    (``route_and_tally(stop_after_cut=True)``)."""
    (key, active, alive, fd_fail, fd_hist, fd_seen, fd_streak, fd_ok,
     alerted, down_arrivals) = _fd_phase(config, state, inputs, random_loss)
    tallied = route_and_tally(config, state, down_arrivals, inputs,
                              active, alive, stop_after_cut=True)
    return dataclasses.replace(
        tallied,
        active=active,
        alive=inputs.alive,
        fd_fail=fd_fail,
        fd_hist=fd_hist,
        fd_seen=fd_seen,
        fd_streak=fd_streak,
        fd_ok=fd_ok,
        alerted=alerted,
        round=state.round + 1,
        rng_key=key,
    )


@functools.partial(make_jit, "sim.engine.run_rounds", static_argnums=0)
def run_rounds(config: SimConfig, state: SimState, inputs: RoundInputs) -> SimState:
    """Scan ``step`` over stacked per-round inputs (leading axis = rounds)."""

    def body(carry: SimState, per_round: RoundInputs):
        return step(config, carry, per_round), ()

    final, _ = jax.lax.scan(body, state, inputs)
    return final


def _run_rounds_const(
    config: SimConfig, state: SimState, inputs: RoundInputs, rounds: int,
    random_loss: bool = True,
) -> SimState:
    """Scan ``rounds`` rounds under a constant fault plane (inputs without a
    leading rounds axis). Avoids materializing [R, C, K] fault arrays -- the
    path used for large-capacity runs."""

    def body(carry: SimState, _):
        return step(config, carry, inputs, random_loss), ()

    final, _ = jax.lax.scan(body, state, None, length=rounds)
    return final


# ``rounds`` is the scan length, so it must stay static; the driver bounds
# the distinct values it dispatches (power-of-two tail chunks) to keep this
# class's compile count flat.  # devlint: static-shape
run_rounds_const = make_jit(
    "sim.engine.run_rounds_const", _run_rounds_const,
    static_argnums=(0, 3, 4),
)
# The driver's carried-state variant: the previous round batch's state is
# dead the moment the call returns, so its buffers are donated to the
# output (no [C, K]-scale copy per dispatch). Tests and differential
# callers that reuse the input state must use the plain variant above.
# Same bounded scan-length discipline as above.  # devlint: static-shape
run_rounds_const_donated = make_jit(
    "sim.engine.run_rounds_const.donated", _run_rounds_const,
    static_argnums=(0, 3, 4), donate_argnums=(1,),
)


def _run_until_decided_const(
    config: SimConfig,
    state: SimState,
    inputs: RoundInputs,
    max_rounds: jax.Array,
    uniform_delivery: bool = True,
    stop_when_announced: bool = False,
) -> SimState:
    """Run up to ``max_rounds`` rounds of a *constant, deterministic* fault
    plane in ONE device dispatch, exiting as soon as consensus decides.

    With the fault plane fixed for the whole dispatch and no random ingress
    loss, the probe phase is closed-form: each monitoring edge's probe
    outcome is the same every probing round, so the round at which it fires
    is computable up front -- for the cumulative policy, when the counter
    crosses the threshold (PingPongFailureDetector.java:69-77); for the
    windowed policy, by stepping the (<= fd_window)-step window recurrence
    at trace time until it saturates (after W recorded probes with a
    constant outcome the window is in steady state, so the first firing
    probe index is always <= W). The while-loop body is then pure
    elementwise arithmetic -- no per-round gathers -- and rounds after the
    decision are never executed at all, unlike the scan path's masked
    no-ops. Produces bit-identical state to scanning ``step`` with
    ``random_loss=False`` over the same inputs, with one exception:
    ``rng_key`` is not advanced (this path draws no random numbers, whereas
    the scan path splits the key every round).
    """
    assert config.fd_policy in ("cumulative", "windowed")
    c, k = config.capacity, config.k
    active = state.active
    alive = inputs.alive & active
    subj = state.subjects
    edge_live = active[:, None] & active[subj]
    observer_up = alive[:, None]
    target_up = alive[subj]
    probe_ok = target_up & ~inputs.probe_drop
    fail_event = edge_live & observer_up & ~probe_ok  # constant per round

    # Probe index (1-based) at which each observer-indexed edge fires; never
    # fires here otherwise. An edge already at/over threshold but unalerted
    # fires on its next qualifying probe. With staggered phases an observer
    # probes only at relative rounds p_rel+1, p_rel+1+rpi, ... where p_rel
    # re-bases its fixed phase onto this dispatch's starting round.
    never = jnp.int32(0x7FFFFFFF)
    rpi = config.rounds_per_interval
    if rpi > 1:
        p_rel = (probe_phases(config) - state.round) % rpi  # [C]
    if config.fd_policy == "windowed":
        # step the window recurrence W times at trace time (W <= 16 cheap
        # elementwise ops over [C, K], once per dispatch): record the first
        # probe index at which window_step reports a crossing. Probed edges
        # shift their constant outcome in; by probe W the window is entirely
        # new bits, so later probes cannot produce a first firing.
        probed = edge_live & observer_up
        fail = probed & ~probe_ok
        w, _, maskw = _window_params(config)
        hist, seen = state.fd_hist, state.fd_seen
        fire_probe = jnp.full((c, k), never, jnp.int32)
        for j in range(1, w + 1):
            hist, seen, crossed = window_step(config, hist, seen, probed, fail)
            fire_probe = jnp.where(
                crossed & (fire_probe == never), jnp.int32(j), fire_probe
            )
        fires = (fire_probe != never) & ~state.alerted
    else:
        fire_probe = jnp.maximum(
            config.fd_threshold - state.fd_fail.astype(jnp.int32), 1
        )
        if config.fd_gray_confirm > 0:
            # gray streak path: with a constant fault plane a failing edge
            # fails every probe, so the streak alert fires at probe
            # confirm - streak0 (>= 1) on edges whose healthy history was
            # established before this dispatch (fd_ok cannot advance on a
            # failing edge, so the qualification is constant here)
            qualified = state.fd_ok >= config.fd_gray_warmup
            gray_probe = jnp.maximum(
                config.fd_gray_confirm - state.fd_streak.astype(jnp.int32), 1
            )
            fire_probe = jnp.where(
                qualified, jnp.minimum(fire_probe, gray_probe), fire_probe
            )
        fires = fail_event & ~state.alerted
    if rpi > 1:
        fire_round = p_rel[:, None] + 1 + (fire_probe - 1) * rpi
    else:
        fire_round = fire_probe
    fire = jnp.where(fires, fire_round, never)
    cols = jnp.arange(k, dtype=jnp.int32)[None, :]
    # dst-indexed arrival round (see the gather-not-scatter note in ``step``).
    # Proactive DOWN reports (graceful leave) arrive in the first round; the
    # scan path re-delivers them every round, but reports latch with OR so
    # first-round delivery is bit-identical.
    fire_dst = jnp.where(active[:, None], fire[state.observers, cols], never)
    fire_dst = jnp.where(
        inputs.down_reports & active[:, None], jnp.int32(1), fire_dst
    )

    state = dataclasses.replace(
        state, alive=jnp.where(state.decided, state.alive, inputs.alive)
    )

    # Fast-forward over provably-inert rounds: from a *fresh* configuration
    # (no reports, nothing announced, no votes cast or in flight, no join
    # traffic) a round with no alert arrivals is a strict no-op -- counts stay
    # zero, the implicit pass, the vote casting, and the tally cannot fire --
    # so execution can start at the first arrival round. Skipped rounds still
    # count toward the budget, the round counter, and the closed-form FD
    # reconstruction below, so the result (including decided_round and
    # virtual-time billing) is bit-identical to sequential execution. Saves
    # ~threshold-1 loop iterations per decision dispatch.
    fresh = (
        ~state.decided
        & ~jnp.any(state.reports)
        & ~jnp.any(state.announced)
        & ~jnp.any(state.seen_down)
        & ~jnp.any(state.voted)
        & ~jnp.any(state.vote_new)
        & ~jnp.any(state.vote_hist)
        & ~jnp.any(state.arrival_hist)
        & ~jnp.any(inputs.join_reports)
    )
    first_arrival = jnp.min(fire_dst)  # == `never` when no edge will fire
    start = jnp.where(
        fresh,
        jnp.clip(
            jnp.minimum(first_arrival - 1, max_rounds.astype(jnp.int32)),
            0,
            None,
        ),
        0,
    )
    state = dataclasses.replace(state, round=state.round + start)

    def cond(carry):
        st, r = carry
        keep = (r < max_rounds) & ~st.decided
        if stop_when_announced:
            # pause the dispatch at the round a group proposal is announced
            # (extern rows excluded), so the bridge can broadcast the
            # pre-decision cut to real members before votes tally -- ONE
            # dispatch instead of a host-driven round-at-a-time loop
            keep &= ~jnp.any(st.announced[: config.groups])
        return keep

    def body(carry):
        st, r = carry
        r = r + 1
        down_arrivals = fire_dst == r
        st = route_and_tally(
            config, st, down_arrivals, inputs, active, alive,
            uniform_delivery=uniform_delivery, gate_implicit=True,
        )
        st = dataclasses.replace(st, round=st.round + 1)
        return st, r

    final, r_exec = jax.lax.while_loop(
        cond, body, (state, start)
    )
    # Reconstruct the per-edge FD state the executed rounds produced (number
    # of scheduled probes within [1, r_exec] per observer).
    if rpi > 1:
        probes = jnp.maximum(0, (r_exec - 1 - p_rel) // rpi + 1)[:, None]
    else:
        probes = r_exec
    alerted = state.alerted | (fire <= r_exec)
    if config.fd_policy == "windowed":
        # hist after p recorded probes of constant outcome f:
        # (hist0 << p | f * (2^p - 1)) masked -- only min(p, W) matters
        # (shift in uint32: uint16 shifts by >= 16 are undefined)
        p_eff = jnp.minimum(probes, w).astype(jnp.uint32)
        h32 = state.fd_hist.astype(jnp.uint32) << p_eff
        fills = jnp.where(fail, (jnp.uint32(1) << p_eff) - 1, jnp.uint32(0))
        hist_new = ((h32 | fills) & jnp.uint32(maskw)).astype(jnp.uint16)
        fd_hist = jnp.where(probed, hist_new, state.fd_hist)
        fd_seen = jnp.where(
            probed,
            jnp.minimum(
                state.fd_seen.astype(jnp.int32) + probes, w
            ).astype(jnp.uint8),
            state.fd_seen,
        )
        return dataclasses.replace(
            final, fd_hist=fd_hist, fd_seen=fd_seen, alerted=alerted
        )
    fd_fail = jnp.minimum(
        state.fd_fail.astype(jnp.int32) + probes * fail_event.astype(jnp.int32),
        255,
    ).astype(jnp.uint8)
    if config.fd_gray_confirm > 0:
        # reconstruct the streak counters the executed rounds produced:
        # constant outcome means a failing edge's streak grows by its probe
        # count (saturating) and a succeeding edge's resets with any probe
        ok_event = edge_live & observer_up & probe_ok
        fd_streak = jnp.minimum(
            state.fd_streak.astype(jnp.int32)
            + probes * fail_event.astype(jnp.int32),
            255,
        )
        fd_streak = jnp.where(
            ok_event & (probes >= 1), 0, fd_streak
        ).astype(jnp.uint8)
        fd_ok = jnp.where(
            ok_event,
            jnp.minimum(state.fd_ok.astype(jnp.int32) + probes, 255),
            state.fd_ok.astype(jnp.int32),
        ).astype(jnp.uint8)
        return dataclasses.replace(
            final, fd_fail=fd_fail, fd_streak=fd_streak, fd_ok=fd_ok,
            alerted=alerted,
        )
    return dataclasses.replace(final, fd_fail=fd_fail, alerted=alerted)


run_until_decided_const = make_jit(
    "sim.engine.run_until_decided_const", _run_until_decided_const,
    static_argnums=(0, 4, 5),
)
# Carried-state variant for the driver's decision loop (see
# run_rounds_const_donated): the input state is donated, so callers must
# not reuse it after the dispatch.
run_until_decided_const_donated = make_jit(
    "sim.engine.run_until_decided_const.donated", _run_until_decided_const,
    static_argnums=(0, 4, 5), donate_argnums=(1,),
)


@functools.partial(make_jit, "sim.engine.device_initial_state",
                   static_argnums=(0,))
def device_initial_state(
    config: SimConfig,
    ring_rank: jax.Array,  # int32[K, C] rank of each node in the full ring order
    active: jax.Array,  # bool[C]
    alive: jax.Array,  # bool[C]
    group_of: jax.Array,  # int32[C]
    auto_vote: jax.Array,  # bool[C] (False = slot voted by a bridged real node)
    rng_key: jax.Array,
) -> SimState:
    """Fresh-configuration state built entirely on device.

    The adjacency rebuild (MembershipView ringAdd/ringDelete at a view change)
    is a masked sort of resident per-ring *ranks* (each node's position in the
    full-capacity ring order, host-computed once from the signed xxHash keys):
    inactive entries sort to the end, the first n slots are the active
    membership in ring order, and predecessor/successor are index arithmetic
    mod n. Ranks are distinct int32, so the order is exactly the host
    ``build_adjacency`` order without needing 64-bit keys on device or moving
    the [C, K] adjacency over PCIe at every view change.
    """
    c, k = config.capacity, config.k
    top = jnp.int32(0x7FFFFFFF)
    keys = jnp.where(active[None, :], ring_rank, top)
    order = jnp.argsort(keys, axis=1, stable=True).astype(jnp.int32)  # [K, C]
    n = active.sum().astype(jnp.int32)
    n1 = jnp.maximum(n, 1)
    p = jnp.arange(c, dtype=jnp.int32)[None, :]
    pred_idx = jnp.where(p < n, (p - 1) % n1, p)
    succ_idx = jnp.where(p < n, (p + 1) % n1, p)
    preds = jnp.take_along_axis(order, pred_idx, axis=1)
    succs = jnp.take_along_axis(order, succ_idx, axis=1)

    base = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[:, None], (c, k))
    ring_ids = jnp.broadcast_to(
        jnp.arange(k, dtype=jnp.int32)[:, None], (k, c)
    ).reshape(-1)
    nodes_flat = order.reshape(-1)
    subjects = base.at[nodes_flat, ring_ids].set(preds.reshape(-1))
    observers = base.at[nodes_flat, ring_ids].set(succs.reshape(-1))

    g = config.groups
    p = config.proposal_rows
    return SimState(
        active=active,
        alive=alive,
        group_of=group_of,
        subjects=subjects,
        observers=observers,
        fd_fail=jnp.zeros((c, k), jnp.uint8),
        fd_hist=jnp.zeros((c, k), jnp.uint16),
        fd_seen=jnp.zeros((c, k), jnp.uint8),
        fd_streak=jnp.zeros((c, k), jnp.uint8),
        fd_ok=jnp.zeros((c, k), jnp.uint8),
        alerted=jnp.zeros((c, k), bool),
        reports=jnp.zeros((g, c, k), bool),
        arrival_hist=jnp.zeros((config.max_delivery_delay, c, k), bool),
        seen_down=jnp.zeros(g, bool),
        announced=jnp.zeros(p, bool),
        announced_round=jnp.asarray(0, jnp.int32),
        proposal=jnp.zeros((p, c), bool),
        auto_vote=auto_vote,
        voted=jnp.zeros(c, bool),
        vote_prop=jnp.zeros(c, jnp.int32),
        vote_new=jnp.zeros(c, bool),
        vote_hist=jnp.zeros((config.max_delivery_delay, c), bool),
        votes_recv=jnp.zeros((g, c), bool),
        classic_rnd=jnp.zeros(c, jnp.int32),
        classic_vrnd=jnp.zeros(c, jnp.int32),
        classic_vval=jnp.full(c, -1, jnp.int32),
        decided=jnp.asarray(False),
        decided_group=jnp.asarray(0, jnp.int32),
        decided_round=jnp.asarray(0, jnp.int32),
        round=jnp.asarray(0, jnp.int32),
        rng_key=rng_key,
    )


# --------------------------------------------------------------------- #
# Packed decision summary
# --------------------------------------------------------------------- #
# Remote-device transports (the TPU tunnel) pay roughly one round-trip
# latency PER BUFFER fetched, so the driver's post-dispatch sync packs
# everything a decision needs into ONE uint32 word stream and fetches that
# single array. Layout: 5 header words (decided, decided_group,
# decided_round, round, announced_round), then ceil(P/32) words of
# announced bits, then P * ceil(C/32) words of proposal bits (row-major,
# LSB-first within each word).

_SUMMARY_HEADER = 5


def _words_per(n: int) -> int:
    return (n + 31) // 32


@functools.partial(make_jit, "sim.engine.pack_decision", static_argnums=(0,))
def pack_decision(config: SimConfig, state: SimState) -> jax.Array:
    """Bit-pack the decision-relevant slice of ``state`` into one uint32
    array (see layout note above). Dispatch is async; the caller fetches the
    result with a single ``jax.device_get``, paying the host<->device
    round trip exactly once per protocol batch."""
    shifts = jnp.arange(32, dtype=jnp.uint32)

    def bits_to_words(bits: jax.Array) -> jax.Array:
        n = bits.shape[-1]
        pad = (-n) % 32
        if pad:
            bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
        w = bits.reshape(bits.shape[:-1] + (-1, 32)).astype(jnp.uint32) << shifts
        return w.sum(axis=-1, dtype=jnp.uint32)

    header = jnp.stack(
        [
            state.decided.astype(jnp.uint32),
            state.decided_group.astype(jnp.uint32),
            state.decided_round.astype(jnp.uint32),
            state.round.astype(jnp.uint32),
            state.announced_round.astype(jnp.uint32),
        ]
    )
    announced = bits_to_words(state.announced)  # [ceil(P/32)]
    proposal = bits_to_words(state.proposal)  # [P, ceil(C/32)]
    return jnp.concatenate([header, announced, proposal.reshape(-1)])


def unpack_decision(
    config: SimConfig, words: np.ndarray
) -> Tuple[bool, np.ndarray, int, np.ndarray, int, int, int]:
    """Host-side inverse of ``pack_decision``. Returns ``(decided,
    announced[P], announced_round, proposal[P, C], decided_group,
    decided_round, round)``."""
    p, c = config.proposal_rows, config.capacity
    words = np.asarray(words, dtype=np.uint32)
    pw, cw = _words_per(p), _words_per(c)

    def words_to_bits(w: np.ndarray, n: int) -> np.ndarray:
        bits = ((w[..., None] >> np.arange(32, dtype=np.uint32)) & 1).astype(bool)
        return bits.reshape(w.shape[:-1] + (-1,))[..., :n]

    off = _SUMMARY_HEADER
    announced = words_to_bits(words[off : off + pw], p)
    proposal = words_to_bits(
        words[off + pw : off + pw + p * cw].reshape(p, cw), c
    )
    return (
        bool(words[0]),
        announced,
        int(np.int32(words[4])),
        proposal,
        int(np.int32(words[1])),
        int(np.int32(words[2])),
        int(np.int32(words[3])),
    )


def const_inputs(
    config: SimConfig,
    alive: np.ndarray,
    probe_drop: Optional[np.ndarray] = None,
    drop_prob: Optional[np.ndarray] = None,
    join_reports: Optional[np.ndarray] = None,
    deliver: Optional[np.ndarray] = None,
    down_reports: Optional[np.ndarray] = None,
    deliver_delay: Optional[np.ndarray] = None,
) -> RoundInputs:
    """A single-round fault plane (for run_rounds_const)."""
    c, k, g = config.capacity, config.k, config.groups
    return RoundInputs(
        alive=jnp.asarray(alive),
        probe_drop=jnp.zeros((c, k), bool) if probe_drop is None else jnp.asarray(probe_drop),
        drop_prob=jnp.zeros(c, jnp.float32) if drop_prob is None else jnp.asarray(drop_prob),
        join_reports=jnp.zeros((c, k), bool) if join_reports is None else jnp.asarray(join_reports),
        down_reports=jnp.zeros((c, k), bool) if down_reports is None else jnp.asarray(down_reports),
        deliver=jnp.ones((g, c), bool) if deliver is None else jnp.asarray(deliver),
        deliver_delay=(
            jnp.zeros((g, c), jnp.int32)
            if deliver_delay is None
            else jnp.asarray(deliver_delay, dtype=jnp.int32)
        ),
    )
