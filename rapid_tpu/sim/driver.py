"""Host driver: runs the device round loop and applies view changes.

The host owns the control plane -- the exact mirror of what real Rapid nodes do
outside the hot loop: ring/adjacency construction at configuration changes
(MembershipView ringAdd/ringDelete), configuration identity (the chained
xxHash64, bit-compatible with the JVM), and the identifiersSeen set (which is
append-only across the cluster's lifetime, MembershipView.java:51,155).

The fault API mirrors the BASELINE.json scenarios: correlated crash bursts,
asymmetric one-way link loss, lossy ingress, flip-flop reachability, and join
waves. Faults persist across configurations the way they would against a real
cluster: crashes stay crashed, ingress partitions are re-mapped onto the new
adjacency, and pending joiners re-attempt in each new configuration (a real
joiner whose phase-2 landed in a superseded configuration retries,
Cluster.java:313-344).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import (
    HANDOFF_BYTES_BUCKETS,
    HANDOFF_CHUNKS_BUCKETS,
    PARTITIONS_MOVED_BUCKETS,
    SERVING_LATENCY_BUCKETS_MS,
    FlightRecorder,
    Metrics,
    StableViewTimer,
    TraceContext,
    Tracer,
    global_metrics,
    global_tracer,
)
from ..runtime import jitwatch
from .engine import (
    RoundInputs,
    SimConfig,
    SimState,
    device_initial_state,
    pack_decision,
    run_rounds_const_donated,
    run_until_decided_const_donated,
    unpack_decision,
)
from .topology import (
    VirtualCluster,
    config_fold,
    ring_order,
)


# the classic exchange's delivery hops (phase1a, 1b, 2a, 2b), billed at the
# same one-round-per-hop quantization as fast-round vote propagation; under
# heterogeneous latency the winning coordinator's actual phase cutoffs are
# billed instead (sim/classic.py: each phase closes when the majority's
# responses have arrived)
_CLASSIC_ROUND_HOPS = 4


def _pow2_chunks(n: int, batch: int) -> List[int]:
    """Split ``n`` rounds into scan lengths drawn from {batch} and powers of
    two. The scan length is a static argument of run_rounds_const (a distinct
    executable per value), so an arbitrary tail (max_rounds % batch) would
    mint unbounded compile classes; power-of-two tails cap them at
    log2(batch) + 1 while executing exactly ``n`` rounds."""
    chunks: List[int] = []
    while n > 0:
        step = batch if n >= batch else 1 << (n.bit_length() - 1)
        chunks.append(step)
        n -= step
    return chunks


@dataclass
class ViewChangeRecord:
    """One decided configuration change."""

    cut: np.ndarray  # node ids added/removed
    added: np.ndarray
    removed: np.ndarray
    configuration_id: int
    virtual_time_ms: int  # protocol-time of the decision
    wall_time_s: float  # host+device time spent simulating to it
    membership_size: int
    via_classic_round: bool = False  # decided by the Paxos fallback


class Simulator:  # guarded-by: sim-loop
    def __init__(
        self,
        n_nodes: int,
        capacity: Optional[int] = None,
        config: Optional[SimConfig] = None,
        seed: int = 0,
        mesh=None,
        speculate: bool = True,
        identities=None,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        """``mesh``: a jax.sharding.Mesh (from shard.engine.make_mesh) to run
        the round loop sharded over multiple devices -- per-edge state
        row-sharded over every mesh axis, alert fan-out as a psum over
        ICI/DCN. The whole fault/join/leave API and view-change machinery is
        identical in both modes; sharded dispatches use the scan path (the
        early-exit closed form is single-device).

        ``speculate``: overlap view-change precomputation with the decision
        fetch (_speculate_view_change). Semantically invisible; the flag
        exists so differential tests can pin that invisibility.

        ``identities``: optional [(hostname bytes, port, id_high, id_low)]
        seated into slots 0.. before any state is built, replacing the
        synthesized identities -- the cross-plane parity entry: seat the
        protocol plane's exact endpoints and NodeIds and the configuration
        ids fold bit-identically on both planes."""
        capacity = capacity if capacity is not None else n_nodes
        assert n_nodes <= capacity
        self.config = config if config is not None else SimConfig(capacity=capacity)
        assert self.config.capacity == capacity
        assert self.config.fd_interval_ms % self.config.rounds_per_interval == 0, (
            "fd_interval_ms must divide evenly into sub-interval rounds"
        )
        if mesh is not None:
            n_dev = int(np.prod(list(mesh.shape.values())))
            assert capacity % n_dev == 0, (
                f"capacity {capacity} must divide evenly over the mesh's "
                f"{n_dev} devices (row-sharded per-edge state)"
            )
        self.mesh = mesh
        self.cluster = VirtualCluster.synthesize(capacity, self.config.k, seed=seed)
        if identities is not None:
            assert len(identities) <= capacity
            for slot, (host, port, id_high, id_low) in enumerate(identities):
                self.cluster.assign_identity(slot, host, port, id_high, id_low)
        self.active = np.zeros(capacity, dtype=bool)
        self.active[:n_nodes] = True
        self.alive = self.active.copy()
        self.group_of = np.zeros(capacity, dtype=np.int32)
        # slots whose fast-round votes the engine casts itself. The bridge
        # seam: TpuSimMessaging clears a slot when a real member owns it, so
        # only that node's actually-received votes count toward the tally
        self.auto_vote = np.ones(capacity, dtype=bool)
        # identifiersSeen is an append-only *value* history of every NodeId
        # ever admitted (MembershipView.java:51,155): stored by (high, low)
        # value, not by slot, so slots can be re-seated with fresh identities
        # (a rejoining process draws a fresh UUID, Cluster.java:327-331)
        # without corrupting the configuration-id fold over the history.
        slots = np.flatnonzero(self.active)
        self._seen_ids = np.stack(
            [self.cluster.id_high[slots], self.cluster.id_low[slots]], axis=1
        )  # [M, 2] int64, admission order
        # the membership test over the history is built lazily: it is only
        # consulted on identity admissions (joins), and materializing a
        # million-tuple set up front is a real construction cost
        self._seen_set: Optional[Set[Tuple[int, int]]] = None
        self._seen_hashes: Optional[np.ndarray] = None  # [M, 2] uint64
        self.seed = seed
        self.speculate = speculate
        self.virtual_ms = 0
        # telemetry injection (None -> per-sim registries attached to the
        # process-global plane); stored as overrides so from_configuration's
        # __new__ path reconstructs identically via _init_runtime_state
        self._metrics_override = metrics
        self._tracer_override = tracer
        self._init_runtime_state()

    def _init_runtime_state(self) -> None:
        """Everything past identity/membership: device caches, fresh device
        state, metrics, the all-clear fault plane, and the hash pre-warms.
        Shared by __init__ and from_configuration so restored simulators can
        never silently diverge from freshly-constructed ones."""
        capacity = self.config.capacity
        self._sharded_runs: dict = {}
        # configuration-id memo; invalidated whenever its inputs (active
        # membership / identifier history) change, i.e. at view changes
        self._config_id: Optional[int] = None
        # speculative view-change precomputation (see _speculate_view_change):
        # (new-active bytes, seed, config id, fresh SimState, alive bytes).
        # Must exist before the first _fresh_state call below.
        self._spec: Optional[Tuple[bytes, int, int, SimState, bytes]] = None
        self._init_device_caches()
        self.state = self._fresh_state(self.seed)
        self._billed_rounds = 0  # rounds of this configuration already billed
        self._rounds_executed = 0  # host mirror of state.round (per config)
        self.view_changes: List[ViewChangeRecord] = []
        metrics_override = getattr(self, "_metrics_override", None)
        tracer_override = getattr(self, "_tracer_override", None)
        self.metrics = (
            metrics_override
            if metrics_override is not None
            else Metrics(parent=global_metrics(), plane="sim")
        )
        self.tracer = (
            tracer_override
            if tracer_override is not None
            else Tracer(parent=global_tracer(), plane="sim", track="sim")
        )
        # detection -> decision -> view-installed on the VIRTUAL clock, with
        # the same bucket edges as the protocol plane's StableViewTimer, so
        # time_to_stable_view_ms distributions compare bucket-for-bucket
        self._stable_view = StableViewTimer(
            self.metrics, "sim", clock=lambda: self.virtual_ms
        )
        # cross-plane trace parity: the first fault injection of a churn
        # episode mints a trace context (the sim's fd_signal equivalent);
        # the view_change span adopts it as a remote-span edge, exactly as a
        # real member's view_change parents onto the detecting node's
        # fd_signal. Cleared when the view installs.
        self._churn_ctx: Optional[TraceContext] = None
        # forensics mirror: the sim's HLC runs on the virtual clock, so a
        # sim journal is deterministic run-to-run and merges causally with
        # real members' bundles (None keeps pre-forensics journal entries)
        self.hlc = None
        if self.config.forensics:
            from ..forensics.hlc import HlcClock

            self.hlc = HlcClock(clock=lambda: self.virtual_ms)
        self.recorder = FlightRecorder(
            node="sim", clock=lambda: self.virtual_ms,
            hlc=self.hlc, metrics=self.metrics,
        )
        # fault plane
        self._ingress_partitioned: Set[int] = set()
        self._drop_prob = np.zeros(capacity, dtype=np.float32)
        self._deliver = np.ones((self.config.groups, capacity), dtype=bool)
        self._pending_joiners: Set[int] = set()
        self._join_reports_armed = False
        self._pending_leavers: Set[int] = set()
        self._last_announcement: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # host-side randomness for the classic-fallback coordinator race
        # (which nodes' expovariate timers fire first, FastPaxos.java:200-203);
        # seeded so runs replay deterministically
        self._host_rng = np.random.default_rng(self.seed ^ 0x5EED_C1A5)
        self._down_reports_dev: Optional[jax.Array] = None
        self._injected_down = np.zeros(
            (self.config.capacity, self.config.k), dtype=bool
        )
        # profiling plane (opt-in via enable_profiling; like placement, a
        # restored simulator re-enables it explicitly)
        self._profiler = None
        # placement plane (opt-in via enable_placement; not part of protocol
        # state, so from_configuration restores re-enable it explicitly)
        self._placement = None
        self._placement_diffs: List = []
        # handoff plane (opt-in via enable_handoff; requires placement)
        self._handoff_stores = None
        self._handoff_sizes: Optional[np.ndarray] = None
        self._handoff_chunk_size = 1 << 16
        self._handoff_chunk_ms = 1
        self._handoff_max_chunk_retries = 8
        self._handoff_nemesis = None
        self._handoff_transfers: List = []
        # serving plane (opt-in via enable_serving; requires handoff -- the
        # KV blobs live inside the handoff stores so view changes move them)
        self._serving_enabled = False
        self._serving_request_ms = 1
        self._serving_nemesis = None
        self._serving_cache: dict = {}  # (slot, partition) -> decoded KV map
        self._serving_acked: dict = {}  # key -> (version, value) at ack time
        self._serving_eps: dict = {}
        # durability plane (opt-in via enable_durability; requires serving):
        # per-slot WAL-record counts so restart replay bills virtual time
        self._durability_enabled = False
        self._durability_replay_ms = 1
        self._durable_pending: dict = {}  # slot -> records since checkpoint
        # SLO plane (opt-in via enable_slo; None is the kill-switch-off
        # path: serving requests run the exact pre-SLO code)
        self._slo = None
        # hierarchy mirror (opt-in via enable_hierarchy; derived
        # composition state like placement, so from_configuration restores
        # re-enable it explicitly)
        self._hier_cell_of: Optional[np.ndarray] = None
        self._hier_n_cells = 0
        self._hier_round_ms = 1
        self._hier_leaders_per_cell = 1
        self._hier_rows: dict = {}
        self._hier_rounds = 0
        # membership-invariant element hashes: construction cost, not
        # protocol time (they feed every configuration_id fold)
        self.cluster.node_hashes()
        self._sorted_identifiers()
        self._seen_id_hashes()

    def _rep(self, arr) -> jax.Array:
        """Place as replicated over the mesh (or the default device)."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def _row(self, arr) -> jax.Array:
        """Place row-sharded over every mesh axis (observer-sharded [C, K])."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            arr, NamedSharding(self.mesh, P(self.mesh.axis_names, None))
        )

    def _init_device_caches(self) -> None:
        """Device-resident constants allocated once per simulator: the signed
        ring keys (so adjacency rebuilds never re-upload them) and the
        all-clear fault-plane arrays (so quiet rounds transfer nothing but
        the [C] liveness mask). In mesh mode every fault-plane array is placed
        under its dispatch sharding at creation, so dispatches never reshard."""
        c, k, g = self.config.capacity, self.config.k, self.config.groups
        self._ring_rank_dev = jnp.asarray(self.cluster.ring_rank())
        self._ring_rank_dirty = False
        zeros_ck = np.zeros((c, k), bool)
        self._zero_ck_row = self._row(zeros_ck)  # probe_drop role
        self._zero_ck = self._rep(zeros_ck)  # join/down report roles
        self._zero_drop_prob = self._rep(np.zeros(c, np.float32))
        self._ones_deliver = self._rep(np.ones((g, c), bool))
        self._zero_delay = self._rep(np.zeros((g, c), np.int32))
        self._deliver_delay = np.zeros((g, c), dtype=np.int32)
        self._i32_cache: dict = {}  # py int -> device int32 scalar
        self._deliver_delay_dev: Optional[jax.Array] = None
        self._alive_dev: Optional[jax.Array] = None
        self._probe_drop_dev: Optional[jax.Array] = None
        self._subjects_host: Optional[np.ndarray] = None
        self._observers_host: Optional[np.ndarray] = None
        self._ring_nodes: Optional[List[np.ndarray]] = None
        self._ids_sorted: Optional[np.ndarray] = None

    def _i32(self, n: int) -> jax.Array:
        """Cached device int32 scalar for dispatch budgets: a run uses a
        handful of distinct batch sizes, so each is uploaded once instead of
        minting a fresh host->device transfer on every dispatch."""
        dev = self._i32_cache.get(n)
        if dev is None:
            with jitwatch.host_transfer("sim.batch_budget"):
                dev = jnp.int32(n)
            self._i32_cache[n] = dev
        return dev

    def _fresh_state(self, seed: int) -> SimState:
        """Fresh-configuration state, built on device (engine.device_initial_state)."""
        # extern proposal rows, the per-sender vote dedup, and the classic
        # round counter are per-configuration, like every consensus latch
        self._extern_rows: dict = {}  # proposal-mask bytes -> extern row
        self._extern_voted: Set[int] = set()
        self._last_announcement = None
        self._classic_attempts = 0
        if self._ring_rank_dirty:
            # identities assigned since the last rebuild (joiner seating)
            self._ring_rank_dev = jnp.asarray(self.cluster.ring_rank())
            self._ring_rank_dirty = False
        self._subjects_host = None
        self._observers_host = None
        self._ring_nodes = None
        self._alive_dev = None
        self._probe_drop_dev = None  # partition set maps onto new adjacency
        self._down_reports_dev = None  # leave alerts map onto new adjacency
        spec = self._spec
        if (
            spec is not None
            and spec[0] == self.active.tobytes()
            and spec[1] == seed
            # the alive mask the worker baked in must still hold (a revive
            # or crash between speculation and decision invalidates it)
            and spec[4] == (self.alive & self.active).tobytes()
        ):
            self._spec = None
            self.metrics.incr("speculation_hits_fresh_state")
            return spec[3]
        state = device_initial_state(
            self.config,
            self._ring_rank_dev,
            jnp.asarray(self.active),
            jnp.asarray(self.alive & self.active),
            jnp.asarray(self.group_of),
            jnp.asarray(self.auto_vote),
            jax.random.PRNGKey(seed),
        )
        if self.mesh is not None:
            from ..shard.engine import place_state

            state = place_state(state, self.mesh)
        return state

    # ------------------------------------------------------------------ #
    # Fault injection (BASELINE.json configs)
    # ------------------------------------------------------------------ #

    def _fd_signal(self, **attrs: object) -> None:
        """Root of a churn episode's trace on the sim plane: mirrors the
        protocol plane's edge-FD signal so merged traces show one trace_id
        from injection to view install regardless of plane."""
        signal = self.tracer.event("fd_signal", virtual_ms=self.virtual_ms,
                                   **attrs)
        if self._churn_ctx is None:
            self._churn_ctx = TraceContext(
                trace_id=signal.trace_id or signal.span_id,
                parent_span_id=signal.span_id,
                origin="sim",
            )
        # the journal entry carries the episode's trace id so attribution
        # (slo/attrib.py) can reconstruct injection -> install from the
        # journal alone, without the span ring
        self.recorder.record(
            "fd_signal", trace_id=self._churn_ctx.trace_id, **attrs
        )

    def crash(self, node_ids: np.ndarray) -> None:
        """Crash-stop burst: nodes stop responding to probes and stop voting."""
        self._stable_view.detection()
        self._fd_signal(cause="crash", nodes=len(np.atleast_1d(node_ids)))
        self.alive[np.atleast_1d(node_ids)] = False
        # enqueue the liveness transfer now (async) so the decision loop's
        # dispatch never waits on a host->device round trip for it
        self._alive_dev = self._rep(self.alive)

    def revive(self, node_ids: np.ndarray) -> None:
        """Flip-flop support: nodes become reachable again (cumulative FD
        counters are deliberately NOT reset -- PingPongFailureDetector.java:116-118)."""
        node_ids = np.atleast_1d(node_ids)
        self.alive[node_ids] = self.active[node_ids]
        self._alive_dev = self._rep(self.alive)

    def leave(self, node_ids: np.ndarray) -> None:
        """Graceful leave: each leaver proactively notifies its K observers,
        which broadcast DOWN alerts immediately -- leave is just an eagerly
        triggered edge failure (MembershipService.java:366-371,534-554), so
        the cut decides in ~1 round instead of waiting out the FD threshold.
        Leavers keep responding to probes until the view change removes them
        (a leaving process shuts down only after its notification round)."""
        self._stable_view.detection()
        self._fd_signal(cause="leave", nodes=len(np.atleast_1d(node_ids)))
        for node in np.atleast_1d(node_ids):
            node = int(node)
            assert self.active[node], f"node {node} is not a member"
            # a crashed process cannot send a leave notification; its removal
            # must go through failure detection
            assert self.alive[node], f"node {node} is crashed, cannot leave"
            self._pending_leavers.add(node)
        self._down_reports_dev = None

    def inject_down_report(self, dst: int, rings) -> None:
        """Externally sourced DOWN reports for ``dst`` on the given rings --
        how alerts broadcast by *real* processes (bridged via TpuSimMessaging)
        enter the simulated cut detector's report table. One-shot per
        configuration, like any other alert."""
        self._stable_view.detection()
        self._fd_signal(cause="injected_report", dst=int(dst))
        self._injected_down[dst, list(rings)] = True
        self._down_reports_dev = None

    def assign_identity(
        self, slot: int, hostname: bytes, port: int, id_high: int, id_low: int
    ) -> None:
        """Seat a process identity in an inactive slot ahead of its join; see
        VirtualCluster.assign_identity. Re-seating a slot whose previous
        identity was admitted in some past configuration is legal -- the
        identifier history is stored by value -- but identifier *reuse* is
        not, exactly as the reference rejects seen UUIDs
        (MembershipView.java:101-116)."""
        assert not self.active[slot] and slot not in self._pending_joiners
        assert (id_high, id_low) not in self._seen_identifier_set(), (
            "identifier reuse"
        )
        self.cluster.assign_identity(slot, hostname, port, id_high, id_low)
        # the device rank table is only consumed at the next configuration
        # rebuild (_fresh_state); defer the argsort + upload until then so a
        # burst of seatings pays it once, off the message-handling path
        self._ring_rank_dirty = True
        self._ring_nodes = None
        self._spec = None  # endpoint hashes / rank table changed

    def _seen_identifier_set(self) -> Set[Tuple[int, int]]:
        """Membership test over the identifier history, materialized on first
        admission-path use (joins); the append-only array form is the source
        of truth."""
        if self._seen_set is None:
            self._seen_set = {(int(h), int(l)) for h, l in self._seen_ids}
        return self._seen_set

    def is_identifier_seen(self, id_high: int, id_low: int) -> bool:
        return (id_high, id_low) in self._seen_identifier_set()

    @property
    def identifiers_seen(self) -> Set[Tuple[int, int]]:
        """The append-only identifier history, as (high, low) values."""
        return set(self._seen_identifier_set())

    @property
    def pending_joiners(self) -> Set[int]:
        return set(self._pending_joiners)

    @property
    def pending_leavers(self) -> Set[int]:
        return set(self._pending_leavers)

    def endpoint_of(self, slot: int) -> Tuple[bytes, int]:
        host = bytes(
            self.cluster.hostnames[slot, : self.cluster.host_lengths[slot]]
        )
        return host, int(self.cluster.ports[slot])

    # ------------------------------------------------------------------ #
    # Placement plane (placement/device.py)
    # ------------------------------------------------------------------ #

    @property
    def placement(self):
        """The DevicePlacement (None unless enable_placement ran)."""
        return self._placement

    @property
    def placement_diffs(self) -> List:
        """DeviceDiff per view change since placement was enabled."""
        return list(self._placement_diffs)

    def enable_placement(
        self,
        partitions: int = 8192,
        replicas: int = 3,
        seed: int = 0,
        weights: Optional[np.ndarray] = None,
    ) -> None:
        """Attach the placement plane: a deterministic shard map over the
        live membership, updated incrementally inside every view change.

        The full [P, R] build over the whole slot universe happens HERE,
        once -- deliberately outside any timed path (it is the same
        one-time-cost class as the ring-hash pre-warms above). View changes
        afterwards touch only the minimal-motion subset. Placement never
        advances virtual_ms: the map is state *derived from* the membership,
        not part of the protocol the simulator is timing."""
        from ..placement.device import DevicePlacement
        from ..placement.engine import PlacementConfig

        cfg = PlacementConfig(
            partitions=partitions, replicas=replicas, seed=seed
        )
        placement = DevicePlacement(
            cfg,
            self.cluster.hostnames,
            self.cluster.host_lengths,
            self.cluster.ports,
            weights,
        )
        placement.build(self.active)
        self._placement = placement
        self._placement_diffs = []
        self.metrics.incr("placement.rebuilds")
        self.metrics.set_gauge("placement.imbalance", placement.imbalance())
        self.recorder.record(
            "placement_rebalance",
            configuration_id=self.configuration_id(),
            moved=0, version=placement.version,
        )

    # ------------------------------------------------------------------ #
    # Handoff plane (handoff/device.py)
    # ------------------------------------------------------------------ #

    @property
    def handoff_stores(self):
        """Per-slot InMemoryPartitionStore dict (None unless enabled)."""
        return self._handoff_stores

    @property
    def handoff_transfers(self) -> List:
        """DeviceTransferPlan lists, one per view change since enabling."""
        return list(self._handoff_transfers)

    def enable_handoff(
        self,
        sizes: Optional[np.ndarray] = None,
        chunk_size: int = 1 << 16,
        chunk_ms: int = 1,
        fault_plan=None,
        max_chunk_retries: int = 8,
    ) -> None:
        """Attach the handoff plane: per-slot partition stores seeded for
        the current owners, with every subsequent placement diff's moved
        partitions transferred chunk-by-chunk between stores.

        Transfers are billed on virtual time (``chunk_ms`` per chunk plus
        any fault-plan delay) strictly AFTER the view installs, so the
        detection->decision->install stable-view distributions the bench
        pins are untouched. ``fault_plan`` (faults.FaultPlan) makes chunk
        pulls suffer deterministic drops/duplicates/delays -- dropped
        chunks retry up to ``max_chunk_retries`` before the session fails
        over to the next surviving source, mirroring the live engine."""
        from ..handoff.store import InMemoryPartitionStore

        if self._placement is None:
            raise RuntimeError("enable_placement must run before enable_handoff")
        partitions = self._placement.config.partitions
        if sizes is None:
            sizes = (977 * np.arange(partitions, dtype=np.int64)) % 5000
        sizes = np.asarray(sizes, dtype=np.int64)
        if sizes.shape[0] != partitions:
            raise ValueError("sizes must have one entry per partition")
        self._handoff_sizes = sizes
        self._handoff_chunk_size = int(chunk_size)
        self._handoff_chunk_ms = int(chunk_ms)
        self._handoff_max_chunk_retries = int(max_chunk_retries)
        self._handoff_transfers = []
        if fault_plan is not None:
            from ..faults import Nemesis

            class _VirtualClock:
                def __init__(self, sim: "Simulator") -> None:
                    self._sim = sim

                def now_ms(self) -> int:
                    return self._sim.virtual_ms

            self._handoff_nemesis = Nemesis(
                fault_plan, _VirtualClock(self), metrics=self.metrics
            ).arm()
        else:
            self._handoff_nemesis = None
        stores = {
            slot: InMemoryPartitionStore()
            for slot in range(self.config.capacity)
        }
        assign = self._placement.assign
        for p in range(partitions):
            payload = self._handoff_payload(p, int(sizes[p]))
            for slot in assign[p]:
                if slot >= 0:
                    stores[int(slot)].put(p, payload)
        self._handoff_stores = stores

    @staticmethod
    def _handoff_payload(partition: int, size: int) -> bytes:
        """Deterministic per-partition content (cheap, numpy-generated)."""
        if size <= 0:
            return b""
        pattern = (
            np.arange(size, dtype=np.int64) * 31 + partition * 977 + 7
        ) & 0xFF
        return pattern.astype(np.uint8).tobytes()

    def _run_handoff(self, old_assign: np.ndarray, parent_span) -> None:
        """Execute every transfer the just-applied placement diff implies,
        deterministically (store-to-store, fault plan consulted per chunk).
        Runs after view_installed; bills virtual time for the chunk pulls."""
        from ..handoff.device import device_transfer_plans
        from ..handoff.plan import chunk_spans, content_fingerprint
        from ..types import Endpoint, HandoffRequest

        placement = self._placement
        plans = device_transfer_plans(
            old_assign, placement.assign, self.active, placement.keys64,
            placement.version, placement.config.seed, self._handoff_sizes,
            self._handoff_chunk_size,
        )
        self._handoff_transfers.append(plans)
        stores = self._handoff_stores
        nemesis = self._handoff_nemesis
        billed_ms = 0
        moved_ok: Set[Tuple[int, int]] = set()
        endpoints: dict = {}

        def ep(slot: int) -> Endpoint:
            cached = endpoints.get(slot)
            if cached is None:
                host, port = self.endpoint_of(slot)
                cached = endpoints[slot] = Endpoint(hostname=host, port=port)
            return cached

        for plan in plans:
            span = self.tracer.begin(
                "handoff_session", virtual_ms=self.virtual_ms,
                partition=plan.partition, session=plan.session_id,
                sources=len(plan.sources),
            )
            span.parent_id = parent_span.span_id
            span.trace_id = parent_span.trace_id
            self.metrics.incr("handoff.sessions_started")
            completed = False
            not_found = 0
            reachable = 0
            for idx, src in enumerate(plan.sources):
                if not self.alive[src]:
                    self.metrics.incr("handoff.failovers")
                    continue
                reachable += 1
                data = stores[src].get(plan.partition)
                if data is None:
                    not_found += 1
                    continue
                schedule = chunk_spans(len(data), self._handoff_chunk_size)
                pulled = True
                n_chunks = 0
                for offset, length in schedule if schedule else ((0, 0),):
                    request = HandoffRequest(
                        sender=ep(plan.recipient),
                        session_id=plan.session_id,
                        partition=plan.partition, offset=offset,
                        length=length,
                    )
                    retries = 0
                    while True:
                        billed_ms += self._handoff_chunk_ms
                        if nemesis is not None:
                            decision = nemesis.decide(
                                ep(plan.recipient), ep(src), request, "egress"
                            )
                            billed_ms += decision.delay_ms
                            if decision.drop:
                                retries += 1
                                self.metrics.incr("handoff.retries")
                                if retries > self._handoff_max_chunk_retries:
                                    pulled = False
                                    break
                                continue
                            for _ in range(decision.duplicates):
                                self.metrics.incr("handoff.chunks_duplicate")
                        self.metrics.incr("handoff.chunks_sent")
                        self.metrics.incr("handoff.chunks_received")
                        self.metrics.incr("handoff.bytes_moved", length)
                        n_chunks += 1
                        break
                    if not pulled:
                        break
                if not pulled:
                    self.metrics.incr("handoff.failovers")
                    continue
                fingerprint = content_fingerprint(plan.partition, data)
                src_fp = stores[src].fingerprint(plan.partition)
                if src_fp is not None and fingerprint != src_fp:
                    self.metrics.incr("handoff.fingerprint_mismatches")
                    continue
                stores[plan.recipient].put(plan.partition, data)
                completed = True
                self.metrics.incr("handoff.sessions_completed")
                self.metrics.observe(
                    "handoff.session_bytes", len(data),
                    buckets=HANDOFF_BYTES_BUCKETS,
                )
                self.metrics.observe(
                    "handoff.session_chunks", max(1, n_chunks),
                    buckets=HANDOFF_CHUNKS_BUCKETS,
                )
                span.attrs["bytes"] = len(data)
                self.recorder.record(
                    "handoff_complete", partition=plan.partition,
                    session=plan.session_id, bytes=len(data), source=int(src),
                )
                break
            if not completed:
                if reachable > 0 and not_found == reachable:
                    # every reachable source is authoritative and empty:
                    # nothing to move (the live engine's vacuous completion)
                    completed = True
                    self.metrics.incr("handoff.sessions_completed")
                    span.attrs["empty"] = True
                else:
                    self.metrics.incr("handoff.sessions_failed")
                    span.attrs["failed"] = True
                    self.recorder.record(
                        "handoff_failed", partition=plan.partition,
                        session=plan.session_id, sources=len(plan.sources),
                    )
            if completed:
                moved_ok.add((plan.partition, plan.recipient))
            self.tracer.end(span, virtual_ms=self.virtual_ms)
        # releases: a donor drops its copy once every recipient of that
        # partition verified (a failed transfer keeps the old replica alive)
        by_partition: dict = {}
        for plan in plans:
            by_partition.setdefault(plan.partition, []).append(plan)
        for partition, group in by_partition.items():
            if not all((partition, g.recipient) in moved_ok for g in group):
                continue
            new_row = set(int(s) for s in placement.assign[partition] if s >= 0)
            old_row = [int(s) for s in old_assign[partition] if s >= 0]
            for slot in old_row:
                if slot in new_row or not self.alive[slot]:
                    continue
                if stores[slot].get(partition) is not None:
                    stores[slot].delete(partition)
                    self.metrics.incr("handoff.releases")
        # billed strictly after view_installed: the stable-view timer has
        # already stamped this churn, so the bench pin cannot move
        self.virtual_ms += billed_ms

    # ------------------------------------------------------------------ #
    # Serving plane (serving/engine.py mirror)
    # ------------------------------------------------------------------ #

    @property
    def serving_enabled(self) -> bool:
        return self._serving_enabled

    @property
    def serving_acked(self) -> dict:
        """Oracle: every acknowledged write, key -> (version, value) as of
        the ack. Zero-lost-writes checks read each key back and require a
        version >= the oracle's."""
        return dict(self._serving_acked)

    def enable_serving(self, request_ms: int = 1, fault_plan=None) -> None:
        """Attach the serving plane mirror: replicated Get/Put over the
        handoff stores. KV state persists as the same deterministic
        ``encode_kv`` blobs the live engine writes, INSIDE the handoff
        stores -- so every view change moves serving data through the
        verified handoff sessions for free, exactly like the live plane.

        Each client op bills ``request_ms`` of virtual time (one leader
        round trip); a dead leader costs one extra hop (redirect) and
        reads fall back to quorum reads until the next view installs.
        ``fault_plan`` makes replication writes suffer deterministic
        drops/duplicates/delays; a write only acks with a majority."""
        from ..serving.kv import encode_kv

        if self._handoff_stores is None:
            raise RuntimeError("enable_handoff must run before enable_serving")
        if fault_plan is not None:
            from ..faults import Nemesis

            class _VirtualClock:
                def __init__(self, sim: "Simulator") -> None:
                    self._sim = sim

                def now_ms(self) -> int:
                    return self._sim.virtual_ms

            self._serving_nemesis = Nemesis(
                fault_plan, _VirtualClock(self), metrics=self.metrics
            ).arm()
        else:
            self._serving_nemesis = None
        self._serving_request_ms = int(request_ms)
        # replace the synthetic handoff payloads with empty KV blobs: from
        # here on the stores hold serving data, and fingerprints still
        # agree across replicas because encode_kv is deterministic
        empty = encode_kv({})
        for store in self._handoff_stores.values():
            for p in store.partitions():
                store.put(p, empty)
        self._serving_cache = {}
        self._serving_acked = {}
        self._serving_eps = {}
        self._serving_enabled = True

    def _serving_ep(self, slot: int):
        from ..types import Endpoint

        cached = self._serving_eps.get(slot)
        if cached is None:
            host, port = self.endpoint_of(slot)
            cached = self._serving_eps[slot] = Endpoint(
                hostname=host, port=port
            )
        return cached

    def _serving_kv(self, slot: int, p: int) -> dict:
        from ..serving.kv import decode_kv

        kv = self._serving_cache.get((slot, p))
        if kv is None:
            kv = decode_kv(self._handoff_stores[slot].get(p))
            self._serving_cache[(slot, p)] = kv
        return kv

    def _serving_persist(self, slot: int, p: int, kv: dict) -> None:
        from ..serving.kv import encode_kv

        self._handoff_stores[slot].put(p, encode_kv(kv))
        if self._durability_enabled:
            # one persisted blob == one WAL append on the live plane; the
            # count is what a post-crash replay has to re-apply
            self._durable_pending[slot] = self._durable_pending.get(slot, 0) + 1

    # -- SLO plane ----------------------------------------------------------- #

    def enable_slo(self, settings=None, catalog=None, windows=None):
        """Attach the SLO plane (slo/): online SLIs over the serving path,
        multi-window burn-rate alerts, and churn-episode attribution
        against this simulator's journal. ``settings.enabled`` is the kill
        switch: when False this is a no-op returning None and every
        serving request runs the exact pre-SLO path. Returns the SloPlane
        (or None when disabled)."""
        from ..settings import SLOSettings
        from ..slo import SloPlane

        if settings is None:
            settings = SLOSettings(enabled=True)
        if not settings.enabled:
            self._slo = None
            return None
        self._slo = SloPlane(
            settings, metrics=self.metrics, recorder=self.recorder,
            catalog=catalog, windows=windows,
        )
        return self._slo

    def slo_plane(self):
        """The live SLO plane (None unless enable_slo attached one)."""
        return self._slo

    # -- hierarchy mirror --------------------------------------------------- #

    def enable_hierarchy(
        self,
        cells: int = 0,
        topology=None,
        parent_round_ms: int = 1,
        leaders_per_cell: int = 1,
    ) -> None:
        """Attach the hierarchy mirror: the device plane's analogue of the
        engine's two-level composition (hierarchy/plane.py).

        Slots partition into cells by the same pure functions the engine
        uses -- topology zones when a LatencyTopology is given (slots ARE
        topology indices), the seeded rendezvous hash over the slot's
        endpoint otherwise -- so a protocol-plane member and its seated
        device slot always land in the same cell. Each view change then
        recomputes ONLY the touched cells' rows (epoch fold, leader order,
        membership fingerprint over the cell-local slice of the active
        mask) and, when the composition moved, bills one parent round of
        ``parent_round_ms`` on the virtual clock -- cross-cell agreement
        costs O(cells) work and one round of latency, never O(members).
        Everything is a pure function of (membership, seed), so runs stay
        bit-deterministic and `global_fingerprint` is comparable 1:1 with
        the engine's composed fingerprints."""
        from ..hierarchy.cells import cell_count, cell_of_endpoint
        from ..types import Endpoint as _Endpoint

        resolved = cell_count(cells, topology)
        cell_of = np.zeros(self.config.capacity, dtype=np.int32)
        for slot in range(self.config.capacity):
            if topology is not None:
                cell_of[slot] = topology.zone_of(slot)
            else:
                host, port = self.endpoint_of(slot)
                cell_of[slot] = cell_of_endpoint(
                    _Endpoint(hostname=host, port=port), resolved
                )
        self._hier_cell_of = cell_of
        self._hier_n_cells = resolved
        self._hier_round_ms = int(parent_round_ms)
        self._hier_leaders_per_cell = int(leaders_per_cell)
        self._hier_rows = {}
        self._hier_rounds = 0
        for cell in range(resolved):
            self._hierarchy_recompute_cell(cell)
        self.metrics.set_gauge("hierarchy.cells", resolved)

    def _hierarchy_recompute_cell(self, cell: int) -> None:
        """Rebuild one cell's composed-view row from its cell-local slice
        of the active mask (hierarchy/parent.py CellState discipline)."""
        from ..hierarchy.parent import (
            CellState, cell_fingerprint, cell_leaders, _fold,
        )
        from ..types import Endpoint as _Endpoint

        slots = np.flatnonzero(self.active & (self._hier_cell_of == cell))
        if not len(slots):
            self._hier_rows.pop(cell, None)
            return
        _, _, host_h, port_h = self.cluster.node_hashes()
        members = []
        for slot in slots:
            host, port = self.endpoint_of(int(slot))
            members.append(_Endpoint(hostname=host, port=port))
        leaders = cell_leaders(members, self._hier_leaders_per_cell)
        # the cell's epoch is a config-id-style chained fold over the
        # cell-local slice's element hashes: it moves exactly when the
        # cell's membership moves, the same contract the engine's
        # per-cell Rapid configuration id provides
        epoch = _fold(
            sorted(
                int(host_h[slot]) ^ int(port_h[slot]) for slot in slots
            )
        )
        self._hier_rows[cell] = CellState(
            cell=cell,
            epoch=epoch,
            size=len(members),
            leader=str(leaders[0]),
            fingerprint=cell_fingerprint(members),
        )

    def _hierarchy_view_change(self, record, vc_span) -> None:
        """Mirror one view change into the composition: recompute touched
        cells only, bill one parent round when the composition moved."""
        touched = sorted(
            {int(self._hier_cell_of[s]) for s in record.added}
            | {int(self._hier_cell_of[s]) for s in record.removed}
        )
        before = self.global_fingerprint()
        for cell in touched:
            self._hierarchy_recompute_cell(cell)
        after = self.global_fingerprint()
        if after == before:
            return
        # one leader-to-leader parent round carries the moved cells' digests
        # to every other cell: O(cells) messages, one round of latency
        self._hier_rounds += 1
        self.virtual_ms += self._hier_round_ms
        self.metrics.incr("hierarchy.parent_rounds")
        self.metrics.set_gauge("hierarchy.live_cells", len(self._hier_rows))
        self.recorder.record(
            "parent_round",
            virtual_ms=self.virtual_ms,
            round=self._hier_rounds,
            cells=len(self._hier_rows),
            touched=len(touched),
            global_fingerprint=after,
            trace_id=vc_span.trace_id,
        )

    @property
    def hierarchy_enabled(self) -> bool:
        return self._hier_cell_of is not None

    @property
    def parent_rounds(self) -> int:
        """Parent rounds billed since enable_hierarchy."""
        return self._hier_rounds

    def hierarchy_rows(self):
        """The composed global view: CellState rows sorted by cell."""
        return tuple(
            self._hier_rows[cell] for cell in sorted(self._hier_rows)
        )

    def global_fingerprint(self) -> int:
        """Composed global fingerprint (hierarchy/parent.py fold) of the
        mirror's current rows."""
        from ..hierarchy.parent import compose_fingerprint

        return compose_fingerprint(self.hierarchy_rows())

    def cell_of_slot(self, slot: int) -> int:
        """Cell of device slot ``slot`` (enable_hierarchy must have run)."""
        return int(self._hier_cell_of[slot])

    def serving_drive_open_loop(self, arrivals):
        """Drive the serving mirror with an open-loop arrival stream
        (slo/sli.py OpenLoopGenerator): each arrival is scheduled on the
        virtual clock independently of completions. When the server is
        idle the clock advances to the arrival; when it is behind, the
        request queues and its measured latency (completion minus
        *scheduled arrival*) includes the queueing delay -- the
        coordinated-omission fix the closed-loop driver lacked. Feeds the
        SLO plane when one is attached. Returns
        ``[(arrival, status, latency_ms), ...]``."""
        from ..types import PutAck

        if not self._serving_enabled:
            raise RuntimeError("serving is not enabled on this simulator")
        results = []
        for a in arrivals:
            at = int(a.at_ms)
            if self.virtual_ms < at:
                self.virtual_ms = at  # idle server: wait for the client
            if self._slo is not None:
                self._slo.record_offered(at)
            if a.op == "put":
                ack = self.serving_put(a.key, a.value)
            else:
                ack = self.serving_get(a.key)
            latency_ms = float(self.virtual_ms - at)
            ok = ack.status in (PutAck.STATUS_OK, PutAck.STATUS_NOT_FOUND) \
                if a.op == "get" else ack.status == PutAck.STATUS_OK
            if self._slo is not None:
                self._slo.record(self.virtual_ms, ok, latency_ms)
            results.append((a, int(ack.status), latency_ms))
        return results

    # -- durability mirror -------------------------------------------------- #

    def enable_durability(self, replay_record_ms: int = 1) -> None:
        """Attach the durability mirror: every serving persist counts as one
        WAL append, and :meth:`restart_slot` bills the log-over-snapshot
        replay on the virtual clock (``replay_record_ms`` per un-checkpointed
        record) -- the sim analogue of ``DurablePartitionStore`` recovery."""
        if not self._serving_enabled:
            raise RuntimeError("enable_serving must run before enable_durability")
        self._durability_replay_ms = int(replay_record_ms)
        self._durable_pending = {}
        self._durability_enabled = True

    def checkpoint_slot(self, slot: int) -> None:
        """Snapshot the slot's store: replay debt drops to zero, exactly as
        ``DurablePartitionStore.checkpoint`` truncates the log."""
        if not self._durability_enabled:
            raise RuntimeError("durability is not enabled on this simulator")
        self._durable_pending[slot] = 0
        self.metrics.incr("durability.snapshots")
        self.recorder.record("durability_checkpoint", node=f"slot{int(slot)}")

    def durable_pending(self, slot: int) -> int:
        """Records a restart of ``slot`` would replay (un-checkpointed)."""
        return self._durable_pending.get(int(slot), 0)

    def restart_slot(self, slot: int, down_ms: int = 0) -> int:
        """Crash-and-recover ``slot`` with its store intact: the node is dead
        for ``down_ms`` of virtual time, then replays its WAL debt at
        ``replay_record_ms`` per record before answering again. Returns the
        replayed-record count. The identity is retained -- a restart is not
        a leave, so no identifier churn and no view change is implied (the
        FD may still evict if ``down_ms`` outlasts detection)."""
        if not self._durability_enabled:
            raise RuntimeError("durability is not enabled on this simulator")
        slot = int(slot)
        self.crash(np.asarray([slot]))
        replayed = self._durable_pending.get(slot, 0)
        self.virtual_ms += int(down_ms) + replayed * self._durability_replay_ms
        if replayed:
            self.metrics.incr("durability.replayed_records", replayed)
        self.recorder.record(
            "durability_recovered", node=f"slot{slot}", replayed=replayed,
        )
        self.revive(np.asarray([slot]))
        return replayed

    def _serving_reconcile(self, old_assign) -> None:
        """Anti-entropy at the view-change boundary, BEFORE handoff runs:
        merge each partition's KV map (max version per key) across its live
        old-row replicas and persist the merged blob back to each of them.

        Any acked write reached a majority of the old row, so as long as
        only a minority crashed at least one live replica still holds it;
        after the merge EVERY live replica holds it, and handoff then
        propagates complete blobs to the new owners no matter which source
        replica it happens to copy from. Without this step a new leader
        whose replication Put was dropped would serve a stale local copy
        -- an acked write silently lost."""
        for p in range(old_assign.shape[0]):
            live = [
                int(s) for s in old_assign[p] if s >= 0 and self.alive[int(s)]
            ]
            if len(live) < 2:
                continue
            merged: dict = {}
            for s in live:
                for key, (version, value) in self._serving_kv(s, p).items():
                    cur = merged.get(key)
                    if cur is None or version > cur[0]:
                        merged[key] = (version, value)
            for s in live:
                if self._serving_kv(s, p) != merged:
                    self.metrics.incr("serving.reconciled_replicas")
                    self._serving_cache[(s, p)] = dict(merged)
                    self._serving_persist(s, p, merged)

    def _serving_row(self, key: bytes):
        from ..serving.kv import partition_of

        p = partition_of(key, self._placement.config.partitions)
        row = [int(s) for s in self._placement.assign[p] if s >= 0]
        live = [s for s in row if self.alive[s]]
        return p, row, live

    def serving_put(self, key: bytes, value: bytes):
        """One closed-loop client write: route to the first live replica in
        placement order, replicate to the row, ack on majority. Returns a
        PutAck (STATUS_OK or STATUS_RETRY)."""
        from ..types import Put, PutAck

        if not self._serving_enabled:
            raise RuntimeError("serving is not enabled on this simulator")
        self.metrics.incr("serving.puts")
        t0 = self.virtual_ms
        self.virtual_ms += self._serving_request_ms
        p, row, live = self._serving_row(key)
        majority = len(row) // 2 + 1
        status = PutAck.STATUS_RETRY
        version = 0
        if live:
            leader = live[0]
            if row[0] != leader:
                # the map still names a dead leader: one redirect hop
                self.metrics.incr("serving.not_leader_redirects")
                self.virtual_ms += self._serving_request_ms
            kv = self._serving_kv(leader, p)
            version = kv.get(key, (0, b""))[0] + 1
            msg = Put(
                sender=self._serving_ep(leader), key=key, value=value,
                request_id=0, replicate=1, version=version,
            )
            acks = 0
            for slot in row:
                if not self.alive[slot]:
                    continue
                if slot != leader and self._serving_nemesis is not None:
                    decision = self._serving_nemesis.decide(
                        self._serving_ep(slot), self._serving_ep(leader),
                        msg, "egress",
                    )
                    # slow_ms covers disk_stall rules: the replica answers,
                    # but only after the stalled fsync returns
                    self.virtual_ms += decision.delay_ms + decision.slow_ms
                    if decision.drop:
                        continue
                skv = kv if slot == leader else self._serving_kv(slot, p)
                if version > skv.get(key, (0, b""))[0]:
                    skv[key] = (version, value)
                    self._serving_persist(slot, p, skv)
                acks += 1
                if slot != leader:
                    self.metrics.incr("serving.replication_writes")
                    self.metrics.incr("serving.put_acks")
            if acks >= majority:
                status = PutAck.STATUS_OK
                self._serving_acked[key] = (version, value)
            else:
                self.metrics.incr("serving.put_retries")
        else:
            self.metrics.incr("serving.put_retries")
        self.metrics.observe(
            "serving.request_ms", float(self.virtual_ms - t0),
            buckets=SERVING_LATENCY_BUCKETS_MS,
        )
        return PutAck(
            sender=self._serving_ep(row[0]) if row else None,
            status=status, key=key, version=version,
        )

    def serving_get(self, key: bytes):
        """One closed-loop client read: leader read while the placement
        leader is alive, quorum read (max version across a live majority)
        during the churn window. Returns a PutAck."""
        from ..types import PutAck

        if not self._serving_enabled:
            raise RuntimeError("serving is not enabled on this simulator")
        self.metrics.incr("serving.gets")
        t0 = self.virtual_ms
        self.virtual_ms += self._serving_request_ms
        p, row, live = self._serving_row(key)
        majority = len(row) // 2 + 1
        status = PutAck.STATUS_RETRY
        version = 0
        value = b""
        if live and self.alive[row[0]]:
            self.metrics.incr("serving.leader_reads")
            version, value = self._serving_kv(row[0], p).get(key, (0, b""))
            status = PutAck.STATUS_OK if version else PutAck.STATUS_NOT_FOUND
        elif live:
            # leader churn: redirect hop + quorum read across live replicas
            self.metrics.incr("serving.not_leader_redirects")
            self.metrics.incr("serving.quorum_reads")
            self.virtual_ms += self._serving_request_ms
            if len(live) >= majority:
                for slot in live:
                    v, blob = self._serving_kv(slot, p).get(key, (0, b""))
                    if v > version:
                        version, value = v, blob
                status = (
                    PutAck.STATUS_OK if version else PutAck.STATUS_NOT_FOUND
                )
        self.metrics.observe(
            "serving.request_ms", float(self.virtual_ms - t0),
            buckets=SERVING_LATENCY_BUCKETS_MS,
        )
        return PutAck(
            sender=self._serving_ep(row[0]) if row else None,
            status=status, key=key, value=value, version=version,
        )

    def one_way_ingress_partition(self, node_ids: np.ndarray) -> None:
        """Asymmetric failure: probes TO these nodes are lost, their own
        traffic still flows (paper §7, iptables INPUT partitions). Persists
        across view changes until lifted."""
        self._stable_view.detection()
        self._ingress_partitioned.update(int(i) for i in np.atleast_1d(node_ids))
        self._probe_drop_dev = None

    def ingress_loss(self, node_ids: np.ndarray, probability: float) -> None:
        """Lossy ingress (e.g. 80% loss): probes to these nodes fail with
        the given probability each round."""
        self._drop_prob[np.atleast_1d(node_ids)] = probability

    def clear_link_faults(self) -> None:
        self._ingress_partitioned.clear()
        self._drop_prob[:] = 0.0
        self._deliver[:] = True
        self._deliver_delay[:] = 0
        self._deliver_delay_dev = None
        self._probe_drop_dev = None

    # ------------------------------------------------------------------ #
    # Heterogeneous broadcast delivery (almost-everywhere agreement)
    # ------------------------------------------------------------------ #

    def set_delivery_groups(self, group_of: np.ndarray) -> None:
        """Partition nodes into delivery classes (config.groups must cover
        the assignment). Nodes in the same group share one cut-detector view
        of the alert stream; the fault plane drops broadcasts per
        (receiving group, sender)."""
        group_of = np.asarray(group_of, dtype=np.int32)
        assert group_of.shape == (self.config.capacity,)
        assert group_of.max(initial=0) < self.config.groups
        self.group_of = group_of
        self.state = dataclasses.replace(
            self.state, group_of=self._rep(group_of)
        )
        self._spec = None  # speculated fresh state baked in the old groups

    def drop_broadcasts(self, receiver_group: int, sender_nodes: np.ndarray) -> None:
        """Group ``receiver_group`` stops hearing broadcasts originating from
        ``sender_nodes`` (models lossy/partitioned dissemination)."""
        self._deliver[receiver_group, np.atleast_1d(sender_nodes)] = False

    def delay_broadcasts(
        self, receiver_group: int, sender_nodes: np.ndarray, rounds: int
    ) -> None:
        """Heterogeneous broadcast latency (timing, not loss): alerts from
        ``sender_nodes`` reach ``receiver_group`` ``rounds`` rounds after
        firing. Requires config.max_delivery_delay >= rounds. With staggered
        FD phases this reproduces the paper's Fig.-11 regime -- nodes cross
        H at different times holding different report snapshots and can
        propose different cuts purely from timing."""
        assert 0 <= rounds <= self.config.max_delivery_delay, (
            f"delay {rounds} exceeds config.max_delivery_delay="
            f"{self.config.max_delivery_delay}"
        )
        self._deliver_delay[receiver_group, np.atleast_1d(sender_nodes)] = rounds
        self._deliver_delay_dev = None

    # ------------------------------------------------------------------ #
    # Bridged (external) voters
    # ------------------------------------------------------------------ #

    def set_auto_vote(self, slot: int, enabled: bool) -> None:
        """Transfer fast-round vote ownership of a slot between the engine
        and an external voter (a bridged real member, sim/bridge.py). With
        auto_vote off, the slot's vote counts only when the host registers
        the node's actually-received FastRoundPhase2bMessage. Clear it before
        the slot's first configuration as a member -- an already-cast vote is
        not retracted."""
        self.auto_vote[slot] = bool(enabled)
        self.state = dataclasses.replace(
            self.state, auto_vote=self._rep(self.auto_vote)
        )
        self._spec = None  # speculated fresh state baked in the old owner

    def register_extern_vote(self, slot: int, cut: np.ndarray) -> bool:
        """Count an external member's fast-round vote in the device tally
        (FastPaxos.java:134-150): intern the voted cut as a proposal row
        (identical values pool with group proposals in the [P, P] equality
        tally), mark the sender's per-node vote state, and put the vote in
        flight so it arrives -- like any vote -- one delivery round later.
        Per-sender dedup: only the first vote of a configuration counts.
        Returns True iff the vote was registered."""
        if slot in self._extern_voted:
            return False  # dedup by sender (FastPaxos.java:134-141)
        from .engine import FAST_RANK

        if self._classic_attempts > 0:
            # only the classic fallback raises per-node round ranks past the
            # fast rank, so until one has run this configuration the device
            # rank is the fresh-state zero and the gate below cannot fire --
            # no host sync on the common (fast-path-only) registration
            rank = int(np.asarray(
                jitwatch.fetch("sim.extern_vote_rank",
                               self.state.classic_rnd[slot])
            ))
            if rank >= FAST_RANK:
                # the slot already joined a classic round: its fast vote must
                # not count toward a fast quorum (registerFastRoundVote
                # refuses once rnd.round > 1, Paxos.java:246-248) -- same
                # gate the engine applies to auto-voting slots
                return False
        mask = np.zeros(self.config.capacity, dtype=bool)
        mask[np.atleast_1d(cut)] = True
        key = mask.tobytes()
        row = self._extern_rows.get(key)
        st = self.state
        if row is None:
            if len(self._extern_rows) >= self.config.extern_proposals:
                import logging

                logging.getLogger(__name__).warning(
                    "no free extern proposal row (extern_proposals=%d); "
                    "dropping external vote from slot %d",
                    self.config.extern_proposals, slot,
                )
                return False
            row = self.config.groups + len(self._extern_rows)
            self._extern_rows[key] = row
            st = dataclasses.replace(
                st,
                proposal=st.proposal.at[row].set(self._rep(mask)),
                announced=st.announced.at[row].set(True),
            )
        self.state = dataclasses.replace(
            st,
            voted=st.voted.at[slot].set(True),
            vote_prop=st.vote_prop.at[slot].set(row),
            vote_new=st.vote_new.at[slot].set(True),
        )
        self._extern_voted.add(slot)
        return True

    def _probe_drop_mask(self) -> np.ndarray:
        """Map the partitioned-destination set onto the current adjacency."""
        mask = np.zeros(self.config.capacity, dtype=bool)
        if self._ingress_partitioned:
            mask[list(self._ingress_partitioned)] = True
        if self._subjects_host is None:
            # cached once per adjacency rebuild  # devlint: sync-point
            self._subjects_host = np.asarray(self.state.subjects)
        return mask[self._subjects_host]

    def _has_down_reports(self) -> bool:
        return bool(self._pending_leavers) or bool(self._injected_down.any())

    def _down_reports(self) -> jax.Array:
        """dst-indexed proactive DOWN reports: pending leavers (ring-k report
        for a leaver arrives iff its ring-k observer is alive to broadcast --
        the leaver's notification is consumed by that observer,
        MembershipService.java:366-371) plus externally injected reports from
        bridged real processes."""
        if self._down_reports_dev is None:
            mask = self._injected_down.copy()
            if self._pending_leavers:
                if self._observers_host is None:
                    # cached once per adjacency rebuild  # devlint: sync-point
                    self._observers_host = np.asarray(self.state.observers)
                leavers = sorted(self._pending_leavers)
                obs = self._observers_host[leavers]  # [L, K]
                mask[leavers] |= self.alive[obs] & self.active[obs]
            self._down_reports_dev = self._rep(mask)
        return self._down_reports_dev

    def _const_inputs(self, join_reports: Optional[np.ndarray]) -> RoundInputs:
        """This dispatch's fault plane, reusing the device-resident all-clear
        arrays whenever a fault class is inactive."""
        if self._alive_dev is None:
            self._alive_dev = self._rep(self.alive)
        if self._ingress_partitioned and self._probe_drop_dev is None:
            self._probe_drop_dev = self._row(self._probe_drop_mask())
        return RoundInputs(
            alive=self._alive_dev,
            probe_drop=(
                self._probe_drop_dev
                if self._ingress_partitioned
                else self._zero_ck_row
            ),
            drop_prob=(
                self._rep(self._drop_prob)
                if (self._drop_prob > 0).any()
                else self._zero_drop_prob
            ),
            join_reports=(
                self._zero_ck if join_reports is None else self._rep(join_reports)
            ),
            down_reports=(
                self._down_reports() if self._has_down_reports() else self._zero_ck
            ),
            deliver=(
                self._ones_deliver
                if self._deliver.all()
                else self._rep(self._deliver)
            ),
            deliver_delay=self._deliver_delay_cached(),
        )

    def _deliver_delay_cached(self) -> jax.Array:
        if not self._deliver_delay.any():
            return self._zero_delay
        if self._deliver_delay_dev is None:
            self._deliver_delay_dev = self._rep(self._deliver_delay)
        return self._deliver_delay_dev

    # ------------------------------------------------------------------ #
    # Profiling plane
    # ------------------------------------------------------------------ #

    def enable_profiling(self, settings=None):
        """Attach the continuous profiling plane (profiling/): sampled
        shadow attribution of the dispatch pipeline into FD-scan /
        cut-detector / consensus-count phases, real-fetch timing of the
        host-transfer leg, and a metric history ring ticked once per
        dispatch. ``settings.enabled`` is the kill switch: when False this
        is a no-op returning None and the dispatch loop stays exactly the
        raw path. The shadow prefixes are compiled here, up front, for both
        random-loss classes, so no later sample compiles inside a jitwatch
        timed window (the bench's zero-steady-state-compile pin). Shadow
        sampling is single-device; in mesh mode only the history ring and
        host-transfer phase are recorded. Returns the PhaseProfiler (or
        None when disabled)."""
        from ..profiling import PhaseProfiler
        from ..settings import ProfilingSettings

        if settings is None:
            settings = ProfilingSettings(enabled=True)
        if not settings.enabled:
            self._profiler = None
            return None
        prof = PhaseProfiler(self.metrics, settings, plane="sim")
        if self.mesh is None:
            inputs = self._const_inputs(None)
            for random_loss in (False, True):
                prof.warm(self.config, self.state, inputs, random_loss)
        self._profiler = prof
        return prof

    # ------------------------------------------------------------------ #
    # Joins
    # ------------------------------------------------------------------ #

    def request_joins(self, node_ids: np.ndarray) -> None:
        """A join wave: each joining slot's K expected observers emit UP
        alerts with the ring numbers the joiner assigned
        (MembershipService.java:229-251). Pending joiners re-attempt in every
        new configuration until admitted."""
        self._stable_view.detection()
        for node in np.atleast_1d(node_ids):
            node = int(node)
            assert not self.active[node], f"node {node} already a member"
            nid = (int(self.cluster.id_high[node]), int(self.cluster.id_low[node]))
            assert nid not in self._seen_identifier_set(), (
                f"identifier reuse at {node}"
            )
            self._pending_joiners.add(node)
        self._join_reports_armed = False

    def cancel_join(self, slot: int) -> None:
        """Withdraw a pending join (the joiner gave up or died before
        admission); its UP reports stop being armed from the next dispatch."""
        self._pending_joiners.discard(slot)
        self._join_reports_armed = False

    def _arm_pending_joins(self) -> Optional[np.ndarray]:
        """Build this configuration's join reports and write each joiner's
        expected observers into its (otherwise unused) observers row so the
        implicit-invalidation pass covers joins (MultiNodeCutDetector.java:146-158)."""
        if not self._pending_joiners or self._join_reports_armed:
            return None
        self._join_reports_armed = True
        k = self.config.k
        join_reports = np.zeros((self.config.capacity, k), dtype=bool)
        # once per join wave, not per dispatch  # devlint: sync-point
        observers = np.asarray(self.state.observers).copy()
        for node in sorted(self._pending_joiners):
            obs_ids, obs_alive = self._expected_observers(node)
            join_reports[node, :] = obs_alive
            observers[node, :] = obs_ids
        self.state = dataclasses.replace(self.state, observers=self._rep(observers))
        return join_reports

    def expected_observers(self, node: int) -> Tuple[np.ndarray, np.ndarray]:
        """Public alias of _expected_observers (used by the messaging bridge)."""
        return self._expected_observers(node)

    def _expected_observers(self, node: int) -> Tuple[np.ndarray, np.ndarray]:
        """The node's ring predecessors (MembershipView.java:293-304 for
        joiners; equally the expected-observer set of a present member) and
        whether each is alive to vouch."""
        k = self.config.k
        ids = np.zeros(k, dtype=np.int32)
        alive = np.zeros(k, dtype=bool)
        if self._ring_nodes is None:
            full_order = self.cluster.full_ring_order()
            self._ring_nodes = [
                full_order[ring][self.active[full_order[ring]]] for ring in range(k)
            ]
        signed = self.cluster.ring_hashes.view(np.int64)
        for ring in range(k):
            ring_nodes = self._ring_nodes[ring]
            me = signed[ring, node]
            pos = np.searchsorted(signed[ring, ring_nodes], me)
            pred = ring_nodes[pos - 1] if pos > 0 else ring_nodes[-1]
            ids[ring] = pred
            alive[ring] = self.alive[pred]
        return ids, alive

    # ------------------------------------------------------------------ #
    # Round loop
    # ------------------------------------------------------------------ #

    def run_until_decision(
        self, max_rounds: int = 64, batch: int = 8,
        classic_fallback_after_rounds: Optional[int] = 8,
        stop_when_announced: bool = False,
    ) -> Optional[ViewChangeRecord]:
        """Run device batches until consensus decides a cut, then apply the
        view change. Returns the record, or None if no decision in budget.

        If the fast round stalls (proposals announced but no value's received
        votes reach the 3/4 supermajority in any group's tally -- too many
        members crashed, blind, or holding diverging proposals) for
        ``classic_fallback_after_rounds`` rounds, a classic Paxos recovery
        round runs with per-node acceptor state on device (sim/classic.py,
        FastPaxos.java:189-195): phase1 promises, the coordinator value-pick
        rule over the reported (vrnd, vval) pairs, and phase2 acceptances,
        deciding iff a majority accepts (Paxos.java:229-236).

        ``stop_when_announced``: return (None) as soon as a proposal is
        announced but undecided, leaving the announcement snapshot in
        ``last_announcement`` -- the bridge's hook for informing real members
        so their votes can join the tally before the decision."""
        t0 = time.perf_counter()
        rounds_done = 0
        while rounds_done < max_rounds:
            join_reports = self._arm_pending_joins()
            inputs = self._const_inputs(join_reports)
            n = min(batch, max_rounds - rounds_done)
            random_loss = bool((self._drop_prob > 0).any())
            prof = self._profiler
            if prof is not None:
                # shadow attribution samples 1-of-N dispatches against the
                # live pre-dispatch state (pure, non-donated prefixes; the
                # donated production dispatch below is untouched); the
                # history ring ticks every dispatch
                if self.mesh is None and prof.should_sample():
                    prof.sample(self.config, self.state, inputs, random_loss)
                prof.tick_history()
            if stop_when_announced and not random_loss:
                # the const/mesh while_loop pauses at the announcement round
                # in-engine, so the whole remaining budget rides one dispatch
                # (the bridge's phase A) instead of a host-driven
                # round-at-a-time loop; the scan path keeps per-batch stops
                n = max_rounds - rounds_done
            with self.tracer.span(
                "device_rounds", virtual_ms=self.virtual_ms, rounds=n
            ) as dispatch_span:
                if self.mesh is not None:
                    # inputs are already placed under their dispatch shardings;
                    # the while_loop runner exits at the decision round (and,
                    # for the bridge's phase A, at the announcement round) and
                    # takes the budget as a dynamic operand (no re-jit when the
                    # batch size changes). The carried state is donated: the
                    # pre-dispatch shards die with the call.
                    self.state = self._sharded_run_until(
                        random_loss, stop_when_announced
                    )(self.state, inputs, self._i32(n))
                elif random_loss:
                    # the per-round RNG-consuming scan path: random ingress
                    # loss is the one fault with no closed form (both FD
                    # policies have one under a deterministic constant plane).
                    # The scan length is static, so an arbitrary tail length
                    # (max_rounds % batch) would mint a fresh executable per
                    # distinct value; power-of-two tail chunks bound the
                    # compile classes at log2(batch) while executing exactly
                    # the same number of rounds.
                    for chunk in _pow2_chunks(n, batch):
                        # chunk values bounded by _pow2_chunks  # devlint: static-shape
                        self.state = run_rounds_const_donated(
                            self.config, self.state, inputs, chunk, random_loss
                        )
                else:
                    # deterministic constant plane: one early-exiting
                    # dispatch (pauses at announcements under
                    # stop_when_announced)
                    self.state = run_until_decided_const_donated(
                        self.config, self.state, inputs, self._i32(n),
                        bool(self._deliver.all()), stop_when_announced,
                    )
                # ONE host<->device round trip syncs the batch and fetches
                # everything a decision needs. Remote-device transports bill
                # per fetched buffer, so the sync is a single bit-packed
                # uint32 array (engine.pack_decision), not a tuple of seven.
                # The [C]-sized per-node vote arrays are NOT in this sync --
                # they are only needed by the rare classic-fallback branch,
                # which pays its own fetch. While the fetch blocks, a
                # speculative worker precomputes the predicted view change's
                # config id and fresh state (consumed below iff the guess
                # matches the decision).
                packed = pack_decision(self.config, self.state)
                spec_worker = self._speculate_view_change()
                if prof is not None:
                    t_fetch = time.perf_counter()
                    words = jitwatch.fetch("sim.decision_words", packed)
                    prof.record_host_transfer(
                        (time.perf_counter() - t_fetch) * 1000.0
                    )
                else:
                    words = jitwatch.fetch("sim.decision_words", packed)
                if spec_worker is not None:
                    spec_worker.join()
                (decided, announced_np, announced_round_np, proposal_np,
                 decided_group, decided_round, round_np) = unpack_decision(
                    self.config, words
                )
                announced_any = announced_np.any()
            # bill the rounds metric by what actually executed: early-exit
            # dispatches (decision / announcement-stop) run fewer rounds
            # than requested, and the bridge budgets its pump phases off
            # this counter
            self.metrics.incr(
                "rounds", int(round_np) - self._rounds_executed
            )
            self._rounds_executed = int(round_np)
            self.metrics.incr("device_dispatches")
            # close the span's virtual extent with the rounds that actually
            # executed (billing happens later, at decision/announcement); the
            # Span object is already recorded, so mutating it is enough
            dispatch_span.virtual_end_ms = self.virtual_ms + (
                self._rounds_executed - self._billed_rounds
            ) * self._round_ms
            rounds_done += n
            if decided:
                return self._apply_view_change(
                    t0, (proposal_np, decided_group, decided_round)
                )
            if announced_any:
                self._last_announcement = (announced_np, proposal_np)
                # stop only on a *group* (cut-detector) announcement: extern
                # rows are host-registered real-member votes, not swarm
                # proposals to inform anyone about
                if stop_when_announced and announced_np[: self.config.groups].any():
                    # bill exactly the rounds this configuration has executed
                    # (the announcement-stop dispatch may have run fewer than
                    # the requested budget)
                    self.virtual_ms += (
                        int(round_np) - self._billed_rounds
                    ) * self._round_ms
                    self._billed_rounds = int(round_np)
                    return None
                # rounds the announced proposal has actually been stalled --
                # the fallback timer runs from propose(), not from the start
                # of the dispatch batch (FastPaxos.java:105-107)
                stalled_rounds = int(round_np) - int(announced_round_np)
                if (
                    classic_fallback_after_rounds is not None
                    and stalled_rounds >= classic_fallback_after_rounds
                ):
                    winner, exchange_rounds = self._run_classic_round()
                    if winner is not None:
                        # no need to write the decision back to the device:
                        # _apply_view_change consumes the fetched arrays and
                        # replaces the device state wholesale. The exchange
                        # bills its hops (1a/1b/2a/2b -- four rounds with no
                        # latency skew, the winning coordinator's actual
                        # majority cutoffs otherwise) like every other
                        # delivery hop.
                        record = self._apply_view_change(
                            t0, (proposal_np, winner,
                                 int(round_np) + exchange_rounds)
                        )
                        record.via_classic_round = True
                        return record
        self.virtual_ms += rounds_done * self._round_ms
        self._billed_rounds += rounds_done
        return None

    def _speculate_view_change(self) -> Optional[threading.Thread]:
        """Start a worker that precomputes the view change the fault plane
        predicts (cut = dead-or-leaving members) while the main thread is
        blocked in the post-dispatch device fetch -- on remote-device
        transports that wait is a full network round trip, long enough to
        hide the configuration-id fold and the fresh-state dispatch behind.

        The prediction is a guess: `_apply_view_change` / `configuration_id`
        consume the precomputed values only when the decided membership
        matches them bit-for-bit, so a partial cut, an extern-proposal
        winner, or any other surprise just falls back to the normal path.
        Joins are never speculated (admissions mutate the identifier
        history). All caches the worker reads are warmed here, on the
        calling thread, so the worker is read-only."""
        if not self.speculate or self._pending_joiners:
            return None
        cut_pred = self.active & ~self.alive
        if self._pending_leavers:
            cut_pred[list(self._pending_leavers)] = self.active[
                list(self._pending_leavers)
            ]
        if not cut_pred.any():
            return None
        new_active = self.active & ~cut_pred
        key = new_active.tobytes()
        if self._spec is not None and self._spec[0] == key:
            return None  # this outcome is already speculated
        # warm every cache the worker touches (all read-only afterwards)
        self._sorted_identifiers()
        self._seen_id_hashes()
        self.cluster.node_hashes()
        self.cluster.full_ring_order()
        if self._ring_rank_dirty:
            self._ring_rank_dev = jnp.asarray(self.cluster.ring_rank())
            self._ring_rank_dirty = False
        seed = self.seed + len(self.view_changes) + 1
        alive_pred = self.alive & new_active

        def work() -> None:
            try:
                _, _, host_h, port_h = self.cluster.node_hashes()
                order = self._sorted_identifiers()
                seen_h = self._seen_id_hashes()
                order0 = ring_order(self.cluster, new_active, 0)
                cid = config_fold(
                    seen_h[order, 0], seen_h[order, 1],
                    host_h[order0], port_h[order0],
                )
                state = device_initial_state(
                    self.config,
                    self._ring_rank_dev,
                    jnp.asarray(new_active),
                    jnp.asarray(alive_pred),
                    jnp.asarray(self.group_of),
                    jnp.asarray(self.auto_vote),
                    jax.random.PRNGKey(seed),
                )
                if self.mesh is not None:
                    from ..shard.engine import place_state

                    state = place_state(state, self.mesh)
                self._spec = (key, seed, cid, state, alive_pred.tobytes())
            except Exception:  # a failed guess must never break the run
                self._spec = None

        worker = threading.Thread(target=work, daemon=True)
        worker.start()
        return worker

    @property
    def last_announcement(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(announced[P], proposal[P, C]) snapshot from the most recent
        dispatch that saw an undecided announcement; None in a fresh
        configuration."""
        return self._last_announcement

    @property
    def _round_ms(self) -> int:
        """Protocol time per engine round (a whole FD interval, or a fraction
        of one under the staggered-phase asynchrony model)."""
        return self.config.fd_interval_ms // self.config.rounds_per_interval

    def _sharded_run(self, rounds: int, random_loss: bool):
        """The jitted mesh scan loop, cached per (length, loss-model). Kept
        for differential testing against the early-exit runner."""
        key = (rounds, random_loss)
        if key not in self._sharded_runs:
            from ..shard.engine import make_sharded_run

            self._sharded_runs[key] = make_sharded_run(
                self.config, self.mesh, rounds, random_loss
            )
        return self._sharded_runs[key]

    def _sharded_run_until(self, random_loss: bool,
                           stop_when_announced: bool = False):
        """The jitted mesh decision loop, cached per (loss-model,
        announcement-stop): the round budget is a dynamic operand, so every
        batch size shares one executable."""
        key = ("until", random_loss, stop_when_announced)
        if key not in self._sharded_runs:
            from ..shard.engine import make_sharded_run_until

            self._sharded_runs[key] = make_sharded_run_until(
                self.config, self.mesh, random_loss, stop_when_announced,
                donate=True,
            )
        return self._sharded_runs[key]

    def _run_classic_round(self) -> Tuple[Optional[int], int]:
        """One classic recovery attempt with per-node acceptor state on
        device (sim/classic.py). Every live node's expovariate fallback timer
        races (FastPaxos.java:200-203: delay ~ Exp(1/N), so ~1 start/sec
        cluster-wide); the node(s) whose timers fire first within the attempt
        window coordinate *concurrently* -- their phase1 promises contend on
        the shared acceptor state, a later-arriving higher rank steals the
        quorum from an earlier one mid-exchange, and safety rests on the
        acceptors (rank checks + the Fig.-2 value pick), not on any host-side
        single-coordinator shortcut. The attempt's round number grows with
        each failure, so retries outrank earlier rounds. Recovery traffic
        rides the delivery-group fault plane AND the latency plane (see
        sim/classic.py).

        Returns (decided proposal row or None, the winning coordinator's
        exchange rounds to bill -- _CLASSIC_ROUND_HOPS when the attempt
        failed or no latency skew is active).
        """
        from .classic import RANK_BITS, ClassicCoordinator

        live = self.active & self.alive
        n = int(self.active.sum())
        if int(live.sum()) <= n // 2:
            return None, _CLASSIC_ROUND_HOPS
        if 2 + self._classic_attempts >= (1 << (31 - RANK_BITS)):
            # rank space exhausted: stay stalled gracefully
            return None, _CLASSIC_ROUND_HOPS
        self._classic_attempts += 1
        live_slots = np.flatnonzero(live)
        # expovariate arrival times, mean n per node => cluster-wide the
        # earliest fires ~Exp(1) into the window; everyone firing within one
        # round of it races this attempt (capped: >3-way races are vanishing)
        times = self._host_rng.exponential(scale=max(n, 1), size=len(live_slots))
        order = np.argsort(times)
        sorted_times = times[order]
        racing = min(1 + int((sorted_times[1:] - sorted_times[0] < 1.0).sum()), 3)
        coordinators = [
            ClassicCoordinator(
                self, round_no=1 + self._classic_attempts,
                slot=int(live_slots[order[i]]),
            )
            for i in range(racing)
        ]
        # phase1 wave in arrival order. Ranks are (round, slot) pairs -- the
        # higher SLOT outranks within the shared round regardless of who
        # fired first (the reference breaks ties by address hash the same
        # way, Paxos.java:97-110) -- so a later-arriving lower rank wins
        # nothing, while a later-arriving higher rank steals the quorum from
        # the earlier coordinator mid-exchange; acceptor-side rank checks
        # arbitrate both interleavings
        promised = [c.phase1() for c in coordinators]
        decided = None
        exchange_rounds = _CLASSIC_ROUND_HOPS
        for coordinator, ok in zip(coordinators, promised):
            if not ok:
                continue
            row = coordinator.pick_value()
            if row is None:
                continue
            won = coordinator.phase2(row)
            if won is not None and decided is None:
                decided = won
                exchange_rounds = coordinator.elapsed_rounds
        if racing > 1:
            self.metrics.incr("classic_coordinator_races")
        return decided, exchange_rounds

    def _apply_view_change(
        self,
        t0: float,
        fetched: Tuple[np.ndarray, int, int],  # (proposal[G,C], group, round)
    ) -> ViewChangeRecord:
        self.metrics.incr("view_changes")
        vc_span = self.tracer.begin(
            "view_change", virtual_ms=self.virtual_ms
        )
        if self._churn_ctx is not None:
            # remote-span edge: parent the install under the churn episode's
            # root so the merged Chrome trace stitches injection -> install
            vc_span.parent_id = self._churn_ctx.parent_span_id
            vc_span.trace_id = (
                self._churn_ctx.trace_id or vc_span.trace_id
            )
            vc_span.attrs.setdefault("origin", self._churn_ctx.origin)
        self._config_id = None  # membership / identifier history change below
        proposal_np, decided_group, decided_round = fetched
        # the winning proposal row's value is the decided cut
        cut = proposal_np[int(decided_group)]
        decided_round = int(decided_round)
        removed = np.flatnonzero(cut & self.active)
        added = np.flatnonzero(cut & ~self.active)
        self.active[removed] = False
        self.active[added] = True
        self.alive[added] = True
        if len(added):
            new_ids = np.stack(
                [self.cluster.id_high[added], self.cluster.id_low[added]], axis=1
            )
            self._seen_ids = np.concatenate([self._seen_ids, new_ids])
            if self._seen_set is not None:
                self._seen_set.update((int(h), int(l)) for h, l in new_ids)
            if self._seen_hashes is not None:
                high_h, low_h, _, _ = self.cluster.node_hashes()
                self._seen_hashes = np.concatenate(
                    [
                        self._seen_hashes,
                        np.stack([high_h[added], low_h[added]], axis=1),
                    ]
                )
            self._ids_sorted = None
        self._pending_joiners.difference_update(int(i) for i in added)
        self._ingress_partitioned.difference_update(int(i) for i in removed)
        self._join_reports_armed = False  # still-pending joiners re-attempt
        # removed leavers shut down for good; still-pending leavers re-notify
        # their observers in the new configuration
        left = self._pending_leavers.intersection(int(i) for i in removed)
        self._pending_leavers.difference_update(left)
        self.alive[list(left)] = False
        self._injected_down[:] = False  # alerts are per-configuration

        # protocol-time: only the rounds of this configuration not yet billed
        # (decided_round includes the vote-delivery round between announcement
        # and decision), plus the batching window before the alert broadcast
        unbilled = decided_round - self._billed_rounds
        self.virtual_ms += (
            unbilled * self._round_ms + self.config.batching_window_ms
        )
        self._billed_rounds = 0
        self._rounds_executed = 0  # fresh configuration: state.round resets
        # the consensus decision landed at the decided round; the view is
        # installed once the fresh state below replaces the device plane --
        # both stamped on the virtual clock for cross-plane comparability
        self._stable_view.decision(self.virtual_ms)
        record = ViewChangeRecord(
            cut=np.flatnonzero(cut),
            added=added,
            removed=removed,
            configuration_id=self.configuration_id(),
            virtual_time_ms=self.virtual_ms,
            wall_time_s=time.perf_counter() - t0,
            membership_size=int(self.active.sum()),
        )
        self.view_changes.append(record)
        # new configuration: rebuild adjacency, reset per-config state;
        # crashes persist across configurations
        self.state = self._fresh_state(self.seed + len(self.view_changes))
        # a speculation is valid for exactly one view change: the identifier
        # history can grow afterwards, which changes the config-id fold even
        # for an identical active mask
        self._spec = None
        self._stable_view.view_installed(self.virtual_ms)
        # fault-array occupancy: host mirrors only, refreshed once per view
        # change flush -- never a per-round device pull
        self.metrics.set_gauge(
            "sim.fault.crashed", int((self.active & ~self.alive).sum())
        )
        self.metrics.set_gauge(
            "sim.fault.ingress_partitioned", len(self._ingress_partitioned)
        )
        self.metrics.set_gauge(
            "sim.fault.lossy", int((self._drop_prob > 0).sum())
        )
        self.metrics.set_gauge("sim.membership_size", record.membership_size)
        self.metrics.set_gauge(
            "sim.pending_joiners", len(self._pending_joiners)
        )
        if self._placement is not None:
            # Incremental map update: removal-affected rows recompute, added
            # columns merge -- sub-second even at 100k x 8192 because only
            # the minimal-motion set is touched. Host-side work on mirrors
            # already fetched; bills NO protocol time (virtual_ms is the
            # membership protocol's clock, and the map is derived state).
            p_span = self.tracer.begin(
                "placement_rebalance", virtual_ms=self.virtual_ms,
                size=record.membership_size,
            )
            p_span.parent_id = vc_span.span_id
            p_span.trace_id = vc_span.trace_id
            old_assign = (
                self._placement.assign.copy()
                if self._handoff_stores is not None else None
            )
            diff = self._placement.apply_view_change(self.active)
            self._placement_diffs.append(diff)
            p_span.attrs.update(
                moved=diff.moved, version=self._placement.version,
            )
            self.tracer.end(p_span, virtual_ms=self.virtual_ms)
            self.metrics.incr("placement.rebuilds")
            self.metrics.observe(
                "placement.partitions_moved", diff.moved,
                buckets=PARTITIONS_MOVED_BUCKETS,
            )
            self.metrics.set_gauge(
                "placement.imbalance", self._placement.imbalance()
            )
            self.recorder.record(
                "placement_rebalance",
                configuration_id=record.configuration_id,
                moved=diff.moved, version=self._placement.version,
            )
            if old_assign is not None:
                if self._serving_enabled:
                    # before blobs move: make every live old-row replica
                    # hold the union of acked writes, so handoff ships
                    # complete content whichever source it copies from
                    self._serving_reconcile(old_assign)
                self.recorder.record(
                    "handoff_started",
                    configuration_id=record.configuration_id,
                    version=self._placement.version,
                )
                self._run_handoff(old_assign, p_span)
                if self._serving_enabled:
                    # handoff just copied/released blobs between stores:
                    # every cached decode may be stale, and new leaders per
                    # partition come straight from the fresh assign rows
                    self._serving_cache = {}
                    self.metrics.incr(
                        "serving.leader_changes",
                        int(np.count_nonzero(
                            old_assign[:, 0] != self._placement.assign[:, 0]
                        )),
                    )
        if self._hier_cell_of is not None:
            # composition mirror: touched cells' rows recompute on their
            # cell-local slices, one virtual-time parent round when the
            # composed fingerprint moved (billed after install, like
            # handoff: the stable-view distributions stay untouched)
            self._hierarchy_view_change(record, vc_span)
        vc_span.attrs.update(
            cut=len(record.cut), added=len(record.added),
            removed=len(record.removed),
            configuration_id=record.configuration_id,
        )
        self.tracer.end(vc_span, virtual_ms=self.virtual_ms)
        self.recorder.record(
            "view_install",
            configuration_id=record.configuration_id,
            size=record.membership_size,
            trace_id=vc_span.trace_id,
            removed=len(record.removed),
            added=len(record.added),
        )
        self._churn_ctx = None  # next churn episode roots a fresh trace
        if self._slo is not None:
            # the install may have jumped the virtual clock: re-evaluate the
            # burn windows at the new now before the next request lands
            self._slo.tick(self.virtual_ms)
        return record

    # ------------------------------------------------------------------ #

    def configuration_id(self) -> int:
        """Bit-exact configuration identity of the current membership.

        Element hashes are cached (endpoint hashes on the cluster, identifier
        hashes on the append-only history); the fold over the current
        ordering runs once per configuration (its inputs -- the active mask
        and the identifier history -- mutate only at view changes, which
        invalidate the memo), and when the speculative worker already folded
        this exact membership, not even that. The memo matters at scale: the
        bridge stamps/validates every real-member message with this id, and
        a 100k fold per received vote would dwarf the protocol itself."""
        if self._config_id is not None:
            return self._config_id
        if self._spec is not None and self._spec[0] == self.active.tobytes():
            self.metrics.incr("speculation_hits_config_id")
            self._config_id = self._spec[2]
            return self._config_id
        _, _, host_h, port_h = self.cluster.node_hashes()
        order = self._sorted_identifiers()
        seen_h = self._seen_id_hashes()
        order0 = ring_order(self.cluster, self.active, 0)
        self._config_id = config_fold(
            seen_h[order, 0], seen_h[order, 1], host_h[order0], port_h[order0]
        )
        return self._config_id

    def sorted_identifiers(self) -> np.ndarray:
        """The identifier history as [M, 2] (high, low) values in NodeId
        (signed-lexicographic) order."""
        return self._seen_ids[self._sorted_identifiers()]

    def _sorted_identifiers(self) -> np.ndarray:
        """Indices into the seen-identifier history in NodeId (high, low)
        signed-lexicographic order, cached until a new identifier is admitted
        (the history is append-only)."""
        if self._ids_sorted is None:
            self._ids_sorted = np.lexsort(
                (self._seen_ids[:, 1], self._seen_ids[:, 0])
            )
        return self._ids_sorted

    def _seen_id_hashes(self) -> np.ndarray:
        """xxHash64 of each seen identifier's high/low values ([M, 2] uint64),
        computed from the values themselves (slot-independent) and maintained
        incrementally at admissions."""
        if self._seen_hashes is None or len(self._seen_hashes) != len(self._seen_ids):
            from ..hashing import xxh64_batch_auto
            from .topology import _int64_le_bytes

            m = len(self._seen_ids)
            eight = np.full(m, 8, dtype=np.int64)
            self._seen_hashes = np.stack(
                [
                    xxh64_batch_auto(
                        _int64_le_bytes(self._seen_ids[:, 0]), eight, 0
                    ),
                    xxh64_batch_auto(
                        _int64_le_bytes(self._seen_ids[:, 1]), eight, 0
                    ),
                ],
                axis=1,
            )
        return self._seen_hashes

    def ready(self) -> "Simulator":
        """Block until construction/rebuild work has drained from the device
        queue -- separates setup cost from measured protocol time."""
        jitwatch.drain(
            "sim.ready",
            jax.tree_util.tree_leaves(self.state),
            (self._zero_ck, self._zero_ck_row, self._zero_drop_prob,
             self._ones_deliver),
        )
        return self

    @property
    def membership_size(self) -> int:
        return int(self.active.sum())

    def members(self) -> np.ndarray:
        return np.flatnonzero(self.active)

    # ------------------------------------------------------------------ #
    # Checkpoint / resume
    # ------------------------------------------------------------------ #

    def save_configuration(self, path: str, extra: Optional[dict] = None) -> None:
        """Persist the configuration snapshot -- the same information a real
        Rapid node needs to bootstrap an identical view (MembershipView
        Configuration, MembershipView.java:517-548): node identities, current
        membership, the append-only identifiersSeen set, and the clock.
        Per-round device state is deliberately NOT persisted; a restarted
        simulator, like a restarted process, starts a fresh configuration.

        ``extra``: additional arrays merged into the archive under
        ``extra_``-prefixed keys (the bridge persists its real-member plane
        this way); ignored by from_configuration."""
        np.savez_compressed(
            path,
            **{f"extra_{k}": v for k, v in (extra or {}).items()},
            hostnames=self.cluster.hostnames,
            host_lengths=self.cluster.host_lengths,
            ports=self.cluster.ports,
            id_high=self.cluster.id_high,
            id_low=self.cluster.id_low,
            ring_hashes=self.cluster.ring_hashes,
            active=self.active,
            alive=self.alive,
            identifiers_seen=self._seen_ids,  # [M, 2] (high, low) values
            virtual_ms=np.int64(self.virtual_ms),
            group_of=self.group_of,
            params=np.array(
                [self.config.capacity, self.config.k, self.config.h, self.config.l,
                 self.config.fd_threshold, self.config.fd_interval_ms,
                 self.config.batching_window_ms, self.seed, self.config.groups],
                dtype=np.int64,
            ),
        )

    @staticmethod
    def from_configuration(
        path: str, mesh=None, config_overrides: Optional[dict] = None
    ) -> "Simulator":
        """Rebuild a simulator from a configuration snapshot; the
        configuration id of the restored instance equals the saved one.
        ``config_overrides``: SimConfig fields to replace on top of the saved
        parameters (e.g. extern_proposals for a restored bridge swarm)."""
        with np.load(path) as data:
            params = [int(x) for x in data["params"]]
            (capacity, k, h, l, fd_threshold, fd_interval_ms,
             batching_window_ms, seed) = params[:8]
            groups = params[8] if len(params) > 8 else 1  # pre-groups snapshots
            config = SimConfig(
                capacity=capacity, k=k, h=h, l=l, fd_threshold=fd_threshold,
                fd_interval_ms=fd_interval_ms, batching_window_ms=batching_window_ms,
                groups=groups,
            )
            if config_overrides:
                config = dataclasses.replace(config, **config_overrides)
            sim = Simulator.__new__(Simulator)
            sim.config = config
            sim.speculate = True
            if mesh is not None:
                n_dev = int(np.prod(list(mesh.shape.values())))
                assert config.capacity % n_dev == 0, (
                    f"snapshot capacity {config.capacity} must divide evenly "
                    f"over the mesh's {n_dev} devices"
                )
            sim.mesh = mesh
            sim.cluster = VirtualCluster(
                hostnames=data["hostnames"],
                host_lengths=data["host_lengths"],
                ports=data["ports"],
                id_high=data["id_high"],
                id_low=data["id_low"],
                ring_hashes=data["ring_hashes"],
            )
            sim.active = data["active"].copy()
            sim.alive = data["alive"].copy()
            seen = data["identifiers_seen"]
            if seen.ndim == 1:
                # pre-value-history snapshots stored slot indices
                slots = seen.astype(np.int64)
                seen = np.stack(
                    [sim.cluster.id_high[slots], sim.cluster.id_low[slots]],
                    axis=1,
                )
            sim._seen_ids = seen.copy()
            sim._seen_set = None  # rebuilt lazily from the restored history
            sim._seen_hashes = None
            sim.seed = seed
            sim.virtual_ms = int(data["virtual_ms"])
            sim.group_of = (
                data["group_of"].copy()
                if "group_of" in data
                else np.zeros(capacity, dtype=np.int32)
            )
            # bridged-vote ownership is a live-bridge property, not part of a
            # configuration snapshot: a restored swarm starts all-simulated
            sim.auto_vote = np.ones(capacity, dtype=bool)
        sim._init_runtime_state()
        return sim
