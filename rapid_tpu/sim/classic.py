"""Classic Paxos recovery round with per-node acceptor state on device.

The simulation plane's fallback when the fast round stalls, with the same
message-level semantics as the object plane's ``rapid_tpu.paxos`` (and the
reference ``Paxos.java``), scaled to 100k+ virtual nodes: the coordinator
exchange is host-driven (four hops -- phase1a broadcast, phase1b responses,
phase2a broadcast, phase2b tally), but every acceptor's (rnd, vrnd, vval)
lives in device arrays, so rank contention between concurrent coordinators is
resolved by the acceptors themselves, not by a host-side shortcut.

Mapping to the reference:

- ranks are (round, node) pairs (Paxos.java:97-110,328-334) packed into one
  int32 as ``round << RANK_BITS | node`` -- lexicographic order becomes
  integer order. The fast round is rank (1, 1) (registerFastRoundVote,
  Paxos.java:244-258); classic rounds start at round 2, so every classic rank
  outranks the fast round.
- the fast-round participation of each node is *derived* from the engine's
  ``voted``/``vote_prop`` arrays rather than stored again, so the jitted hot
  path never writes acceptor state.
- phase1a/1b (``phase1``): an acceptor promises iff ``rank > rnd``
  (Paxos.java:135-145); the device aggregates what the coordinator's
  phase1b inbox would hold -- responder count, the max vrnd among voted
  responders, per-value counts at that vrnd, and per-value counts overall.
- the coordinator value-pick rule (``pick_value``) is Figure 2 of the Fast
  Paxos paper as implemented by selectProposalUsingCoordinatorRule
  (Paxos.java:269-326): a single value at the highest vrnd wins; else a
  value with more than N/4 votes at that vrnd; else any reported vval; with
  no valid vote the coordinator does not proceed.
- phase2a/2b (``phase2``): an acceptor accepts iff ``rank >= rnd`` and
  ``vrnd != rank`` (Paxos.java:205-213); the decision needs more than N/2
  acceptances (Paxos.java:229-236).

Recovery traffic rides the same delivery-group fault plane as alert and vote
broadcasts: an acceptor only hears a coordinator whose group-delivery edge is
up (phase1a/2a), and only responses the coordinator's own group hears count
toward its quorums (phase1b/2b) -- so a partitioned coordinator cannot
manufacture a decision, exactly as lost gRPC traffic starves the reference's
coordinator (Paxos.java:160-236). Acceptor state still advances for every
acceptor that heard the broadcast, even when its response is lost on the way
back.

Heterogeneous latency rides the exchange too (one fabric carries every
message type, UnicastToAllBroadcaster.java:46-52): acceptor a's phase
response arrives at the coordinator ``2 + delay[group(a), coord] +
delay[group(coord), a]`` rounds after the phase broadcast (one round per
hop, the same quantization as the fast-round vote hop, plus each hop's
per-(group, sender) delay). The coordinator proceeds the moment a majority
of the membership has responded (Paxos.java:160-190 collects exactly the
first > N/2 responses), so its phase1b inbox holds only responses that
arrived by that cutoff -- a skewed acceptor's (vrnd, vval) report can miss
the value pick, and the exchange bills the cutoff times instead of the flat
four hops.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import jitwatch
from ..runtime.jitwatch import make_jit
from .engine import FAST_RANK, RANK_BITS, SimConfig, SimState


def make_rank(round_no: int, node: int) -> int:
    assert 2 <= round_no < (1 << (31 - RANK_BITS)), round_no
    assert 0 <= node < (1 << RANK_BITS), node
    return (round_no << RANK_BITS) | node


def _effective(state: SimState):
    """Acceptor state with the fast round folded in: a node that cast a fast
    vote holds rnd = vrnd = FAST_RANK, vval = its fast vote, unless a classic
    round already moved it further."""
    fast = jnp.where(state.voted, jnp.int32(FAST_RANK), 0)
    rnd = jnp.maximum(state.classic_rnd, fast)
    vrnd = jnp.maximum(state.classic_vrnd, fast)
    vval = jnp.where(
        state.classic_vrnd >= fast, state.classic_vval,
        jnp.where(state.voted, state.vote_prop, -1),
    )
    return rnd, vrnd, vval


class Phase1Summary(NamedTuple):
    promised: jax.Array  # int32[] responders in the inbox (> N/2 needed)
    max_vrnd: jax.Array  # int32[] highest vrnd among voted responders (0=none)
    at_max: jax.Array  # int32[P] per-VALUE votes at max_vrnd (row-pooled)
    any_vval: jax.Array  # int32[P] per-VALUE votes at any vrnd (row-pooled)
    rep: jax.Array  # int32[P] canonical (lowest) row holding each row's value
    cutoff: jax.Array  # int32[] rounds until the quorum-closing response


def _inbox_cutoff(
    config: SimConfig,
    responders: jax.Array,  # bool[C] responses that will eventually arrive
    resp_time: jax.Array,  # int32[C] per-acceptor response round-trip rounds
    n: jax.Array,  # membership size
):
    """(in_inbox, cutoff): the coordinator proceeds the round its (> N/2)-th
    response arrives (Paxos.java:160-190), so its inbox holds exactly the
    responses whose arrival time is <= that cutoff. With no quorum the
    cutoff is the last response's arrival (the phase fails on count). With
    zero delays every response takes 2 rounds and this is the whole heard
    set at cutoff 2 -- the flat four-hop exchange."""
    max_t = 2 + 2 * config.max_delivery_delay
    tvals = jnp.arange(2, max_t + 1, dtype=jnp.int32)  # possible arrivals
    by_t = (
        responders[None, :] & (resp_time[None, :] <= tvals[:, None])
    ).sum(axis=1)  # [T] cumulative responses by each time
    reached = by_t > (n // 2)
    cutoff = jnp.where(
        jnp.any(reached),
        tvals[jnp.argmax(reached)],
        max_t,
    )
    return responders & (resp_time <= cutoff), cutoff


@functools.partial(make_jit, "sim.classic.phase1", static_argnums=0)
def phase1(
    config: SimConfig,
    state: SimState,
    rank: jax.Array,
    hears_coord: jax.Array,  # bool[C] acceptor hears the coordinator's 1a/2a
    coord_hears: jax.Array,  # bool[C] coordinator hears the acceptor's 1b/2b
    resp_time: jax.Array,  # int32[C] response round-trip (2 + both hop delays)
):
    """Phase1a broadcast + the aggregate of the phase1b responses.

    Every live acceptor that *hears the broadcast* and has ``rnd < rank``
    promises (bumps rnd) and reports its (vrnd, vval); only responses the
    coordinator's delivery group hears, arriving by the majority cutoff,
    enter the summary -- what its phase1b inbox would actually contain
    (Paxos.java:135-145,160-190). Votes are counted per *value*: proposal
    rows holding identical cut masks (a group row and an extern row interned
    from real members' votes) pool their counts through the same [P, P]
    equality matrix as the fast-round tally, with ``rep`` naming each
    value's canonical row."""
    live = state.active & state.alive
    rnd, vrnd, vval = _effective(state)
    promise = live & hears_coord & (rank > rnd)
    classic_rnd = jnp.where(promise, rank, state.classic_rnd)

    n = state.active.sum()
    heard, cutoff = _inbox_cutoff(config, promise & coord_hears, resp_time, n)
    has_vote = heard & (vrnd > 0) & (vval >= 0)
    max_vrnd = jnp.max(jnp.where(has_vote, vrnd, 0))
    p = config.proposal_rows
    rows = jnp.clip(vval, 0, p - 1)
    at_max_row = (
        jnp.zeros(p, jnp.int32)
        .at[rows]
        .add((has_vote & (vrnd == max_vrnd)).astype(jnp.int32))
    )
    any_row = jnp.zeros(p, jnp.int32).at[rows].add(has_vote.astype(jnp.int32))
    eq = jnp.all(
        state.proposal[:, None, :] == state.proposal[None, :, :], axis=2
    ).astype(jnp.int32)  # [P, P]
    summary = Phase1Summary(
        promised=heard.sum(),
        max_vrnd=max_vrnd,
        at_max=eq @ at_max_row,
        any_vval=eq @ any_row,
        rep=jnp.argmax(eq, axis=1).astype(jnp.int32),
        cutoff=cutoff,
    )
    return dataclasses.replace(state, classic_rnd=classic_rnd), summary


@functools.partial(make_jit, "sim.classic.phase2", static_argnums=0)
def phase2(
    config: SimConfig,
    state: SimState,
    rank: jax.Array,
    row: jax.Array,
    hears_coord: jax.Array,
    coord_hears: jax.Array,
    resp_time: jax.Array,  # int32[C] response round-trip (2 + both hop delays)
):
    """Phase2a broadcast + the phase2b acceptance count.

    An acceptor that hears the broadcast accepts iff ``rnd <= rank`` and
    ``vrnd != rank`` (Paxos.java:205-213); more than N/2 acceptances decide
    (Paxos.java:229-236) -- counted from the coordinator's vantage (only
    phase2b broadcasts its group hears, arriving by the majority cutoff), a
    conservative stand-in for the reference's any-node-with-majority-decides.
    Returns (state, acceptances in the inbox, cutoff rounds)."""
    live = state.active & state.alive
    rnd, vrnd, _ = _effective(state)
    accept = live & hears_coord & (rank >= rnd) & (vrnd != rank)
    state = dataclasses.replace(
        state,
        classic_rnd=jnp.where(accept, rank, state.classic_rnd),
        classic_vrnd=jnp.where(accept, rank, state.classic_vrnd),
        classic_vval=jnp.where(accept, row, state.classic_vval),
    )
    n = state.active.sum()
    in_inbox, cutoff = _inbox_cutoff(
        config, accept & coord_hears, resp_time, n
    )
    return state, in_inbox.sum(), cutoff


class ClassicCoordinator:
    """One coordinator's view of one classic round (host side of
    Paxos.java:97-132,160-236). Multiple instances may run concurrently
    against the same simulator; the shared device acceptor state arbitrates
    their rank contention."""

    def __init__(self, sim, round_no: int, slot: int) -> None:
        self.sim = sim
        self.slot = slot
        self.rank = make_rank(round_no, slot)
        self._summary: Optional[Phase1Summary] = None
        # recovery traffic rides the delivery-group fault plane: which
        # acceptors hear THIS coordinator's broadcasts, and whose responses
        # its own group hears
        deliver = sim._deliver  # noqa: SLF001 -- [G, C] host fault plane
        group_of = sim.group_of
        self._hears_coord = jnp.asarray(deliver[group_of, slot])
        self._coord_hears = jnp.asarray(deliver[group_of[slot], :])
        # ... and the latency plane: acceptor a's phase response arrives
        # 2 + delay[group(a), coord] + delay[group(coord), a] rounds after
        # the phase broadcast (base one round per hop, each hop skewed by
        # the same per-(group, sender) delay as alert/vote broadcasts)
        delay = sim._deliver_delay  # noqa: SLF001 -- [G, C] host fault plane
        self._resp_time = jnp.asarray(
            2 + delay[group_of, slot] + delay[group_of[slot], :],
            dtype=jnp.int32,
        )
        # rounds the exchange has billed so far (phase cutoffs; 4 with no
        # delays -- the flat 1a/1b/2a/2b hops)
        self.elapsed_rounds = 0

    def phase1(self) -> bool:
        """Run phase1a/1b; True iff a majority of the membership promised."""
        # the classic exchange is the cold recovery path and its input state
        # is shared with concurrent coordinators, so it stays undonated
        self.sim.state, summary = phase1(  # devlint: no-donate
            self.sim.config, self.sim.state, jnp.int32(self.rank),
            self._hears_coord, self._coord_hears, self._resp_time,
        )
        self._summary = jitwatch.fetch("sim.classic.phase1b", summary)
        self.elapsed_rounds += int(self._summary.cutoff)
        n = int(self.sim.active.sum())
        return int(self._summary.promised) > n // 2

    def pick_value(self) -> Optional[int]:
        """The Fig.-2 coordinator rule over the phase1b aggregate
        (Paxos.java:269-326), on value-pooled counts (canonical rows via
        ``rep``). Returns the chosen proposal row, or None when no responder
        reported a valid vote (the coordinator must not proceed)."""
        s = self._summary
        assert s is not None, "phase1 must run first"
        n = int(self.sim.active.sum())
        at_max = np.asarray(s.at_max)
        rep = np.asarray(s.rep)
        # distinct VALUES at the max vrnd, each named by its canonical row
        candidates = np.unique(rep[at_max > 0])
        if len(candidates) == 1:
            return int(candidates[0])
        if len(candidates) > 1:
            over = candidates[at_max[candidates] > n // 4]
            if len(over):
                return int(over[0])
        reported = np.unique(rep[np.asarray(s.any_vval) > 0])
        if len(reported):
            return int(reported[0])
        return None

    def phase2(self, row: int) -> Optional[int]:
        """Run phase2a/2b for ``row``; returns the row iff a majority
        accepted (the decision), else None (outranked by a concurrent
        coordinator)."""
        self.sim.state, accepted, cutoff = phase2(  # devlint: no-donate
            self.sim.config, self.sim.state, jnp.int32(self.rank),
            jnp.int32(row), self._hears_coord, self._coord_hears,
            self._resp_time,
        )
        accepted, cutoff = jitwatch.fetch(
            "sim.classic.phase2b", (accepted, cutoff)
        )
        self.elapsed_rounds += int(cutoff)
        n = int(self.sim.active.sum())
        return row if int(accepted) > n // 2 else None
