"""Test harness configuration.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (the driver
dry-runs the real multi-chip path separately). The axon TPU plugin in this
image overrides JAX_PLATFORMS from the environment, so the platform must be
forced through jax.config before any test imports jax — one canonical
implementation lives in ``__graft_entry__._force_cpu_mesh``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runtime lockdep is on for the whole tier-1 suite: every lock created in
# rapid_tpu/ is instrumented, so every existing cluster/handoff/nemesis test
# doubles as a deadlock probe. MUST be set before anything imports rapid_tpu:
# class-level locks (e.g. grpc's shared-loop lock) are created at import time.
# Opt out for A/B timing with RAPID_LOCKDEP=0.
os.environ.setdefault("RAPID_LOCKDEP", "1")

# Runtime jitwatch is on for the whole tier-1 suite: every device-plane jit
# entry is created through the make_jit seam, so every test doubles as a
# recompile/compile-budget probe (and timed windows arm jax.transfer_guard).
# Same ordering constraint as lockdep: the seam samples the env at module
# import. Opt out for A/B timing with RAPID_JITWATCH=0.
os.environ.setdefault("RAPID_JITWATCH", "1")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lockdep_gate():
    """Fail the session if any lock-order violation was recorded, even one
    swallowed by a protocol thread's blanket exception handler."""
    yield
    from rapid_tpu.runtime import lockdep

    assert lockdep.violations() == [], (
        "lockdep recorded lock-order violations during the run:\n"
        + "\n".join(lockdep.violations())
    )


@pytest.fixture(scope="session", autouse=True)
def _jitwatch_gate():
    """Fail the session if any jitwatch violation (steady-state recompile,
    compile-budget breach, transfer-guard trip) was recorded, even one
    swallowed by a blanket exception handler."""
    yield
    from rapid_tpu.runtime import jitwatch

    assert jitwatch.violations() == [], (
        "jitwatch recorded violations during the run:\n"
        + "\n".join(jitwatch.violations())
    )


if os.environ.get("RAPID_TPU_PALLAS_HW"):
    # opt-in hardware runs (test_pallas_kernels.py::test_hardware_*) keep the
    # real accelerator visible
    import jax  # noqa: unused-import
else:
    from __graft_entry__ import _force_cpu_mesh

    _force_cpu_mesh(8)
