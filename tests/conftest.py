"""Test harness configuration.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (the driver
dry-runs the real multi-chip path separately). The axon TPU plugin in this
image overrides JAX_PLATFORMS from the environment, so the platform must be
forced through jax.config before any test imports jax — one canonical
implementation lives in ``__graft_entry__._force_cpu_mesh``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("RAPID_TPU_PALLAS_HW"):
    # opt-in hardware runs (test_pallas_kernels.py::test_hardware_*) keep the
    # real accelerator visible
    import jax  # noqa: unused-import
else:
    from __graft_entry__ import _force_cpu_mesh

    _force_cpu_mesh(8)
