"""Test harness configuration.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (the driver
dry-runs the real multi-chip path separately). The axon TPU plugin in this
image overrides JAX_PLATFORMS from the environment, so the platform must be
forced through jax.config before any test imports jax.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

if os.environ.get("RAPID_TPU_PALLAS_HW"):
    # opt-in hardware runs (test_pallas_kernels.py::test_hardware_*) keep the
    # real accelerator visible
    import jax  # noqa: E402
else:
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
