"""Test harness configuration.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (the driver
dry-runs the real multi-chip path separately); set the platform before any
jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
