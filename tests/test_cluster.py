"""Functional battery over the public Cluster API, mirroring ClusterTest.java
(805 LoC): joins (sequential, parallel, staged), crash failures, asymmetric
message drops, join races under drops, churn, and graceful leave -- all
in-process on deterministic virtual time.
"""

import pytest


from rapid_tpu.monitoring.pingpong import PingPongFailureDetectorFactory
from rapid_tpu.types import JoinMessage, PreJoinMessage, ProbeMessage

from harness import ClusterHarness


@pytest.fixture
def harness():
    h = ClusterHarness(seed=42)
    yield h
    h.shutdown()


def test_single_node_cluster(harness):
    seed = harness.start_seed()
    assert seed.get_membership_size() == 1
    assert seed.get_memberlist() == [seed.listen_address]


def test_sequential_joins(harness):
    """ClusterTest.java:150-175."""
    harness.start_seed()
    for i in range(1, 10):
        harness.join(i)
        harness.wait_and_verify_agreement(i + 1)
    assert all(c.get_membership_size() == 10 for c in harness.instances.values())


def test_parallel_joins_through_single_seed(harness):
    """ClusterTest.java:184-191 (scaled to 30 in-process nodes)."""
    harness.create_cluster(30, parallel=True)
    harness.wait_and_verify_agreement(30)


def test_staged_join_waves(harness):
    """ClusterTest.java:198-206: waves of concurrent joiners."""
    harness.start_seed()
    total = 1
    for wave in range(3):
        promises = [harness.join_async(total + i) for i in range(5)]
        ok = harness.scheduler.run_until(
            lambda: all(p.done() and p.exception() is None for p in promises),
            timeout_ms=300_000,
        )
        assert ok
        total += 5
        harness.wait_and_verify_agreement(total)


def test_crash_one_node(harness):
    harness.create_cluster(10)
    harness.wait_and_verify_agreement(10)
    harness.fail_nodes([harness.addr(9)])
    harness.wait_and_verify_agreement(9)


def test_crash_multiple_nodes(harness):
    """ClusterTest.java:276-315 (12/50 there; 6/25 here -- same >20% ratio)."""
    harness.create_cluster(25)
    harness.wait_and_verify_agreement(25)
    failing = [harness.addr(i) for i in range(19, 25)]
    harness.fail_nodes(failing)
    harness.wait_and_verify_agreement(19)
    for cluster in harness.instances.values():
        members = set(cluster.get_memberlist())
        assert not members & set(failing)


def test_crash_seed_node(harness):
    harness.create_cluster(10)
    harness.wait_and_verify_agreement(10)
    harness.fail_nodes([harness.addr(0)])
    harness.wait_and_verify_agreement(9)


def test_asymmetric_probe_drops(harness):
    """ClusterTest.java:343-358: drop all probes *to* some nodes; the cluster
    must remove exactly those nodes despite them being able to send."""
    h = ClusterHarness(seed=7, use_static_fd=False)
    try:
        from rapid_tpu.messaging.inprocess import InProcessClient

        def pingpong(i):
            addr = h.addr(i)
            return PingPongFailureDetectorFactory(
                addr, InProcessClient(addr, h.network, h.settings)
            )

        h.start_seed(0, fd=pingpong(0))
        for i in range(1, 12):
            h.join(i, fd=pingpong(i))
        h.wait_and_verify_agreement(12)
        victims = {h.addr(10), h.addr(11)}
        h.network.add_filter(
            lambda s, d, m: not (isinstance(m, ProbeMessage) and d in victims)
        )
        for victim in victims:
            h.instances.pop(victim)
        h.wait_and_verify_agreement(10, timeout_ms=600_000)
        for cluster in h.instances.values():
            assert not set(cluster.get_memberlist()) & victims
    finally:
        h.shutdown()


def test_join_with_dropped_join_messages(harness):
    """ClusterTest.java:365-412: seed drops the first phase-1 and phase-2
    messages; the joiner's retry logic must still get it in."""
    harness.start_seed()
    seed_server = harness.servers[harness.addr(0)]
    dropped = {"prejoin": 0, "join": 0}

    def drop_first_n(msg) -> bool:
        if isinstance(msg, PreJoinMessage) and dropped["prejoin"] < 1:
            dropped["prejoin"] += 1
            return False
        if isinstance(msg, JoinMessage) and dropped["join"] < 1:
            dropped["join"] += 1
            return False
        return True

    seed_server.interceptors.append(drop_first_n)
    harness.join(1, timeout_ms=600_000)
    harness.wait_and_verify_agreement(2)
    assert dropped["prejoin"] == 1 and dropped["join"] == 1


def test_rejoin_after_crash(harness):
    """ClusterTest.java:418-504 (churn): a crashed node rejoins with the same
    address and a fresh identifier."""
    harness.create_cluster(10)
    harness.wait_and_verify_agreement(10)
    victim = harness.addr(9)
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(9)
    harness.blacklist.discard(victim)
    harness.join(9)
    harness.wait_and_verify_agreement(10)


def test_churn_loop(harness):
    """Repeated crash+rejoin cycles keep converging."""
    harness.create_cluster(8)
    harness.wait_and_verify_agreement(8)
    for _ in range(3):
        victim = harness.addr(7)
        harness.fail_nodes([victim])
        harness.wait_and_verify_agreement(7)
        harness.blacklist.discard(victim)
        harness.join(7)
        harness.wait_and_verify_agreement(8)


def test_graceful_leave(harness):
    """ClusterTest.java:510-522: leaveGracefully triggers a proactive DOWN cut
    without waiting for failure detection."""
    harness.create_cluster(10)
    harness.wait_and_verify_agreement(10)
    leaver = harness.instances.pop(harness.addr(9))
    done = leaver.leave_gracefully_async()
    ok = harness.scheduler.run_until(done.done, timeout_ms=120_000)
    assert ok
    harness.wait_and_verify_agreement(9)


def test_join_nonexistent_seed_fails(harness):
    promise = harness._builder(harness.addr(1)).join_async(harness.addr(99))
    ok = harness.scheduler.run_until(promise.done, timeout_ms=600_000)
    assert ok
    assert promise.exception() is not None


def test_memberlist_identical_across_nodes(harness):
    harness.create_cluster(15)
    harness.wait_and_verify_agreement(15)
    lists = [tuple(c.get_memberlist()) for c in harness.instances.values()]
    assert len(set(lists)) == 1
    configs = {c.get_current_configuration_id() for c in harness.instances.values()}
    assert len(configs) == 1


def test_classic_paxos_fallback_in_full_stack(harness):
    """PaxosTests-style droppable message types, through the whole stack:
    with every FastRoundPhase2bMessage dropped network-wide, a crash must
    still be resolved by the scheduled classic Paxos rounds
    (FastPaxos.java:105-107,189-195)."""
    from rapid_tpu.types import FastRoundPhase2bMessage

    harness.create_cluster(6)
    harness.wait_and_verify_agreement(6)
    harness.network.add_filter(
        lambda s, d, m: not isinstance(m, FastRoundPhase2bMessage)
    )
    harness.fail_nodes([harness.addr(5)])
    # needs fallback delay (1s base + Exp(mean N s) jitter) -- virtual time
    harness.wait_and_verify_agreement(5, timeout_ms=600_000)


def test_fast_round_message_delay_still_converges(harness):
    """Delaying (not dropping) consensus messages by 300ms must not break
    agreement -- the Delayer interceptor scenario."""
    from rapid_tpu.types import FastRoundPhase2bMessage

    harness.create_cluster(8)
    harness.wait_and_verify_agreement(8)
    harness.network.add_delay(
        lambda s, d, m: 300 if isinstance(m, FastRoundPhase2bMessage) else 0
    )
    harness.fail_nodes([harness.addr(7)])
    harness.wait_and_verify_agreement(7)


def test_hundred_node_parallel_join_and_crash(harness):
    """Full reference scale (ClusterTest.java:184-191 hundred-node join;
    :276-315 twelve-node crash) -- seconds of wall clock under virtual time."""
    harness.create_cluster(100, parallel=True)
    harness.wait_and_verify_agreement(100)
    failing = [harness.addr(i) for i in range(88, 100)]
    harness.fail_nodes(failing)
    harness.wait_and_verify_agreement(88)
    for cluster in harness.instances.values():
        assert not set(cluster.get_memberlist()) & set(failing)


def test_crash_beyond_fast_paxos_quorum(harness):
    """ClusterTest.java:276-315's 16/50 case: with 32% of members crashed,
    the 34 survivors cannot reach the fast-round supermajority
    (50 - (49//4) = 38), so convergence MUST ride the classic Paxos
    fallback (majority 26 <= 34) -- no message interference needed."""
    harness.create_cluster(50, parallel=True)
    harness.wait_and_verify_agreement(50)
    failing = [harness.addr(i) for i in range(34, 50)]
    harness.fail_nodes(failing)
    # classic rounds start after the expovariate fallback delay (mean N s)
    harness.wait_and_verify_agreement(34, timeout_ms=1_200_000)
    for cluster in harness.instances.values():
        assert not set(cluster.get_memberlist()) & set(failing)


def test_refused_view_change_parks_and_applies_when_alerts_land(harness):
    """The vote-quorum-before-UP-alerts race (every delivery is best-effort
    and independently ordered): a member whose FastPaxos decides a proposal
    naming a joiner it has no identity for must refuse the view change
    (applying it would fork the configuration id; the reference NPEs,
    MembershipService.java:396) -- but PARK it, because that
    configuration's FastPaxos has decided and will never re-fire. When the
    UUID-carrying alerts arrive a moment later, the parked decision
    applies."""
    from rapid_tpu.types import (
        AlertMessage,
        BatchedAlertMessage,
        EdgeStatus,
        Endpoint,
        FastRoundVoteBatch,
        NodeId,
    )

    harness.create_cluster(4)
    harness.wait_and_verify_agreement(4)
    node = harness.instances[harness.addr(0)]
    service = node._membership_service  # noqa: SLF001
    config_id = node.get_current_configuration_id()
    joiner = Endpoint.from_parts("127.0.0.1", 4999)
    joiner_id = NodeId(1234, 5678)

    # quorum of identical votes arrives FIRST (N=4 => F=0, quorum=4)
    service.handle_message(FastRoundVoteBatch(
        senders=tuple(harness.addr(i) for i in range(4)),
        configuration_id=config_id,
        endpoints=(joiner,),
    ))
    harness.scheduler.run_for(500)
    assert service.metrics.get("view_changes_refused_missing_identity") == 1
    assert node.get_membership_size() == 4  # refused, not forked

    # ... then the UP alert lands: the parked decision applies
    service.handle_message(BatchedAlertMessage(
        sender=harness.addr(1),
        messages=(AlertMessage(
            edge_src=harness.addr(1),
            edge_dst=joiner,
            edge_status=EdgeStatus.UP,
            configuration_id=config_id,
            ring_numbers=(0,),
            node_id=joiner_id,
        ),),
    ))
    harness.scheduler.run_for(500)
    assert node.get_membership_size() == 5
    assert joiner in node.get_memberlist()
    assert node.get_current_configuration_id() != config_id
