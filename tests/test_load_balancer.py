"""The end-to-end application scenario (paper §7 Fig. 13's shape): a
workload router over the gateway swarm reroutes after a SINGLE view change
when 10 of 50 backends fail at once, and never routes to a dead backend
afterwards."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from examples.load_balancer import run_scenario  # noqa: E402


@pytest.mark.slow
def test_ten_of_fifty_backend_failures_rebalance_in_one_view_change():
    out = run_scenario(backends=50, fail=10, seed=23, quiet=True)
    # the whole failed set lands in ONE view change (Fig. 13's headline)
    assert out["view_changes"] == 1
    assert out["cut"] == out["victims"] and len(out["cut"]) == 10
    # the router's next routes are clean, and only moved keys moved
    assert out["dead_routes"] == []
    assert 0 < out["moved"] < out["keys"]
    # both sides of the wire agree on the configuration
    assert out["config_id_router"] == out["config_id_swarm"]
