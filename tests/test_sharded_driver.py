"""Simulator driver on a device mesh: the full fault/join/leave/view-change
API must behave identically sharded (8 virtual CPU devices) and unsharded.
"""

import numpy as np
import pytest

from rapid_tpu.shard.engine import make_mesh
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_sharded_driver_crash_matches_single_device(mesh):
    records = {}
    for label, m in (("sharded", mesh), ("single", None)):
        sim = Simulator(256, seed=41, mesh=m)
        sim.crash(np.array([10, 77, 200]))
        rec = sim.run_until_decision(max_rounds=16, batch=8)
        assert rec is not None
        records[label] = rec
    a, b = records["sharded"], records["single"]
    assert sorted(a.cut) == sorted(b.cut) == [10, 77, 200]
    assert a.configuration_id == b.configuration_id
    assert a.virtual_time_ms == b.virtual_time_ms


def test_sharded_driver_join_leave_cycle(mesh):
    sim = Simulator(120, capacity=128, seed=42, mesh=mesh)
    sim.request_joins(np.array([120, 121]))
    rec = sim.run_until_decision(max_rounds=8, batch=4)
    assert rec is not None and sorted(rec.cut) == [120, 121]
    assert sim.membership_size == 122

    sim.leave(np.array([5]))
    rec2 = sim.run_until_decision(max_rounds=8, batch=4)
    assert rec2 is not None and list(rec2.cut) == [5]
    assert sim.membership_size == 121

    # parity against an unsharded simulator running the same history
    ref = Simulator(120, capacity=128, seed=42)
    ref.request_joins(np.array([120, 121]))
    ref.run_until_decision(max_rounds=8, batch=4)
    ref.leave(np.array([5]))
    ref_rec = ref.run_until_decision(max_rounds=8, batch=4)
    assert ref_rec is not None
    assert ref_rec.configuration_id == rec2.configuration_id


def test_sharded_driver_windowed_policy(mesh):
    config = SimConfig(capacity=128, fd_policy="windowed")
    sim = Simulator(128, config=config, seed=43, mesh=mesh)
    sim.crash(np.array([3]))
    rec = sim.run_until_decision(max_rounds=20, batch=10)
    assert rec is not None and list(rec.cut) == [3]
    # window fills at round 10, votes arrive round 11
    assert rec.virtual_time_ms == 11 * 1000 + 100


def test_sharded_driver_staggered_phases(mesh):
    """The staggered-phase asynchrony model produces identical records on the
    mesh and on a single device."""
    records = {}
    for label, m in (("sharded", mesh), ("single", None)):
        config = SimConfig(capacity=128, rounds_per_interval=5)
        sim = Simulator(128, config=config, seed=44, mesh=m)
        sim.crash(np.array([8, 90]))
        rec = sim.run_until_decision(max_rounds=64, batch=16)
        assert rec is not None
        records[label] = (
            tuple(sorted(int(i) for i in rec.cut)),
            rec.configuration_id,
            rec.virtual_time_ms,
        )
    assert records["sharded"] == records["single"]


def test_sharded_until_bit_identical_to_scan(mesh):
    """The early-exit while_loop runner and the scan runner must produce
    bit-identical state from the same start (VERDICT r2 item 4)."""
    import jax
    import jax.numpy as jnp

    from rapid_tpu.shard.engine import make_sharded_run, make_sharded_run_until

    for random_loss in (False, True):
        sim = Simulator(256, seed=44, mesh=mesh)
        sim.crash(np.array([7, 31]))
        if random_loss:
            sim.ingress_loss(np.array([5, 9]), 0.3)
        inputs = sim._const_inputs(sim._arm_pending_joins())
        rounds = 12
        scan = make_sharded_run(sim.config, mesh, rounds, random_loss)
        until = make_sharded_run_until(sim.config, mesh, random_loss)
        out_scan = scan(sim.state, inputs)
        out_until = until(sim.state, inputs, jnp.int32(rounds))
        flat_a, _ = jax.tree_util.tree_flatten(out_scan)
        flat_b, _ = jax.tree_util.tree_flatten(out_until)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_decision_single_dispatch_no_rejit(mesh):
    """A mesh-mode decision completes in ONE device dispatch when the batch
    covers it, and different batch sizes share one cached executable."""
    sim = Simulator(256, seed=45, mesh=mesh)
    sim.crash(np.array([12]))
    rec = sim.run_until_decision(max_rounds=32, batch=32)
    assert rec is not None and list(rec.cut) == [12]
    assert sim.metrics.get("device_dispatches") == 1

    # second decision with a different batch size: the cached ("until", loss)
    # executable is reused -- the budget is a dynamic operand
    n_cached = len(sim._sharded_runs)
    sim.crash(np.array([40]))
    rec2 = sim.run_until_decision(max_rounds=32, batch=5)
    assert rec2 is not None and list(rec2.cut) == [40]
    assert len(sim._sharded_runs) == n_cached


def test_sharded_driver_2d_dcn_ici_mesh():
    """The full driver (early-exit runner included) on a (hosts, chips) 2D
    mesh: decisions and configuration ids match the single-device run."""
    mesh2d = make_mesh(shape=(2, 4))
    records = {}
    for label, m in (("2d", mesh2d), ("single", None)):
        sim = Simulator(256, seed=47, mesh=m)
        sim.crash(np.array([3, 99]))
        rec = sim.run_until_decision(max_rounds=16, batch=16)
        assert rec is not None
        records[label] = rec
    a, b = records["2d"], records["single"]
    assert sorted(a.cut) == sorted(b.cut) == [3, 99]
    assert a.configuration_id == b.configuration_id
    assert a.virtual_time_ms == b.virtual_time_ms


def test_multihost_mesh_entry_degenerate_single_process():
    """make_multihost_mesh without a coordinator: the degenerate 1-host
    ("dcn", "ici") mesh over local devices runs the full sharded decision
    path (on a pod slice the same call site gets hosts x chips; the step
    program is identical)."""
    from rapid_tpu.shard.engine import make_multihost_mesh

    mesh = make_multihost_mesh(chips_per_host=4)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.shape["dcn"] == 1 and mesh.shape["ici"] == 4
    sim = Simulator(36, capacity=36, seed=31, mesh=mesh)
    sim.crash(np.array([4, 17]))
    rec = sim.run_until_decision(max_rounds=32, batch=8)
    assert rec is not None and set(rec.cut) == {4, 17}
    # identical outcome to the single-device driver
    ref = Simulator(36, capacity=36, seed=31)
    ref.crash(np.array([4, 17]))
    ref_rec = ref.run_until_decision(max_rounds=32, batch=8)
    assert ref_rec.configuration_id == rec.configuration_id
    assert ref_rec.virtual_time_ms == rec.virtual_time_ms
