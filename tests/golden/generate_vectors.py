"""Generate tests/golden/parity_vectors.json -- the frozen JVM contract.

Run from the repo root: python tests/golden/generate_vectors.py

The committed JSON is the contract; regenerating it is only legitimate after
a deliberate, independently cross-validated change to the hash chain or wire
schema (e.g. re-proven against protoc output from the reference's
rapid.proto and the published xxHash vectors). A regenerate-to-make-tests-
pass is exactly the silent drift the golden file exists to catch.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from golden import fixtures as fx  # noqa: E402

from rapid_tpu.hashing import endpoint_hash, xxh64  # noqa: E402
from rapid_tpu.membership import MembershipView  # noqa: E402
from rapid_tpu.messaging import grpc_transport as gt  # noqa: E402


def build_views():
    """The three fixed configurations, built through the object plane."""
    view = MembershipView(fx.K)
    for i in range(fx.INITIAL):
        ep, nid = fx.member(i)
        view.ring_add(ep, nid)
    yield "initial20", view
    for i in fx.DELETED:
        view.ring_delete(fx.member(i)[0])
    yield "after_delete3", view
    for i in fx.ADDED:
        ep, nid = fx.member(i)
        view.ring_add(ep, nid)
    yield "after_add5", view


def main() -> None:
    vectors = {
        "xxh64": {
            data.hex(): {
                str(seed): f"{xxh64(data, seed):016x}" for seed in fx.HASH_SEEDS
            }
            for data in fx.HASH_SAMPLES
        },
        "endpoint_hashes": {
            fx.ep_str(ep): {
                str(seed): f"{endpoint_hash(ep.hostname, ep.port, seed):016x}"
                for seed in range(fx.K)
            }
            for ep in (fx.member(i)[0] for i in range(3))
        },
        "configurations": {},
        "requests": {},
        "responses": {},
    }
    for name, view in build_views():
        vectors["configurations"][name] = {
            "configuration_id": view.get_current_configuration_id(),
            "rings": [
                [fx.ep_str(ep) for ep in view.get_ring(ring)]
                for ring in range(fx.K)
            ],
        }
    for msg in fx.REQUEST_SAMPLES:
        wire = gt.to_wire_request(msg)
        vectors["requests"][type(msg).__name__] = wire.SerializeToString(
            deterministic=True
        ).hex()
    for msg in fx.RESPONSE_SAMPLES:
        wire = gt.to_wire_response(msg)
        vectors["responses"][type(msg).__name__] = wire.SerializeToString(
            deterministic=True
        ).hex()

    out = os.path.join(os.path.dirname(__file__), "parity_vectors.json")
    with open(out, "w") as f:
        json.dump(vectors, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
