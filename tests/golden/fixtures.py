"""Fixed identities and message samples shared by the golden-vector
generator and tests/test_golden_parity.py.

Everything here is deliberately hard-coded: the golden contract freezes what
these exact inputs must hash/order/serialize to, so a regression cannot move
both the implementation and the expectation at once.
"""

from __future__ import annotations

from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    ConsensusResponse,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    NodeStatus,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Rank,
    Response,
)

K = 10


def member(i: int) -> tuple[Endpoint, NodeId]:
    """The i-th fixed identity: a stable endpoint and a spread-out NodeId
    (negative highs exercise the signed NodeId ordering)."""
    ep = Endpoint.from_parts(f"192.168.{i // 8}.{i % 8 + 1}", 20000 + 17 * i)
    nid = NodeId(high=(i * 2654435761) % (1 << 63) - (i % 3) * (1 << 62),
                 low=(i * 40503) % (1 << 31) - 7 * i)
    return ep, nid


INITIAL = 20  # members 0..19 form the base configuration
DELETED = (3, 7, 15)  # removed for the second configuration
ADDED = range(20, 25)  # joined for the third configuration

EP_A, NID_A = member(0)
EP_B, NID_B = member(1)

REQUEST_SAMPLES = [
    PreJoinMessage(sender=EP_A, node_id=NID_A),
    JoinMessage(sender=EP_A, node_id=NID_A, ring_numbers=(0, 4, 9),
                configuration_id=-6148914691236517206,
                metadata=(("role", b"db"),)),
    BatchedAlertMessage(sender=EP_B, messages=(
        AlertMessage(edge_src=EP_A, edge_dst=EP_B, edge_status=EdgeStatus.DOWN,
                     configuration_id=3, ring_numbers=(2,)),
        AlertMessage(edge_src=EP_B, edge_dst=EP_A, edge_status=EdgeStatus.UP,
                     configuration_id=3, ring_numbers=(0, 1), node_id=NID_A,
                     metadata=(("x", b"y"),)),
    )),
    ProbeMessage(sender=EP_A),
    FastRoundPhase2bMessage(sender=EP_A, configuration_id=8,
                            endpoints=(EP_A, EP_B)),
    Phase1aMessage(sender=EP_A, configuration_id=8, rank=Rank(2, -1)),
    Phase1bMessage(sender=EP_B, configuration_id=8, rnd=Rank(2, 3),
                   vrnd=Rank(1, 1), vval=(EP_A,)),
    Phase2aMessage(sender=EP_A, configuration_id=8, rnd=Rank(2, 3),
                   vval=(EP_B,)),
    Phase2bMessage(sender=EP_B, configuration_id=8, rnd=Rank(2, 3),
                   endpoints=(EP_A, EP_B)),
    LeaveMessage(sender=EP_A),
]

RESPONSE_SAMPLES = [
    JoinResponse(sender=EP_A, status_code=JoinStatusCode.SAFE_TO_JOIN,
                 configuration_id=5, endpoints=(EP_A, EP_B),
                 identifiers=(NID_A,), metadata=((EP_A, (("k", b"v"),)),)),
    ProbeResponse(NodeStatus.BOOTSTRAPPING),
    ConsensusResponse(),
    Response(),
]

HASH_SAMPLES = [b"", b"a", b"hello world", b"192.168.0.1", bytes(range(32))]
HASH_SEEDS = [0, 1, 9, 0xC0FFEE]


def ep_str(ep: Endpoint) -> str:
    return f"{ep.hostname.decode()}:{ep.port}"
