"""Fixture MessageBatch envelopes shared by the golden generator
(generate_batch_frames.py) and the pinning tests (test_batch_messaging.py).

Every object here is deterministic: fixed endpoints, fixed ids, and -- for
byte-for-byte stability -- never trace-stamped (an unstamped message encodes
no ``__tc`` envelope key).
"""

from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    GossipEnvelope,
    MessageBatch,
    NodeId,
    ProbeMessage,
)

BATCH_SENDER = Endpoint.from_parts("10.9.0.1", 7001)
PEER_A = Endpoint.from_parts("10.9.0.2", 7002)
PEER_B = Endpoint.from_parts("10.9.0.3", 7003)

ALERT_DOWN = AlertMessage(
    edge_src=PEER_A, edge_dst=PEER_B, edge_status=EdgeStatus.DOWN,
    configuration_id=-11, ring_numbers=(0, 2),
)
ALERT_UP = AlertMessage(
    edge_src=PEER_B, edge_dst=PEER_A, edge_status=EdgeStatus.UP,
    configuration_id=-11, ring_numbers=(1,), node_id=NodeId(5, 6),
    metadata=(("zone", b"z1"),),
)
ALERTS = BatchedAlertMessage(
    sender=BATCH_SENDER, messages=(ALERT_DOWN, ALERT_UP),
)
VOTE = FastRoundPhase2bMessage(
    sender=BATCH_SENDER, configuration_id=-11, endpoints=(PEER_A, PEER_B),
)
GOSSIP = GossipEnvelope(
    sender=BATCH_SENDER, gossip_id=NodeId(41, 42), ttl=3,
    payload=ProbeMessage(sender=BATCH_SENDER), kind=GossipEnvelope.KIND_PAYLOAD,
)

# named (request_no, batch) pairs pinned on the native msgpack wire. The
# inner messages are request-surface types (what broadcasters actually
# send): an AlertBatcher flush, a fast-round vote, a gossip relay. The
# heterogeneous case is the envelope's reason to exist -- one churn wave's
# traffic riding a single frame per peer.
TCP_BATCHES = {
    "alerts_pair": (
        7,
        MessageBatch(sender=BATCH_SENDER, messages=(ALERTS, ALERTS)),
    ),
    "heterogeneous": (
        1025,
        MessageBatch(
            sender=BATCH_SENDER, messages=(ALERTS, VOTE, GOSSIP),
        ),
    ),
    "singleton": (
        0,
        MessageBatch(sender=BATCH_SENDER, messages=(VOTE,)),
    ),
}

# the gRPC schema mirrors rapid.proto and cannot carry GossipEnvelope, so
# its pinned batch holds only reference-surface messages
GRPC_BATCH = MessageBatch(
    sender=BATCH_SENDER, messages=(ALERTS, VOTE),
)
