"""Fixture telemetry-scrape messages shared by the golden generator
(generate_scrape_frames.py) and the pinning tests (test_profiling.py).

Everything here is deterministic: fixed endpoints, fixed timestamps, and
hand-written history lines in exactly the sorted-key JSON form
``MetricsHistory.to_wire`` emits -- so the pinned frames freeze both the
new wire fields (ClusterStatusRequest.include_history,
ClusterStatusResponse.history: proto field 33) and the snapshot-line
dialect they carry. Never trace-stamped (an unstamped message encodes no
``__tc`` envelope key).
"""

from rapid_tpu.types import (
    ClusterStatusRequest,
    ClusterStatusResponse,
    Endpoint,
)

SCRAPER = Endpoint.from_parts("10.9.1.1", 7101)
MEMBER = Endpoint.from_parts("10.9.1.2", 7102)

# exactly what MetricsHistory.to_wire produces: one sorted-key JSON object
# per line with ts_s / seq / counters / gauges / histograms ([count, sum])
# tables; ``seq`` is the per-incarnation monotonic stamp the scrape
# assembler uses to split series across restarts
HISTORY_LINES = (
    '{"counters": {"rounds": 3.0}, "gauges": {"msg.queue_depth{peer=10.9.1.3:7103}": 128.0}, '
    '"histograms": {"profile.phase_ms{phase=fd_scan,plane=sim}": [3, 1.5]}, "seq": 1, "ts_s": 12.0}',
    '{"counters": {"rounds": 5.0}, "gauges": {}, '
    '"histograms": {"profile.phase_ms{phase=fd_scan,plane=sim}": [5, 2.25]}, "seq": 2, "ts_s": 13.0}',
)

SCRAPE_REQUEST = ClusterStatusRequest(sender=SCRAPER, include_history=16)

SCRAPE_RESPONSE = ClusterStatusResponse(
    sender=MEMBER,
    configuration_id=-6148914691236517206,
    membership_size=3,
    reports_tracked=1,
    consensus_votes=2,
    metric_names=("rounds",),
    metric_values=(5,),
    history=HISTORY_LINES,
)

# an SLO-plane-bearing status: the four parallel alert tuples the SLO PR
# appended (proto fields 37-40) -- one healthy alert and one firing alert
# attributed to view-change trace 7, pinning burn-milli integer scaling
SLO_RESPONSE = ClusterStatusResponse(
    sender=MEMBER,
    configuration_id=-6148914691236517206,
    membership_size=3,
    reports_tracked=1,
    consensus_votes=2,
    slo_names=("serving.availability:fast", "serving.latency:fast"),
    slo_burn_milli=(150, 42100),
    slo_firing=(0, 1),
    slo_attributed_trace=(0, 7),
)

# a forensics-plane-bearing status: journal truncation accounting plus
# the node's hybrid logical clock (proto fields 41-45) -- the coordinates
# evidence bundles merge cluster timelines on; incarnation 2 pins a
# restarted member's persisted boot count
HLC_RESPONSE = ClusterStatusResponse(
    sender=MEMBER,
    configuration_id=-6148914691236517206,
    membership_size=3,
    reports_tracked=1,
    consensus_votes=2,
    journal_dropped=6,
    journal_capacity=256,
    hlc_physical_ms=1_750_000,
    hlc_logical=4,
    hlc_incarnation=2,
)

# a hierarchy-plane-bearing status: the member's cell coordinates plus a
# two-cell composed global view as parallel arrays (proto fields 46-53)
# -- the single-integer agreement surfaces (parent config id, composed
# fingerprint) statusz cross-checks; negative ids pin signed carriage
HIERARCHY_RESPONSE = ClusterStatusResponse(
    sender=MEMBER,
    configuration_id=-6148914691236517206,
    membership_size=3,
    reports_tracked=1,
    consensus_votes=2,
    cell_id=1,
    cell_size=3,
    parent_configuration_id=-4242424242424242424,
    global_fingerprint=7777777777777777777,
    global_cells=(0, 1),
    global_epochs=(-111, -222),
    global_sizes=(2, 3),
    global_leaders=("10.9.1.9:7109", "10.9.1.2:7102"),
)

# named (request_no, message) pairs pinned on the native msgpack wire
TCP_SCRAPES = {
    "request_with_history": (11, SCRAPE_REQUEST),
    # a pre-profiling scrape: default include_history=0 must still encode
    # (old peers' frames simply omit what their dataclass defaults fill)
    "request_plain": (12, ClusterStatusRequest(sender=SCRAPER)),
    "response_with_history": (13, SCRAPE_RESPONSE),
    "response_with_slo": (14, SLO_RESPONSE),
    "response_with_hlc": (15, HLC_RESPONSE),
    "response_with_hierarchy": (16, HIERARCHY_RESPONSE),
}
