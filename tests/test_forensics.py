"""Forensics plane: hybrid logical clocks, evidence bundles, timelines.

The acceptance scenario is the one from the PR issue: a staggered 3-node
churn under a +/-500ms clock_skew plan must produce an evidence bundle
whose merged timeline orders fd_signal -> alerts -> view_install
correctly by HLC while the nodes' own (skewed) clocks provably disagree
-- a message that "arrives before it was sent" by local clocks lands
after its send on the HLC axis. Everything runs on virtual time, so the
whole file is tier-1.
"""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from rapid_tpu.durability import FSYNC_NEVER
from rapid_tpu.faults import FaultPlan
from rapid_tpu.forensics.bundle import (
    build_bundle,
    capture_local_evidence,
    install_exit_hooks,
    load_bundle,
    verify_bundle,
    write_bundle,
)
from rapid_tpu.forensics.hlc import HlcClock, HlcStamp, hlc_of, stamp_hlc
from rapid_tpu.forensics.timeline import detect_signatures, merge_timeline
from rapid_tpu.messaging import codec
from rapid_tpu.observability import FlightRecorder, Metrics
from rapid_tpu.settings import (
    DurabilitySettings,
    ForensicsSettings,
    Settings,
)
from rapid_tpu.types import Endpoint, ProbeMessage

from harness import ClusterHarness

REPO = __file__.rsplit("/", 2)[0]


def _forensics_settings(**kw) -> Settings:
    return Settings(forensics=ForensicsSettings(enabled=True, **kw))


# ---------------------------------------------------------------------------
# HLC unit semantics
# ---------------------------------------------------------------------------


class TestHlc:
    def test_now_is_strictly_monotonic_under_a_frozen_clock(self):
        clock = HlcClock(clock=lambda: 1000)
        stamps = [clock.now() for _ in range(50)]
        for a, b in zip(stamps, stamps[1:]):
            assert b.pair() > a.pair()
        # frozen physical time: all advancement is logical
        assert all(s.physical_ms == 1000 for s in stamps)

    def test_physical_advance_resets_logical(self):
        t = [1000]
        clock = HlcClock(clock=lambda: t[0])
        clock.now()
        clock.now()
        t[0] = 2000
        stamp = clock.now()
        assert stamp == HlcStamp(2000, 0, 1)

    def test_regressing_physical_clock_never_moves_stamps_backward(self):
        t = [5000]
        clock = HlcClock(clock=lambda: t[0])
        high = clock.now()
        t[0] = 100  # wall clock stepped back (NTP slew, skew fault)
        low = clock.now()
        assert low.pair() > high.pair()
        assert low.physical_ms == high.physical_ms  # held, logical bumped

    def test_merge_is_strictly_greater_than_both_inputs(self):
        t = [1000]
        clock = HlcClock(clock=lambda: t[0])
        local = clock.now()
        # remote far ahead (the +500 skewed peer), equal, and behind
        for remote in (HlcStamp(9000, 3), HlcStamp(1000, 7), HlcStamp(10, 2)):
            merged = clock.merge(remote)
            assert merged.pair() > remote.pair()
            assert merged.pair() > local.pair()
            local = merged

    def test_causal_chain_across_skewed_nodes(self):
        # A (+500) sends to B (-500): every hop must order after its cause
        # even though B's physical clock reads 1000ms behind A's.
        a = HlcClock(clock=lambda: 1500)
        b = HlcClock(clock=lambda: 500)
        send = a.now()
        recv = b.merge(send)
        after = b.now()
        assert send.pair() < recv.pair() < after.pair()

    def test_wire_round_trip(self):
        stamp = HlcStamp(12345, 7, incarnation=3)
        assert HlcStamp.from_wire(stamp.to_wire()) == stamp
        assert HlcStamp.from_wire([5, 2]) == HlcStamp(5, 2, 1)

    @pytest.mark.parametrize("raw", [
        None, 42, "x", [], [1], ["a", "b"], [-1, 0], [0, -2],
        [1, 1, 0], [1, 1, -5], {"physical": 1},
    ])
    def test_malformed_wire_stamps_are_rejected(self, raw):
        assert HlcStamp.from_wire(raw) is None

    def test_clock_failure_falls_back_to_last_physical(self):
        state = {"ok": True}

        def dying():
            if not state["ok"]:
                raise RuntimeError("clock is gone")
            return 700

        clock = HlcClock(clock=dying)
        clock.now()
        state["ok"] = False
        stamp = clock.now()  # must not raise, must still advance
        assert stamp.physical_ms == 700 and stamp.logical >= 1


# ---------------------------------------------------------------------------
# Wire carriage + the kill switch
# ---------------------------------------------------------------------------


class TestWireKillSwitch:
    def test_unstamped_frame_has_no_hlc_key(self):
        msg = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        frame = codec.encode(1, msg)
        assert b"__hlc" not in frame

    def test_kill_switch_off_reproduces_pre_forensics_bytes(self):
        # two identical messages, one stamped: the unstamped frame must be
        # byte-identical to the stamped frame minus the rider -- i.e. the
        # rider is the ONLY delta the forensics plane can introduce
        plain = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        stamped = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        stamp_hlc(stamped, HlcStamp(1234, 5, 2))
        plain_frame = codec.encode(1, plain)
        stamped_frame = codec.encode(1, stamped)
        assert b"__hlc" in stamped_frame
        assert b"__hlc" not in plain_frame
        # and a second unstamped encoding is bit-identical (determinism)
        again = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        assert codec.encode(1, again) == plain_frame

    def test_stamp_round_trips_through_the_codec(self):
        msg = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        stamp_hlc(msg, HlcStamp(777, 3, 4))
        _no, decoded = codec.decode(codec.encode(1, msg))
        assert hlc_of(decoded) == HlcStamp(777, 3, 4)

    def test_decoder_strips_rider_from_unstamped_peers(self):
        msg = ProbeMessage(sender=Endpoint.from_parts("127.0.0.1", 9))
        _no, decoded = codec.decode(codec.encode(1, msg))
        assert hlc_of(decoded) is None


# ---------------------------------------------------------------------------
# Flight recorder: drop accounting + exit hooks
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_overflow_counts_drops_and_bills_the_metric(self):
        metrics = Metrics()
        rec = FlightRecorder(capacity=4, node="n1", metrics=metrics)
        for i in range(10):
            rec.record("probe", virtual_ms=i)
        assert len(rec) == 4
        assert rec.dropped == 6
        assert metrics.snapshot()["journal.dropped_events"] == 6

    def test_entries_carry_hlc_when_the_clock_is_attached(self):
        rec = FlightRecorder(capacity=8, node="n1",
                             hlc=HlcClock(clock=lambda: 250))
        entry = rec.record("probe", virtual_ms=1)
        assert entry["hlc"][0] == 250 and len(entry["hlc"]) == 3

    def test_install_exit_hooks_is_idempotent(self, tmp_path):
        rec = FlightRecorder(capacity=8, node="n1")
        path = str(tmp_path / "journal.jsonl")
        assert install_exit_hooks(rec, path) is True
        assert install_exit_hooks(rec, path) is False  # second call: no-op

    def test_dump_is_atomic_and_loadable(self, tmp_path):
        rec = FlightRecorder(capacity=8, node="n1")
        rec.record("probe", virtual_ms=5, peer="n2")
        path = tmp_path / "journal.jsonl"
        rec.dump(str(path))
        lines = path.read_text().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["kind"] == "probe"
        # no tmp droppings left behind by the tmp+replace protocol
        assert [p.name for p in tmp_path.iterdir()] == ["journal.jsonl"]


# ---------------------------------------------------------------------------
# The acceptance scenario: staggered churn under +/-500ms skew
# ---------------------------------------------------------------------------


class TestSkewedChurnTimeline:
    def test_bundle_orders_causality_despite_skewed_clocks(self):
        h = ClusterHarness(seed=31, settings=_forensics_settings())
        plan = (
            FaultPlan(seed=3)
            .clock_skew(h.addr(1), offset_ms=500)
            .clock_skew(h.addr(2), offset_ms=-500)
        )
        h.with_faults(plan)
        try:
            h.start_seed(0)
            h.join(1)
            h.join(2)
            h.wait_and_verify_agreement(3)
            h.fail_nodes([h.addr(0)])
            # 3 -> 2 can't quorum the fast round; the classic fallback
            # reconverges in ~700s of virtual time under this skew
            h.wait_and_verify_agreement(2, timeout_ms=1_500_000)

            survivor = h.instances[h.addr(1)]
            promise = survivor.capture_bundle_async(trigger="explicit")
            ok = h.scheduler.run_until(promise.done, timeout_ms=120_000)
            assert ok and promise.exception() is None
            bundle = promise.peek()

            # both survivors contributed evidence; nothing unreachable
            assert bundle["manifest"]["members"] == 2
            assert bundle["manifest"]["unreachable"] == []
            assert verify_bundle(bundle)

            events = merge_timeline([bundle])
            assert events, "merged timeline is empty"
            assert all(e.hlc is not None for e in events), (
                "forensics-on journals must be HLC-stamped"
            )
            n1, n2 = str(h.addr(1)), str(h.addr(2))

            # causality on the HLC axis: the failure is detected, alerts
            # fire, and only then does the shrunk view install
            first_fd = min(
                i for i, e in enumerate(events) if e.kind == "fd_signal"
            )
            last_view = max(
                i for i, e in enumerate(events)
                if e.kind == "view_install" and e.node == n2
            )
            alerts = [
                i for i, e in enumerate(events)
                if e.kind in ("alert_out", "alert_in")
            ]
            assert first_fd < last_view
            assert any(first_fd < i < last_view for i in alerts), (
                "no alert between failure detection and the view install"
            )

            # the wall-clock order is provably wrong: an alert received on
            # the -500 node carries a LOCAL receive time earlier than the
            # +500 sender's send time ("arrived before it was sent"), yet
            # the HLC merge rule still orders receive after send
            inversions = [
                (o, i)
                for o in events
                if o.node == n1 and o.kind == "alert_out"
                for i in events
                if i.node == n2 and i.kind == "alert_in"
                and i.hlc_key > o.hlc_key
                and i.virtual_ms is not None and o.virtual_ms is not None
                and i.virtual_ms < o.virtual_ms
            ]
            assert inversions, (
                "expected at least one wall-vs-HLC inversion across the "
                "+/-500ms skew"
            )
        finally:
            h.shutdown()


# ---------------------------------------------------------------------------
# Bundle capture under partial reachability (never blocks)
# ---------------------------------------------------------------------------


class TestPartialReachability:
    def test_unresponsive_member_is_named_not_waited_on(self):
        settings = _forensics_settings(bundle_member_timeout_ms=2000)
        # real ping-pong FDs so the drop/duplicate nemesis has probe
        # traffic to chew on while the capture fans out
        h = ClusterHarness(seed=7, use_static_fd=False, settings=settings)
        plan = (
            FaultPlan(seed=11)
            .duplicate(0.25, msg_types=[ProbeMessage])
            .drop(0.2, msg_types=[ProbeMessage])
        )
        h.with_faults(plan)
        try:
            h.start_seed(0)
            h.join(1)
            h.join(2)
            h.wait_and_verify_agreement(3)
            # gray member: still in the view, answers nothing (every
            # ingress frame dropped at its server)
            h.servers[h.addr(2)].interceptors.append(lambda _msg: False)

            started = h.scheduler.now_ms()
            promise = h.instances[h.addr(0)].capture_bundle_async(
                trigger="explicit"
            )
            ok = h.scheduler.run_until(promise.done, timeout_ms=120_000)
            assert ok and promise.exception() is None
            elapsed = h.scheduler.now_ms() - started
            # bounded by the per-member deadline, not the cluster's patience
            assert elapsed <= 60_000, f"capture stalled for {elapsed}ms"

            bundle = promise.peek()
            assert bundle["manifest"]["unreachable"] == [str(h.addr(2))]
            records = {m["node"]: m for m in bundle["members"]}
            assert records[str(h.addr(2))]["reachable"] is False
            assert records[str(h.addr(1))]["reachable"] is True
            assert records[str(h.addr(1))]["journal"], (
                "reachable members must still contribute their journal"
            )
            assert verify_bundle(bundle)
        finally:
            h.shutdown()


# ---------------------------------------------------------------------------
# Restarted members: the incarnation axis
# ---------------------------------------------------------------------------


class TestRestartIncarnation:
    def test_restart_bumps_incarnation_and_never_merges_two_lives(
        self, tmp_path
    ):
        settings = Settings(
            forensics=ForensicsSettings(enabled=True),
            durability=DurabilitySettings(
                enabled=True, fsync_policy=FSYNC_NEVER
            ),
        )
        h = ClusterHarness(seed=13, settings=settings)
        dirs = {i: str(tmp_path / f"node{i}") for i in range(3)}
        placement = {"partitions": 16, "replicas": 3, "seed": 7}
        try:
            h.start_seed(0, placement=placement, durability=dirs[0])
            h.join(1, placement=placement, durability=dirs[1])
            h.join(2, placement=placement, durability=dirs[2])
            h.wait_and_verify_agreement(3)
            victim = h.instances[h.addr(2)]
            assert victim.get_cluster_status().hlc_incarnation == 1

            first = h.instances[h.addr(0)].capture_bundle_async(
                trigger="explicit"
            )
            assert h.scheduler.run_until(first.done, timeout_ms=120_000)

            # power loss, then back with the same WAL directory before the
            # failure detector concludes (the PR 17 rejoin idiom)
            victim.get_partition_store().crash()
            h.fail_nodes([h.addr(2)])
            h.blacklist.discard(h.addr(2))
            revived = h.join(2, placement=placement, durability=dirs[2])
            h.wait_and_verify_agreement(3)
            assert revived.get_cluster_status().hlc_incarnation == 2

            second = h.instances[h.addr(0)].capture_bundle_async(
                trigger="explicit"
            )
            assert h.scheduler.run_until(second.done, timeout_ms=120_000)

            merged = merge_timeline([first.peek(), second.peek()])
            n2 = str(h.addr(2))
            lives = {e.hlc[2] for e in merged if e.node == n2 and e.hlc}
            assert lives == {1, 2}, f"expected both incarnations, got {lives}"
            # the restarted recorder restarts seq at 1: identical
            # (seq, kind) pairs across the two lives must NOT dedupe
            by_life = {
                1: {(e.seq, e.kind) for e in merged
                    if e.node == n2 and e.hlc and e.hlc[2] == 1},
                2: {(e.seq, e.kind) for e in merged
                    if e.node == n2 and e.hlc and e.hlc[2] == 2},
            }
            colliding = by_life[1] & by_life[2]
            assert colliding, "test needs overlapping (seq, kind) pairs"
            # while a stable member's overlapping tails DO dedupe
            n0 = str(h.addr(0))
            n0_keys = [
                (e.hlc[2], e.seq, e.kind) for e in merged
                if e.node == n0 and e.hlc
            ]
            assert len(n0_keys) == len(set(n0_keys))
        finally:
            h.shutdown()

    def test_durable_incarnation_survives_reopen(self, tmp_path):
        from rapid_tpu.durability import DurablePartitionStore

        store = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER
        )
        assert store.bump_incarnation() == 1
        store.crash()
        reopened = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER
        )
        assert reopened.incarnation == 1
        assert reopened.bump_incarnation() == 2


# ---------------------------------------------------------------------------
# tools/forensics.py: the CI-shaped report/verify contract
# ---------------------------------------------------------------------------


def _cli(*argv):
    return subprocess.run(
        [sys.executable, f"{REPO}/tools/forensics.py", *argv],
        capture_output=True, text=True, timeout=120,
    )


def _bundle_with(journal_events, path):
    rec = FlightRecorder(capacity=64, node="10.0.0.1:9001",
                         hlc=HlcClock(clock=lambda: 1000))
    for kind, detail in journal_events:
        rec.record(kind, virtual_ms=100, **detail)
    local = capture_local_evidence(node="10.0.0.1:9001", recorder=rec)
    bundle = build_bundle("explicit", local)
    write_bundle(bundle, str(path))
    return bundle


class TestForensicsCli:
    def test_seeded_stuck_handoff_exits_3(self, tmp_path):
        path = tmp_path / "stuck.json"
        _bundle_with([
            ("handoff_started", {"sessions": 2, "version": 4}),
            ("handoff_complete", {"partition": 0}),
        ], path)
        proc = _cli("report", str(path))
        assert proc.returncode == 3, proc.stdout + proc.stderr
        assert "stuck_handoff" in proc.stdout

    def test_clean_bundle_exits_0(self, tmp_path):
        path = tmp_path / "clean.json"
        _bundle_with([
            ("handoff_started", {"sessions": 1, "version": 4}),
            ("handoff_complete", {"partition": 0}),
        ], path)
        proc = _cli("report", str(path))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_verify_detects_tampering(self, tmp_path):
        path = tmp_path / "bundle.json"
        _bundle_with([("probe", {"peer": "x"})], path)
        assert _cli("verify", str(path)).returncode == 0
        doc = load_bundle(str(path))
        doc["members"][0]["metrics"] = {"messages.forged": 1}
        path.write_text(json.dumps(doc))
        assert _cli("verify", str(path)).returncode == 3

    def test_detectors_match_the_cli_verdict(self, tmp_path):
        path = tmp_path / "stuck2.json"
        bundle = _bundle_with([
            ("handoff_started", {"sessions": 3, "version": 9}),
        ], path)
        findings = detect_signatures(merge_timeline([bundle]))
        assert [f["signature"] for f in findings] == ["stuck_handoff"]
        assert findings[0]["stuck"] == 3


# ---------------------------------------------------------------------------
# Search-plane witnesses carry evidence when the flag is on
# ---------------------------------------------------------------------------


# the hand-minimized witness of the historical promote-sync bug
# (tests/test_search.py): starve one replica of Puts, evict a leader, and
# mute Get quorum traffic to the fresh replica
BUG_PLAN = {"seed": 7, "rules": [
    {"type": "DropRule", "at": "egress", "windows": [[0, None]],
     "src": None, "dst": "node:7003", "msg_types": ["Put"],
     "probability": 1.0},
    {"type": "PartitionRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7000", "msg_types": None},
    {"type": "DropRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7002", "msg_types": ["Get"],
     "probability": 1.0},
]}
BUG_SPEC = {"harness": "engine", "n": 5, "partitions": 16, "replicas": 3,
            "horizon_ms": 4000, "ops": 40, "keys": 6, "plan": BUG_PLAN}


class TestSearchWitnessBundles:
    def test_violating_probe_pins_a_verifiable_bundle(self, monkeypatch):
        from rapid_tpu.search.runner import run_probe

        # resurrect the historical promote-sync bug so the probe violates
        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        spec = dict(BUG_SPEC, forensics=True)
        result = run_probe(spec)
        assert result.violated
        bundle = result.info.get("bundle")
        assert bundle is not None
        assert bundle["trigger"] == "invariant_violation"
        assert "linearizability" in bundle["detail"]["kinds"]
        assert verify_bundle(bundle)
        assert merge_timeline([bundle]), "witness bundle has no journal"

    def test_flag_off_probes_carry_no_bundle(self, monkeypatch):
        from rapid_tpu.search.runner import run_probe

        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        result = run_probe(dict(BUG_SPEC))
        assert result.violated
        assert "bundle" not in result.info

    def test_pin_to_file_writes_the_evidence_sidecar(self, tmp_path,
                                                     monkeypatch):
        from rapid_tpu.search.hunt import pin_to_file
        from rapid_tpu.search.runner import run_probe

        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        witness = run_probe(dict(BUG_SPEC, forensics=True))
        pin = {
            "kinds": sorted({v["invariant"] for v in witness.violations}),
            "spec": dict(BUG_SPEC, forensics=True),
            "bundle": witness.info["bundle"],
        }
        path = tmp_path / "witness.json"
        pin_to_file(pin, str(path), "witness", "pinned by the test")
        # the corpus artifact itself carries no bundle (scenario replays
        # stay byte-identical to flag-off pins)...
        artifact = json.loads(path.read_text())
        assert "bundle" not in artifact
        # ...the evidence rides the sidecar, readable by the CLI
        sidecar = load_bundle(str(path) + ".bundle.json")
        assert verify_bundle(sidecar)
        assert _cli("report", str(path) + ".bundle.json").returncode in (0, 3)
