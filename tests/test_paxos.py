"""Consensus unit tests, mirroring PaxosTests.java and
FastPaxosWithoutFallbackTests.java quorum arithmetic.
"""

import random

import pytest

from rapid_tpu.fast_paxos import FastPaxos
from rapid_tpu.messaging.base import IBroadcaster, IMessagingClient
from rapid_tpu.paxos import Paxos
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1bMessage,
    Rank,
)

class NoOpClient(IMessagingClient):
    def send_message(self, remote, msg):
        return Promise.completed(None)

    def send_message_best_effort(self, remote, msg):
        return Promise.completed(None)

    def shutdown(self):
        pass

class NoOpBroadcaster(IBroadcaster):
    def broadcast(self, msg):
        return []

    def set_membership(self, recipients):
        pass

def hosts(*specs):
    return tuple(Endpoint.from_string(s) for s in specs)

P1 = hosts("127.0.0.1:5891", "127.0.0.1:5821")
P2 = hosts("127.0.0.1:5821", "127.0.0.1:5872")
NOISE = hosts("127.0.0.1:1", "127.0.0.1:2")

ADDR = Endpoint.from_parts("127.0.0.1", 1234)

def make_paxos(n):
    return Paxos(ADDR, 1, n, NoOpClient(), NoOpBroadcaster(), lambda v: None)

def p1b(vrnd: Rank, vval) -> Phase1bMessage:
    return Phase1bMessage(sender=ADDR, configuration_id=1, rnd=vrnd, vrnd=vrnd, vval=vval)

# (N, p1_votes_at_highest_rank, p2_votes_at_lower_rank, proposals, valid choice indexes)
# Mirrors PaxosTests.coordinatorRuleTests (PaxosTests.java:252-286).
COORDINATOR_CASES = [
    (6, 4, 2, (P1, P2, NOISE), {0}),
    (6, 5, 1, (P1, P2, NOISE), {0}),
    (6, 6, 0, (P1, P2, NOISE), {0}),
    (9, 6, 3, (P1, P2, NOISE), {0, 1}),
    (9, 7, 2, (P1, P2, NOISE), {0}),
    (9, 8, 1, (P1, P2, NOISE), {0}),
    (6, 1, 5, (P1, P2, NOISE), {0, 1}),
    (6, 2, 4, (P1, P2, NOISE), {0, 1}),
    (6, 3, 3, (P1, P2, NOISE), {0}),
    (6, 3, 3, (P2, P1, NOISE), {0}),
    (6, 4, 1, (P1, P2, NOISE), {0}),
]

@pytest.mark.parametrize("n,p1n,p2n,proposals,valid", COORDINATOR_CASES)
def test_coordinator_rule(n, p1n, p2n, proposals, valid):
    """Highest-vrnd votes dominate; >N/4 identical wins; 100 shuffled quorums."""
    valid_values = {proposals[i] for i in valid}
    rng = random.Random(hash((n, p1n, p2n)) & 0xFFFF)
    for _ in range(100):
        paxos = make_paxos(n)
        messages = []
        for _ in range(p1n):
            messages.append(p1b(Rank(1, 1), proposals[0]))
        for _ in range(p2n):
            messages.append(p1b(Rank(0, 2**31 - 1), proposals[1]))
        for i in range(p1n + p2n, n):
            messages.append(p1b(Rank(0, i), NOISE))
        rng.shuffle(messages)
        quorum = messages[: (n // 2) + 1]
        chosen = paxos.select_proposal_using_coordinator_rule(quorum)
        assert chosen in valid_values, f"chose {chosen}"

# Classic-round cases (PaxosTests.java:180-188): all votes at the same rank,
# p2 gets `p2votes` and p1 the rest; quorum = all N.
CLASSIC_CASES = [
    (6, 5, {P2}),
    (6, 1, {P1}),
    (6, 4, {P1, P2}),
    (6, 2, {P1, P2}),
    (5, 4, {P2}),
    (5, 1, {P1}),
    (10, 4, {P1, P2}),
    (10, 1, {P1, P2}),
]

@pytest.mark.parametrize("n,p2votes,valid", CLASSIC_CASES)
def test_coordinator_rule_same_rank(n, p2votes, valid):
    """Same vrnd for all: single distinct value or >N/4 identical decides;
    otherwise any reported value may be picked."""
    rng = random.Random(n * 100 + p2votes)
    for _ in range(100):
        paxos = make_paxos(n)
        messages = [p1b(Rank(1, 1), P2) for _ in range(p2votes)]
        messages += [p1b(Rank(1, 1), P1) for _ in range(n - p2votes)]
        rng.shuffle(messages)
        chosen = paxos.select_proposal_using_coordinator_rule(messages)
        assert chosen in valid

def test_empty_phase1b_raises():
    with pytest.raises(ValueError):
        make_paxos(5).select_proposal_using_coordinator_rule([])

def test_all_empty_vvals_choose_nothing():
    """Quorum of acceptors that never voted => empty choice, coordinator waits
    (Paxos.java:308-325)."""
    paxos = make_paxos(5)
    msgs = [p1b(Rank(0, i), ()) for i in range(3)]
    assert paxos.select_proposal_using_coordinator_rule(msgs) == ()

# ---------------------------------------------------------------------------
# Fast-round quorum arithmetic (FastPaxosWithoutFallbackTests.java:85-90)
# ---------------------------------------------------------------------------

QUORUM_TABLE = {
    5: 4,
    6: 5,
    48: 37,
    49: 37,
    50: 38,
    51: 39,
    99: 75,
    100: 76,
    101: 76,
    102: 77,
}

def voter(i: int) -> Endpoint:
    return Endpoint.from_parts("127.0.0.1", 10_000 + i)

def fast_vote(i: int, proposal) -> FastRoundPhase2bMessage:
    return FastRoundPhase2bMessage(sender=voter(i), configuration_id=7, endpoints=proposal)

def make_fast_paxos(n, on_decide):
    return FastPaxos(
        ADDR, 7, n, NoOpClient(), NoOpBroadcaster(), VirtualScheduler(), on_decide,
        rng=random.Random(0),
    )

@pytest.mark.parametrize("n,quorum", sorted(QUORUM_TABLE.items()))
def test_fast_round_exact_quorum(n, quorum):
    """Decision exactly at N - floor((N-1)/4) identical votes, not before."""
    proposal = hosts("127.0.0.9:1")
    decided = []
    fp = make_fast_paxos(n, decided.append)
    for i in range(quorum - 1):
        fp.handle_messages(fast_vote(i, proposal))
        assert not decided
    fp.handle_messages(fast_vote(quorum - 1, proposal))
    assert decided == [list(proposal)]

@pytest.mark.parametrize("n,quorum", sorted(QUORUM_TABLE.items()))
def test_fast_round_with_f_conflicts(n, quorum):
    """F conflicting votes still allow a decision; F+1 conflicts block it
    (FastPaxosWithoutFallbackTests.java:131-150)."""
    f = n - quorum
    proposal = hosts("127.0.0.9:1")
    conflict = hosts("127.0.0.9:2")
    decided = []
    fp = make_fast_paxos(n, decided.append)
    for i in range(f):
        fp.handle_messages(fast_vote(i, conflict))
    for i in range(f, n):
        fp.handle_messages(fast_vote(i, proposal))
    assert decided == [list(proposal)]

    decided2 = []
    fp2 = make_fast_paxos(n, decided2.append)
    for i in range(f + 1):
        fp2.handle_messages(fast_vote(i, conflict))
    for i in range(f + 1, n):
        fp2.handle_messages(fast_vote(i, proposal))
    assert decided2 == []

def test_fast_round_duplicate_votes_ignored():
    proposal = hosts("127.0.0.9:1")
    decided = []
    fp = make_fast_paxos(6, decided.append)
    for _ in range(10):
        fp.handle_messages(fast_vote(0, proposal))
    assert not decided

def test_fast_round_config_mismatch_ignored():
    proposal = hosts("127.0.0.9:1")
    decided = []
    fp = make_fast_paxos(5, decided.append)
    for i in range(5):
        fp.handle_messages(
            FastRoundPhase2bMessage(sender=voter(i), configuration_id=99, endpoints=proposal)
        )
    assert not decided

def test_classic_fallback_end_to_end():
    """Wire N Paxos instances directly; one coordinator runs phase1a..2b and
    every node decides the same value."""
    n = 5
    addrs = [Endpoint.from_parts("127.0.0.1", 4000 + i) for i in range(n)]
    decisions = {}
    nodes = {}

    class Net(IMessagingClient, IBroadcaster):
        def send_message(self, remote, msg):
            nodes[remote].__getattribute__(HANDLERS[type(msg).__name__])(msg)
            return Promise.completed(None)

        send_message_best_effort = send_message

        def shutdown(self):
            pass

        def broadcast(self, msg):
            for node in list(nodes.values()):
                node.__getattribute__(HANDLERS[type(msg).__name__])(msg)
            return []

        def set_membership(self, recipients):
            pass

    HANDLERS = {
        "Phase1aMessage": "handle_phase1a",
        "Phase1bMessage": "handle_phase1b",
        "Phase2aMessage": "handle_phase2a",
        "Phase2bMessage": "handle_phase2b",
    }
    net = Net()
    for addr in addrs:
        nodes[addr] = Paxos(
            addr, 1, n, net, net,
            lambda v, a=addr: decisions.setdefault(a, tuple(v)),
        )
    # nobody voted in a fast round; coordinator proposes after a quorum of
    # empty phase1bs, so seed one node with a fast-round vote first
    value = hosts("10.0.0.1:1", "10.0.0.2:2")
    for node in nodes.values():
        node.register_fast_round_vote(value)
    nodes[addrs[0]].start_phase1a(2)
    assert len(decisions) == n
    assert set(decisions.values()) == {value}

def test_vote_batch_tallies_like_individual_votes():
    """FastRoundVoteBatch is pure transport fan-in: unpacking it (as
    MembershipService._handle_vote_batch does) reaches the decision exactly
    where the equivalent individual votes would, with per-sender dedup
    intact."""
    from rapid_tpu.types import FastRoundVoteBatch

    n, quorum = 50, QUORUM_TABLE[50]
    proposal = hosts("127.0.0.9:1")
    decided = []
    fp = make_fast_paxos(n, decided.append)
    batch = FastRoundVoteBatch(
        senders=tuple(voter(i) for i in range(quorum - 1)),
        configuration_id=7,
        endpoints=proposal,
    )
    for sender in batch.senders:
        fp.handle_messages(FastRoundPhase2bMessage(
            sender=sender, configuration_id=batch.configuration_id,
            endpoints=batch.endpoints,
        ))
    assert not decided  # quorum - 1 distinct senders: not yet
    # duplicate senders (a replayed batch) must not fake the quorum
    for sender in batch.senders:
        fp.handle_messages(FastRoundPhase2bMessage(
            sender=sender, configuration_id=batch.configuration_id,
            endpoints=batch.endpoints,
        ))
    assert not decided
    fp.handle_messages(fast_vote(quorum - 1, proposal))
    assert decided == [list(proposal)]

def test_service_vote_batch_reaches_decision():
    """End-to-end through MembershipService.handle_message: one
    FastRoundVoteBatch frame completes the fast round and applies the view
    change (the gateway's decision-delivery path)."""

    from harness import ClusterHarness
    from rapid_tpu.types import FastRoundVoteBatch

    h = ClusterHarness(seed=91)
    h.create_cluster(6, parallel=False)
    h.wait_and_verify_agreement(6)
    target = h.instances[h.addr(0)]
    service = target._membership_service  # noqa: SLF001
    cut = (h.addr(5),)
    config_id = target.get_current_configuration_id()
    # a quorum's worth of votes (6 -> 5) in ONE frame
    batch = FastRoundVoteBatch(
        senders=tuple(h.addr(i) for i in range(5)),
        configuration_id=config_id,
        endpoints=cut,
    )
    service.handle_message(batch)
    ok = h.scheduler.run_until(
        lambda: target.get_membership_size() == 5, timeout_ms=60_000
    )
    assert ok, "vote batch did not drive the view change"
    assert h.addr(5) not in target.get_memberlist()
    h.shutdown()
