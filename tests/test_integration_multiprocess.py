"""Tier-3 integration: real OS processes running the standalone agent over
real sockets, mirroring the reference's multi-JVM harness
(RapidNodeRunner.runNode, RapidNodeRunner.java:64-87: shell out the agent,
redirect output, assert liveness and convergence, reap processes).
"""

import os
import random
import re
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
AGENT = REPO / "examples" / "standalone_agent.py"


class AgentRunner:
    """RapidNodeRunner equivalent: launches and reaps agent processes."""

    def __init__(self, tmpdir: Path):
        self.tmpdir = tmpdir
        self.procs = []

    def run_node(self, listen: str, seed: str = None, fd_interval_ms: int = 100):
        log_path = self.tmpdir / f"agent-{listen.replace(':', '-')}.log"
        cmd = [sys.executable, str(AGENT), "--listen-address", listen,
               "--fd-interval-ms", str(fd_interval_ms)]
        if seed:
            cmd += ["--seed-address", seed]
        log = open(log_path, "w")
        env = dict(os.environ, PYTHONUNBUFFERED="1")
        proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env, cwd=str(REPO)
        )
        self.procs.append((proc, log_path))
        return proc, log_path

    def kill_all(self):
        for proc, _ in self.procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc, _ in self.procs:
            proc.wait(timeout=10)


@pytest.fixture
def runner(tmp_path):
    r = AgentRunner(tmp_path)
    yield r
    r.kill_all()


def wait_for_membership(log_path: Path, size: int, timeout_s: float = 30) -> bool:
    pattern = re.compile(rf"membership size={size}\b")
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if log_path.exists() and pattern.search(log_path.read_text()):
            return True
        time.sleep(0.2)
    return False


def test_single_agent_liveness(runner):
    """RapidNodeRunnerTest.java:27-38."""
    port = random.randint(21000, 29000)
    proc, log = runner.run_node(f"127.0.0.1:{port}")
    assert wait_for_membership(log, 1, 20), log.read_text()
    assert proc.poll() is None


def test_three_agents_converge(runner):
    """Seed + 2 joiners in separate OS processes converge to size 3; killing
    one converges the survivors to size 2."""
    base = random.randint(30000, 39000)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr)
    assert wait_for_membership(seed_log, 1, 20)
    _, log1 = runner.run_node(f"127.0.0.1:{base + 1}", seed=seed_addr)
    assert wait_for_membership(log1, 2, 30), log1.read_text()
    _, log2 = runner.run_node(f"127.0.0.1:{base + 2}", seed=seed_addr)
    for log in (seed_log, log1, log2):
        assert wait_for_membership(log, 3, 30), log.read_text()

    # crash the last joiner; survivors must converge to 2
    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    assert wait_for_membership(seed_log, 2, 60), seed_log.read_text()[-2000:]
    assert wait_for_membership(log1, 2, 60)
