"""Tier-3 integration: real OS processes running the standalone agent over
real sockets, mirroring the reference's multi-JVM harness
(RapidNodeRunner.runNode, RapidNodeRunner.java:64-87: shell out the agent,
redirect output, assert liveness and convergence, reap processes).
"""

import os

from harness import free_port_base
import re
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
AGENT = REPO / "examples" / "standalone_agent.py"


class AgentRunner:
    """RapidNodeRunner equivalent: launches and reaps agent processes."""

    def __init__(self, tmpdir: Path):
        self.tmpdir = tmpdir
        self.procs = []

    def run_node(self, listen: str, seed: str = None, fd_interval_ms: int = 100,
                 gateway: str = None, transport: str = None,
                 broadcaster: str = None, join_timeout: float = None):
        log_path = self.tmpdir / f"agent-{listen.replace(':', '-')}.log"
        cmd = [sys.executable, str(AGENT), "--listen-address", listen,
               "--fd-interval-ms", str(fd_interval_ms)]
        if join_timeout:
            cmd += ["--join-timeout", str(join_timeout)]
        if seed:
            cmd += ["--seed-address", seed]
        if gateway:
            cmd += ["--gateway-address", gateway]
        if transport:
            cmd += ["--transport", transport]
        if broadcaster:
            cmd += ["--broadcaster", broadcaster]
        log = open(log_path, "w")
        env = dict(os.environ, PYTHONUNBUFFERED="1")
        proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env, cwd=str(REPO)
        )
        self.procs.append((proc, log_path))
        return proc, log_path

    def kill_all(self):
        for proc, _ in self.procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc, _ in self.procs:
            proc.wait(timeout=10)


@pytest.fixture
def runner(tmp_path):
    r = AgentRunner(tmp_path)
    yield r
    r.kill_all()


def wait_for_membership(log_path: Path, size: int, timeout_s: float = 30) -> bool:
    pattern = re.compile(rf"membership size={size}\b")
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if log_path.exists() and pattern.search(log_path.read_text()):
            return True
        time.sleep(0.2)
    return False


def test_single_agent_liveness(runner):
    """RapidNodeRunnerTest.java:27-38."""
    port = free_port_base(1)
    proc, log = runner.run_node(f"127.0.0.1:{port}")
    assert wait_for_membership(log, 1, 20), log.read_text()
    assert proc.poll() is None


def test_three_agents_converge(runner):
    """Seed + 2 joiners in separate OS processes converge to size 3; killing
    one converges the survivors to size 2."""
    base = free_port_base(16)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr)
    assert wait_for_membership(seed_log, 1, 20)
    _, log1 = runner.run_node(f"127.0.0.1:{base + 1}", seed=seed_addr)
    assert wait_for_membership(log1, 2, 30), log1.read_text()
    _, log2 = runner.run_node(f"127.0.0.1:{base + 2}", seed=seed_addr)
    for log in (seed_log, log1, log2):
        assert wait_for_membership(log, 3, 30), log.read_text()

    # crash the last joiner; survivors must converge to 2
    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    assert wait_for_membership(seed_log, 2, 60), seed_log.read_text()[-2000:]
    assert wait_for_membership(log1, 2, 60)


GATEWAY = REPO / "examples" / "swarm_gateway.py"

_STATUS = re.compile(r"size=(\d+) config=(-?\d+)")


def last_status(log_path: Path):
    """Latest (size, config) from an agent/gateway log."""
    if not log_path.exists():
        return None
    matches = _STATUS.findall(log_path.read_text())
    return (int(matches[-1][0]), int(matches[-1][1])) if matches else None


def wait_for_size(log_paths, size, timeout_s=120):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        stats = [last_status(p) for p in log_paths]
        if all(s is not None and s[0] == size for s in stats):
            return True
        time.sleep(0.3)
    return False


class GatewayRunner:
    def __init__(self, tmpdir: Path):
        self.tmpdir = tmpdir
        self.proc = None
        self.log_path = tmpdir / "gateway.log"

    def start(self, listen: str, n_virtual: int, pump_interval_ms: int = 100):
        cmd = [sys.executable, str(GATEWAY), "--listen-address", listen,
               "--n-virtual", str(n_virtual), "--platform", "cpu",
               "--pump-interval-ms", str(pump_interval_ms)]
        log = open(self.log_path, "w")
        env = dict(os.environ, PYTHONUNBUFFERED="1")
        self.proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env, cwd=str(REPO)
        )
        # the gateway prints "SEED host:port" once the socket is up AND the
        # swarm engine is compile-warmed (which dominates at large capacity)
        seed_re = re.compile(r"^SEED (\S+)$", re.MULTILINE)
        deadline = time.time() + 360
        while time.time() < deadline:
            if self.log_path.exists():
                m = seed_re.search(self.log_path.read_text())
                if m:
                    return m.group(1)
            assert self.proc.poll() is None, self.log_path.read_text()
            time.sleep(0.3)
        raise AssertionError(f"gateway never started: {self.log_path.read_text()}")

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)


@pytest.fixture
def gateway_runner(tmp_path):
    r = GatewayRunner(tmp_path)
    yield r
    r.kill()


@pytest.mark.slow
def test_agents_join_tpu_swarm_over_sockets(runner, gateway_runner):
    """The north star, end to end: 3 real OS processes join a socket-hosted
    swarm of 1000 TPU-simulated virtual nodes, converge to bit-identical
    configuration ids on both sides of the wire, and the swarm detects and
    removes a SIGKILLed agent (VERDICT r2 item 1)."""
    base = free_port_base(16)
    gw_addr = f"127.0.0.1:{base}"
    seed = gateway_runner.start(gw_addr, n_virtual=1000)

    logs = []
    for i in range(1, 4):
        _, log = runner.run_node(
            f"127.0.0.1:{base + i}", seed=seed, fd_interval_ms=200,
            gateway=gw_addr,
        )
        logs.append(log)
        # joins go through one seed; stagger to keep config ids in lockstep
        assert wait_for_size([log], 1000 + i, timeout_s=180), log.read_text()[-3000:]

    all_logs = logs + [gateway_runner.log_path]
    assert wait_for_size(all_logs, 1003, timeout_s=120)
    configs = {last_status(p)[1] for p in all_logs}
    assert len(configs) == 1, f"config divergence: {configs}"

    # SIGKILL one agent: the swarm's simulated FDs detect the death and the
    # survivors observe the removal cut
    victim_proc, victim_log = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    survivor_logs = logs[:-1] + [gateway_runner.log_path]
    assert wait_for_size(survivor_logs, 1002, timeout_s=180), \
        gateway_runner.log_path.read_text()[-3000:]
    configs = {last_status(p)[1] for p in survivor_logs}
    assert len(configs) == 1, f"config divergence after cut: {configs}"


@pytest.mark.slow
def test_ten_agents_converge_kill_and_rejoin(runner):
    """Tier-3 at the reference's scale (RapidNodeRunnerTest.java:41-56 launches
    10 JVMs but only asserts liveness): 10 real OS processes join through one
    seed, every process converges to the full member list, three are SIGKILLed
    and the survivors converge on exactly that cut, then a fresh agent rejoins
    on a killed agent's address."""
    n = 10
    base = free_port_base(16)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr, fd_interval_ms=200)
    assert wait_for_membership(seed_log, 1, 30)
    logs = [seed_log]
    for i in range(1, n):
        _, log = runner.run_node(f"127.0.0.1:{base + i}", seed=seed_addr,
                                 fd_interval_ms=200)
        logs.append(log)
    assert wait_for_size(logs, n, timeout_s=180), \
        "\n".join(p.read_text()[-500:] for p in logs)
    configs = {last_status(p)[1] for p in logs}
    assert len(configs) == 1

    # SIGKILL three agents at once: survivors must converge on that exact cut
    victims = runner.procs[-3:]
    for proc, _ in victims:
        proc.send_signal(signal.SIGKILL)
    for proc, _ in victims:
        proc.wait(timeout=10)
    survivor_logs = logs[:-3]
    assert wait_for_size(survivor_logs, n - 3, timeout_s=180), \
        seed_log.read_text()[-3000:]
    configs = {last_status(p)[1] for p in survivor_logs}
    assert len(configs) == 1

    # rejoin on a killed agent's address (fresh UUID, same host:port)
    _, rejoin_log = runner.run_node(f"127.0.0.1:{base + n - 1}", seed=seed_addr,
                                    fd_interval_ms=200)
    assert wait_for_size(survivor_logs + [rejoin_log], n - 2, timeout_s=180), \
        rejoin_log.read_text()[-3000:]
    configs = {last_status(p)[1] for p in survivor_logs + [rejoin_log]}
    assert len(configs) == 1


@pytest.mark.slow
def test_three_agents_converge_over_grpc(runner):
    """Tier-3 over the wire-compatible gRPC transport (the reference's
    default): real OS processes speaking rapid.proto bytes converge and
    recover from a SIGKILL, like the TCP tier does."""
    pytest.importorskip("grpc")  # declared as the optional [grpc] extra
    base = free_port_base(16)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr, fd_interval_ms=200,
                                  transport="grpc")
    assert wait_for_membership(seed_log, 1, 30), seed_log.read_text()[-2000:]
    logs = [seed_log]
    for i in (1, 2):
        _, log = runner.run_node(f"127.0.0.1:{base + i}", seed=seed_addr,
                                 fd_interval_ms=200, transport="grpc")
        logs.append(log)
    assert wait_for_size(logs, 3, timeout_s=120), \
        "\n".join(p.read_text()[-500:] for p in logs)
    configs = {last_status(p)[1] for p in logs}
    assert len(configs) == 1

    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    assert wait_for_size(logs[:-1], 2, timeout_s=120), seed_log.read_text()[-2000:]
    configs = {last_status(p)[1] for p in logs[:-1]}
    assert len(configs) == 1


@pytest.mark.slow
def test_three_agents_converge_over_native_tcp(runner):
    """Tier-3 over the native epoll transport: real OS processes whose
    server half is the C++ reactor (native/rapid_io.cpp) converge and
    recover from a SIGKILL, like the pure-Python TCP tier does."""
    from rapid_tpu.runtime.native_io import available

    if not available():
        pytest.skip("librapid_io.so unavailable (no toolchain)")
    base = free_port_base(16)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr, fd_interval_ms=200,
                                  transport="native-tcp")
    assert wait_for_membership(seed_log, 1, 30), seed_log.read_text()[-2000:]
    logs = [seed_log]
    for i in (1, 2):
        _, log = runner.run_node(f"127.0.0.1:{base + i}", seed=seed_addr,
                                 fd_interval_ms=200, transport="native-tcp")
        logs.append(log)
    assert wait_for_size(logs, 3, timeout_s=120), \
        "\n".join(p.read_text()[-500:] for p in logs)
    configs = {last_status(p)[1] for p in logs}
    assert len(configs) == 1

    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    assert wait_for_size(logs[:-1], 2, timeout_s=120), seed_log.read_text()[-2000:]
    configs = {last_status(p)[1] for p in logs[:-1]}
    assert len(configs) == 1


@pytest.mark.slow
def test_five_agents_converge_over_gossip(runner):
    """Tier-3 with epidemic dissemination: real OS processes over TCP with
    --broadcaster gossip converge on joins and on a SIGKILL cut -- alert
    batches and consensus votes riding gossip relay over real sockets."""
    base = free_port_base(16)
    seed_addr = f"127.0.0.1:{base}"
    _, seed_log = runner.run_node(seed_addr, fd_interval_ms=200,
                                  broadcaster="gossip")
    assert wait_for_membership(seed_log, 1, 30), seed_log.read_text()[-2000:]
    logs = [seed_log]
    for i in range(1, 5):
        _, log = runner.run_node(f"127.0.0.1:{base + i}", seed=seed_addr,
                                 fd_interval_ms=200, broadcaster="gossip")
        logs.append(log)
    assert wait_for_size(logs, 5, timeout_s=120), \
        "\n".join(p.read_text()[-500:] for p in logs)
    configs = {last_status(p)[1] for p in logs}
    assert len(configs) == 1

    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    assert wait_for_size(logs[:-1], 4, timeout_s=120), \
        "\n".join(p.read_text()[-500:] for p in logs[:-1])
    configs = {last_status(p)[1] for p in logs[:-1]}
    assert len(configs) == 1


@pytest.mark.slow
def test_north_star_at_ten_thousand_virtual_nodes(runner, gateway_runner):
    """The north-star scenario at 10x the round-3 proof: 5 real OS processes
    join a socket-hosted swarm of 10,000 simulated virtual nodes, converge
    to bit-identical configuration ids on both sides of the wire, and the
    swarm detects and removes a SIGKILLed agent."""
    base = free_port_base(16)
    gw_addr = f"127.0.0.1:{base}"
    # the gateway CLI warms the engine before printing SEED, so agents
    # arrive at a compiled swarm
    seed = gateway_runner.start(gw_addr, n_virtual=10_000)

    logs = []
    for i in range(1, 6):
        _, log = runner.run_node(
            f"127.0.0.1:{base + i}", seed=seed, fd_interval_ms=200,
            gateway=gw_addr,
        )
        logs.append(log)
        assert wait_for_size([log], 10_000 + i, timeout_s=240), \
            log.read_text()[-3000:]

    all_logs = logs + [gateway_runner.log_path]
    assert wait_for_size(all_logs, 10_005, timeout_s=180)
    configs = {last_status(p)[1] for p in all_logs}
    assert len(configs) == 1, f"config divergence: {configs}"

    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    survivor_logs = logs[:-1] + [gateway_runner.log_path]
    assert wait_for_size(survivor_logs, 10_004, timeout_s=240), \
        gateway_runner.log_path.read_text()[-3000:]
    configs = {last_status(p)[1] for p in survivor_logs}
    assert len(configs) == 1, f"config divergence after cut: {configs}"


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("RAPID_TPU_HEAVY"),
    reason="several-minute run; set RAPID_TPU_HEAVY=1 to include",
)
def test_north_star_at_one_hundred_thousand_virtual_nodes(runner, gateway_runner):
    """The BASELINE.json north star at FULL scale: real OS processes join a
    socket-hosted swarm of 100,000 simulated virtual nodes, converge to
    bit-identical configuration ids, and observe a virtual cut. Join cost
    is dominated by the member's own 100k-view bootstrap (bulk ring build)
    and the one-frame quorum vote batch."""
    base = free_port_base(16)
    gw_addr = f"127.0.0.1:{base}"
    seed = gateway_runner.start(gw_addr, n_virtual=100_000)

    logs = []
    for i in (1, 2):
        _, log = runner.run_node(
            f"127.0.0.1:{base + i}", seed=seed, fd_interval_ms=500,
            gateway=gw_addr, join_timeout=300,
        )
        logs.append(log)
        assert wait_for_size([log], 100_000 + i, timeout_s=360), \
            log.read_text()[-3000:]

    all_logs = logs + [gateway_runner.log_path]
    assert wait_for_size(all_logs, 100_002, timeout_s=240)
    configs = {last_status(p)[1] for p in all_logs}
    assert len(configs) == 1, f"config divergence: {configs}"

    # SIGKILL one agent: the swarm senses the death and both survivors of
    # the 100k-member configuration converge on the removal cut
    victim_proc, _ = runner.procs[-1]
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    survivor_logs = logs[:-1] + [gateway_runner.log_path]
    assert wait_for_size(survivor_logs, 100_001, timeout_s=360), \
        gateway_runner.log_path.read_text()[-3000:]
    configs = {last_status(p)[1] for p in survivor_logs}
    assert len(configs) == 1, f"config divergence after cut: {configs}"
