"""xxHash64 bit-exactness and batch/scalar equivalence.

The ring order and configuration IDs must match the JVM reference
(zero-allocation-hashing LongHashFunction.xx, Utils.java:211-230), which is
canonical XXH64 over little-endian primitive bytes -- so matching the public
XXH64 vectors is matching the JVM.
"""

import random

import numpy as np
import pytest

from rapid_tpu.hashing import (
    configuration_id,
    endpoint_hash,
    endpoint_hash_batch,
    pack_hostnames,
    to_signed,
    xxh64,
    xxh64_batch,
    xxh64_int,
    xxh64_long,
)

# Published XXH64 test vectors (xxHash reference implementation).
KNOWN_VECTORS = [
    (b"", 0, 0xEF46DB3751D8E999),
    (b"a", 0, 0xD24EC4F1A98C6E5B),
    (b"abc", 0, 0x44BC2CF5AD770999),
    (b"Nobody inspects the spammish repetition", 0, 0xFBCEA83C8A378BF1),
]


@pytest.mark.parametrize("data,seed,expected", KNOWN_VECTORS)
def test_known_vectors(data, seed, expected):
    assert xxh64(data, seed) == expected


def test_scalar_batch_equivalence():
    rng = random.Random(7)
    samples = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 150))) for _ in range(500)]
    data, lengths = pack_hostnames(samples)
    for seed in (0, 1, 9, 2**31 - 1, 123456789):
        batch = xxh64_batch(data, lengths, seed)
        scalar = np.array([xxh64(s, seed) for s in samples], dtype=np.uint64)
        assert np.array_equal(batch, scalar)


def test_length_boundaries():
    """Every code path boundary: 0,1,3,4,7,8,11,12,15,16,31,32,33,63,64,65 bytes."""
    for n in (0, 1, 3, 4, 7, 8, 11, 12, 15, 16, 31, 32, 33, 63, 64, 65, 100):
        data = bytes(range(256))[:n] if n <= 256 else None
        payload = (data * 3)[:n] if data is not None else b""
        d, l = pack_hostnames([payload])
        assert int(xxh64_batch(d, l, 5)[0]) == xxh64(payload, 5)


def test_int_long_hashing():
    # hashInt == hash of the 4 LE bytes, hashLong == hash of the 8 LE bytes
    assert xxh64_int(1234, 3) == xxh64((1234).to_bytes(4, "little"), 3)
    assert xxh64_long(-1, 0) == xxh64(b"\xff" * 8, 0)
    assert xxh64_long(2**63 - 1, 0) == xxh64((2**63 - 1).to_bytes(8, "little"), 0)


def test_endpoint_hash_batch_matches_scalar():
    hosts = [f"host-{i}.example.com".encode() for i in range(200)]
    ports = np.arange(200) + 2000
    d, l = pack_hostnames(hosts)
    for seed in range(10):
        batch = endpoint_hash_batch(d, l, ports, seed)
        scalar = np.array(
            [endpoint_hash(h, int(p), seed) for h, p in zip(hosts, ports)],
            dtype=np.uint64,
        )
        assert np.array_equal(batch, scalar)


def test_to_signed():
    assert to_signed(0) == 0
    assert to_signed(2**63) == -(2**63)
    assert to_signed(2**64 - 1) == -1
    assert to_signed(2**63 - 1) == 2**63 - 1


def test_configuration_id_order_sensitivity():
    ids = [(1, 2), (3, 4)]
    eps = [(b"127.0.0.1", 1), (b"127.0.0.1", 2)]
    a = configuration_id(ids, eps)
    b = configuration_id(ids, list(reversed(eps)))
    assert a != b  # chained hash is order sensitive (MembershipView.java:535-547)
    assert a == configuration_id(ids, eps)  # and deterministic
