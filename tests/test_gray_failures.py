"""Gray failures, WAN topology, skewed clocks, and versioned-wire replay
(ISSUE 6).

Pins the tentpole acceptance criteria: the new rule families (SlowNodeRule /
LossyLinkRule / ClockSkewRule / WireVersionRule) are deterministic from the
plan seed, validated at plan construction, device-replayable (or explicitly
absorbed) per RULE_CATALOG, and parity-preserving across the protocol and
device planes; the LatencyTopology tier math compiles onto delivery groups;
and the hardened retry loop's decorrelated-jitter deadlines stay exact under
injected DelayRule/DropRule links.
"""

import pytest

from harness import ClusterHarness
from rapid_tpu import Endpoint, Settings
from rapid_tpu.faults import (
    FaultPlan,
    Nemesis,
    SkewedScheduler,
    UnsupportedDeviceFault,
    _device_rules,
    replay_on_simulator,
)
from rapid_tpu.messaging.retries import RetryPolicy, call_with_retries
from rapid_tpu.observability import Metrics, global_metrics
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.sim.topology import LatencyTopology
from rapid_tpu.types import ProbeMessage, ProbeResponse, Response

A = Endpoint.from_parts("10.0.0.1", 50)
B = Endpoint.from_parts("10.0.0.2", 50)


# ---------------------------------------------------------------------------
# LatencyTopology: tier math and device compilation inputs
# ---------------------------------------------------------------------------


def test_latency_topology_tiers_and_matrix():
    topo = LatencyTopology(racks=8, zones=4, regions=2,
                           rack_rtt_ms=1, zone_rtt_ms=4, region_rtt_ms=20,
                           inter_region_rtt_ms=150)
    n = 32
    m = topo.rtt_matrix(n)
    assert m.shape == (n, n)
    for i in range(n):
        assert m[i, i] == 0
        for j in range(n):
            assert m[i, j] == m[j, i] == topo.rtt_ms(i, j)
    # widest separating tier wins: same rack -> rack RTT, same zone but
    # different rack -> zone RTT, cross-region -> inter-region RTT
    assert topo.rtt_ms(0, 8) == 1        # both rack 0
    assert topo.rtt_ms(0, 4) == 4        # racks 0/4, both zone 0
    assert topo.rtt_ms(0, 2) == 20       # zones 0/2, both region 0
    assert topo.rtt_ms(0, 1) == 150      # regions 0/1
    assert topo.one_way_ms(0, 1) == 75
    groups = topo.group_assignment(n)
    assert sorted(set(int(g) for g in groups)) == [0, 1, 2, 3]
    assert all(int(groups[i]) == topo.zone_of(i) for i in range(n))
    # inter-zone delay rounds: same-region zones sit below one 250 ms round,
    # cross-region zones cost one-way 75 // 250 = 0 at 250 but 75 // 25 = 3
    assert topo.delay_rounds(0, 2, round_ms=250) == 0
    assert topo.delay_rounds(0, 1, round_ms=25) == 3


def test_latency_topology_validation():
    with pytest.raises(ValueError):
        LatencyTopology(racks=2, zones=4)  # fewer racks than zones
    with pytest.raises(ValueError):
        LatencyTopology(zone_rtt_ms=10, region_rtt_ms=5)  # tiers decrease


# ---------------------------------------------------------------------------
# FaultPlan construction-time validation (satellite)
# ---------------------------------------------------------------------------


def test_fault_plan_rejects_bad_windows():
    with pytest.raises(ValueError):
        FaultPlan(seed=0).drop(0.5, windows=((1000, 1000),))  # end == start
    with pytest.raises(ValueError):
        FaultPlan(seed=0).drop(0.5, windows=((2000, 500),))  # end < start
    with pytest.raises(ValueError):
        FaultPlan(seed=0).drop(0.5, windows=((-5, 100),))  # negative start
    # open-ended and well-ordered windows are fine
    FaultPlan(seed=0).drop(0.5, windows=((0, None), (10, 20)))


def test_fault_plan_rejects_contradictory_partition_overlap():
    with pytest.raises(ValueError):
        (
            FaultPlan(seed=0)
            .partition_one_way(dst=B, windows=((0, 5000),))
            .partition_one_way(dst=B, windows=((4000, None),))
        )
    with pytest.raises(ValueError):
        (
            FaultPlan(seed=0)
            .partition_one_way(dst=B)
            .flip_flop(period_ms=2000, dst=B)
        )
    # disjoint windows on one link, or different links, are fine
    (
        FaultPlan(seed=0)
        .partition_one_way(dst=B, windows=((0, 1000),))
        .partition_one_way(dst=B, windows=((2000, 3000),))
        .partition_one_way(dst=A)
    )


def test_lossy_link_probability_validation():
    with pytest.raises(ValueError):
        FaultPlan(seed=0).lossy_link(0.0)  # not lossy
    with pytest.raises(ValueError):
        FaultPlan(seed=0).lossy_link(1.0)  # that's a partition
    FaultPlan(seed=0).lossy_link(0.05)


# ---------------------------------------------------------------------------
# SlowNodeRule: alive but late
# ---------------------------------------------------------------------------


class _RecordingClient:
    def __init__(self, scheduler):
        self.sched = scheduler
        self.sent = []  # (virtual time, remote, msg)

    def send_message_best_effort(self, remote, msg):
        self.sent.append((self.sched.now_ms(), remote, msg))
        return Promise.completed(Response())

    def send_message(self, remote, msg):
        return self.send_message_best_effort(remote, msg)

    def shutdown(self):
        pass


def test_slow_node_past_timeout_times_out_sender_but_delivers():
    sched = VirtualScheduler()
    settings = Settings()
    nem = Nemesis(FaultPlan(seed=1).slow_node(B, response_delay_ms=5000),
                  sched, metrics=Metrics()).arm(0)
    inner = _RecordingClient(sched)
    client = nem.client(inner, address=A, settings=settings)
    p = client.send_message_best_effort(B, ProbeMessage(sender=A))
    # the sender's deadline expires first ...
    sched.run_for(settings.probe_message_timeout_ms - 1)
    assert not p.done() and inner.sent == []
    sched.run_for(2)
    assert p.done() and isinstance(p.exception(), TimeoutError)
    # ... but the message IS delivered, 5000 ms late: alive, not dead
    sched.run_for(5000)
    assert [t for t, _, _ in inner.sent] == [5000]
    assert nem.metrics.get("nemesis_slowed") == 1


def test_slow_node_within_timeout_only_inflates_latency():
    sched = VirtualScheduler()
    nem = Nemesis(FaultPlan(seed=1).slow_node(B, response_delay_ms=300),
                  sched, metrics=Metrics()).arm(0)
    inner = _RecordingClient(sched)
    client = nem.client(inner, address=A, settings=Settings())
    p = client.send_message_best_effort(B, ProbeMessage(sender=A))
    sched.run_for(299)
    assert not p.done()
    sched.run_for(2)
    assert p.done() and p.exception() is None
    assert [t for t, _, _ in inner.sent] == [300]


def test_fd_rtt_estimate_tracks_probe_latency():
    """fd.rtt_ms: the observable separating a gray node from a dead one --
    the EWMA inflates while probes still answer inside the timeout."""
    from rapid_tpu.monitoring.pingpong import PingPongFailureDetector

    sched = VirtualScheduler()

    class _LaggedResponder:
        def __init__(self, lag_ms):
            self.lag_ms = lag_ms

        def send_message_best_effort(self, remote, msg):
            p = Promise()
            sched.schedule(
                self.lag_ms, lambda: p.try_set_result(ProbeResponse())
            )
            return p

    metrics = Metrics()
    fd = PingPongFailureDetector(
        A, B, _LaggedResponder(120), notifier=lambda: None,
        metrics=metrics, clock=sched.now_ms,
    )
    assert fd.rtt_ms() is None
    fd()
    sched.run_for(121)
    assert fd.rtt_ms() == 120.0
    hist = metrics.histogram("fd.rtt_ms")
    assert hist is not None and hist["count"] == 1
    # EWMA: a second, slower answer drags the estimate up by alpha
    fd._client = _LaggedResponder(520)  # the node turns gray
    fd()
    sched.run_for(521)
    assert fd.rtt_ms() == pytest.approx(0.875 * 120 + 0.125 * 520)


# ---------------------------------------------------------------------------
# ClockSkewRule: one node's drifted timer stack
# ---------------------------------------------------------------------------


def test_skewed_scheduler_arithmetic_exact():
    inner = VirtualScheduler()
    sk = SkewedScheduler(inner, offset_ms=100, rate=2.0)
    assert sk.now_ms() == 100
    fired = []
    sk.schedule(200, lambda: fired.append(sk.now_ms()))  # 200 skewed = 100 true
    inner.run_for(99)
    assert fired == []
    inner.run_for(2)
    assert fired == [100 + 2 * inner.now_ms() - 2]  # fired at true 100
    assert sk.now_ms() == 100 + 2 * inner.now_ms()


def test_clock_skew_scheduler_for_and_retry_backoff():
    """A skewed node's retry backoff runs on ITS clock: delays it asks for
    in its own time cost delay/rate of true time."""
    sched = VirtualScheduler()
    nem = Nemesis(FaultPlan(seed=3).clock_skew(A, rate=2.0), sched,
                  metrics=Metrics()).arm(0)
    skewed = nem.scheduler_for(A)
    assert isinstance(skewed, SkewedScheduler)
    assert nem.scheduler_for(B) is sched  # only the named node drifts
    assert nem.scheduler_for(A) is skewed  # cached, one clock per node

    outcomes = [RuntimeError("x")] * 3 + ["ok"]
    times = []

    def attempt():
        times.append(sched.now_ms())  # record TRUE time
        out = outcomes.pop(0)
        p = Promise()
        if isinstance(out, Exception):
            p.try_set_exception(out)
        else:
            p.try_set_result(out)
        return p

    p = call_with_retries(
        attempt, 3, scheduler=skewed,
        policy=RetryPolicy(base_delay_ms=100, max_delay_ms=1000, jitter="none"),
    )
    assert sched.run_until(p.done, timeout_ms=60_000)
    assert p.peek() == "ok"
    # skewed delays 100, 200, 400 cost true 50, 100, 200
    assert times == [0, 50, 150, 350]


def test_clock_skew_cluster_converges_with_no_collateral(  # noqa: D103
):
    n = 4
    h = ClusterHarness(seed=5, use_static_fd=False)
    skewed = h.addr(1)
    h.with_faults(FaultPlan(seed=5).clock_skew(skewed, offset_ms=350, rate=1.25))
    h.nemesis.arm()
    try:
        h.create_cluster(n, parallel=False)
        h.wait_and_verify_agreement(n)
        h.fail_nodes([h.addr(n - 1)])
        h.wait_and_verify_agreement(n - 1)
        members = set(h.instances[h.addr(0)].get_memberlist())
        assert skewed in members  # skew alone never evicts
        assert members == {h.addr(i) for i in range(n - 1)}
        drift = h.nemesis.scheduler_for(skewed).now_ms() - h.scheduler.now_ms()
        assert drift > 0
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# WireVersionRule: versioned-wire rolling-upgrade replay
# ---------------------------------------------------------------------------


def test_wire_roundtrip_identity_across_versions():
    from rapid_tpu.messaging.codec import (
        WIRE_VERSION,
        encode,
        encode_versioned,
        wire_roundtrip,
    )
    from rapid_tpu.types import (
        AlertMessage,
        BatchedAlertMessage,
        EdgeStatus,
        JoinResponse,
        JoinStatusCode,
        MessageBatch,
        NodeId,
    )

    alert = AlertMessage(
        edge_src=A, edge_dst=B, edge_status=EdgeStatus.DOWN,
        configuration_id=-42, ring_numbers=(0, 3),
    )
    messages = [
        ProbeMessage(sender=A),
        ProbeResponse(),
        Response(),
        alert,
        BatchedAlertMessage(sender=A, messages=(alert,)),
        MessageBatch(sender=A, messages=(
            BatchedAlertMessage(sender=A, messages=(alert,)),
            ProbeMessage(sender=B),
        )),
        JoinResponse(sender=B, status_code=JoinStatusCode.SAFE_TO_JOIN,
                     configuration_id=7, endpoints=(A, B),
                     identifiers=(NodeId(1, 2),)),
    ]
    for msg in messages:
        # current version: byte parity with the plain encoder
        assert encode_versioned(9, msg, WIRE_VERSION) == encode(9, msg)
        for version in (0, 1, 2, 7):
            assert wire_roundtrip(msg, version) == msg
        # a NEWER dialect differs on the wire (reserved __-prefixed
        # extension keys) yet decodes to the same value
        assert encode_versioned(9, msg, WIRE_VERSION + 1) != encode(9, msg)


def test_wire_versioned_cluster_converges_through_churn():
    n = 4
    h = ClusterHarness(seed=21, use_static_fd=False)
    plan = FaultPlan(seed=21)
    for i in (0, 2):  # half the cluster already upgraded
        plan.wire_version(h.addr(i), version=2)
    h.with_faults(plan)
    h.nemesis.arm()  # versioned from the very first join byte
    try:
        h.create_cluster(n, parallel=False)
        h.wait_and_verify_agreement(n)
        h.fail_nodes([h.addr(n - 1)])
        h.wait_and_verify_agreement(n - 1)
        assert h.nemesis.metrics.get("nemesis_wire_versioned") > 0
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# retry deadlines under injected links (satellite)
# ---------------------------------------------------------------------------


def _run_retry_under_faulty_link():
    """send_message (the hardened loop: decorrelated jitter from the plan's
    per-sender rng, per-type deadline) across a link that drops everything
    for 3 s then only delays: the schedule must be identical on every
    replay and the post-heal attempt must land inside the deadline."""
    sched = VirtualScheduler()
    settings = Settings(retry_base_delay_ms=200, retry_max_delay_ms=2000)
    plan = (
        FaultPlan(seed=17)
        .drop(1.0, dst=B, windows=((0, 3000),))
        .delay(base_ms=600, dst=B, windows=((3000, None),))
    )
    nem = Nemesis(plan, sched, metrics=Metrics()).arm(0)
    inner = _RecordingClient(sched)
    client = nem.client(inner, address=A, settings=settings)
    p = client.send_message(B, ProbeMessage(sender=A))
    assert sched.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is None  # healed within the 6000 ms deadline
    assert len(inner.sent) == 1
    delivered_at = inner.sent[0][0]
    assert 3600 <= delivered_at < 6000  # post-heal, DelayRule-inflated
    backoff = nem.metrics.histogram("retry_backoff_ms")
    assert backoff is not None and backoff["count"] >= 1
    return sched.now_ms(), delivered_at, nem.metrics.get("retry_attempts")


def test_retry_deadline_under_faulty_link_is_deterministic():
    assert _run_retry_under_faulty_link() == _run_retry_under_faulty_link()


# ---------------------------------------------------------------------------
# device plane: compilation bounds, topology replay, and parity
# ---------------------------------------------------------------------------


def test_device_rule_bounds_for_gray_rules():
    # wire versioning and mild skew are invisible to the round model
    absorbed = (
        FaultPlan(seed=0)
        .wire_version(B, version=2)
        .clock_skew(B, rate=1.25)
        .slow_node(B, response_delay_ms=100)  # under one round: absorbed
    )
    assert _device_rules(absorbed, round_ms=1000) == []
    # a slower-than-round node compiles (partition-equivalent cut)
    slow = FaultPlan(seed=0).slow_node(B, response_delay_ms=1000)
    assert [idx for idx, _ in _device_rules(slow, round_ms=1000)] == [0]
    # a lossy link compiles onto ingress_loss
    lossy = FaultPlan(seed=0).lossy_link(0.2, dst=B)
    assert [idx for idx, _ in _device_rules(lossy, round_ms=1000)] == [0]
    # extreme skew would shear FD deadlines across nodes: refused, loudly
    with pytest.raises(UnsupportedDeviceFault):
        _device_rules(FaultPlan(seed=0).clock_skew(B, rate=3.0), round_ms=1000)


def _zone_loss_replay(seed):
    from rapid_tpu.faults import endpoint_slots
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig

    n = 64
    topo = LatencyTopology(racks=8, zones=4, regions=2,
                           rack_rtt_ms=0, zone_rtt_ms=2, region_rtt_ms=4,
                           inter_region_rtt_ms=1000)
    config = SimConfig(capacity=n, groups=4, max_delivery_delay=2,
                       rounds_per_interval=4)
    sim = Simulator(n, config=config, seed=seed)
    by_slot = {slot: ep for ep, slot in endpoint_slots(sim).items()}
    victims = [i for i in range(n) if topo.zone_of(i) == 3]
    plan = FaultPlan(seed=seed).with_topology(topo)
    for v in victims:
        plan.partition_one_way(dst=by_slot[v], windows=((2000, None),))
    records = replay_on_simulator(sim, plan, duration_ms=60_000)
    cut = sorted({int(c) for rec in records for c in rec.cut})
    assert cut == victims
    return [
        (tuple(int(c) for c in rec.cut), rec.configuration_id,
         rec.virtual_time_ms)
        for rec in records
    ]


def test_topology_zone_loss_device_replay_is_deterministic():
    first = _zone_loss_replay(31)
    assert first == _zone_loss_replay(31)
    assert first != _zone_loss_replay(32)  # the seed is load-bearing


def test_slow_node_two_plane_parity():
    """The gray-node acceptance pin: one seeded SlowNodeRule plan replayed
    on the protocol plane (in-process virtual-time cluster, real FDs) and
    the device plane (seated identities) produces the same single cut --
    exactly the slow node, zero collateral -- and the same config id."""
    from rapid_tpu.sim.driver import Simulator

    n = 4
    h = ClusterHarness(seed=7, use_static_fd=False)
    victim = h.addr(n - 1)

    def plan():
        return FaultPlan(seed=7).slow_node(victim, response_delay_ms=5000)

    h.with_faults(plan())
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant during bootstrap
    h.create_cluster(n, parallel=False)
    h.wait_and_verify_agreement(n)
    full_cfg = (
        h.instances[h.addr(0)]._membership_service._view.get_configuration()
    )
    h.nemesis.arm()  # the victim turns gray now
    vic = h.instances.pop(victim)  # keeps running: slow, not dead
    try:
        h.wait_and_verify_agreement(n - 1)
        survivor = h.instances[h.addr(0)]
        ip_members = tuple(survivor.get_memberlist())
        ip_config = survivor.get_current_configuration_id()
        assert vic.get_membership_size() >= 1  # the gray node is alive
    finally:
        vic.shutdown()
        h.shutdown()
    assert victim not in ip_members and len(ip_members) == n - 1

    identities = [
        (ep.hostname, ep.port, nid.high, nid.low)
        for ep, nid in zip(
            (h.addr(i) for i in range(n)), full_cfg.node_ids
        )
    ]
    sim = Simulator(n, seed=7, identities=identities)
    records = replay_on_simulator(sim, plan(), duration_ms=40_000)
    assert len(records) == 1
    assert [int(c) for c in records[0].cut] == [n - 1]
    assert records[0].configuration_id == ip_config


def test_gray_slow_node_records_rtt_before_eviction():
    """The fd.rtt_ms histogram accumulates while the cluster runs -- the
    observable a gray-failure dashboard would watch."""
    hist = global_metrics().histogram("fd.rtt_ms")
    before = hist["count"] if hist is not None else 0
    h = ClusterHarness(seed=9, use_static_fd=False)
    try:
        h.create_cluster(3, parallel=False)
        h.wait_and_verify_agreement(3)
    finally:
        h.shutdown()
    hist = global_metrics().histogram("fd.rtt_ms")
    assert hist is not None and hist["count"] > before
