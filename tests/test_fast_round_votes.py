"""Per-node fast-round votes: cast, deliver, dedup, tally (FastPaxos.java:125-156).

The engine simulates the vote broadcast as a real delivery hop rather than
assuming every live member's vote arrives the moment its group announces:
votes are cast once per sender (dedup latch), spend one round in flight, can
be dropped by the delivery fault plane (and are then lost for good, like the
reference's best-effort unicast), and only *received* votes count toward the
N - floor((N-1)/4) quorum. The last test is the cross-plane differential:
the object-model stack (untouched Cluster/MembershipService/FastPaxos over
the in-process transport) and the TPU sim agree on decision-round timing for
the same crash fault once the object plane's vote hop is given the same
one-round latency the sim bills.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig, const_inputs, run_rounds_const
from rapid_tpu.types import FastRoundPhase2bMessage

from harness import ClusterHarness


def _round_by_round(config, state, inputs, rounds):
    """Yield state after each single engine round."""
    for _ in range(rounds):
        state = run_rounds_const(config, state, inputs, 1, False)
        yield state


def test_decision_exactly_one_round_after_announcement():
    """Votes cast the announcement round arrive -- and decide -- the next."""
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=3)
    sim = Simulator(16, config=config, seed=1)
    sim.crash(np.array([15]))
    inputs = const_inputs(config, sim.alive)
    announce_round = decide_round = None
    state = sim.state
    for state in _round_by_round(config, state, inputs, 8):
        r = int(state.round)
        if announce_round is None and bool(np.asarray(state.announced).any()):
            announce_round = r
            # votes are cast this round and still in flight: none received
            assert int(np.asarray(state.vote_new).sum()) == 15
            assert int(np.asarray(state.votes_recv).sum()) == 0
            assert not bool(state.decided)
        if decide_round is None and bool(state.decided):
            decide_round = r
    assert announce_round is not None and decide_round is not None
    assert decide_round == announce_round + 1
    assert int(state.decided_round) == int(state.announced_round) + 1


def test_one_vote_per_sender_dedup():
    """The per-sender dedup latch (FastPaxos.java:134-141): every live member
    votes exactly once per configuration, crashed members never vote."""
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=3)
    sim = Simulator(16, config=config, seed=2)
    sim.crash(np.array([7]))
    inputs = const_inputs(config, sim.alive)
    state = sim.state
    total_casts = 0
    for state in _round_by_round(config, state, inputs, 10):
        total_casts += int(np.asarray(state.vote_new).sum())
    voted = np.asarray(state.voted)
    assert total_casts == 15  # 16 members, the crashed one never votes
    assert voted.sum() == 15 and not voted[7]
    # every received vote is for the single announced proposal row
    assert np.asarray(state.vote_prop)[voted].max() == 0


def test_votes_dropped_on_their_delivery_round_are_lost():
    """A vote is one best-effort broadcast (UnicastToAllBroadcaster.java:46-52):
    if the fault plane drops it on its delivery round, it never reaches any
    tally -- even after the link heals -- and the fast quorum stays
    unreachable."""
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=3)
    sim = Simulator(16, config=config, seed=3)
    sim.crash(np.array([15]))
    clear = const_inputs(config, sim.alive)
    state = sim.state
    # run until the proposal is announced (votes now in flight)
    for state in _round_by_round(config, state, clear, 8):
        if bool(np.asarray(state.announced).any()):
            break
    assert not bool(state.decided)
    # quorum is N - floor((N-1)/4) = 13; drop votes from 13 senders for the
    # one round they are in flight
    deliver = np.ones((1, 16), dtype=bool)
    deliver[0, :13] = False
    state = run_rounds_const(
        config, state, const_inputs(config, sim.alive, deliver=deliver), 1, False
    )
    assert int(np.asarray(state.votes_recv).sum()) == 2  # senders 13, 14
    # link heals, but the dropped votes were lost for good: no decision ever
    state = run_rounds_const(config, state, clear, 20, False)
    assert not bool(state.decided)
    assert int(np.asarray(state.votes_recv).sum()) == 2


def test_non_auto_vote_slots_count_only_registered_votes():
    """Slots with auto_vote=False (bridged real members, sim/bridge.py) do not
    have votes cast for them; the quorum is reachable only once their actual
    votes are registered into the per-node state -- the seam that lets a real
    node swing or block a simulated decision."""
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=3)
    sim = Simulator(16, config=config, seed=4)
    auto = np.ones(16, dtype=bool)
    real_slots = np.array([0, 1, 2, 3, 4])  # 5 > F = 3 withheld votes
    auto[real_slots] = False
    state = dataclasses.replace(sim.state, auto_vote=jnp.asarray(auto))
    sim.crash(np.array([15]))
    inputs = const_inputs(config, sim.alive)
    # auto voters: 16 - 5 - 1 crashed = 10 < quorum 13 -> the fast round stalls
    state = run_rounds_const(config, state, inputs, 12, False)
    assert bool(np.asarray(state.announced).any()) and not bool(state.decided)
    assert int(np.asarray(state.voted).sum()) == 10
    # the host registers the real members' votes for the announced proposal
    # (row 0) -- what TpuSimMessaging does when FastRoundPhase2bMessages arrive
    state = dataclasses.replace(
        state,
        voted=state.voted.at[real_slots].set(True),
        vote_prop=state.vote_prop.at[real_slots].set(0),
        vote_new=state.vote_new.at[real_slots].set(True),
    )
    state = run_rounds_const(config, state, inputs, 2, False)
    assert bool(state.decided)
    assert int(np.asarray(state.decided_group)) == 0


def test_cross_plane_decision_round_timing():
    """Differential timing parity: for the same crash fault the object-model
    plane and the sim plane agree on decision-round timing.

    Mapping: the sim quantizes delivery to rounds -- the vote broadcast costs
    exactly one round (one FD interval at rounds_per_interval=1). Giving the
    object plane's vote messages the same one-interval latency, both planes
    decide 11 FD intervals (10 probe rounds to the threshold + 1 vote hop)
    plus one batching window after the crash; and removing the object plane's
    vote latency shifts its decision earlier by exactly one interval, which
    is precisely the round the sim bills for vote propagation."""
    fd_interval = 1000

    # --- sim plane: N=10, one crash ------------------------------------
    sim = Simulator(10, seed=5)
    sim.crash(np.array([9]))
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None and list(rec.cut) == [9]
    assert rec.virtual_time_ms == 11 * fd_interval + 100

    # --- object plane, parameterized by the vote-hop latency ------------
    def run_object(vote_delay_ms: int) -> int:
        view_change_times = []
        h = ClusterHarness(seed=1, use_static_fd=False)  # real PingPong FDs
        from rapid_tpu.events import ClusterEvents

        h.start_seed(0, subscriptions=[(
            ClusterEvents.VIEW_CHANGE,
            lambda cid, changes: view_change_times.append(h.scheduler.now_ms()),
        )])
        for i in range(1, 10):
            h.join(i)
        h.wait_and_verify_agreement(10)
        h.network.add_delay(
            lambda s, d, m: vote_delay_ms
            if isinstance(m, FastRoundPhase2bMessage)
            else 0
        )
        # every view change cancels and recreates all FD jobs with initial
        # delay 0, so after the last join the whole cluster's FDs tick in
        # lockstep at t_f + k*interval; crash 1ms before a tick so the first
        # failing probe lands on the very next tick (the sim's round 1)
        t_f = view_change_times[-1]
        k = (h.scheduler.now_ms() - t_f) // fd_interval + 1
        h.scheduler.run_until_time(t_f + k * fd_interval - 1)
        t_crash = h.scheduler.now_ms()
        h.fail_nodes([h.addr(9)])
        ok = h.scheduler.run_until(
            lambda: h.converged(9), timeout_ms=60_000, poll_ms=1
        )
        assert ok, "object plane never converged after the crash"
        elapsed = h.scheduler.now_ms() - t_crash
        h.shutdown()
        return elapsed

    with_hop = run_object(vote_delay_ms=fd_interval)
    without_hop = run_object(vote_delay_ms=0)
    # the modeled vote round corresponds exactly to vote propagation time
    assert with_hop - without_hop == fd_interval
    # same decision round as the sim: 11 intervals + batching, measured from
    # the first failing probe (1ms after the crash, by the tick alignment
    # above). The sim bills exactly one batching window; the object plane's
    # quiescence batcher fires one-to-two windows after the alert enqueue
    # (MembershipService.java:602-626), so the planes agree to within one
    # extra window -- far inside the round quantum.
    assert 0 <= (with_hop - 1) - rec.virtual_time_ms <= 100
