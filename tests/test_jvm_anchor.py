"""The turnkey JVM-anchor tool (tools/jvm_anchor.py): skip semantics and
log-parsing, testable without a java runtime."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import jvm_anchor  # noqa: E402


def test_skips_cleanly_and_exits_zero_without_java(monkeypatch):
    env = dict(os.environ, PATH="/nonexistent")  # guarantee no java
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jvm_anchor.py"),
         "--no-write"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "Direct JVM anchor" in out.stdout
    assert "pending" in out.stdout
    assert "SKIP" in out.stdout


def test_view_change_config_id_parse(tmp_path):
    """StandaloneAgent.java:82-84 logs 'View change detected: {changes}
    {configurationId}'; the LAST one is the agent's final configuration."""
    log = tmp_path / "agent-1.log"
    log.write_text(
        "2026-01-01 INFO Node 127.0.0.1:1235 -- cluster size 9\n"
        "2026-01-01 INFO View change detected: [UP 127.0.0.1:1236] 111222333\n"
        "2026-01-01 INFO View change detected: [DOWN 127.0.0.1:1236] -444555666\n"
    )
    assert jvm_anchor.last_config_id(str(log)) == -444555666
    empty = tmp_path / "agent-2.log"
    empty.write_text("no view changes here\n")
    assert jvm_anchor.last_config_id(str(empty)) is None


def test_record_row_is_idempotent(tmp_path, monkeypatch):
    baseline = tmp_path / "BASELINE.md"
    baseline.write_text(
        "# header\n\n## Build targets (from BASELINE.json)\n\n| x |\n"
    )
    monkeypatch.setattr(jvm_anchor, "BASELINE_MD", str(baseline))
    jvm_anchor.record("pending — first", write=True)
    text = baseline.read_text()
    assert text.count("**Direct JVM anchor**") == 1
    assert "pending — first" in text
    jvm_anchor.record("verified — second", write=True)
    text = baseline.read_text()
    assert text.count("**Direct JVM anchor**") == 1
    assert "verified — second" in text and "first" not in text
