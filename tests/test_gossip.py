"""Gossip (epidemic) broadcaster: the IBroadcaster alternative the
reference names but never ships (IBroadcaster.java:24-26). Unit semantics
(dedup, TTL, fanout) plus full protocol convergence -- alert batches and
consensus votes riding epidemic relay instead of unicast-to-all."""

import random

from harness import ClusterHarness
from rapid_tpu import Endpoint
from rapid_tpu.messaging import codec
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.types import GossipEnvelope, NodeId, ProbeMessage


class RecordingClient:
    def __init__(self):
        self.sent = []
        self.address = Endpoint.from_parts("127.0.0.1", 9)

    def send_message_best_effort(self, remote, msg):
        self.sent.append((remote, msg))

    send_message = send_message_best_effort


def members(n):
    return [Endpoint.from_parts("127.0.0.1", 1000 + i) for i in range(n)]


def test_broadcast_sends_to_self_plus_fanout():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1000)
    g = GossipBroadcaster(client, me, fanout=3, rng=random.Random(1))
    g.set_membership(members(20))
    g.broadcast(ProbeMessage(sender=me))
    targets = [t for t, _ in client.sent]
    assert targets[0] == me  # self-delivery through the transport
    assert len(targets) == 4 and len(set(targets)) == 4
    assert all(isinstance(m, GossipEnvelope) for _, m in client.sent)
    # TTL ~ log2(20) + 2
    assert client.sent[0][1].ttl == 7


def test_receive_dedups_and_relays_with_decremented_ttl():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1001)
    g = GossipBroadcaster(client, me, fanout=2, rng=random.Random(2))
    g.set_membership(members(10))
    env = GossipEnvelope(
        sender=members(10)[5], gossip_id=NodeId(7, 8), ttl=3,
        payload=ProbeMessage(sender=members(10)[5]),
    )
    payload = g.receive(env)
    assert isinstance(payload, ProbeMessage)
    assert len(client.sent) == 2
    assert all(m.ttl == 2 and m.gossip_id == NodeId(7, 8) for _, m in client.sent)
    assert all(t != me for t, _ in client.sent)  # no self-relay
    # second sighting: payload NOT re-delivered, but still relayed (blind
    # counter, relay_budget=2)
    assert g.receive(env) is None
    assert len(client.sent) == 4
    # third sighting: budget exhausted, no relay
    assert g.receive(env) is None
    assert len(client.sent) == 4


def test_dedup_eviction_is_age_guarded(monkeypatch):
    """Under burst load the dedup table must NOT FIFO-evict entries for
    envelopes that could still be circulating: a live envelope evicted and
    re-seen would be delivered locally a second time with a fresh relay
    budget. Young entries survive beyond the size cap; old ones are evicted
    once the cap is exceeded."""
    import rapid_tpu.messaging.gossip as gossip_mod

    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1003)
    g = GossipBroadcaster(client, me, fanout=0, rng=random.Random(4))
    g.set_membership(members(4))
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 8)

    clock = [100.0]
    monkeypatch.setattr(gossip_mod.time, "monotonic", lambda: clock[0])

    def env_for(i: int) -> GossipEnvelope:
        return GossipEnvelope(
            sender=members(4)[0], gossip_id=NodeId(0, i), ttl=0,
            payload=ProbeMessage(sender=members(4)[0]),
        )

    live = env_for(0)
    assert g.receive(live) is not None
    # 20 more envelopes at the same instant: cap (8) exceeded but every
    # entry is young, so nothing is evicted...
    for i in range(1, 21):
        g.receive(env_for(i))
    assert len(g._seen) == 21
    # ...and the live envelope is still deduped
    assert g.receive(live) is None

    # after the propagation window passes, new traffic evicts the old tail
    clock[0] += gossip_mod._SEEN_MIN_AGE_S + 1.0
    for i in range(21, 40):
        g.receive(env_for(i))
    assert len(g._seen) <= 21
    assert (0, 0) not in g._seen  # the old entry aged out


def test_pushpull_payload_store_is_hard_capped(monkeypatch):
    """The age guard lets the dedup TABLE grow past the cap while entries are
    young, but stored full payloads (pushpull's pull-answer store) must not
    grow with it: beyond the cap the oldest stored envelopes are dropped
    (entry payload -> None) while their dedup keys survive, so dedup safety
    is intact and pulls for dropped ids are simply unanswered (best-effort,
    repaired via a fresher advertiser)."""
    import rapid_tpu.messaging.gossip as gossip_mod

    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1004)
    g = GossipBroadcaster(
        client, me, fanout=0, rng=random.Random(5), mode="pushpull"
    )
    g.set_membership(members(4))
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 8)
    clock = [100.0]
    monkeypatch.setattr(gossip_mod.time, "monotonic", lambda: clock[0])

    def env_for(i: int) -> GossipEnvelope:
        return GossipEnvelope(
            sender=members(4)[0], gossip_id=NodeId(0, i), ttl=0,
            payload=ProbeMessage(sender=members(4)[0]),
        )

    # a burst far past the cap, all inside the age window: the table grows
    # (age guard) but payload-bearing entries stay hard-capped
    for i in range(40):
        g.receive(env_for(i))
    cap = max(gossip_mod._SEEN_CAP, 4 * 4)
    assert len(g._seen) == 40
    stored = [k for k, e in g._seen.items() if e[2] is not None]
    assert len(stored) <= cap
    # oldest dropped first; the newest envelopes still answer pulls
    assert (0, 39) in stored and (0, 0) not in stored
    # dedup keys survive the payload drop
    assert g.receive(env_for(0)) is None


def test_receive_ttl_zero_delivers_without_relay():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1002)
    g = GossipBroadcaster(client, me, fanout=2, rng=random.Random(3))
    g.set_membership(members(10))
    env = GossipEnvelope(
        sender=members(10)[3], gossip_id=NodeId(1, 2), ttl=0,
        payload=ProbeMessage(sender=members(10)[3]),
    )
    assert isinstance(g.receive(env), ProbeMessage)
    assert client.sent == []


def test_envelope_codec_roundtrip():
    """GossipEnvelope crosses the framed wire with its nested payload."""
    env = GossipEnvelope(
        sender=Endpoint.from_parts("10.0.0.1", 5001),
        gossip_id=NodeId(-3, 99),
        ttl=5,
        payload=ProbeMessage(sender=Endpoint.from_parts("10.0.0.2", 5002)),
    )
    request_no, decoded = codec.decode(codec.encode(42, env))
    assert request_no == 42
    assert decoded == env


def _gossip_factory(client, rng):
    return GossipBroadcaster(client, client.address, fanout=4, rng=rng)


def test_cluster_converges_on_gossip_broadcaster():
    """Full protocol over epidemic dissemination: 16 nodes join, two crash,
    the cut decides, and every instance converges to the same view."""
    h = ClusterHarness(seed=77)
    h.broadcaster_factory = _gossip_factory
    h.create_cluster(16, parallel=False)
    h.wait_and_verify_agreement(16)
    victims = [h.addr(6), h.addr(11)]
    h.fail_nodes(victims)
    h.wait_and_verify_agreement(14)
    configs = {
        c.get_current_configuration_id() for c in h.instances.values()
    }
    assert len(configs) == 1


def test_gossip_join_wave_converges():
    """Parallel joins through one seed with gossip dissemination."""
    h = ClusterHarness(seed=78)
    h.broadcaster_factory = _gossip_factory
    h.create_cluster(12, parallel=True)
    h.wait_and_verify_agreement(12)


def test_pushpull_advertises_instead_of_repushing():
    """Anti-entropy mode (VERDICT r3 item 8): the payload is pushed eagerly
    only on the first sighting; the second sighting (within relay_budget)
    sends tiny IHAVE advertisements, bounding duplicate payload traffic."""
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1010)
    g = GossipBroadcaster(client, me, fanout=2, rng=random.Random(6),
                          mode="pushpull")
    g.set_membership(members(10))
    env = GossipEnvelope(
        sender=members(10)[5], gossip_id=NodeId(9, 9), ttl=3,
        payload=ProbeMessage(sender=members(10)[5]),
    )
    assert isinstance(g.receive(env), ProbeMessage)
    assert len(client.sent) == 2  # first sighting: eager full-payload relay
    assert all(
        m.kind == GossipEnvelope.KIND_PAYLOAD and m.payload is not None
        for _, m in client.sent
    )
    assert g.receive(env) is None  # second sighting: IHAVE only
    assert len(client.sent) == 4
    for _, m in client.sent[2:]:
        assert m.kind == GossipEnvelope.KIND_IHAVE and m.payload is None
    assert g.receive(env) is None  # budget exhausted: silence
    assert len(client.sent) == 4


def test_pushpull_ihave_pull_repair_roundtrip():
    """A node that only hears an advertisement PULLs the payload from the
    advertiser, which answers from its store -- and the pulled payload then
    delivers locally like a first sighting."""
    advertiser_client, holder_client = RecordingClient(), RecordingClient()
    adv_addr = Endpoint.from_parts("127.0.0.1", 1011)
    hol_addr = Endpoint.from_parts("127.0.0.1", 1012)
    advertiser = GossipBroadcaster(
        advertiser_client, adv_addr, fanout=1, rng=random.Random(7),
        mode="pushpull",
    )
    holder = GossipBroadcaster(
        holder_client, hol_addr, fanout=1, rng=random.Random(8),
        mode="pushpull",
    )
    for g in (advertiser, holder):
        g.set_membership(members(6))
    origin = members(6)[0]
    env = GossipEnvelope(
        sender=origin, gossip_id=NodeId(4, 2), ttl=2,
        payload=ProbeMessage(sender=origin),
    )
    advertiser.receive(env)  # advertiser now stores the payload
    ihave = GossipEnvelope(
        sender=adv_addr, gossip_id=NodeId(4, 2), ttl=1,
        kind=GossipEnvelope.KIND_IHAVE,
    )
    assert holder.receive(ihave) is None  # no local delivery from an IHAVE
    pulls = [
        (t, m) for t, m in holder_client.sent
        if m.kind == GossipEnvelope.KIND_PULL
    ]
    assert len(pulls) == 1 and pulls[0][0] == adv_addr
    # a duplicate advertisement while the pull is in flight does not re-pull
    assert holder.receive(ihave) is None
    assert len([
        (t, m) for t, m in holder_client.sent
        if m.kind == GossipEnvelope.KIND_PULL
    ]) == 1
    # the advertiser answers the pull with the stored payload...
    advertiser_client.sent.clear()
    advertiser.receive(pulls[0][1])
    answers = [
        m for _, m in advertiser_client.sent
        if m.kind == GossipEnvelope.KIND_PAYLOAD
    ]
    assert len(answers) == 1 and isinstance(answers[0].payload, ProbeMessage)
    # ...and the puller delivers it as a first sighting
    assert isinstance(holder.receive(answers[0]), ProbeMessage)


def test_cluster_converges_on_pushpull_gossip():
    """Full protocol over the anti-entropy mode: 16 nodes, two crash, exact
    cut, identical configuration ids everywhere."""
    h = ClusterHarness(seed=79)
    h.broadcaster_factory = lambda client, rng: GossipBroadcaster(
        client, client.address, fanout=4, rng=rng, mode="pushpull"
    )
    h.create_cluster(16, parallel=False)
    h.wait_and_verify_agreement(16)
    h.fail_nodes([h.addr(6), h.addr(11)])
    h.wait_and_verify_agreement(14)
    configs = {
        c.get_current_configuration_id() for c in h.instances.values()
    }
    assert len(configs) == 1


def test_gossip_refused_on_jvm_wire_transport():
    """Build-time rejection of the gossip + gRPC pairing: the JVM wire has
    no GossipEnvelope, so best-effort dissemination would fail silently."""
    import pytest

    pytest.importorskip("grpc")
    from rapid_tpu.cluster import ClusterBuilder, JoinException
    from rapid_tpu.messaging.grpc_transport import GrpcClient, GrpcServer

    addr = Endpoint.from_parts("127.0.0.1", 45991)
    client, server = GrpcClient(addr), GrpcServer(addr)
    builder = (
        ClusterBuilder(addr)
        .set_messaging_client_and_server(client, server)
        .set_broadcaster_factory(_gossip_factory)
    )
    with pytest.raises(JoinException, match="native-codec transport"):
        builder.start()


def test_vote_batch_codec_roundtrip_and_tally():
    """FastRoundVoteBatch: wire round-trip, and the service tallies it
    exactly as the equivalent individual votes (reaching a decision)."""
    from rapid_tpu.types import FastRoundVoteBatch

    eps = members(8)
    batch = FastRoundVoteBatch(
        senders=tuple(eps[:6]), configuration_id=-9, endpoints=(eps[7],)
    )
    request_no, decoded = codec.decode(codec.encode(5, batch))
    assert request_no == 5 and decoded == batch
