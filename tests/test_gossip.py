"""Gossip (epidemic) broadcaster: the IBroadcaster alternative the
reference names but never ships (IBroadcaster.java:24-26). Unit semantics
(dedup, TTL, fanout) plus full protocol convergence -- alert batches and
consensus votes riding epidemic relay instead of unicast-to-all."""

import random

from harness import ClusterHarness
from rapid_tpu import Endpoint
from rapid_tpu.messaging import codec
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.types import GossipEnvelope, NodeId, ProbeMessage


class RecordingClient:
    def __init__(self):
        self.sent = []
        self.address = Endpoint.from_parts("127.0.0.1", 9)

    def send_message_best_effort(self, remote, msg):
        self.sent.append((remote, msg))

    send_message = send_message_best_effort


def members(n):
    return [Endpoint.from_parts("127.0.0.1", 1000 + i) for i in range(n)]


def test_broadcast_sends_to_self_plus_fanout():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1000)
    g = GossipBroadcaster(client, me, fanout=3, rng=random.Random(1))
    g.set_membership(members(20))
    g.broadcast(ProbeMessage(sender=me))
    targets = [t for t, _ in client.sent]
    assert targets[0] == me  # self-delivery through the transport
    assert len(targets) == 4 and len(set(targets)) == 4
    assert all(isinstance(m, GossipEnvelope) for _, m in client.sent)
    # TTL ~ log2(20) + 2
    assert client.sent[0][1].ttl == 7


def test_receive_dedups_and_relays_with_decremented_ttl():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1001)
    g = GossipBroadcaster(client, me, fanout=2, rng=random.Random(2))
    g.set_membership(members(10))
    env = GossipEnvelope(
        sender=members(10)[5], gossip_id=NodeId(7, 8), ttl=3,
        payload=ProbeMessage(sender=members(10)[5]),
    )
    payload = g.receive(env)
    assert isinstance(payload, ProbeMessage)
    assert len(client.sent) == 2
    assert all(m.ttl == 2 and m.gossip_id == NodeId(7, 8) for _, m in client.sent)
    assert all(t != me for t, _ in client.sent)  # no self-relay
    # second sighting: payload NOT re-delivered, but still relayed (blind
    # counter, relay_budget=2)
    assert g.receive(env) is None
    assert len(client.sent) == 4
    # third sighting: budget exhausted, no relay
    assert g.receive(env) is None
    assert len(client.sent) == 4


def test_receive_ttl_zero_delivers_without_relay():
    client = RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 1002)
    g = GossipBroadcaster(client, me, fanout=2, rng=random.Random(3))
    g.set_membership(members(10))
    env = GossipEnvelope(
        sender=members(10)[3], gossip_id=NodeId(1, 2), ttl=0,
        payload=ProbeMessage(sender=members(10)[3]),
    )
    assert isinstance(g.receive(env), ProbeMessage)
    assert client.sent == []


def test_envelope_codec_roundtrip():
    """GossipEnvelope crosses the framed wire with its nested payload."""
    env = GossipEnvelope(
        sender=Endpoint.from_parts("10.0.0.1", 5001),
        gossip_id=NodeId(-3, 99),
        ttl=5,
        payload=ProbeMessage(sender=Endpoint.from_parts("10.0.0.2", 5002)),
    )
    request_no, decoded = codec.decode(codec.encode(42, env))
    assert request_no == 42
    assert decoded == env


def _gossip_factory(client, rng):
    return GossipBroadcaster(client, client.address, fanout=4, rng=rng)


def test_cluster_converges_on_gossip_broadcaster():
    """Full protocol over epidemic dissemination: 16 nodes join, two crash,
    the cut decides, and every instance converges to the same view."""
    h = ClusterHarness(seed=77)
    h.broadcaster_factory = _gossip_factory
    h.create_cluster(16, parallel=False)
    h.wait_and_verify_agreement(16)
    victims = [h.addr(6), h.addr(11)]
    h.fail_nodes(victims)
    h.wait_and_verify_agreement(14)
    configs = {
        c.get_current_configuration_id() for c in h.instances.values()
    }
    assert len(configs) == 1


def test_gossip_join_wave_converges():
    """Parallel joins through one seed with gossip dissemination."""
    h = ClusterHarness(seed=78)
    h.broadcaster_factory = _gossip_factory
    h.create_cluster(12, parallel=True)
    h.wait_and_verify_agreement(12)


def test_gossip_refused_on_jvm_wire_transport():
    """Build-time rejection of the gossip + gRPC pairing: the JVM wire has
    no GossipEnvelope, so best-effort dissemination would fail silently."""
    import pytest

    pytest.importorskip("grpc")
    from rapid_tpu.cluster import ClusterBuilder, JoinException
    from rapid_tpu.messaging.grpc_transport import GrpcClient, GrpcServer

    addr = Endpoint.from_parts("127.0.0.1", 45991)
    client, server = GrpcClient(addr), GrpcServer(addr)
    builder = (
        ClusterBuilder(addr)
        .set_messaging_client_and_server(client, server)
        .set_broadcaster_factory(_gossip_factory)
    )
    with pytest.raises(JoinException, match="native-codec transport"):
        builder.start()


def test_vote_batch_codec_roundtrip_and_tally():
    """FastRoundVoteBatch: wire round-trip, and the service tallies it
    exactly as the equivalent individual votes (reaching a decision)."""
    from rapid_tpu.types import FastRoundVoteBatch

    eps = members(8)
    batch = FastRoundVoteBatch(
        senders=tuple(eps[:6]), configuration_id=-9, endpoints=(eps[7],)
    )
    request_no, decoded = codec.decode(codec.encode(5, batch))
    assert request_no == 5 and decoded == batch
