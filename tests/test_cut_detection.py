"""Multi-node cut detector watermark semantics, mirroring CutDetectionTest.java.

Uses K=10, H=8, L=2 exactly as the reference tests (CutDetectionTest.java:34-36).
"""

import random
import uuid

import pytest

from rapid_tpu.cut_detector import MultiNodeCutDetector
from rapid_tpu.membership import MembershipView
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint, NodeId

K, H, L = 10, 8, 2
CONFIG_ID = -1  # does not affect the detector


def ep(port: int, host: str = "127.0.0.2") -> Endpoint:
    return Endpoint.from_parts(host, port)


def src(i: int) -> Endpoint:
    return Endpoint.from_parts("127.0.0.1", i)


def alert(src_ep, dst_ep, status, ring) -> AlertMessage:
    return AlertMessage(
        edge_src=src_ep,
        edge_dst=dst_ep,
        edge_status=status,
        configuration_id=CONFIG_ID,
        ring_numbers=(ring,),
    )


def test_invalid_watermarks_rejected():
    with pytest.raises(ValueError):
        MultiNodeCutDetector(K, K + 1, L)
    with pytest.raises(ValueError):
        MultiNodeCutDetector(K, 3, 4)  # L > H
    with pytest.raises(ValueError):
        MultiNodeCutDetector(2, 2, 1)  # K < K_MIN
    with pytest.raises(ValueError):
        MultiNodeCutDetector(K, H, 0)


def test_proposal_at_hth_report():
    """CutDetectionTest.java:43-59."""
    wb = MultiNodeCutDetector(K, H, L)
    dst = ep(2)
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
        assert wb.num_proposals == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dst, EdgeStatus.UP, H - 1))
    assert ret == [dst]
    assert wb.num_proposals == 1


def test_duplicate_reports_ignored():
    """Same (dst, ring) reported twice counts once (MultiNodeCutDetector.java:97-101)."""
    wb = MultiNodeCutDetector(K, H, L)
    dst = ep(2)
    for _ in range(H):
        wb.aggregate_for_proposal(alert(src(1), dst, EdgeStatus.UP, 0))
    assert wb.num_proposals == 0


def test_blocking_one_blocker():
    """CutDetectionTest.java:62-91: a node in (L, H) blocks another's proposal."""
    wb = MultiNodeCutDetector(K, H, L)
    dst1, dst2 = ep(2), ep(2, "127.0.0.3")
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst1, EdgeStatus.UP, i)) == []
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst2, EdgeStatus.UP, i)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dst2, EdgeStatus.UP, H - 1))
    assert sorted(map(str, ret)) == sorted(map(str, [dst1, dst2]))
    assert wb.num_proposals == 1


def test_blocking_three_blockers():
    """CutDetectionTest.java:96-137."""
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [ep(2, f"127.0.0.{i}") for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dsts[0], EdgeStatus.UP, H - 1)) == []
    assert wb.aggregate_for_proposal(alert(src(H), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert len(ret) == 3
    assert wb.num_proposals == 1


def test_multiple_blockers_past_h_no_double_fire():
    """CutDetectionTest.java:140-189: reports past H don't re-trigger."""
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [ep(2, f"127.0.0.{i}") for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i))
    wb.aggregate_for_proposal(alert(src(H), dsts[0], EdgeStatus.UP, H - 1))
    # duplicate announcements for the same ring are ignored
    assert wb.aggregate_for_proposal(alert(src(H + 1), dsts[0], EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    wb.aggregate_for_proposal(alert(src(H), dsts[2], EdgeStatus.UP, H - 1))
    assert wb.aggregate_for_proposal(alert(src(H + 1), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate_for_proposal(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert len(ret) == 3
    assert wb.num_proposals == 1


def test_below_l_does_not_block():
    """CutDetectionTest.java:192-230: a node with < L reports doesn't block."""
    wb = MultiNodeCutDetector(K, H, L)
    dst1, dst2, dst3 = (ep(2, f"127.0.0.{i}") for i in (2, 3, 4))
    for i in range(H - 1):
        wb.aggregate_for_proposal(alert(src(i + 1), dst1, EdgeStatus.UP, i))
    for i in range(L - 1):
        wb.aggregate_for_proposal(alert(src(i + 1), dst2, EdgeStatus.UP, i))
    for i in range(H - 1):
        wb.aggregate_for_proposal(alert(src(i + 1), dst3, EdgeStatus.UP, i))
    assert wb.aggregate_for_proposal(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    ret = wb.aggregate_for_proposal(alert(src(H), dst3, EdgeStatus.UP, H - 1))
    assert len(ret) == 2
    assert wb.num_proposals == 1


def test_batch():
    """CutDetectionTest.java:234-252."""
    wb = MultiNodeCutDetector(K, H, L)
    endpoints = [ep(2 + i) for i in range(3)]
    proposal = []
    for endpoint in endpoints:
        for ring in range(K):
            proposal.extend(
                wb.aggregate_for_proposal(alert(src(1), endpoint, EdgeStatus.UP, ring))
            )
    assert len(proposal) == len(endpoints)


def test_link_invalidation():
    """CutDetectionTest.java:255-301: implicit detection of edges between
    failing nodes unblocks the cut; the expected cut has 4 nodes."""
    rng = random.Random(11)
    view = MembershipView(K)
    num_nodes = 30
    endpoints = []
    for i in range(num_nodes):
        node = ep(2 + i)
        endpoints.append(node)
        view.ring_add(node, NodeId.from_uuid(uuid.UUID(int=rng.getrandbits(128))))

    wb = MultiNodeCutDetector(K, H, L)
    dst = endpoints[0]
    observers = view.get_observers_of(dst)
    assert len(observers) == K

    # alerts from observers[0 .. H-1) about dst
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(observers[i], dst, EdgeStatus.DOWN, i)) == []
        assert wb.num_proposals == 0

    # alerts *about* observers[H-1 .. K) of dst
    failed_observers = set()
    for i in range(H - 1, K):
        observers_of_observer = view.get_observers_of(observers[i])
        failed_observers.add(observers[i])
        for j in range(K):
            assert (
                wb.aggregate_for_proposal(
                    alert(observers_of_observer[j], observers[i], EdgeStatus.DOWN, j)
                )
                == []
            )
            assert wb.num_proposals == 0

    # dst sits at H-1 reports; link invalidation brings everything stable
    ret = wb.invalidate_failing_edges(view)
    assert len(ret) == 4
    assert wb.num_proposals == 1
    for node in ret:
        assert node in failed_observers or node == dst


def test_clear_resets_state():
    wb = MultiNodeCutDetector(K, H, L)
    dst = ep(2)
    for i in range(H):
        wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i))
    assert wb.num_proposals == 1
    wb.clear()
    assert wb.num_proposals == 0
    # detector accepts the same reports again after clear
    for i in range(H - 1):
        assert wb.aggregate_for_proposal(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    ret = wb.aggregate_for_proposal(alert(src(H), dst, EdgeStatus.UP, H - 1))
    assert ret == [dst]
