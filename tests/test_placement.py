"""Placement plane: the deterministic weighted-rendezvous shard map.

Both implementations of the same pure function -- the object model
(placement/engine.py, sorted-view candidate order, scalar xxh64) and the
vectorized device plane (placement/device.py, slot-column candidate order,
batched xxh64 + jittable top-R) -- must agree bit-for-bit on assignments
and map fingerprints across arbitrary churn. On top of parity this battery
pins the properties the subsystem exists for: determinism from
(configuration id, view, weights, seed) alone, weighted proportionality via
virtual instances, and minimal motion (only partitions that lost a replica
move; uniform-weight noise bound is exactly zero).
"""

import jax
import numpy as np
import pytest

from rapid_tpu import Endpoint
from rapid_tpu.events import NodeStatusChange
from rapid_tpu.placement import (
    MAX_WEIGHT,
    PlacementConfig,
    PlacementSubscriber,
    build_map,
    diff_maps,
    weight_of,
)
from rapid_tpu.placement.device import DevicePlacement, build_jit, topr_full
from rapid_tpu.placement.engine import PlacementEngine
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.types import EdgeStatus

from harness import ClusterHarness


def members(n, base_port=9000):
    return [Endpoint.from_parts(f"10.0.{i // 200}.{i % 200}", base_port + i)
            for i in range(n)]


def device_universe(eps, weights=None):
    """Column arrays for a *sorted* endpoint universe (the order parity
    with the engine's sorted-view candidate indexing requires)."""
    eps = sorted(eps)
    max_len = max(len(ep.hostname) for ep in eps)
    hostnames = np.zeros((len(eps), max_len), dtype=np.uint8)
    host_lengths = np.zeros(len(eps), dtype=np.int64)
    ports = np.zeros(len(eps), dtype=np.int64)
    w = np.ones(len(eps), dtype=np.int32)
    for slot, ep in enumerate(eps):
        hostnames[slot, : len(ep.hostname)] = np.frombuffer(ep.hostname, np.uint8)
        host_lengths[slot] = len(ep.hostname)
        ports[slot] = ep.port
        if weights:
            w[slot] = weights.get(ep, 1)
    return eps, hostnames, host_lengths, ports, w


def rows_as_endpoints(assign, eps):
    return [tuple(eps[int(s)] for s in row if s >= 0) for row in assign]


# ---------------------------------------------------------------------- #
# Determinism and the pure-function contract
# ---------------------------------------------------------------------- #

def test_build_map_deterministic():
    eps = members(12)
    weights = {eps[0]: 4, eps[3]: 2}
    config = PlacementConfig(partitions=64, replicas=3, seed=11)
    a = build_map(eps, weights, config, configuration_id=77)
    b = build_map(list(reversed(eps)), dict(weights), config, 77)
    assert a == b  # input order is irrelevant: the sorted view decides
    c = build_map(eps, weights, PlacementConfig(64, 3, seed=12), 77)
    assert c.assignments != a.assignments or c.version != a.version


def test_every_member_computes_the_same_map():
    """Two engines fed the same views are indistinguishable -- the property
    that lets every node derive the map locally with zero coordination."""
    eps = members(9)
    config = PlacementConfig(partitions=32, replicas=3, seed=5)
    e1, e2 = PlacementEngine(config), PlacementEngine(config)
    for cid, view in [(1, eps), (2, eps[:6]), (3, eps[:6] + eps[7:])]:
        m1, d1 = e1.update(cid, view, {})
        m2, d2 = e2.update(cid, list(reversed(view)), {})
        assert m1 == m2
        assert d1 == d2


def test_weight_of_parsing():
    assert weight_of((("capacity", b"4"),), "capacity", 1) == 4
    assert weight_of((), "capacity", 2) == 2
    assert weight_of((("capacity", b"junk"),), "capacity", 1) == 1
    assert weight_of((("capacity", b"0"),), "capacity", 1) == 1  # clamp low
    assert weight_of((("capacity", b"9999"),), "capacity", 1) == MAX_WEIGHT


def test_replicas_clamped_to_membership():
    eps = members(2)
    config = PlacementConfig(partitions=16, replicas=3, seed=0)
    pmap = build_map(eps, {}, config, 1)
    assert all(len(row) == 2 for row in pmap.assignments)


# ---------------------------------------------------------------------- #
# Engine <-> device parity across churn
# ---------------------------------------------------------------------- #

def test_engine_device_parity_across_churn():
    """Full churn cycle -- build, remove a burst, add some back -- lands
    on bit-identical assignments and fingerprints on both planes, with the
    device plane running its incremental path."""
    all_eps = members(40)
    weights = {all_eps[1]: 3, all_eps[17]: 5, all_eps[30]: 2}
    config = PlacementConfig(partitions=256, replicas=3, seed=9)
    eps, hostnames, host_lengths, ports, w = device_universe(all_eps, weights)
    placement = DevicePlacement(config, hostnames, host_lengths, ports, w)

    active = np.zeros(len(eps), dtype=bool)
    active[:32] = True
    placement.build(active)

    def check(live_mask):
        live = [eps[i] for i in np.flatnonzero(live_mask)]
        pmap = build_map(live, weights, config, configuration_id=0)
        got = rows_as_endpoints(placement.assign, eps)
        assert got == list(pmap.assignments)
        assert placement.version == pmap.version
        return pmap

    prev = check(active)

    # removal burst: incremental update == engine full rebuild
    removed = np.array([2, 9, 10, 17])
    active2 = active.copy()
    active2[removed] = False
    diff = placement.apply_view_change(active2)
    cur = check(active2)
    engine_diff = diff_maps(prev, cur)
    assert sorted(diff.partitions_moved.tolist()) == list(
        engine_diff.partitions_moved
    )
    prev = cur

    # addition burst (rejoin two, admit four fresh slots)
    active3 = active2.copy()
    active3[[2, 9, 33, 34, 35, 36]] = True
    diff = placement.apply_view_change(active3)
    engine_diff = diff_maps(prev, check(active3))
    assert sorted(diff.partitions_moved.tolist()) == list(
        engine_diff.partitions_moved
    )

    # incremental state == from-scratch rebuild of the same active set
    fresh = DevicePlacement(config, hostnames, host_lengths, ports, w)
    fresh.build(active3)
    assert np.array_equal(fresh.assign, placement.assign)
    assert fresh.version == placement.version


def test_apply_weight_change_matches_engine_rebuild():
    """Re-weighting a live slot is an explicit full rebuild: the device
    plane's post-change assignments, version, and moved set agree with the
    engine rebuilding from scratch under the new weight dict."""
    all_eps = members(12)
    config = PlacementConfig(partitions=128, replicas=3, seed=6)
    eps, hostnames, host_lengths, ports, w = device_universe(all_eps)
    placement = DevicePlacement(config, hostnames, host_lengths, ports, w)
    active = np.ones(len(eps), dtype=bool)
    active[4] = False
    placement.build(active)
    live = [eps[i] for i in np.flatnonzero(active)]
    before = build_map(live, {}, config, configuration_id=0)
    assert rows_as_endpoints(placement.assign, eps) == list(before.assignments)

    new_w = w.copy()
    new_w[0] = 4
    new_w[7] = 2
    diff = placement.apply_weight_change(new_w)
    after = build_map(
        live, {eps[0]: 4, eps[7]: 2}, config, configuration_id=0
    )
    assert rows_as_endpoints(placement.assign, eps) == list(after.assignments)
    assert placement.version == after.version
    assert diff.old_version == before.version
    assert diff.new_version == after.version
    engine_diff = diff_maps(before, after)
    assert sorted(diff.partitions_moved.tolist()) == list(
        engine_diff.partitions_moved
    )
    # load_delta sums to zero slots-moved bookkeeping and only over actives
    assert int(diff.load_delta.sum()) == 0
    assert not diff.load_delta[4]

    # guard rails: shape mismatch and use-before-build both refuse
    with pytest.raises(ValueError):
        placement.apply_weight_change(np.ones(3, dtype=np.int32))
    virgin = DevicePlacement(config, hostnames, host_lengths, ports, w)
    with pytest.raises(RuntimeError):
        virgin.apply_weight_change(new_w)


def test_jit_build_matches_numpy():
    all_eps = members(24)
    config = PlacementConfig(partitions=128, replicas=3, seed=4)
    _, hostnames, host_lengths, ports, w = device_universe(
        all_eps, {all_eps[5]: 4}
    )
    placement = DevicePlacement(config, hostnames, host_lengths, ports, w)
    active = np.ones(len(all_eps), dtype=bool)
    active[[3, 11]] = False
    ref_assign, ref_scores = topr_full(
        placement.part32, placement.inst32, placement.weights, active,
        placement.replicas,
    )
    jit_assign, jit_scores = build_jit(
        placement.part32, placement.inst32, placement.weights, active,
        placement.replicas,
    )
    assert np.array_equal(jit_assign, ref_assign)
    assert np.array_equal(jit_scores, ref_scores)


def test_jit_build_sharded_over_mesh():
    """The jitted build row-sharded over the 8-device CPU mesh (the same
    NamedSharding scheme as shard/engine.py) agrees with the numpy path."""
    from rapid_tpu.shard.engine import make_mesh

    assert len(jax.devices()) == 8, "conftest should have forced 8 CPU devices"
    mesh = make_mesh(8)
    all_eps = members(32)
    config = PlacementConfig(partitions=512, replicas=3, seed=6)
    _, hostnames, host_lengths, ports, w = device_universe(
        all_eps, {all_eps[0]: 2}
    )
    placement = DevicePlacement(config, hostnames, host_lengths, ports, w)
    active = np.ones(len(all_eps), dtype=bool)
    active[7] = False
    ref_assign, ref_scores = topr_full(
        placement.part32, placement.inst32, placement.weights, active,
        placement.replicas,
    )
    mesh_assign, mesh_scores = build_jit(
        placement.part32, placement.inst32, placement.weights, active,
        placement.replicas, mesh=mesh,
    )
    assert np.array_equal(mesh_assign, ref_assign)
    assert np.array_equal(mesh_scores, ref_scores)


# ---------------------------------------------------------------------- #
# Minimal motion and weighted balance
# ---------------------------------------------------------------------- #

def test_minimal_motion_exact_set():
    """Removing nodes moves exactly the partitions that held one of them as
    a replica -- no collateral movement, the rendezvous property the paper's
    Fig.-13 single-rebalance claim rests on. Uniform weights, so the noise
    bound is exactly zero."""
    eps = members(20)
    config = PlacementConfig(partitions=512, replicas=3, seed=3)
    old = build_map(eps, {}, config, 1)
    victims = {eps[4], eps[13]}
    new = build_map([e for e in eps if e not in victims], {}, config, 2)
    diff = diff_maps(old, new)
    expected = {
        p for p, row in enumerate(old.assignments)
        if any(v in row for v in victims)
    }
    assert set(diff.partitions_moved) == expected  # noise == 0
    # survivors keep every replica they had
    for p, (old_row, new_row) in enumerate(zip(old.assignments, new.assignments)):
        kept = [n for n in old_row if n not in victims]
        assert all(n in new_row for n in kept), p


def test_addition_minimal_motion():
    """A joiner only steals partitions where it out-scores an incumbent."""
    eps = members(20)
    config = PlacementConfig(partitions=512, replicas=3, seed=3)
    old = build_map(eps[:19], {}, config, 1)
    new = build_map(eps, {}, config, 2)
    diff = diff_maps(old, new)
    for p in diff.partitions_moved:
        assert eps[19] in new.assignments[p]
        # exactly one slot changed and the rest survived
        assert len(set(old.assignments[p]) - set(new.assignments[p])) == 1


def test_weighted_proportionality():
    """A capacity-4 node owns ~4x the partitions of a capacity-1 node."""
    eps = members(16)
    heavy = eps[7]
    config = PlacementConfig(partitions=4096, replicas=1, seed=13)
    pmap = build_map(eps, {heavy: 4}, config, 1)
    counts = pmap.counts()
    fair = config.partitions / (len(eps) - 1 + 4)
    assert counts[heavy] > 2.5 * fair  # ~4x fair share, generous slack
    others = [counts.get(e, 0) for e in eps if e != heavy]
    assert max(others) < 2.0 * fair
    assert pmap.imbalance() < 1.6


# ---------------------------------------------------------------------- #
# Subscriber: the map from VIEW_CHANGE events alone
# ---------------------------------------------------------------------- #

def test_subscriber_tracks_view_changes():
    eps = members(8)
    config = PlacementConfig(partitions=64, replicas=3, seed=2)
    sub = PlacementSubscriber(config)
    up = [
        NodeStatusChange(ep, EdgeStatus.UP,
                         (("capacity", b"3"),) if i == 2 else ())
        for i, ep in enumerate(eps)
    ]
    sub(101, up)
    weights = {eps[2]: 3}
    assert sub.map == build_map(eps, weights, config, 101)
    assert sub.last_diff is None

    down = [NodeStatusChange(eps[5], EdgeStatus.DOWN, ())]
    sub(102, down)
    expect = build_map([e for e in eps if e != eps[5]], weights, config, 102)
    assert sub.map == expect
    assert sub.last_diff is not None
    assert sub.last_diff.configuration_id == 102
    assert sub.view_changes == 2


# ---------------------------------------------------------------------- #
# Protocol-plane integration (in-process cluster on virtual time)
# ---------------------------------------------------------------------- #

@pytest.fixture
def harness():
    h = ClusterHarness(seed=7)
    yield h
    h.shutdown()


def test_cluster_placement_agreement_and_rebalance(harness):
    """Every member derives the identical map from its own view; one crash
    burst produces one rebalance whose moved set is minimal."""
    placement = {"partitions": 64, "replicas": 3, "seed": 1}
    harness.start_seed(0, placement=placement)
    for i in range(1, 6):
        harness.join(i, placement=placement)
    harness.wait_and_verify_agreement(6)

    maps = [inst.get_placement_map() for inst in harness.instances.values()]
    assert all(m is not None for m in maps)
    assert len({m.version for m in maps}) == 1
    assert all(m.configuration_id == maps[0].configuration_id for m in maps)
    before = maps[0]
    assert len(before.members) == 6

    victim = harness.addr(5)
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(5)

    maps = {ep: inst.get_placement_map()
            for ep, inst in harness.instances.items()}
    assert len({m.version for m in maps.values()}) == 1
    after = next(iter(maps.values()))
    assert victim not in after.members
    diffs = [inst.get_placement_diff() for inst in harness.instances.values()]
    assert all(d is not None for d in diffs)
    expected = {
        p for p, row in enumerate(before.assignments) if victim in row
    }
    for d in diffs:
        assert set(d.partitions_moved) == expected
        assert d.new_version == after.version
    # the status RPC surfaces the same version it computed locally
    for ep, inst in harness.instances.items():
        status = inst.get_cluster_status()
        assert status.placement_version == inst.get_placement_map().version
        assert status.placement_partitions == 64
        assert status.placement_owned == len(
            inst.get_placement_map().owned(ep)
        )


def test_statusz_renders_placement_fields(harness):
    """tools/statusz.py surfaces the placement triple in both text and JSON
    form, and omits the text line for placement-free nodes."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "statusz", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "statusz.py")
    )
    statusz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(statusz)

    harness.start_seed(0, placement={"partitions": 32, "replicas": 3})
    inst = harness.instances[harness.addr(0)]
    status = inst.get_cluster_status()
    text = statusz.render(status)
    assert f"placement: version={status.placement_version}" in text
    assert "partitions=32" in text
    blob = statusz.to_json(status)
    assert blob["placement_version"] == status.placement_version
    assert blob["placement_partitions"] == 32
    assert blob["placement_owned"] == 32  # sole member owns everything

    plain = ClusterHarness(seed=8)
    try:
        plain.start_seed(0)
        bare = plain.instances[plain.addr(0)].get_cluster_status()
        assert "placement:" not in statusz.render(bare)
        assert statusz.to_json(bare)["placement_partitions"] == 0
    finally:
        plain.shutdown()


def test_status_placement_fields_survive_both_wires():
    """The placement triple in ClusterStatusResponse round-trips through
    the msgpack codec AND the gRPC wire (fields 13-15); an old frame
    without them parses back to the defaults."""
    from rapid_tpu.messaging import grpc_transport as gt
    from rapid_tpu.messaging.codec import decode, encode
    from rapid_tpu.messaging.wire_schema import MSG
    from rapid_tpu.types import ClusterStatusResponse

    r = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=9,
        membership_size=3, placement_version=-123456789,
        placement_partitions=64, placement_owned=21,
    )
    assert decode(encode(7, r)) == (7, r)
    wire = gt.to_wire_response(r).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == r
    old = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=1,
        membership_size=2,
    )
    wire = gt.to_wire_response(old).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == old and back.placement_partitions == 0


def test_cluster_without_placement_reports_zero(harness):
    harness.start_seed(0)
    inst = harness.instances[harness.addr(0)]
    assert inst.get_placement_map() is None
    status = inst.get_cluster_status()
    assert status.placement_version == 0
    assert status.placement_partitions == 0


# ---------------------------------------------------------------------- #
# Simulator integration (device plane inside the view-change path)
# ---------------------------------------------------------------------- #

def test_sim_placement_rebalance_on_crash():
    sim = Simulator(48, seed=3)
    sim.enable_placement(partitions=128, replicas=3, seed=2)
    before_assign = sim.placement.assign.copy()
    before_version = sim.placement.version
    victims = np.array([5, 6, 7])
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=64)
    assert rec is not None
    diffs = sim.placement_diffs
    assert len(diffs) == 1
    diff = diffs[0]
    expected = np.flatnonzero(np.isin(before_assign, victims).any(axis=1))
    assert np.array_equal(np.sort(diff.partitions_moved), expected)
    assert diff.old_version == before_version
    assert diff.new_version == sim.placement.version != before_version
    assert not np.isin(sim.placement.assign, victims).any()
    # metrics + journal carry the rebalance
    hist = sim.metrics.histogram("placement.partitions_moved")
    assert hist is not None and hist["count"] == 1
    kinds = [e["kind"] for e in sim.recorder.tail()]
    assert kinds.count("placement_rebalance") == 2  # enable + rebalance


def test_sim_placement_never_advances_virtual_time():
    """Placement is derived state: two identical runs, one with the plane
    enabled, must agree on protocol timing exactly (the bench pin's
    guarantee)."""
    a = Simulator(32, seed=11)
    b = Simulator(32, seed=11)
    b.enable_placement(partitions=64)
    for sim in (a, b):
        sim.crash(np.array([3, 9]))
        rec = sim.run_until_decision(max_rounds=64)
        assert rec is not None
    assert a.virtual_ms == b.virtual_ms
    assert a.configuration_id() == b.configuration_id()


@pytest.mark.slow
def test_sim_placement_at_scale():
    """The acceptance scenario: a 100k-node simulated cluster computes and
    diffs an 8192x3 map inside the view-change path; the incremental update
    touches only the minimal-motion rows."""
    sim = Simulator(100_000, seed=1)
    sim.enable_placement(partitions=8192, replicas=3)
    before_assign = sim.placement.assign.copy()
    victims = np.arange(40, 52)
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=64)
    assert rec is not None
    diffs = sim.placement_diffs
    assert len(diffs) == 1
    expected = np.flatnonzero(np.isin(before_assign, victims).any(axis=1))
    assert np.array_equal(np.sort(diffs[0].partitions_moved), expected)
    assert diffs[0].moved <= 8192
    assert not np.isin(sim.placement.assign, victims).any()
