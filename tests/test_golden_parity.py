"""Golden parity vectors: anti-drift contract, frozen as committed data.

Ring orders, configuration IDs, per-seed endpoint hashes, raw xxHash64
values, and the serialized bytes of every RapidRequest/RapidResponse message
type are pinned to tests/golden/parity_vectors.json for a fixed identity
set. Both planes -- the object model (MembershipView) and the simulation
control plane (VirtualCluster/ring_order/configuration_id_vectorized) -- are
asserted against the same file, so a regression cannot silently shift both
implementations together (the cross-plane differential tests alone could
not catch that).

PROVENANCE (honest labeling, VERDICT r2 item 10): the vectors were generated
by THIS repo's own implementation (tests/golden/generate_vectors.py); no JVM
exists in this environment, so the file pins against self-drift rather than
independently proving JVM parity. The JVM chain is transitive, through two
independently-anchored primitives: xxHash64 is pinned to published public
vectors (test_hashing.py), and the wire bytes round-trip bit-for-bit through
protoc-generated classes built from the reference's own rapid.proto
(test_grpc_transport.py). Direct JVM interop is covered by the opt-in
test_jvm_interop.py when a java toolchain and the reference agent jar are
present. Algorithm sources: Utils.java:211-230 (seeded ring hashes),
MembershipView.java:535-547 (chained configuration identity),
rapid/src/main/proto/rapid.proto (wire schema).

The vectors are regenerated only by a deliberate run of
tests/golden/generate_vectors.py after independent cross-validation --
never to make a failing build pass.
"""

import json
from pathlib import Path

import numpy as np

from rapid_tpu.handoff.device import device_transfer_plans
from rapid_tpu.handoff.plan import plan_transfers
from rapid_tpu.hashing import endpoint_hash, xxh64
from rapid_tpu.membership import MembershipView
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.placement import PlacementConfig, build_map, diff_maps
from rapid_tpu.placement.device import DevicePlacement
from rapid_tpu.sim.topology import (
    VirtualCluster,
    configuration_id_vectorized,
    ring_order,
)

from golden import fixtures as fx

GOLDEN = json.loads(
    (Path(__file__).parent / "golden" / "parity_vectors.json").read_text()
)


def test_xxh64_golden():
    for data_hex, by_seed in GOLDEN["xxh64"].items():
        data = bytes.fromhex(data_hex)
        for seed, expect in by_seed.items():
            assert f"{xxh64(data, int(seed)):016x}" == expect


def test_endpoint_hashes_golden():
    """The seeded per-ring address hashes (Utils.java:211-230) that order
    every ring."""
    eps = {fx.ep_str(fx.member(i)[0]): fx.member(i)[0] for i in range(3)}
    for ep_name, by_seed in GOLDEN["endpoint_hashes"].items():
        ep = eps[ep_name]
        for seed, expect in by_seed.items():
            got = endpoint_hash(ep.hostname, ep.port, int(seed))
            assert f"{got:016x}" == expect


def _object_views():
    view = MembershipView(fx.K)
    for i in range(fx.INITIAL):
        view.ring_add(*fx.member(i))
    yield "initial20", view
    for i in fx.DELETED:
        view.ring_delete(fx.member(i)[0])
    yield "after_delete3", view
    for i in fx.ADDED:
        view.ring_add(*fx.member(i))
    yield "after_add5", view


def test_object_plane_matches_golden():
    """MembershipView reproduces the frozen ring orders and configuration
    IDs across add/delete/add configurations."""
    for name, view in _object_views():
        golden = GOLDEN["configurations"][name]
        assert view.get_current_configuration_id() == golden["configuration_id"]
        for ring in range(fx.K):
            got = [fx.ep_str(ep) for ep in view.get_ring(ring)]
            assert got == golden["rings"][ring], f"{name} ring {ring}"


def test_sim_plane_matches_golden():
    """The vectorized control plane (batched xxHash argsorts + the power-
    ladder configuration fold) reproduces the same frozen contract."""
    n = fx.INITIAL + len(fx.ADDED)
    cluster = VirtualCluster.synthesize(n, fx.K, seed=0)
    for i in range(n):
        ep, nid = fx.member(i)
        cluster.assign_identity(i, ep.hostname, ep.port, nid.high, nid.low)

    stages = {
        "initial20": (list(range(fx.INITIAL)), list(range(fx.INITIAL))),
        "after_delete3": (
            [i for i in range(fx.INITIAL) if i not in fx.DELETED],
            list(range(fx.INITIAL)),  # deleted ids stay in identifiersSeen
        ),
        "after_add5": (
            [i for i in range(n) if i not in fx.DELETED],
            list(range(n)),
        ),
    }
    for name, (members, seen) in stages.items():
        golden = GOLDEN["configurations"][name]
        active = np.zeros(n, dtype=bool)
        active[members] = True
        for ring in range(fx.K):
            got = [
                fx.ep_str(fx.member(int(s))[0])
                for s in ring_order(cluster, active, ring)
            ]
            assert got == golden["rings"][ring], f"{name} ring {ring}"
        # identifiers ordered by signed (high, low); endpoints in ring-0 order
        seen = np.array(seen)
        id_order = seen[
            np.lexsort((cluster.id_low[seen], cluster.id_high[seen]))
        ]
        order0 = ring_order(cluster, active, 0)
        config_id = configuration_id_vectorized(
            cluster.id_high[id_order],
            cluster.id_low[id_order],
            cluster.hostnames[order0],
            cluster.host_lengths[order0],
            cluster.ports[order0],
        )
        assert config_id == golden["configuration_id"], name


def _placement_config():
    cfg = GOLDEN["placement"]["config"]
    return PlacementConfig(
        partitions=cfg["partitions"], replicas=cfg["replicas"], seed=cfg["seed"]
    )


def _placement_weights():
    eps = {fx.ep_str(fx.member(i)[0]): fx.member(i)[0] for i in range(25)}
    return {eps[name]: w for name, w in GOLDEN["placement"]["weights"].items()}


def test_placement_engine_matches_golden():
    """The object-plane placement map (weighted rendezvous over the sorted
    view) reproduces the frozen assignments, versions, and the minimal-motion
    moved sets across the three fixed configurations."""
    config = _placement_config()
    weights = _placement_weights()
    prev = None
    for name, view in _object_views():
        golden = GOLDEN["placement"]["maps"][name]
        pmap = build_map(
            view.get_ring(0), weights, config,
            view.get_current_configuration_id(),
        )
        assert pmap.configuration_id == golden["configuration_id"], name
        assert pmap.version == golden["version"], name
        got = [[fx.ep_str(ep) for ep in row] for row in pmap.assignments]
        assert got == golden["assignments"], name
        if prev is not None:
            moved = list(diff_maps(prev, pmap).partitions_moved)
            assert moved == golden["moved_from_prev"], name
        prev = pmap


def test_placement_device_matches_golden():
    """The vectorized device plane, fed the same identities as a fixed slot
    universe with per-stage active masks, lands on the identical frozen
    assignments and map versions."""
    config = _placement_config()
    weights = _placement_weights()
    universe = sorted(fx.member(i)[0] for i in range(25))
    max_len = max(len(ep.hostname) for ep in universe)
    hostnames = np.zeros((len(universe), max_len), dtype=np.uint8)
    host_lengths = np.zeros(len(universe), dtype=np.int64)
    ports = np.zeros(len(universe), dtype=np.int64)
    w = np.ones(len(universe), dtype=np.int32)
    for slot, ep in enumerate(universe):
        hostnames[slot, : len(ep.hostname)] = np.frombuffer(
            ep.hostname, np.uint8
        )
        host_lengths[slot] = len(ep.hostname)
        ports[slot] = ep.port
        w[slot] = weights.get(ep, 1)
    stages = {
        "initial20": set(range(20)),
        "after_delete3": set(range(20)) - set(fx.DELETED),
        "after_add5": set(range(25)) - set(fx.DELETED),
    }
    ep_of = {i: fx.member(i)[0] for i in range(25)}
    slot_of = {ep: slot for slot, ep in enumerate(universe)}
    for name, members in stages.items():
        golden = GOLDEN["placement"]["maps"][name]
        active = np.zeros(len(universe), dtype=bool)
        for i in members:
            active[slot_of[ep_of[i]]] = True
        placement = DevicePlacement(config, hostnames, host_lengths, ports, w)
        placement.build(active)
        got = [
            [fx.ep_str(universe[int(s)]) for s in row if s >= 0]
            for row in placement.assign
        ]
        assert got == golden["assignments"], name
        assert placement.version == golden["version"], name


def test_handoff_plans_match_golden():
    """Both transfer-planning implementations -- the object plane
    (handoff/plan.py over PlacementMaps) and the vectorized device plane
    (handoff/device.py over [P, R] slot arrays) -- reproduce the frozen
    per-transition session lists: pairing, failover chains, sizes, chunk
    counts, and the xxh64-derived session ids."""
    config = _placement_config()
    weights = _placement_weights()
    sizes = {
        int(p): s for p, s in GOLDEN["handoff"]["sizes"].items()
    }
    chunk_size = GOLDEN["handoff"]["chunk_size"]

    # engine plans per stage transition
    maps = []
    for name, view in _object_views():
        maps.append((name, build_map(
            view.get_ring(0), weights, config,
            view.get_current_configuration_id(),
        )))
    engine_plans = {}
    for (_, prev), (name, cur) in zip(maps, maps[1:]):
        engine_plans[name] = plan_transfers(prev, cur, sizes, chunk_size)

    for name, plans in engine_plans.items():
        golden = GOLDEN["handoff"]["transitions"][name]
        assert len(plans) == len(golden), name
        for plan, expect in zip(plans, golden):
            assert plan.partition == expect["partition"], name
            assert fx.ep_str(plan.recipient) == expect["recipient"], name
            assert [fx.ep_str(ep) for ep in plan.sources] == expect["sources"]
            assert plan.size == expect["size"], name
            assert len(plan.chunks) == expect["chunks"], name
            assert plan.session_id == expect["session_id"], name

    # device plans over the same transitions, via the fixed slot universe
    universe = sorted(fx.member(i)[0] for i in range(25))
    max_len = max(len(ep.hostname) for ep in universe)
    hostnames = np.zeros((len(universe), max_len), dtype=np.uint8)
    host_lengths = np.zeros(len(universe), dtype=np.int64)
    ports = np.zeros(len(universe), dtype=np.int64)
    w = np.ones(len(universe), dtype=np.int32)
    for slot, ep in enumerate(universe):
        hostnames[slot, : len(ep.hostname)] = np.frombuffer(
            ep.hostname, np.uint8
        )
        host_lengths[slot] = len(ep.hostname)
        ports[slot] = ep.port
        w[slot] = weights.get(ep, 1)
    stages = [
        ("initial20", set(range(20))),
        ("after_delete3", set(range(20)) - set(fx.DELETED)),
        ("after_add5", set(range(25)) - set(fx.DELETED)),
    ]
    ep_of = {i: fx.member(i)[0] for i in range(25)}
    slot_of = {ep: slot for slot, ep in enumerate(universe)}
    sizes_arr = np.array(
        [sizes[p] for p in range(config.partitions)], dtype=np.int64
    )
    placement = DevicePlacement(config, hostnames, host_lengths, ports, w)
    prev_assign = None
    for name, members in stages:
        active = np.zeros(len(universe), dtype=bool)
        for i in members:
            active[slot_of[ep_of[i]]] = True
        placement.build(active)
        if prev_assign is not None:
            device_plans = device_transfer_plans(
                prev_assign, placement.assign, active, placement.keys64,
                placement.version, config.seed, sizes_arr, chunk_size,
            )
            golden = GOLDEN["handoff"]["transitions"][name]
            assert len(device_plans) == len(golden), name
            for plan, expect in zip(device_plans, golden):
                assert plan.partition == expect["partition"], name
                assert fx.ep_str(universe[plan.recipient]) == expect["recipient"]
                assert [
                    fx.ep_str(universe[s]) for s in plan.sources
                ] == expect["sources"], name
                assert plan.size == expect["size"], name
                assert len(plan.chunks) == expect["chunks"], name
                assert plan.session_id == expect["session_id"], name
        prev_assign = placement.assign.copy()


def test_request_bytes_golden():
    """Every RapidRequest message type serializes to the committed bytes and
    the committed bytes parse back to the identical message."""
    by_name = {type(m).__name__: m for m in fx.REQUEST_SAMPLES}
    assert set(by_name) == set(GOLDEN["requests"])
    for name, expect_hex in GOLDEN["requests"].items():
        msg = by_name[name]
        got = gt.to_wire_request(msg).SerializeToString(deterministic=True)
        assert got.hex() == expect_hex, name
        parsed = gt.from_wire_request(
            MSG["RapidRequest"].FromString(bytes.fromhex(expect_hex))
        )
        assert parsed == msg, name


def test_response_bytes_golden():
    by_name = {type(m).__name__: m for m in fx.RESPONSE_SAMPLES}
    assert set(by_name) == set(GOLDEN["responses"])
    for name, expect_hex in GOLDEN["responses"].items():
        msg = by_name[name]
        got = gt.to_wire_response(msg).SerializeToString(deterministic=True)
        assert got.hex() == expect_hex, name
        parsed = gt.from_wire_response(
            MSG["RapidResponse"].FromString(bytes.fromhex(expect_hex))
        )
        assert parsed == msg, name


def test_all_request_types_covered():
    """The golden file covers the full RapidRequest oneof (rapid.proto:21-35)
    and all response types (rapid.proto:37-45)."""
    assert set(GOLDEN["requests"]) == {
        "PreJoinMessage", "JoinMessage", "BatchedAlertMessage", "ProbeMessage",
        "FastRoundPhase2bMessage", "Phase1aMessage", "Phase1bMessage",
        "Phase2aMessage", "Phase2bMessage", "LeaveMessage",
    }
    assert set(GOLDEN["responses"]) == {
        "JoinResponse", "ProbeResponse", "ConsensusResponse", "Response",
    }
