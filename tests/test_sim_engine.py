"""TPU simulation engine: protocol behavior on device arrays + differential
parity against the object-model protocol stack.

Covers the BASELINE.json fault families: crash bursts, asymmetric one-way
link loss, lossy ingress, flip-flop reachability, and join waves.
"""

import numpy as np
import pytest

from rapid_tpu.membership import MembershipView
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig
from rapid_tpu.sim.topology import VirtualCluster
from rapid_tpu.types import Endpoint, NodeId


def endpoints_of(cluster: VirtualCluster):
    out = []
    for i in range(cluster.capacity):
        host = bytes(cluster.hostnames[i, : cluster.host_lengths[i]])
        out.append(Endpoint(host, int(cluster.ports[i])))
    return out


def view_of(cluster: VirtualCluster, members, k=10) -> MembershipView:
    eps = endpoints_of(cluster)
    view = MembershipView(k)
    for i in members:
        view.ring_add(eps[i], NodeId(int(cluster.id_high[i]), int(cluster.id_low[i])))
    return view


def test_single_crash_produces_singleton_cut():
    sim = Simulator(10, seed=1)
    sim.crash(np.array([3]))
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None
    assert list(rec.cut) == [3]
    assert rec.membership_size == 9
    # protocol time: threshold FD rounds + the vote-delivery round between
    # announcement and decision + batching window
    assert rec.virtual_time_ms == (10 + 1) * 1000 + 100


def test_crash_burst_cut_parity_with_object_model():
    """The decided cut and the resulting configuration ID must equal what the
    object-model (JVM-faithful) stack computes for the same membership."""
    sim = Simulator(50, seed=2)
    victims = np.array([4, 17, 30, 42, 49])
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=40)
    assert set(rec.cut) == set(victims)

    # object model: same identities, delete the same nodes
    view = view_of(sim.cluster, range(50))
    eps = endpoints_of(sim.cluster)
    for v in victims:
        view.ring_delete(eps[v])
    assert rec.configuration_id == view.get_current_configuration_id()
    # and the member lists agree in ring-0 order
    assert [eps[i] for i in sim.members()] != []  # non-empty sanity
    sim_ring0 = [eps[i] for i in __import__("rapid_tpu.sim.topology", fromlist=["ring_order"]).ring_order(sim.cluster, sim.active, 0)]
    assert sim_ring0 == view.get_ring(0)


def test_one_way_ingress_partition():
    """Nodes whose ingress is partitioned (they can send, not receive) are
    removed -- the asymmetric case SWIM-style protocols struggle with."""
    sim = Simulator(30, seed=3)
    victims = np.array([7, 22])
    sim.one_way_ingress_partition(victims)
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None
    assert set(rec.cut) == set(victims)
    # the victims were alive the whole time (they could even vote)
    assert sim.alive[victims].all()


def test_ingress_loss_80_percent():
    """80% probe loss to the victim set: cumulative FD counters cross the
    threshold and the set is removed (paper §7 Fig. 9-10 scenario)."""
    sim = Simulator(30, seed=4)
    victims = np.array([11])
    sim.ingress_loss(victims, 0.8)
    rec = sim.run_until_decision(max_rounds=64)
    assert rec is not None
    assert set(rec.cut) == set(victims)


def test_flip_flop_reachability():
    """Victims alternate reachable/unreachable; the cumulative (never-reset)
    failure counter guarantees eventual removal in ONE view change."""
    sim = Simulator(20, seed=5)
    victims = np.array([2, 9])
    rec = None
    for cycle in range(30):
        if cycle % 2 == 0:
            sim.crash(victims)
        else:
            sim.revive(victims)
        rec = sim.run_until_decision(max_rounds=3, batch=3)
        if rec is not None:
            break
    assert rec is not None, "flip-flop victims never removed"
    assert set(rec.cut) == set(victims)
    assert len(sim.view_changes) == 1  # exactly one stable view change


def test_join_wave():
    sim = Simulator(20, capacity=24, seed=6)
    joiners = np.array([20, 21, 22, 23])
    sim.request_joins(joiners)
    rec = sim.run_until_decision(max_rounds=10)
    assert rec is not None
    assert set(rec.cut) == set(joiners)
    assert set(rec.added) == set(joiners)
    assert rec.membership_size == 24
    # config id parity with object model after the same adds
    view = view_of(sim.cluster, range(24))
    assert rec.configuration_id == view.get_current_configuration_id()


def test_concurrent_join_and_crash():
    """A join wave and a crash burst resolve (possibly over two view changes)
    into the correct final membership."""
    sim = Simulator(20, capacity=22, seed=7)
    sim.request_joins(np.array([20, 21]))
    sim.crash(np.array([5]))
    deadline = 0
    while sim.membership_size != 21 and deadline < 10:
        sim.run_until_decision(max_rounds=20)
        deadline += 1
    members = set(sim.members())
    assert members == (set(range(20)) - {5}) | {20, 21}


def test_sequential_view_changes_accumulate_identifiers():
    """identifiersSeen is append-only across configurations
    (MembershipView.java:51): config ids keep matching the object model."""
    sim = Simulator(30, seed=8)
    view = view_of(sim.cluster, range(30))
    eps = endpoints_of(sim.cluster)
    for victim in (29, 28, 27):
        sim.crash(np.array([victim]))
        rec = sim.run_until_decision(max_rounds=40)
        assert list(rec.cut) == [victim]
        view.ring_delete(eps[victim])
        assert rec.configuration_id == view.get_current_configuration_id()


def test_no_decision_without_fault():
    sim = Simulator(10, seed=9)
    rec = sim.run_until_decision(max_rounds=15)
    assert rec is None
    assert sim.membership_size == 10


def test_quorum_blocks_when_too_many_crash():
    """If more than F = floor((N-1)/4) members crash *silently before
    detecting each other*... the cut still succeeds because crashed nodes are
    the proposal, and voters are the survivors. But if survivors < quorum, no
    fast-round decision is possible."""
    sim = Simulator(8, seed=10)
    # 7 of 8 crash: voters=1 < quorum 7 - floor(7/4) => no decision
    sim.crash(np.arange(1, 8))
    rec = sim.run_until_decision(max_rounds=30)
    assert rec is None


def test_join_blocked_by_crashed_observers_completes_implicitly():
    """Regression: a joiner whose expected observers partly crashed sits in
    the [L,H) flux band; implicit invalidation must complete the join rather
    than wedge the configuration (MultiNodeCutDetector.java:146-158)."""
    sim = Simulator(20, capacity=21, seed=0)
    sim.crash(np.array([0, 1, 2, 3]))
    sim.request_joins(np.array([20]))
    total_changes = 0
    for _ in range(4):
        rec = sim.run_until_decision(max_rounds=40)
        if rec is None:
            break
        total_changes += 1
        if sim.membership_size == 17 and sim.active[20]:
            break
    assert sim.active[20], "joiner never admitted"
    assert not sim.active[[0, 1, 2, 3]].any(), "crashed nodes never removed"
    assert sim.membership_size == 17


def test_one_way_partition_survives_unrelated_view_change():
    """Regression: a persistent ingress partition must be re-mapped onto the
    new adjacency after an unrelated view change, not silently dropped."""
    sim = Simulator(20, capacity=21, seed=1)
    sim.one_way_ingress_partition(np.array([7]))
    sim.request_joins(np.array([20]))
    removed_7 = False
    for _ in range(5):
        rec = sim.run_until_decision(max_rounds=40)
        if rec is None:
            break
        if 7 in set(rec.removed):
            removed_7 = True
            break
    assert removed_7, "partitioned node survived across view changes"


def test_virtual_time_not_double_counted():
    """Regression: a decision spanning multiple run_until_decision calls must
    bill each round once."""
    sim_split = Simulator(10, seed=2)
    sim_split.crash(np.array([3]))
    assert sim_split.run_until_decision(max_rounds=5, batch=5) is None
    rec_split = sim_split.run_until_decision(max_rounds=40)
    sim_one = Simulator(10, seed=2)
    sim_one.crash(np.array([3]))
    rec_one = sim_one.run_until_decision(max_rounds=40)
    assert rec_split.virtual_time_ms == rec_one.virtual_time_ms == 11_100


def test_two_join_requests_both_delivered():
    """Regression: request_joins must accumulate, not overwrite."""
    sim = Simulator(20, capacity=22, seed=3)
    sim.request_joins(np.array([20]))
    sim.request_joins(np.array([21]))
    rec = sim.run_until_decision(max_rounds=10)
    assert rec is not None
    assert set(rec.added) == {20, 21}


def test_classic_paxos_fallback_when_fast_quorum_unreachable():
    """N=8, 2 crashed: fast-round quorum is 7 but only 6 can vote -- the
    classic recovery round among the live majority must decide the cut
    (FastPaxos.java:189-195, Paxos.java:97-236)."""
    sim = Simulator(8, seed=11)
    victims = np.array([6, 7])
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None, "fallback did not decide"
    assert rec.via_classic_round
    assert set(rec.cut) == set(victims)
    assert sim.membership_size == 6

    # with the fallback disabled, the same scenario stalls
    sim2 = Simulator(8, seed=11)
    sim2.crash(victims)
    rec2 = sim2.run_until_decision(max_rounds=40, classic_fallback_after_rounds=None)
    assert rec2 is None


def test_configuration_snapshot_resume(tmp_path):
    """Checkpoint/resume parity (SURVEY §5.4): the restored simulator carries
    the same configuration id and identifiersSeen, and keeps operating."""
    sim = Simulator(30, seed=12)
    sim.crash(np.array([29]))
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None
    path = str(tmp_path / "snap.npz")
    sim.save_configuration(path)

    restored = Simulator.from_configuration(path)
    assert restored.configuration_id() == sim.configuration_id()
    assert restored.membership_size == sim.membership_size == 29
    assert restored.identifiers_seen == sim.identifiers_seen
    # the restored instance keeps working: another crash decides normally
    restored.crash(np.array([28]))
    rec2 = restored.run_until_decision(max_rounds=40)
    assert rec2 is not None and list(rec2.cut) == [28]
    # virtual clock carried over
    assert rec2.virtual_time_ms > rec.virtual_time_ms


def test_deterministic_under_seed():
    """Same seed, same fault schedule => identical view-change history
    (config ids, cut sets, virtual times), even with random ingress loss."""

    def run():
        sim = Simulator(40, seed=13)
        sim.ingress_loss(np.array([5, 6]), 0.7)
        out = []
        for _ in range(3):
            rec = sim.run_until_decision(max_rounds=80)
            if rec is None:
                break
            out.append((tuple(rec.cut), rec.configuration_id, rec.virtual_time_ms))
        return out

    a, b = run(), run()
    assert a, "no view changes decided"
    assert a == b


def test_graceful_leave_decides_without_fd_wait():
    """Leave is a proactive DOWN alert (MembershipService.java:366-371): the
    cut decides in ~1 round instead of waiting out the 10-round FD threshold."""
    sim = Simulator(32, seed=21)
    sim.leave(np.array([4, 19]))
    rec = sim.run_until_decision(max_rounds=8)
    assert rec is not None
    assert sorted(rec.cut) == [4, 19]
    assert rec.membership_size == 30
    # 1 alert round + 1 vote round + batching window, vs 11*1000+100 for a
    # crash (no waiting out the 10-round FD threshold)
    assert rec.virtual_time_ms == 2 * 1000 + 100


def test_graceful_leave_parity_with_object_model():
    """The post-leave configuration id equals the object model's after
    ring_delete of the same nodes."""
    sim = Simulator(20, seed=22)
    sim.leave(np.array([7]))
    rec = sim.run_until_decision(max_rounds=8)
    assert rec is not None and list(rec.cut) == [7]
    view = view_of(sim.cluster, [i for i in range(20)])
    eps = endpoints_of(sim.cluster)
    view.ring_delete(eps[7])
    assert view.get_current_configuration_id() == rec.configuration_id


def test_leave_with_dead_observers_uses_remaining_rings():
    """A leaver whose some observers are crashed still converges: the live
    observers' proactive reports put it past L, and implicit detection plus
    the crashed nodes' own cut handle the rest."""
    sim = Simulator(24, seed=23)
    # crash two nodes first and let that view change settle
    sim.crash(np.array([1, 2]))
    rec = sim.run_until_decision(max_rounds=16)
    assert rec is not None and sorted(rec.cut) == [1, 2]
    # now a graceful leave in the 22-node configuration
    sim.leave(np.array([9]))
    rec2 = sim.run_until_decision(max_rounds=8)
    assert rec2 is not None and list(rec2.cut) == [9]
    assert rec2.membership_size == 21


def test_crashed_node_cannot_leave():
    """A crashed process cannot send a leave notification; its removal must
    go through failure detection (no leave-latency shortcut for dead nodes)."""
    sim = Simulator(16, seed=24)
    sim.crash(np.array([6]))
    with pytest.raises(AssertionError):
        sim.leave(np.array([6]))


def test_windowed_fd_stays_stable_under_flip_flop():
    """The paper's windowed policy (40% of last 10): a 3-rounds-down /
    7-rounds-up flip-flop never accumulates 4 failures in any window, so the
    node is never cut -- while the reference code's cumulative counter
    eventually crosses its threshold and cuts it. This is the stability
    trade-off the two policies encode (paper section 6)."""
    victims = np.array([5])

    def run(policy):
        config = SimConfig(capacity=24, fd_policy=policy)
        sim = Simulator(24, config=config, seed=31)
        decided = None
        for _ in range(6):  # 6 cycles of 3 down + 7 up = 60 rounds
            sim.crash(victims)
            decided = decided or sim.run_until_decision(max_rounds=3, batch=3)
            sim.revive(victims)
            decided = decided or sim.run_until_decision(max_rounds=7, batch=7)
            if decided:
                break
        return decided

    assert run("windowed") is None  # windowed sheds the stale evidence
    cumulative = run("cumulative")  # never-reset counter crosses 10 eventually
    assert cumulative is not None and list(cumulative.cut) == [5]


def test_windowed_fd_cuts_sustained_crash():
    """A sustained crash is cut by the windowed policy once the window fills
    (W=10 probes, all failed), with the same cut set as cumulative."""
    config = SimConfig(capacity=32, fd_policy="windowed")
    sim = Simulator(32, config=config, seed=32)
    sim.crash(np.array([7, 19]))
    rec = sim.run_until_decision(max_rounds=20, batch=10)
    assert rec is not None and sorted(rec.cut) == [7, 19]
    # window fills at round 10, votes arrive round 11
    assert rec.virtual_time_ms == 11 * 1000 + 100


def test_staggered_phases_decide_with_subinterval_resolution():
    """With rounds_per_interval=10, rounds are 100ms and alerts arrive
    staggered by per-node phase: the cut still matches, and the decision time
    lands inside the 10th FD interval with sub-interval resolution rather
    than on a whole-interval boundary."""
    from rapid_tpu.sim.engine import SimConfig

    config = SimConfig(capacity=64, rounds_per_interval=10)
    sim = Simulator(64, config=config, seed=33)
    victims = np.array([5, 40])
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=128, batch=64)
    assert rec is not None and sorted(rec.cut) == [5, 40]
    # announcement in the 10th interval (9000, 10000]; the vote-delivery hop
    # costs one 100ms sub-round and the batching window another 100ms
    assert 9000 < rec.virtual_time_ms - 100 - sim._round_ms <= 10_000


def test_staggered_phases_cut_parity_with_synchronous_model():
    """The asynchrony model changes timing, never the decided cut."""
    from rapid_tpu.sim.engine import SimConfig

    victims = np.array([11, 12, 50])
    cuts = {}
    for rpi in (1, 10):
        config = SimConfig(capacity=64, rounds_per_interval=rpi)
        sim = Simulator(64, config=config, seed=34)
        sim.crash(victims)
        rec = sim.run_until_decision(max_rounds=128, batch=64)
        assert rec is not None
        cuts[rpi] = (tuple(sorted(rec.cut)), rec.configuration_id)
    assert cuts[1] == cuts[10]


def test_pack_decision_roundtrip_matches_state():
    """The bit-packed decision summary (one fetched buffer -- remote-device
    transports bill per-buffer round trips) must reproduce the exact arrays
    the driver previously fetched individually."""
    import jax

    from rapid_tpu.sim.engine import pack_decision, unpack_decision

    sim = Simulator(
        50, capacity=70, seed=9,
        config=SimConfig(capacity=70, extern_proposals=3),
    )
    sim.crash([4, 17])
    rec = sim.run_until_decision(max_rounds=32, batch=32)
    assert rec is not None
    # after a view change the state is fresh; run a couple more rounds with a
    # new crash so announced/proposal are non-trivial mid-flight
    sim.crash([23])
    sim.run_until_decision(max_rounds=10, batch=2, stop_when_announced=True)
    st = sim.state
    words = jax.device_get(pack_decision(sim.config, st))
    (decided, announced, announced_round, proposal, decided_group,
     decided_round, round_no) = unpack_decision(sim.config, words)
    assert decided == bool(st.decided)
    np.testing.assert_array_equal(announced, np.asarray(st.announced))
    np.testing.assert_array_equal(proposal, np.asarray(st.proposal))
    assert announced_round == int(st.announced_round)
    assert decided_group == int(st.decided_group)
    assert decided_round == int(st.decided_round)
    assert round_no == int(st.round)


def test_speculative_view_change_matches_unspeculated_run():
    """The speculative precompute (config-id fold + fresh state built while
    the decision fetch blocks) must be invisible: records, config ids, and
    follow-on view changes identical to a run with speculation disabled."""
    def run(speculate: bool):
        sim = Simulator(60, seed=21, speculate=speculate)
        recs = []
        sim.crash([3, 7, 11])
        recs.append(sim.run_until_decision(max_rounds=32, batch=8))
        sim.leave([20, 21])
        recs.append(sim.run_until_decision(max_rounds=32, batch=8))
        sim.crash([30])
        recs.append(sim.run_until_decision(max_rounds=32, batch=8))
        return recs

    spec, plain = run(True), run(False)
    for a, b in zip(spec, plain):
        assert a is not None and b is not None
        np.testing.assert_array_equal(a.cut, b.cut)
        assert a.configuration_id == b.configuration_id
        assert a.virtual_time_ms == b.virtual_time_ms
        assert a.membership_size == b.membership_size


def test_speculation_discarded_when_prediction_wrong():
    """A revive between speculation and the next batch invalidates the
    speculated alive mask; the run must fall back and stay correct."""
    def run(speculate: bool):
        sim = Simulator(60, seed=22, speculate=speculate)
        sim.crash([5, 6])
        # first batch too short to decide: speculation happens, then the
        # world changes under it
        assert sim.run_until_decision(max_rounds=4, batch=4) is None
        sim.revive([6])
        sim.crash([7])
        recs = []
        while sim.membership_size > 58:
            rec = sim.run_until_decision(max_rounds=64, batch=16)
            assert rec is not None
            recs.append(rec)
        return recs

    spec, plain = run(True), run(False)
    assert set().union(*(set(r.cut) for r in spec)) == {5, 7}
    assert len(spec) == len(plain)
    for a, b in zip(spec, plain):
        np.testing.assert_array_equal(a.cut, b.cut)
        assert a.configuration_id == b.configuration_id
        assert a.virtual_time_ms == b.virtual_time_ms


def test_sim_crash_beyond_fast_quorum_decides_via_classic():
    """The 16/50 boundary on the sim plane: survivors (34) < fast quorum
    (38), so the device tally can never decide; the host's classic recovery
    (majority 26) must -- and the resulting configuration id must match the
    object model's."""
    sim = Simulator(50, seed=44)
    victims = np.arange(34, 50)
    sim.crash(victims)
    rec = sim.run_until_decision(
        max_rounds=64, batch=8, classic_fallback_after_rounds=8
    )
    assert rec is not None and rec.via_classic_round
    assert set(rec.cut) == set(int(v) for v in victims)
    # identifiersSeen covers everyone ever admitted: build the full view,
    # then delete the cut (MembershipView.java:51)
    view = view_of(sim.cluster, range(50))
    eps = endpoints_of(sim.cluster)
    for v in victims:
        view.ring_delete(eps[v])
    assert rec.configuration_id == view.get_current_configuration_id()
