"""Differential fuzzing across execution paths: a randomized fault schedule
(crash, revive, graceful leave, join waves, one-way partitions) is generated
adaptively against the single-device driver, recorded, and replayed against
the mesh-sharded driver. Every decided view change -- cut composition,
configuration id, membership size, protocol time -- must be identical.

The single-device driver exercises the early-exit closed-form dispatch; the
mesh driver exercises the scan-path shard_map program over 8 devices. Any
divergence in latch semantics, report routing, or view-change bookkeeping
between the two lowerings shows up as a history mismatch.
"""

import random

import numpy as np
import pytest

from rapid_tpu.shard.engine import make_mesh
from rapid_tpu.sim.driver import Simulator

CAPACITY = 32
N_START = 24
STEPS = 8
BATCH = 6


def generate_and_run(fuzz_seed: int, mesh=None, script=None, speculate=True,
                     fd_policy="cumulative"):
    """Run a fault schedule; if ``script`` is None, generate it adaptively
    (choices constrained by the live protocol state) and return it.
    Returns (script, history, simulator)."""
    from rapid_tpu.sim.engine import SimConfig

    # reference-default parameters for the cumulative (parity) runs; the
    # windowed runs use a short window so schedules decide within STEPS
    config = (
        SimConfig(capacity=CAPACITY, fd_policy="windowed", fd_threshold=5,
                  fd_window=8, fd_window_threshold=0.5)
        if fd_policy == "windowed"
        else SimConfig(capacity=CAPACITY)
    )
    sim = Simulator(
        N_START, capacity=CAPACITY, config=config, seed=fuzz_seed, mesh=mesh,
        speculate=speculate,
    )
    rng = random.Random(fuzz_seed * 7919)
    recording = script is None
    ops = [] if recording else list(script)
    history = []
    spare = list(range(N_START, CAPACITY))
    crashed: set = set()

    for step in range(STEPS):
        if recording:
            choices = ["crash", "run"]
            alive_members = [
                int(i) for i in np.flatnonzero(sim.active & sim.alive)
            ]
            if crashed & {int(i) for i in np.flatnonzero(sim.active)}:
                choices.append("revive")
            if len(alive_members) > 3:
                choices.append("leave")
            if spare:
                choices.append("join")
            kind = rng.choice(choices)
            if kind == "crash":
                victims = rng.sample(alive_members, k=min(2, len(alive_members)))
                op = ("crash", victims)
            elif kind == "revive":
                pool = sorted(
                    crashed & {int(i) for i in np.flatnonzero(sim.active)}
                )
                op = ("revive", rng.sample(pool, k=1))
            elif kind == "leave":
                leavable = [
                    i for i in alive_members if i not in sim.pending_leavers
                ]
                op = ("leave", rng.sample(leavable, k=1))
            elif kind == "join":
                op = ("join", [spare.pop(0)])
            else:
                op = ("run", [])
            ops.append(op)
        else:
            op = ops[step]

        kind, args = op
        if kind == "crash":
            sim.crash(np.array(args))
            crashed.update(args)
        elif kind == "revive":
            sim.revive(np.array(args))
            crashed.difference_update(args)
        elif kind == "leave":
            sim.leave(np.array(args))
        elif kind == "join":
            if recording:
                pass  # already popped from spare
            else:
                spare.remove(args[0])
            sim.request_joins(np.array(args))
        rec = sim.run_until_decision(max_rounds=BATCH, batch=BATCH)
        if rec is not None:
            crashed.difference_update(int(i) for i in rec.removed)
            history.append(
                (
                    tuple(sorted(int(i) for i in rec.cut)),
                    rec.configuration_id,
                    rec.membership_size,
                    rec.virtual_time_ms,
                )
            )
    return ops, history, sim


@pytest.mark.parametrize("fuzz_seed", [1, 2, 3])
def test_fuzzed_schedule_identical_on_mesh(fuzz_seed):
    script, single_history, _ = generate_and_run(fuzz_seed)
    assert single_history, f"schedule decided nothing: {script}"
    mesh = make_mesh(8)
    _, mesh_history, _ = generate_and_run(fuzz_seed, mesh=mesh, script=script)
    assert mesh_history == single_history, f"schedule: {script}"


def test_fuzzed_schedule_deterministic():
    script, history_a, _ = generate_and_run(5)
    _, history_b, _ = generate_and_run(5, script=script)
    assert history_a == history_b


@pytest.mark.parametrize("fuzz_seed", [13, 14, 17, 18])
def test_fuzzed_schedule_identical_without_speculation(fuzz_seed):
    """The speculative view-change precompute must be invisible under
    arbitrary fault interleavings (crash/revive/leave/join between short
    batches -- exactly the regime where predictions go stale)."""
    script, spec_history, spec_sim = generate_and_run(fuzz_seed)
    assert spec_history, f"schedule decided nothing: {script}"
    _, plain_history, plain_sim = generate_and_run(
        fuzz_seed, script=script, speculate=False
    )
    assert spec_history == plain_history, f"schedule: {script}"
    # the comparison must not be vacuous: the speculated run really consumed
    # precomputed results, the plain run never did
    spec_hits = (
        spec_sim.metrics.get("speculation_hits_config_id")
        + spec_sim.metrics.get("speculation_hits_fresh_state")
    )
    assert spec_hits > 0, f"speculation never consumed; schedule: {script}"
    assert plain_sim.metrics.get("speculation_hits_config_id") == 0
    assert plain_sim.metrics.get("speculation_hits_fresh_state") == 0


# --------------------------------------------------------------------------- #
# Cross-plane fuzzing: protocol plane (full object-model cluster with real
# message passing on virtual time) vs the TPU sim plane, same schedule.
# --------------------------------------------------------------------------- #

def run_cross_plane_schedule(fuzz_seed: int, n_start: int = 10, steps: int = 5):
    """Apply one randomized membership schedule to both planes; after every
    converged step the set of member *indices* must be identical."""
    from harness import BASE_PORT, ClusterHarness

    rng = random.Random(fuzz_seed * 104729)
    capacity = n_start + steps  # at most one join per step

    harness = ClusterHarness(seed=fuzz_seed)
    harness.create_cluster(n_start, parallel=False)
    harness.wait_and_verify_agreement(n_start)
    sim = Simulator(n_start, capacity=capacity, seed=fuzz_seed)

    members = set(range(n_start))  # indices alive in both planes
    next_join = n_start
    schedule = []
    for _ in range(steps):
        choices = []
        if len(members) > 4:
            choices += ["crash", "leave"]
        if next_join < capacity:
            choices.append("join")
        kind = rng.choice(choices)
        if kind == "crash":
            victims = rng.sample(sorted(members), k=min(2, len(members) - 3))
            schedule.append(("crash", victims))
            harness.fail_nodes([harness.addr(i) for i in victims])
            sim.crash(np.array(victims, dtype=int))
            members -= set(victims)
        elif kind == "leave":
            leaver = rng.choice(sorted(members))
            schedule.append(("leave", [leaver]))
            instance = harness.instances.pop(harness.addr(leaver))
            done = instance.leave_gracefully_async()
            assert harness.scheduler.run_until(done.done, timeout_ms=120_000)
            sim.leave(np.array([leaver]))
            members -= {leaver}
        else:
            joiner = next_join
            next_join += 1
            schedule.append(("join", [joiner]))
            harness.join(joiner, seed_index=min(members))
            sim.request_joins(np.array([joiner]))
            members |= {joiner}

        harness.wait_and_verify_agreement(len(members))
        deadline = 8
        while sim.membership_size != len(members) and deadline > 0:
            sim.run_until_decision(max_rounds=16, batch=16)
            deadline -= 1

        protocol_members = {
            int(ep.port) - BASE_PORT for ep in
            next(iter(harness.instances.values())).get_memberlist()
        }
        sim_members = {int(i) for i in sim.members()}
        assert protocol_members == sim_members == members, (
            f"divergence after {schedule}: protocol={sorted(protocol_members)} "
            f"sim={sorted(sim_members)} expected={sorted(members)}"
        )
    harness.shutdown()
    return schedule


@pytest.mark.parametrize("fuzz_seed", [11, 12])
def test_cross_plane_fuzzed_schedule(fuzz_seed):
    schedule = run_cross_plane_schedule(fuzz_seed)
    assert schedule


@pytest.mark.parametrize("fuzz_seed", [31, 32])
def test_fuzzed_windowed_schedule_identical_on_mesh(fuzz_seed):
    """The windowed policy under random churn: single-device closed form vs
    the mesh scan lowering, history-identical (the firing rule is shared,
    engine.window_step; this pins the surrounding plumbing too)."""
    script, single_history, _ = generate_and_run(fuzz_seed, fd_policy="windowed")
    assert single_history, f"schedule decided nothing: {script}"
    mesh = make_mesh(8)
    _, mesh_history, _ = generate_and_run(
        fuzz_seed, mesh=mesh, script=script, fd_policy="windowed"
    )
    assert mesh_history == single_history, f"schedule: {script}"


@pytest.mark.parametrize("fuzz_seed", [33, 34])
def test_fuzzed_windowed_schedule_identical_without_speculation(fuzz_seed):
    script, spec_history, spec_sim = generate_and_run(fuzz_seed, fd_policy="windowed")
    assert spec_history, f"schedule decided nothing: {script}"
    _, plain_history, plain_sim = generate_and_run(
        fuzz_seed, script=script, speculate=False, fd_policy="windowed"
    )
    assert spec_history == plain_history, f"schedule: {script}"
    spec_hits = (
        spec_sim.metrics.get("speculation_hits_config_id")
        + spec_sim.metrics.get("speculation_hits_fresh_state")
    )
    assert spec_hits > 0, f"speculation never consumed; schedule: {script}"
    assert plain_sim.metrics.get("speculation_hits_config_id") == 0
    assert plain_sim.metrics.get("speculation_hits_fresh_state") == 0
