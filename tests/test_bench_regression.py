"""Benchmark regression bounds (VERDICT r2 item 5: drift must fail a test,
not pass CI silently).

Protocol time is device-independent and pinned exactly; wall time is bounded
per backend class -- generous enough for machine noise, tight enough that a
structural regression (accidental re-jit per dispatch, losing the early-exit
path, an extra un-batched hop) trips it.
"""

import time

import jax
import numpy as np
import pytest

from rapid_tpu.sim.driver import Simulator

N = 100_000
FAIL_FRACTION = 0.01

# wall budget for the warmed decision dispatch, by backend class; the real
# bench (TPU v5e) measures ~120 ms, CPU hosts ~1-3 s
WALL_BUDGET_S = {"tpu": 0.25, "cpu": 8.0}


@pytest.mark.slow
def test_bench_100k_protocol_and_wall_budget():
    rng = np.random.default_rng(1234)
    sim = Simulator(N, seed=1234)
    victims = rng.choice(N, size=int(N * FAIL_FRACTION), replace=False)
    sim.crash(victims)
    warm = sim.run_until_decision(max_rounds=16, batch=16)
    assert warm is not None and set(warm.cut) == set(victims)
    # protocol-time regression bound, exact: 10 cumulative FD rounds to cross
    # the threshold + 1 vote-delivery round (1000 ms each) + the 100 ms
    # batching window. Any change to round billing shows up here.
    assert warm.virtual_time_ms == 11 * 1000 + 100

    sim2 = Simulator(N, seed=5678)
    sim2.ready()
    victims2 = rng.choice(N, size=int(N * FAIL_FRACTION), replace=False)
    sim2.crash(victims2)
    t0 = time.perf_counter()
    record = sim2.run_until_decision(max_rounds=16, batch=16)
    wall_s = time.perf_counter() - t0
    assert record is not None and set(record.cut) == set(victims2)
    assert record.virtual_time_ms == 11 * 1000 + 100

    platform = jax.devices()[0].platform
    budget = WALL_BUDGET_S.get(platform, WALL_BUDGET_S["cpu"])
    assert wall_s < budget, (
        f"100k bench took {wall_s:.2f}s on {platform}; budget {budget}s "
        f"(r2 bench: 122.8 ms on TPU v5e)"
    )
