"""MessageBatch transport envelope: frozen wire bytes, rolling-upgrade
dialect identity, flush-window sink semantics, and mixed batched/unbatched
cluster interop over both socket transports.

The envelope (types.py MessageBatch, codec tag 25, gRPC oneof field 17) is
the alert/vote batching seam of the event-loop messaging core: broadcasters
coalesce one flush window of per-peer traffic into one frame. These tests pin
the three claims the seam makes: (1) the bytes are stable -- committed golden
frames in tests/golden/batch_envelope_frames.json decode back to identical
values; (2) the envelope rides every wire dialect unchanged (the PR 6
versioned-wire identity matrix, extended to batches); (3) a cluster where
only SOME nodes batch still converges through churn, because receivers
dispatch inner messages exactly as if each had arrived alone.
"""

import json
import time
from pathlib import Path

from golden.batch_fixtures import ALERTS, GRPC_BATCH, TCP_BATCHES, VOTE
from harness import free_port_base

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import (
    HEADER,
    WIRE_VERSION,
    decode,
    encode,
    encode_versioned,
    wire_roundtrip,
)
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.messaging.unicast import BatchingSink
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.types import MessageBatch

GOLDEN = json.loads(
    (Path(__file__).parent / "golden" / "batch_envelope_frames.json").read_text()
)


# ---------------------------------------------------------------------------
# golden bytes
# ---------------------------------------------------------------------------


def test_batch_frame_bytes_golden():
    """Native-codec batch frames serialize byte-for-byte to the committed
    vectors, and the committed bytes decode back to identical envelopes --
    both the msgpack body and the length-prefixed on-socket framing."""
    assert set(GOLDEN["tcp_frames"]) == set(TCP_BATCHES)
    for name, (request_no, batch) in TCP_BATCHES.items():
        entry = GOLDEN["tcp_frames"][name]
        assert entry["request_no"] == request_no, name
        body = encode(request_no, batch)
        assert body.hex() == entry["body_hex"], name
        framed = HEADER.pack(len(body)) + body
        assert framed.hex() == entry["framed_hex"], name
        got_no, got = decode(bytes.fromhex(entry["body_hex"]))
        assert got_no == request_no, name
        assert got == batch, name


def test_batch_grpc_bytes_golden():
    """The gRPC batch envelope serializes deterministically to the committed
    bytes and parses back identical through the programmatic schema."""
    expect_hex = GOLDEN["grpc_requests"]["MessageBatch"]
    got = gt.to_wire_request(GRPC_BATCH).SerializeToString(deterministic=True)
    assert got.hex() == expect_hex
    parsed = gt.from_wire_request(
        MSG["RapidRequest"].FromString(bytes.fromhex(expect_hex))
    )
    assert parsed == GRPC_BATCH


def test_batch_wire_roundtrip_identity_across_versions():
    """PR 6's rolling-upgrade identity matrix, extended to the batch
    envelope: every dialect a mixed-version cluster can speak round-trips
    the batch to the identical value, and the current dialect is byte-parity
    with the plain encoder."""
    for request_no, batch in TCP_BATCHES.values():
        assert encode_versioned(request_no, batch, WIRE_VERSION) == encode(
            request_no, batch
        )
        for version in (0, 1, 2, 7):
            assert wire_roundtrip(batch, version) == batch
        # a NEWER dialect differs on the wire yet decodes to the same value
        assert encode_versioned(
            request_no, batch, WIRE_VERSION + 1
        ) != encode(request_no, batch)


# ---------------------------------------------------------------------------
# flush-window sink semantics
# ---------------------------------------------------------------------------


class _RecordingClient:
    def __init__(self):
        self.sent = []

    def send_message_best_effort(self, recipient, msg):
        self.sent.append((recipient, msg))


def test_batching_sink_coalesces_per_peer_and_singletons_stay_bare():
    """One flush window: a peer owed several messages gets ONE MessageBatch
    in offer order; a peer owed exactly one gets the bare message (an
    unbatched receiver sees no format change on light traffic); nothing
    leaves the sink before the window expires."""
    sched = VirtualScheduler()
    client = _RecordingClient()
    me = Endpoint.from_parts("127.0.0.1", 101)
    busy = Endpoint.from_parts("127.0.0.1", 102)
    quiet = Endpoint.from_parts("127.0.0.1", 103)
    sink = BatchingSink(client, me, sched, window_ms=20)

    sink.offer(busy, VOTE)
    sink.offer(busy, ALERTS)
    sink.offer(quiet, VOTE)
    assert client.sent == []  # in-window: nothing on the wire yet

    sched.run_until_time(19)
    assert client.sent == []
    sched.run_until_time(20)
    assert dict(client.sent) == {
        busy: MessageBatch(sender=me, messages=(VOTE, ALERTS)),
        quiet: VOTE,
    }

    # the window re-arms: a later offer schedules a fresh flush
    sink.offer(quiet, ALERTS)
    sched.run_until_time(40)
    assert client.sent[-1] == (quiet, ALERTS)


# ---------------------------------------------------------------------------
# mixed batched/unbatched cluster interop (both socket transports)
# ---------------------------------------------------------------------------


def _wait_sizes(clusters, want, deadline_s=30):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if all(c.get_membership_size() == want for c in clusters):
            return
        time.sleep(0.05)
    assert [c.get_membership_size() for c in clusters] == [want] * len(clusters)


def _run_mixed_cluster(make_transport):
    """3 live nodes where only nodes 0 and 2 batch broadcasts: join,
    converge, push a concurrent broadcast burst through a batching node's
    real broadcaster so MessageBatch envelopes actually flow to the
    unbatched node (a quiet membership cluster's windows are singletons,
    which the sink deliberately sends bare), then crash the batched node 2
    and converge again. Proves a batching sender interops with an
    unbatched receiver (and vice versa) through a real churn wave."""
    base = free_port_base(4)
    blacklist = set()

    def settings_for(i):
        return Settings(
            failure_detector_interval_ms=50,
            batching_window_ms=10,
            consensus_fallback_base_delay_ms=300,
            broadcast_flush_window_ms=15 if i % 2 == 0 else 0,
        )

    def build(i, seed=None):
        addr = Endpoint.from_parts("127.0.0.1", base + i)
        settings = settings_for(i)
        client, server = make_transport(addr, settings)
        builder = (
            ClusterBuilder(addr)
            .use_settings(settings)
            .set_messaging_client_and_server(client, server)
            .set_edge_failure_detector_factory(
                StaticFailureDetectorFactory(blacklist)
            )
        )
        if seed is None:
            return builder.start()
        return builder.join(seed, timeout=30)

    seed = build(0)
    clusters = [seed]
    try:
        for i in (1, 2):
            clusters.append(build(i, seed.listen_address))
        _wait_sizes(clusters, 3)
        lists = {tuple(c.get_memberlist()) for c in clusters}
        assert len(lists) == 1

        # burst through batching node 0's real broadcaster: 6 probes fan to
        # every member inside one flush window, so the unbatched node 1 must
        # unwrap genuine MessageBatch envelopes via its service dispatch
        from rapid_tpu.types import ProbeMessage

        for _ in range(6):
            clusters[0]._membership_service._broadcaster.broadcast(
                ProbeMessage(sender=clusters[0].listen_address)
            )
        unbatched = clusters[1]._membership_service.metrics
        deadline = time.time() + 30
        while time.time() < deadline:
            snap = unbatched.snapshot()
            if snap.get("messages.MessageBatch", 0) >= 1:
                break
            time.sleep(0.05)
        snap = unbatched.snapshot()
        assert snap.get("messages.MessageBatch", 0) >= 1, snap
        assert snap.get("messages.ProbeMessage", 0) >= 6, snap

        crashed = clusters.pop()  # node 2: a batching node
        blacklist.add(crashed.listen_address)
        crashed.shutdown()
        _wait_sizes(clusters, 2)
        assert {tuple(c.get_memberlist()) for c in clusters} == {
            (clusters[0].listen_address, clusters[1].listen_address)
        } or len({tuple(c.get_memberlist()) for c in clusters}) == 1
    finally:
        for c in clusters:
            c.shutdown()


def test_mixed_batched_unbatched_tcp_cluster_converges():
    def make(addr, settings):
        transport = TcpClientServer(addr, settings)
        return transport, transport

    _run_mixed_cluster(make)


def test_mixed_batched_unbatched_grpc_cluster_converges():
    def make(addr, settings):
        return gt.GrpcClient(addr, settings), gt.GrpcServer(addr)

    _run_mixed_cluster(make)
