"""Pallas fused FD-phase kernel: interpret-mode equivalence with the stock-jax
formulation, both at the kernel level and through a full simulation run.
"""

import os

import numpy as np
import pytest

from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig
from rapid_tpu.sim.pallas_kernels import fd_phase


def _reference(edge_live, observer_up, probe_ok, fd_fail, alerted, threshold):
    fail_event = edge_live & observer_up & ~probe_ok
    fd = fd_fail + fail_event.astype(np.int32)
    new_down = edge_live & observer_up & (fd >= threshold) & ~alerted
    return fd, alerted | new_down, new_down


def test_fd_phase_kernel_matches_reference():
    rng = np.random.default_rng(7)
    c, k = 256, 10
    edge_live = rng.random((c, k)) < 0.9
    observer_up = rng.random((c, k)) < 0.95
    probe_ok = rng.random((c, k)) < 0.5
    fd_fail = rng.integers(0, 12, size=(c, k)).astype(np.int32)
    alerted = rng.random((c, k)) < 0.1

    got = fd_phase(edge_live, observer_up, probe_ok, fd_fail, alerted,
                   threshold=10, interpret=True)
    want = _reference(edge_live, observer_up, probe_ok, fd_fail, alerted, 10)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), w)


def test_fd_phase_odd_capacity_single_block():
    """Capacities not divisible by the block size fall back to one block."""
    rng = np.random.default_rng(8)
    c, k = 333, 10
    args = (
        rng.random((c, k)) < 0.9,
        np.ones((c, k), dtype=bool),
        rng.random((c, k)) < 0.5,
        rng.integers(0, 11, size=(c, k)).astype(np.int32),
        np.zeros((c, k), dtype=bool),
    )
    got = fd_phase(*args, threshold=10, interpret=True)
    want = _reference(*args, 10)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), w)


def test_kernel_matches_engine_fd_phase_through_run():
    """The exemplar kernel's semantics stay in lockstep with the engine's
    stock-jax FD phase: the per-round state an actual run produces feeds the
    kernel (interpret) and the reference identically. (The former pallas_fd
    engine flag was deleted -- measured slower than XLA, see the module
    docstring -- so equivalence is pinned at the kernel contract.)"""
    config = SimConfig(capacity=64)
    sim = Simulator(64, config=config, seed=9)
    sim.crash(np.array([10, 20, 30]))
    rec = sim.run_until_decision(max_rounds=20)
    assert rec is not None
    rng = np.random.default_rng(9)
    c, k = 64, config.k
    fd_fail = np.asarray(sim.state.fd_fail).astype(np.int32)  # exemplar kernel is int32
    alerted = np.asarray(sim.state.alerted)
    edge_live = rng.random((c, k)) < 0.9
    observer_up = np.ones((c, k), dtype=bool)
    probe_ok = rng.random((c, k)) < 0.5
    got = fd_phase(edge_live, observer_up, probe_ok, fd_fail, alerted,
                   threshold=config.fd_threshold, interpret=True)
    want = _reference(edge_live, observer_up, probe_ok, fd_fail, alerted,
                      config.fd_threshold)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), w)


@pytest.mark.skipif(
    not os.environ.get("RAPID_TPU_PALLAS_HW"),
    reason="opt-in hardware run: RAPID_TPU_PALLAS_HW=1 with a real TPU attached "
    "(tests default to the forced-CPU backend, where the mosaic kernel "
    "cannot lower)",
)
def test_hardware_kernel_matches_stock():
    """Bit-identical outputs from the compiled TPU kernel at bench scale.

    Run with: RAPID_TPU_PALLAS_HW=1 JAX_PLATFORMS='' python -m pytest
    tests/test_pallas_kernels.py -k hardware
    """
    import jax

    assert jax.devices()[0].platform != "cpu", "needs a real accelerator"
    rng = np.random.default_rng(11)
    c, k = 102_400, 10
    args = (
        rng.random((c, k)) < 0.99,
        rng.random((c, k)) < 0.98,
        rng.random((c, k)) < 0.9,
        rng.integers(0, 12, size=(c, k)).astype(np.int32),
        rng.random((c, k)) < 0.05,
    )
    got = fd_phase(*args, threshold=10)
    want = _reference(*args, 10)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), w)
