"""Real-socket TCP transport: codec roundtrips, request/response correlation
under load, and a live real-time cluster -- mirroring NettyClientServerTest
(100 clients -> 1 server, 1 client -> N servers) and the tier-3 strategy.
"""

import threading

import pytest

from rapid_tpu import ClusterBuilder, Endpoint, NodeId, Settings
from rapid_tpu.messaging import codec
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    EdgeStatus,
    FastRoundPhase2bMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    NodeStatus,
    Phase1bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Rank,
    Response,
)

EP1 = Endpoint.from_parts("127.0.0.1", 7101)
EP2 = Endpoint.from_parts("127.0.0.1", 7102)
NID = NodeId(123456789, -987654321)


ROUNDTRIP_MESSAGES = [
    PreJoinMessage(sender=EP1, node_id=NID),
    JoinMessage(sender=EP1, node_id=NID, ring_numbers=(0, 3, 9),
                configuration_id=-5, metadata=(("role", b"backend"),)),
    JoinResponse(sender=EP2, status_code=JoinStatusCode.SAFE_TO_JOIN,
                 configuration_id=42, endpoints=(EP1, EP2), identifiers=(NID,),
                 metadata=((EP1, (("k", b"v"),)),)),
    BatchedAlertMessage(sender=EP1, messages=(
        AlertMessage(edge_src=EP1, edge_dst=EP2, edge_status=EdgeStatus.DOWN,
                     configuration_id=7, ring_numbers=(1, 2)),
        AlertMessage(edge_src=EP2, edge_dst=EP1, edge_status=EdgeStatus.UP,
                     configuration_id=7, ring_numbers=(0,), node_id=NID,
                     metadata=(("a", b"b"),)),
    )),
    ProbeMessage(sender=EP1),
    ProbeResponse(NodeStatus.BOOTSTRAPPING),
    FastRoundPhase2bMessage(sender=EP1, configuration_id=9, endpoints=(EP1, EP2)),
    Phase1bMessage(sender=EP2, configuration_id=9, rnd=Rank(2, -7),
                   vrnd=Rank(1, 1), vval=(EP1,)),
    Response(),
]


@pytest.mark.parametrize("msg", ROUNDTRIP_MESSAGES, ids=lambda m: type(m).__name__)
def test_codec_roundtrip(msg):
    request_no, decoded = codec.decode(codec.encode(77, msg))
    assert request_no == 77
    assert decoded == msg


class EchoService:
    """Answers probes; counts messages."""

    def __init__(self):
        self.count = 0
        self.lock = threading.Lock()

    def handle_message(self, msg):
        with self.lock:
            self.count += 1
        if isinstance(msg, ProbeMessage):
            return Promise.completed(ProbeResponse(NodeStatus.OK))
        return Promise.completed(Response())


@pytest.fixture
def port_base():
    # probe a free block: concurrent batteries must not collide
    from harness import free_port_base

    return free_port_base(12)


def test_many_clients_one_server(port_base):
    """NettyClientServerTest.java:41-81 (100 clients -> 1 server)."""
    server_addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = TcpClientServer(server_addr)
    service = EchoService()
    server.set_membership_service(service)
    server.start()
    try:
        clients = [
            TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1 + i))
            for i in range(20)
        ]
        promises = [
            c.send_message(server_addr, ProbeMessage(sender=c.address))
            for c in clients
            for _ in range(5)
        ]
        for p in promises:
            assert p.result(10) == ProbeResponse(NodeStatus.OK)
        assert service.count == 100
        for c in clients:
            c.shutdown()
    finally:
        server.shutdown()


def test_one_client_many_servers(port_base):
    """NettyClientServerTest.java:83-117."""
    servers = []
    for i in range(10):
        addr = Endpoint.from_parts("127.0.0.1", port_base + i)
        server = TcpClientServer(addr)
        server.set_membership_service(EchoService())
        server.start()
        servers.append(server)
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 100))
    try:
        promises = [
            client.send_message(s.address, ProbeMessage(sender=client.address))
            for s in servers
        ]
        for p in promises:
            assert p.result(10) == ProbeResponse(NodeStatus.OK)
    finally:
        client.shutdown()
        for s in servers:
            s.shutdown()


def test_bootstrapping_before_service_wired(port_base):
    """Probes answered BOOTSTRAPPING before set_membership_service
    (GrpcServer.java:83-95 semantics over TCP)."""
    addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = TcpClientServer(addr)
    server.start()
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1))
    try:
        p = client.send_message_best_effort(addr, ProbeMessage(sender=client.address))
        assert p.result(10) == ProbeResponse(NodeStatus.BOOTSTRAPPING)
        # non-probe messages are dropped (sender times out)
        settings = Settings(message_timeout_ms=200)
        fast_client = TcpClientServer(
            Endpoint.from_parts("127.0.0.1", port_base + 2), settings
        )
        p2 = fast_client.send_message_best_effort(
            addr, PreJoinMessage(sender=fast_client.address, node_id=NID)
        )
        with pytest.raises(TimeoutError):
            p2.result(5)
        fast_client.shutdown()
    finally:
        client.shutdown()
        server.shutdown()


def test_real_time_tcp_cluster(port_base):
    """A live 3-node cluster over real sockets and the real-time scheduler:
    join, converge, crash one, converge again."""
    blacklist = set()
    settings = Settings(
        failure_detector_interval_ms=30,
        batching_window_ms=10,
        consensus_fallback_base_delay_ms=200,
    )

    def build(i, seed=None):
        addr = Endpoint.from_parts("127.0.0.1", port_base + i)
        transport = TcpClientServer(addr, settings)
        builder = (
            ClusterBuilder(addr)
            .use_settings(settings)
            .set_messaging_client_and_server(transport, transport)
            .set_edge_failure_detector_factory(StaticFailureDetectorFactory(blacklist))
        )
        if seed is None:
            return builder.start()
        return builder.join(seed, timeout=30)

    seed = build(0)
    c1 = build(1, seed.listen_address)
    c2 = build(2, seed.listen_address)
    try:
        import time

        deadline = time.time() + 30
        while time.time() < deadline:
            if (
                seed.get_membership_size()
                == c1.get_membership_size()
                == c2.get_membership_size()
                == 3
            ):
                break
            time.sleep(0.05)
        assert seed.get_membership_size() == 3
        assert seed.get_memberlist() == c1.get_memberlist() == c2.get_memberlist()

        # crash c2
        blacklist.add(c2.listen_address)
        c2.shutdown()
        deadline = time.time() + 30
        while time.time() < deadline:
            if seed.get_membership_size() == 2 and c1.get_membership_size() == 2:
                break
            time.sleep(0.05)
        assert seed.get_membership_size() == 2
        assert c1.get_membership_size() == 2
    finally:
        seed.shutdown()
        c1.shutdown()


def test_closed_connections_evicted_from_cache(port_base):
    """Regression (ISSUE 15 satellite): a departed peer's closed connection
    must leave the outbound cache -- and with it the per-peer queue-depth
    digest -- instead of leaking a dead _Connection per churned peer. The
    close callback is identity-checked, so only the closed object itself is
    evicted (a dial-race loser can never evict the winner)."""
    import time

    server_addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = TcpClientServer(server_addr)
    server.set_membership_service(EchoService())
    server.start()
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1))
    try:
        try:
            p = client.send_message(server_addr, ProbeMessage(sender=client.address))
            assert p.result(10) == ProbeResponse(NodeStatus.OK)
            with client._conn_lock:
                assert server_addr in client._connections
            digest = client.transport_digest()
            assert f"msg.queue_depth{{peer={server_addr}}}" in digest
        finally:
            server.shutdown()
        # the peer is gone: the reactor notices EOF and the close callback
        # drops the cached connection (bounded wait, real sockets)
        deadline = time.time() + 10
        while time.time() < deadline:
            with client._conn_lock:
                if server_addr not in client._connections:
                    break
            time.sleep(0.01)
        with client._conn_lock:
            assert server_addr not in client._connections
        assert client.transport_digest() == {}
    finally:
        client.shutdown()
