"""Telemetry plane: labeled metrics, hierarchical tracing, exporters.

Golden files under tests/golden/ pin the exporter wire formats
(telemetry_prometheus.txt, telemetry_chrome_trace.json): both builders below
use fixed timestamps/ids so the output is bit-reproducible.
"""

import gc
import json
import pathlib
import threading

import numpy as np

from rapid_tpu.faults import FaultPlan, Nemesis
from rapid_tpu.observability import (
    STABLE_VIEW_BUCKETS_MS,
    Histogram,
    Metrics,
    Span,
    StableViewTimer,
    Tracer,
    chrome_trace,
    prometheus_text,
)
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.settings import Settings
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.types import Endpoint, ProbeMessage, Response

from harness import ClusterHarness

GOLDEN = pathlib.Path(__file__).parent / "golden"


def test_metrics_counters():
    m = Metrics()
    m.incr("a")
    m.incr("a", 2)
    assert m.get("a") == 3
    assert m.get("missing") == 0
    assert m.snapshot() == {"a": 3}
    m.reset()
    assert m.snapshot() == {}


def test_labeled_counters_and_summed_get():
    m = Metrics()
    m.incr("x", at="egress")
    m.incr("x", 2, at="ingress")
    assert m.get("x", at="egress") == 1
    assert m.get("x", at="ingress") == 2
    # unlabeled read sums across label sets: legacy call sites keep working
    # after a counter gains labels
    assert m.get("x") == 3
    assert m.snapshot() == {"x{at=egress}": 1, "x{at=ingress}": 2}


def test_metrics_thread_safety():
    m = Metrics()
    n_threads, n_iters = 8, 1000

    def worker():
        for _ in range(n_iters):
            m.incr("a")
            m.observe("h", 1.0)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.get("a") == n_threads * n_iters
    assert m.histograms()["h"]["count"] == n_threads * n_iters


def test_histogram_bucket_edges_are_le_inclusive():
    h = Histogram((1, 2, 10_000))
    for v in (1, 1.0, 2, 2.5, 10_000, 10_001):
        h.observe(v)
    # value == edge lands IN that bucket (Prometheus le semantics)
    assert h.counts == [2, 1, 2, 1]
    assert h.count == 6
    assert h.sum == 1 + 1.0 + 2 + 2.5 + 10_000 + 10_001
    snap = h.snapshot()
    assert snap["buckets"] == [1, 2, 10_000]
    assert snap["counts"] == [2, 1, 2, 1]


def test_registry_attach_collect_and_absorb():
    parent = Metrics()
    child = Metrics(parent=parent, node="n1")
    child.incr("proposals")
    child.observe("h", 5.0)
    # live child: visible through collect() with const labels merged,
    # invisible to the parent's own get()/snapshot()
    samples = {
        (kind, name, tuple(sorted(labels.items())))
        for kind, name, labels, _ in parent.collect()
    }
    assert ("counter", "proposals", (("node", "n1"),)) in samples
    assert parent.get("proposals") == 0
    # dead child: final samples fold into the parent (finalizer absorb),
    # so a shut-down component's telemetry survives into exports
    del child
    gc.collect()
    assert parent.get("proposals") == 1
    text = prometheus_text(parent)
    assert 'rapid_proposals_total{node="n1"} 1' in text
    assert 'rapid_h_count{node="n1"} 1' in text


def test_tracer_spans_and_summary():
    t = Tracer()
    with t.span("phase", virtual_ms=5, rounds=2) as s:
        pass
    with t.span("phase"):
        pass
    summary = t.summary()
    assert summary["phase"]["count"] == 2
    assert summary["phase"]["total_ms"] >= 0
    assert t.spans[0].attrs == {"rounds": 2}


def test_tracer_ring_overflow_counts_drops():
    t = Tracer(max_spans=5)
    for i in range(8):
        t.event(f"e{i}")
    assert len(t.spans) == 5
    assert t.dropped == 3
    assert [s.name for s in t.spans] == ["e3", "e4", "e5", "e6", "e7"]
    t.reset()
    assert t.spans == [] and t.dropped == 0


def test_span_tree_reconstruction():
    t = Tracer()
    with t.span("outer") as outer:
        with t.span("inner") as inner:
            leaf = t.event("leaf")
    assert inner.parent_id == outer.span_id
    assert leaf.parent_id == inner.span_id
    tree = t.span_tree()
    assert [s.name for s in tree[None]] == ["outer"]
    assert [s.name for s in tree[outer.span_id]] == ["inner"]
    assert [s.name for s in tree[inner.span_id]] == ["leaf"]


def test_child_tracer_spans_absorbed_on_gc():
    root = Tracer(plane="global", track="global")
    child = Tracer(parent=root, plane="protocol", track="n1")
    child.event("cut_detected")
    assert [s.name for s in root.collect_spans()] == ["cut_detected"]
    del child
    gc.collect()
    assert [s.name for s in root.collect_spans()] == ["cut_detected"]
    # the read path drained the dead child's spans into the root's own ring
    assert [s.name for s in root.spans] == ["cut_detected"]


# -- exporter golden files --------------------------------------------------


def _golden_metrics() -> Metrics:
    m = Metrics()
    m.incr("proposals", 3)
    m.incr("nemesis_dropped", 2, at="egress", msg="ProbeMessage")
    m.set_gauge("sim.membership_size", 99, plane="sim")
    m.observe("time_to_stable_view_ms", 120,
              buckets=STABLE_VIEW_BUCKETS_MS, plane="sim")
    m.observe("time_to_stable_view_ms", 4000,
              buckets=STABLE_VIEW_BUCKETS_MS, plane="sim")
    return m


def _golden_tracers():
    root = Tracer(plane="protocol", track="node-1")
    root.spans.append(Span(
        name="view_change", wall_start_s=1.0, wall_end_s=1.002,
        virtual_start_ms=100, virtual_end_ms=150, attrs={"size": 3},
        span_id=1, parent_id=None, plane="protocol", track="node-1",
    ))
    root.spans.append(Span(
        name="cut_detected", wall_start_s=1.0005, wall_end_s=1.0005,
        virtual_start_ms=110, virtual_end_ms=110, attrs={},
        span_id=2, parent_id=1, plane="protocol", track="node-1",
    ))
    sim = Tracer(parent=root, plane="sim", track="sim")
    sim.spans.append(Span(
        name="device_rounds", wall_start_s=1.001, wall_end_s=1.01,
        virtual_start_ms=0, virtual_end_ms=500, attrs={"rounds": 5},
        span_id=3, parent_id=None, plane="sim", track="sim",
    ))
    return root, sim  # sim returned too: the attach is a weakref


def test_prometheus_export_matches_golden():
    assert prometheus_text(_golden_metrics()) == (
        GOLDEN / "telemetry_prometheus.txt"
    ).read_text()


def test_chrome_trace_matches_golden():
    root, _sim = _golden_tracers()
    assert chrome_trace(root) == json.loads(
        (GOLDEN / "telemetry_chrome_trace.json").read_text()
    )


def test_chrome_trace_planes_and_virtual_track():
    root, _sim = _golden_tracers()
    events = chrome_trace(root)["traceEvents"]
    process_names = {
        e["args"]["name"] for e in events if e.get("name") == "process_name"
    }
    assert process_names == {"protocol", "sim", "virtual-time (ms)"}
    # virtual-track copies put ts at virtual_ms x1000
    virtual_pid = next(
        e["pid"] for e in events
        if e.get("name") == "process_name"
        and e["args"]["name"] == "virtual-time (ms)"
    )
    v = [e for e in events if e.get("ph") == "X" and e["pid"] == virtual_pid]
    by_name = {e["name"]: e for e in v}
    assert by_name["view_change"]["ts"] == 100 * 1000
    assert by_name["view_change"]["dur"] == 50 * 1000
    assert by_name["device_rounds"]["ts"] == 0
    assert by_name["device_rounds"]["dur"] == 500 * 1000


# -- per-plane integration --------------------------------------------------


def test_simulator_records_metrics_and_spans():
    sim = Simulator(10, seed=1)
    sim.crash(np.array([3]))
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None
    snap = sim.metrics.snapshot()
    assert snap["view_changes"] == 1
    assert snap["rounds"] >= 10
    assert snap["device_dispatches"] >= 1
    assert sim.tracer.summary()["device_rounds"]["count"] >= 1


def test_virtual_and_wall_time_span_parity():
    """Simulator spans carry BOTH clocks, and the two planes' stable-view
    histograms share one bucket definition, so distributions line up."""
    sim = Simulator(10, seed=1)
    sim.crash(np.array([3]))
    assert sim.run_until_decision(max_rounds=40) is not None
    by_name = {}
    for s in sim.tracer.spans:
        by_name.setdefault(s.name, []).append(s)
    for name in ("device_rounds", "view_change"):
        for s in by_name[name]:
            assert s.wall_end_s >= s.wall_start_s
            assert s.virtual_start_ms is not None
            assert s.virtual_end_ms >= s.virtual_start_ms
    sim_hist = sim.metrics.histogram("time_to_stable_view_ms", plane="sim")
    assert sim_hist is not None and sim_hist["count"] == 1
    # protocol plane records onto the identical bucket edges
    proto = Metrics()
    timer = StableViewTimer(proto, "protocol", clock=lambda: 0)
    timer.detection(0)
    timer.decision(7)
    timer.view_installed(12)
    proto_hist = proto.histogram("time_to_stable_view_ms", plane="protocol")
    assert proto_hist["buckets"] == sim_hist["buckets"]
    assert proto_hist["buckets"] == list(STABLE_VIEW_BUCKETS_MS)
    assert proto_hist["sum"] == 12.0


def test_stable_view_timer_phases():
    m = Metrics()
    timer = StableViewTimer(m, "protocol", clock=lambda: 0)
    timer.view_installed(5)  # nothing detected: no-op (initial view)
    assert m.histograms() == {}
    timer.detection(10)
    timer.detection(99)  # first detection sticks
    timer.decision(40)
    timer.decision(60)  # last decision wins (parked decision re-applied)
    timer.view_installed(70)
    hists = m.histograms()
    assert hists["latency.detection_to_decision_ms{plane=protocol}"]["sum"] == 50
    assert hists["latency.decision_to_view_ms{plane=protocol}"]["sum"] == 10
    assert hists["time_to_stable_view_ms{plane=protocol}"]["sum"] == 60
    # the cycle reset: a second view change needs a fresh detection
    timer.view_installed(80)
    assert hists["time_to_stable_view_ms{plane=protocol}"]["count"] == 1


def test_service_metrics():
    h = ClusterHarness(seed=1)
    try:
        seed = h.start_seed()
        h.join(1)
        h.wait_and_verify_agreement(2)
        snap = seed._membership_service.metrics.snapshot()
        assert snap["view_changes"] >= 1
        assert snap["proposals"] >= 1
        assert snap["alerts_enqueued"] >= 1
        assert any(k.startswith("messages.") for k in snap)
    finally:
        h.shutdown()


def test_service_traces_protocol_phases():
    h = ClusterHarness(seed=1)
    try:
        seed = h.start_seed()
        h.join(1)
        h.wait_and_verify_agreement(2)
        tracer = seed._membership_service.tracer
        names = {s.name for s in tracer.spans}
        assert {"alert_enqueued", "proposal", "view_change"} <= names
        hist = seed._membership_service.metrics.histogram(
            "time_to_stable_view_ms", plane="protocol"
        )
        assert hist is not None and hist["count"] >= 1
    finally:
        h.shutdown()


def test_nemesis_counters_labeled_in_prometheus_export():
    a = Endpoint.from_parts("10.0.0.1", 50)
    b = Endpoint.from_parts("10.0.0.2", 50)
    sched = VirtualScheduler()
    metrics = Metrics()
    nem = Nemesis(
        FaultPlan(seed=1).partition_one_way(dst=b), sched, metrics=metrics
    ).arm(0)

    class _Sink:
        def send_message_best_effort(self, remote, msg):
            return Promise.completed(Response())

        send_message = send_message_best_effort

        def shutdown(self):
            pass

    client = nem.client(_Sink(), address=a, settings=Settings())
    client.send_message_best_effort(b, ProbeMessage(sender=a))
    text = prometheus_text(metrics)
    assert (
        'rapid_nemesis_dropped_total{at="egress",msg="ProbeMessage"} 1'
        in text
    )
