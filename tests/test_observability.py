"""Tracing/metrics subsystem."""

import numpy as np

from rapid_tpu.observability import Metrics, Tracer
from rapid_tpu.sim.driver import Simulator

from harness import ClusterHarness


def test_metrics_counters():
    m = Metrics()
    m.incr("a")
    m.incr("a", 2)
    assert m.get("a") == 3
    assert m.get("missing") == 0
    assert m.snapshot() == {"a": 3}
    m.reset()
    assert m.snapshot() == {}


def test_tracer_spans_and_summary():
    t = Tracer()
    with t.span("phase", virtual_ms=5, rounds=2) as s:
        pass
    with t.span("phase"):
        pass
    summary = t.summary()
    assert summary["phase"]["count"] == 2
    assert summary["phase"]["total_ms"] >= 0
    assert t.spans[0].attrs == {"rounds": 2}


def test_simulator_records_metrics_and_spans():
    sim = Simulator(10, seed=1)
    sim.crash(np.array([3]))
    rec = sim.run_until_decision(max_rounds=40)
    assert rec is not None
    snap = sim.metrics.snapshot()
    assert snap["view_changes"] == 1
    assert snap["rounds"] >= 10
    assert snap["device_dispatches"] >= 1
    assert sim.tracer.summary()["device_rounds"]["count"] >= 1


def test_service_metrics():
    h = ClusterHarness(seed=1)
    try:
        seed = h.start_seed()
        h.join(1)
        h.wait_and_verify_agreement(2)
        snap = seed._membership_service.metrics.snapshot()
        assert snap["view_changes"] >= 1
        assert snap["proposals"] >= 1
        assert snap["alerts_enqueued"] >= 1
        assert any(k.startswith("messages.") for k in snap)
    finally:
        h.shutdown()
