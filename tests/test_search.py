"""Nemesis-search suite (ROADMAP item 4, "Jepsen in a box"): plan JSON
round-trips, invariant-checker kill-tests, generator/probe determinism,
the guided-beats-unguided coverage contract, and the end-to-end bug demo
(flag on -> search finds it -> shrinker minimizes it -> pinned corpus
file reproduces it; flag off -> clean).

The RAPID_BUG_NEWROW_SYNC flag re-introduces the historical serving
promote-sync hole (new-row sync targets + no graft quarantine); the
search must rediscover it from scratch and shrink the witness to a
handful of rules.
"""

import json
import os
import pathlib
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rapid_tpu.faults import FaultPlan
from rapid_tpu.search.checkers import (
    INVARIANTS,
    ClientOp,
    InvariantViolation,
    check_config_parity,
    check_fingerprint_agreement,
    check_hierarchy_agreement,
    check_leader_agreement,
    check_linearizable_history,
    check_linearizable_single_client,
    check_view_agreement,
)
from rapid_tpu.search.coverage import (
    coverage_from_fault_actions,
    coverage_from_journal,
    transitions,
)
from rapid_tpu.search.fabric import ServingFabric
from rapid_tpu.search.generator import GEN_RULES, PlanGenerator
from rapid_tpu.search.hunt import Hunter, pin_to_file
from rapid_tpu.search.runner import run_probe
from rapid_tpu.types import PutAck

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = sorted((REPO / "scenarios" / "corpus").glob("*.json"))

ENDPOINTS = [f"node:{7000 + i}" for i in range(5)]

# the hand-minimized witness of the historical promote-sync bug: starve
# one replica of Puts, evict a leader, and mute Get quorum traffic to the
# fresh replica -- with the flag on, the promoted leader syncs from the
# new row and crowns the starved copy
BUG_PLAN = {"seed": 7, "rules": [
    {"type": "DropRule", "at": "egress", "windows": [[0, None]],
     "src": None, "dst": "node:7003", "msg_types": ["Put"],
     "probability": 1.0},
    {"type": "PartitionRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7000", "msg_types": None},
    {"type": "DropRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7002", "msg_types": ["Get"],
     "probability": 1.0},
]}
BUG_SPEC = {"harness": "engine", "n": 5, "partitions": 16, "replicas": 3,
            "horizon_ms": 4000, "ops": 40, "keys": 6, "plan": BUG_PLAN}

# churn + double eviction with no Get muting: the plan that exercises the
# graft quarantine (handoff acquirers abstain from quorums until a
# majority of the pre-join row is merged in)
GRAFT_PLAN = {"seed": 7, "rules": [
    {"type": "DropRule", "at": "egress", "windows": [[0, None]],
     "src": None, "dst": "node:7003", "msg_types": ["Put"],
     "probability": 1.0},
    {"type": "PartitionRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7000", "msg_types": None},
    {"type": "SlowNodeRule", "at": "egress", "windows": [[2000, None]],
     "src": None, "dst": "node:7001", "msg_types": None,
     "response_delay_ms": 200},
]}


def probe_spec(plan_json, **overrides):
    spec = dict(BUG_SPEC)
    spec["plan"] = plan_json
    spec.update(overrides)
    return spec


# ---------------------------------------------------------------------------
# FaultPlan JSON round-trip (the corpus file format)
# ---------------------------------------------------------------------------


class TestPlanJson:
    def test_round_trip_is_identity(self):
        from rapid_tpu.types import Endpoint, ProbeMessage

        node = Endpoint.from_string("node:7003")
        plan = (
            FaultPlan(seed=19)
            .drop(0.5, dst=node, windows=((100, 900),))
            .partition_one_way(dst=node, windows=((2000, None),))
            .slow_node(Endpoint.from_string("node:7001"), 250)
            .clock_skew(Endpoint.from_string("node:7000"),
                        offset_ms=200, rate=1.25)
            .lossy_link(0.05, msg_types=(ProbeMessage,))
        )
        data = plan.to_json()
        rebuilt = FaultPlan.from_json(data)
        assert rebuilt.to_json() == data
        assert rebuilt.seed == plan.seed
        assert len(rebuilt.rules) == len(plan.rules)

    def test_round_trip_survives_json_text(self):
        plan = FaultPlan.from_json(BUG_PLAN)
        assert FaultPlan.from_json(
            json.loads(json.dumps(plan.to_json()))
        ).to_json() == plan.to_json()

    def test_load_rejects_unknown_rule_type(self):
        with pytest.raises(ValueError, match="unknown rule type"):
            FaultPlan.from_json({"seed": 1, "rules": [
                {"type": "NopeRule", "at": "egress", "windows": [[0, None]],
                 "src": None, "dst": None, "msg_types": None}]})

    def test_load_reruns_builder_validation(self):
        # construction-time checks re-run on load: a corpus file cannot
        # smuggle in a window or probability the builders would reject
        bad_window = {"type": "DropRule", "at": "egress",
                      "windows": [[5, 3]], "src": None, "dst": None,
                      "msg_types": None, "probability": 0.5}
        with pytest.raises(ValueError, match="can never fire"):
            FaultPlan.from_json({"seed": 1, "rules": [bad_window]})
        bad_prob = dict(bad_window, windows=[[0, None]], probability=1.5)
        with pytest.raises((ValueError, AssertionError)):
            FaultPlan.from_json({"seed": 1, "rules": [bad_prob]})
        with pytest.raises(ValueError, match="without a topology"):
            FaultPlan.from_json({"seed": 1, "rules": [],
                                 "topology_slots": {"node:7000": 0}})


# ---------------------------------------------------------------------------
# invariant-checker kill-tests: each crafted history must be rejected by
# exactly the intended invariant (and its benign twin accepted)
# ---------------------------------------------------------------------------


def _op(client, op, key, value, version, status, invoke, complete):
    return ClientOp(client, op, key, value, version, status, invoke, complete)


class TestCheckerKills:
    def test_lost_acked_write(self):
        history = [
            _op("a", "put", b"k", b"v1", 1, PutAck.STATUS_OK, 0, 100),
            _op("b", "get", b"k", b"", 0, PutAck.STATUS_NOT_FOUND, 200, 210),
        ]
        with pytest.raises(InvariantViolation) as err:
            check_linearizable_history(history)
        assert err.value.invariant == "linearizability"
        assert "lost acked write" in err.value.detail

    def test_stale_read(self):
        history = [
            _op("a", "put", b"k", b"v1", 1, PutAck.STATUS_OK, 0, 50),
            _op("a", "put", b"k", b"v2", 2, PutAck.STATUS_OK, 60, 100),
            _op("b", "get", b"k", b"v1", 1, PutAck.STATUS_OK, 200, 210),
        ]
        with pytest.raises(InvariantViolation) as err:
            check_linearizable_history(history)
        assert err.value.invariant == "linearizability"
        assert "stale read" in err.value.detail

    def test_double_leader_write(self):
        history = [
            _op("a", "put", b"k", b"va", 3, PutAck.STATUS_OK, 0, 50),
            _op("b", "put", b"k", b"vb", 3, PutAck.STATUS_OK, 10, 60),
        ]
        with pytest.raises(InvariantViolation) as err:
            check_linearizable_history(history)
        assert err.value.invariant == "linearizability"
        assert "double-leader" in err.value.detail

    def test_torn_read(self):
        history = [
            _op("a", "put", b"k", b"real", 1, PutAck.STATUS_OK, 0, 50),
            _op("b", "get", b"k", b"fake", 1, PutAck.STATUS_OK, 100, 110),
        ]
        with pytest.raises(InvariantViolation) as err:
            check_linearizable_history(history)
        assert err.value.invariant == "linearizability"
        assert "torn read" in err.value.detail

    def test_non_monotonic_reads(self):
        history = [
            _op("a", "put", b"k", b"v2", 2, PutAck.STATUS_OK, 0, 50),
            _op("b", "get", b"k", b"v2", 2, PutAck.STATUS_OK, 60, 70),
            _op("c", "get", b"k", b"v2", 1, PutAck.STATUS_OK, 80, 90),
        ]
        with pytest.raises(InvariantViolation) as err:
            check_linearizable_history(history)
        assert err.value.invariant == "linearizability"

    def test_benign_history_passes(self):
        history = [
            _op("a", "put", b"k", b"v1", 1, PutAck.STATUS_OK, 0, 50),
            _op("b", "get", b"k", b"v1", 1, PutAck.STATUS_OK, 60, 70),
            _op("a", "put", b"k", b"v2", 2, PutAck.STATUS_OK, 80, 120),
            _op("b", "get", b"k", b"v2", 2, PutAck.STATUS_OK, 130, 140),
            # a read that raced the first put may legally miss it
            _op("c", "get", b"k", b"", 0, PutAck.STATUS_NOT_FOUND, 10, 20),
            # retried puts carry no obligation
            _op("c", "put", b"k", b"lost", 0, PutAck.STATUS_RETRY, 150, 160),
        ]
        assert check_linearizable_history(history) is None

    def test_view_agreement(self):
        with pytest.raises(InvariantViolation) as err:
            check_view_agreement({"n0": (1, 7), "n1": (1, 8)})
        assert err.value.invariant == "view-agreement"
        assert check_view_agreement({"n0": (1, 7), "n1": (1, 7)}) is None

    def test_leader_agreement(self):
        with pytest.raises(InvariantViolation) as err:
            check_leader_agreement({
                "n0": ([4], ["node:7000"]), "n1": ([4], ["node:7001"]),
            })
        assert err.value.invariant == "view-agreement"
        assert "split-brain" in err.value.detail
        assert check_leader_agreement({
            "n0": ([4], ["node:7000"]), "n1": ([4], ["node:7000"]),
        }) is None

    def test_config_parity(self):
        with pytest.raises(InvariantViolation) as err:
            check_config_parity(11, 12)
        assert err.value.invariant == "config-parity"
        assert check_config_parity(11, 11) is None

    def test_fingerprint_agreement(self):
        diverged = [(3, "n0", "aaaa"), (3, "n1", "bbbb"), (4, "n0", "cccc")]
        with pytest.raises(InvariantViolation) as err:
            check_fingerprint_agreement(diverged)
        assert err.value.invariant == "fingerprint-agreement"
        assert check_fingerprint_agreement(
            [(3, "n0", "aaaa"), (3, "n1", "aaaa")]
        ) is None

    def test_hierarchy_agreement(self):
        agreeing = {
            "n0": ((0, 1), ("a:1", "b:2"), 42),
            "n1": ((0, 1), ("a:1", "b:2"), 42),
        }
        assert check_hierarchy_agreement(agreeing) is None
        with pytest.raises(InvariantViolation) as err:
            check_hierarchy_agreement({
                "n0": ((0, 1), ("a:1", "b:2"), 42),
                "n1": ((0, 1), ("a:1", "b:2"), 43),
            })
        assert err.value.invariant == "hierarchy-agreement"
        assert "diverged" in err.value.detail
        # two live leaders for one cell is split-brain even when the
        # composed fingerprints happen to coincide
        with pytest.raises(InvariantViolation) as err:
            check_hierarchy_agreement({
                "n0": ((0,), ("a:1",), 42),
                "n1": ((0,), ("b:2",), 42),
            })
        assert "two live leaders for cell 0" in err.value.detail

    def test_violation_tags_are_closed_set(self):
        with pytest.raises(AssertionError):
            InvariantViolation("made-up-invariant", "nope")
        v = InvariantViolation("linearizability", "witness")
        assert v.to_json() == {
            "invariant": "linearizability", "detail": "witness",
        }
        assert set(v.to_json()["invariant"].split()) <= set(INVARIANTS)


class TestSingleClientPromotion:
    """check_linearizable_single_client moved out of tests/test_serving.py
    into the checker module; the serving suite re-imports it from there."""

    def test_reexport_is_the_same_function(self):
        from rapid_tpu.search import checkers

        assert (check_linearizable_single_client
                is checkers.check_linearizable_single_client)

    def test_single_client_accepts_and_rejects(self):
        ok = [
            ("put", b"k", b"v1", 1, PutAck.STATUS_OK),
            ("get", b"k", b"v1", 1, PutAck.STATUS_OK),
        ]
        assert check_linearizable_single_client(ok) is None
        stale = ok + [
            ("put", b"k", b"v2", 2, PutAck.STATUS_OK),
            ("get", b"k", b"v1", 1, PutAck.STATUS_OK),
        ]
        with pytest.raises(AssertionError, match="stale read"):
            check_linearizable_single_client(stale)


# ---------------------------------------------------------------------------
# generator: determinism + validity + reachability
# ---------------------------------------------------------------------------


class TestGenerator:
    def test_fresh_is_deterministic(self):
        a = PlanGenerator(3, ENDPOINTS, 4000)
        b = PlanGenerator(3, ENDPOINTS, 4000)
        assert [a.fresh(i) for i in range(12)] == [
            b.fresh(i) for i in range(12)
        ]

    def test_mutate_is_deterministic(self):
        a = PlanGenerator(3, ENDPOINTS, 4000)
        b = PlanGenerator(3, ENDPOINTS, 4000)
        base = a.fresh(0)
        assert [a.mutate(base, i) for i in range(12)] == [
            b.mutate(base, i) for i in range(12)
        ]

    def test_every_sample_passes_builder_validation(self):
        for harness in ("engine", "sim"):
            gen = PlanGenerator(5, ENDPOINTS, 4000, harness=harness)
            spec = gen.fresh(0)
            for i in range(30):
                FaultPlan.from_json(spec)  # raises on an invalid emission
                spec = gen.mutate(spec, i) if i % 2 else gen.fresh(i)

    def test_emitted_types_stay_inside_gen_rules(self):
        gen = PlanGenerator(9, ENDPOINTS, 4000)
        seen = {
            rule["type"]
            for i in range(60) for rule in gen.fresh(i)["rules"]
        }
        assert seen <= set(GEN_RULES)
        # the sampler is not degenerate: a healthy slice of the catalog
        # appears within a small sample
        assert len(seen) >= 5

    def test_cell_partition_is_reachable_in_both_harnesses(self):
        for harness in ("engine", "sim"):
            gen = PlanGenerator(7, ENDPOINTS, 20_000, harness=harness)
            specs = [
                rule
                for i in range(300) for rule in gen.fresh(i)["rules"]
                if rule["type"] == "CellPartitionRule"
            ]
            assert specs, harness
            for rule in specs:
                assert 2 <= rule["cells"] <= 8
                assert 0 <= rule["cell"] < rule["cells"]


# ---------------------------------------------------------------------------
# probes: determinism + the graft quarantine under churned double eviction
# ---------------------------------------------------------------------------


class TestProbes:
    def test_engine_probe_is_deterministic(self):
        first = run_probe(BUG_SPEC)
        second = run_probe(BUG_SPEC)
        assert first.coverage == second.coverage
        assert first.violations == second.violations
        assert first.info == second.info

    def test_probe_coverage_has_catalog_transitions(self):
        result = run_probe(BUG_SPEC)
        kinds = {s[1] for s in result.coverage if s[0] == "kind"}
        assert {"view_install", "handoff_started", "kicked"} <= kinds
        assert transitions(result.coverage)

    def test_graft_quarantine_under_double_eviction(self):
        """Churn + a second eviction: every mid-stream acquirer must pull a
        majority of its pre-join row before answering quorums (the fix for
        the chained-view staleness hole this search found), and the run
        must be linearizable with the fix in."""
        fabric = ServingFabric(
            FaultPlan.from_json(GRAFT_PLAN), n=5, partitions=16, replicas=3,
        )
        fabric.run(5000, 40, keys=6)
        events = [e["kind"] for e in fabric.journal()]
        assert events.count("kicked") == 2, "plan must evict twice"
        grafts = [
            e for e in fabric.journal()
            if e["kind"] == "serving_sync" and e["detail"].get("graft")
        ]
        assert grafts, "double eviction must route copies through the graft"
        assert fabric.metrics.get("serving.reconciled_replicas") == len(grafts)
        result = run_probe(probe_spec(GRAFT_PLAN, horizon_ms=5000))
        assert not result.violations, result.violations

    def test_fault_action_coverage_feeds_guidance(self):
        result = run_probe(BUG_SPEC)
        fault_signals = {s for s in result.coverage if s[0] == "fault"}
        assert any(name.startswith("nemesis_dropped")
                   for _, name in fault_signals)
        # the extractor ignores non-nemesis and zero-valued series
        assert coverage_from_fault_actions(
            {"nemesis_dropped{at=egress}": 2.0, "nemesis_slowed": 0.0,
             "view_changes": 5.0}
        ) == frozenset({("fault", "nemesis_dropped{at=egress}")})

    def test_journal_coverage_bigram_extraction(self):
        journal = [
            {"seq": i, "kind": kind}
            for i, kind in enumerate(
                ("fd_signal", "view_install", "not-in-catalog", "kicked")
            )
        ]
        cov = coverage_from_journal(journal)
        assert ("edge", "fd_signal", "view_install") in cov
        assert ("kind", "kicked") in cov
        assert ("edge", "fd_signal", "view_install") in transitions(cov)
        # edges through unknown kinds are not catalog transitions
        assert all("not-in-catalog" not in t for t in transitions(cov))


# ---------------------------------------------------------------------------
# the hunter: budget, determinism, and the guided-coverage contract
# ---------------------------------------------------------------------------


class TestHunter:
    def test_budgeted_hunt_runs_clean_without_the_bug(self):
        report = Hunter(seed=0, budget=200, harness="engine").run()
        assert report.probes == 200
        assert report.violations == []
        assert report.corpus, "a 200-probe hunt must grow a corpus"
        assert report.transition_count() >= 10

    def test_hunt_is_deterministic_per_seed(self):
        a = Hunter(seed=5, budget=15, harness="engine", shrink=False).run()
        b = Hunter(seed=5, budget=15, harness="engine", shrink=False).run()
        assert a.to_json() == b.to_json()
        assert a.coverage == b.coverage
        assert a.corpus == b.corpus

    def test_guided_visits_more_transitions_than_unguided(self):
        """The coverage-bias contract: at the same budget and seed, mutating
        coverage-fresh corpus members must visit strictly more distinct
        EVENT_CATALOG transitions than blind fresh sampling. The budget
        scales with GEN_RULES: every rule added to the catalog spreads the
        mutation budget thinner, so the separation needs a few more probes
        to express itself than it did at the original 13-rule catalog."""
        guided = Hunter(seed=13, budget=60, harness="engine",
                        guided=True, shrink=False).run()
        unguided = Hunter(seed=13, budget=60, harness="engine",
                          guided=False, shrink=False).run()
        assert guided.transition_count() > unguided.transition_count(), (
            f"guided {guided.transition_count()} vs "
            f"unguided {unguided.transition_count()}"
        )


# ---------------------------------------------------------------------------
# end-to-end bug demo: flag on -> found -> shrunk -> pinned -> reproduces
# ---------------------------------------------------------------------------


class TestBugDemo:
    def test_flagged_bug_reproduces_and_fix_holds(self, monkeypatch):
        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        buggy = run_probe(BUG_SPEC)
        assert {v["invariant"] for v in buggy.violations} == {
            "linearizability"
        }
        monkeypatch.delenv("RAPID_BUG_NEWROW_SYNC")
        assert not run_probe(BUG_SPEC).violations

    def test_search_finds_shrinks_and_pins_the_bug(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        report = Hunter(seed=12, budget=120, harness="engine",
                        shrink_budget=150).run()
        assert report.violations, "the search must rediscover the bug"
        assert report.pinned
        pin = report.pinned[0]
        assert "linearizability" in pin["kinds"]
        shrunk_rules = pin["spec"]["plan"]["rules"]
        assert len(shrunk_rules) <= 3, shrunk_rules

        path = tmp_path / "pin.json"
        pin_to_file(pin, str(path), "pin", "test pin")
        artifact = json.loads(path.read_text())
        FaultPlan.from_json(artifact["plan"])  # validation re-runs on load
        probe = {
            k: v for k, v in artifact.items()
            if k not in ("name", "description", "expect")
        }
        assert run_probe(probe).violated, "pinned plan must reproduce"
        monkeypatch.delenv("RAPID_BUG_NEWROW_SYNC")
        assert not run_probe(probe).violated, "fix must hold on the pin"


# ---------------------------------------------------------------------------
# the pinned corpus + scenarios.py integration
# ---------------------------------------------------------------------------


class TestCorpus:
    def test_corpus_exists(self):
        assert CORPUS, "scenarios/corpus must hold at least one pinned plan"

    @pytest.mark.parametrize(
        "path", CORPUS, ids=[p.stem for p in CORPUS]
    )
    def test_pin_loads_and_stays_green(self, path):
        artifact = json.loads(path.read_text())
        assert set(artifact["expect"]["invariants"]) <= set(INVARIANTS)
        FaultPlan.from_json(artifact["plan"])
        probe = {
            k: v for k, v in artifact.items()
            if k not in ("name", "description", "expect")
        }
        result = run_probe(probe)
        assert not result.violations, (
            f"regression: pinned plan {path.name} violates "
            f"{[v['invariant'] for v in result.violations]} again"
        )

    @pytest.mark.parametrize(
        "path", CORPUS, ids=[p.stem for p in CORPUS]
    )
    def test_pin_still_witnesses_the_flagged_bug(self, path, monkeypatch):
        artifact = json.loads(path.read_text())
        probe = {
            k: v for k, v in artifact.items()
            if k not in ("name", "description", "expect")
        }
        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        result = run_probe(probe)
        assert {v["invariant"] for v in result.violations} == set(
            artifact["expect"]["invariants"]
        )

    def test_scenarios_registry_carries_the_corpus(self):
        import scenarios

        names = [f"corpus-{p.stem}" for p in CORPUS]
        for name in names:
            assert name in scenarios.REGISTRY
            assert name in scenarios.BATTERY
            fn, params = scenarios.REGISTRY[name]
            assert fn is scenarios.scenario_pinned_plan
            assert pathlib.Path(params["path"]).exists()
