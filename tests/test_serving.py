"""Serving plane: replicated Get/Put KV over placement + handoff.

Four layers under test, mirroring how the subsystem is built:

- the pure core (serving/kv.py): key->partition routing and the
  deterministic KV blob codec whose byte-stability is what lets handoff
  fingerprints agree across replicas;
- the wire surface: Get/Put/PutAck through both the msgpack codec (tags
  22-24) and the gRPC oneofs, plus the serving columns of
  ClusterStatusResponse;
- the live engine (serving/engine.py) on the in-process virtual-time
  harness: quorum-acked writes, leader reads, read-your-writes across a
  view change with handoff in flight, leader failover mid-write under
  nemesis drop/duplicate/reorder on the replication wire;
- the simulator mirror (sim/driver.py enable_serving): virtual-time
  billed closed-loop ops, byte-identical metric trajectories across
  reruns, zero lost acknowledged writes across churn, and a
  linearizability smoke over a recorded Get/Put history (the seed for
  ROADMAP item 5's checker).
"""

import importlib.util
import os

import numpy as np
import pytest

from rapid_tpu import Endpoint, InMemoryPartitionStore
from rapid_tpu.faults import FaultPlan
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import decode, encode
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.serving import (
    SERVING_SEED,
    decode_kv,
    encode_kv,
    partition_of,
)
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.types import (
    ClusterStatusResponse,
    Get,
    Put,
    PutAck,
)

from harness import ClusterHarness

PLACEMENT = {"partitions": 16, "replicas": 3, "seed": 5}


# ---------------------------------------------------------------------- #
# Pure core
# ---------------------------------------------------------------------- #

def test_partition_of_is_stable_and_bounded():
    seen = set()
    for i in range(512):
        p = partition_of(b"key-%d" % i, 16)
        assert 0 <= p < 16
        seen.add(p)
    assert len(seen) == 16, "512 keys must touch every one of 16 partitions"
    assert partition_of(b"abc", 16) == partition_of(b"abc", 16)
    with pytest.raises(ValueError):
        partition_of(b"abc", 0)
    assert SERVING_SEED == 0x5E41  # routing constant is part of the wire


def test_kv_blob_codec_is_deterministic():
    kv = {b"b": (2, b"vb"), b"a": (1, b"va"), b"c": (9, b"")}
    blob = encode_kv(kv)
    # insertion order must not leak into the bytes: fingerprint agreement
    # across replicas depends on it
    assert blob == encode_kv(dict(sorted(kv.items(), reverse=True)))
    assert decode_kv(blob) == kv
    assert decode_kv(None) == {}
    assert decode_kv(encode_kv({})) == {}


# ---------------------------------------------------------------------- #
# Wire surface
# ---------------------------------------------------------------------- #

def test_serving_messages_survive_both_wires():
    """Get/Put/PutAck round-trip bit-exactly through the msgpack codec
    (tags 22-24) and the gRPC oneofs, optional leader hint included."""
    ep = Endpoint.from_parts("10.1.2.3", 4567)
    hint = Endpoint.from_parts("10.9.9.9", 1111)
    get = Get(sender=ep, key=b"\x00k", quorum=2, map_version=-3)
    put = Put(sender=ep, key=b"k", value=b"\xffv", request_id=77,
              replicate=1, version=12, map_version=5)
    ack = PutAck(sender=ep, status=PutAck.STATUS_NOT_LEADER, key=b"k",
                 value=b"v", version=3, request_id=77, leader=hint,
                 map_version=5)
    for i, msg in enumerate((get, put)):
        assert decode(encode(i, msg)) == (i, msg)
        wire = gt.to_wire_request(msg).SerializeToString(deterministic=True)
        assert gt.from_wire_request(
            MSG["RapidRequest"].FromString(wire)
        ) == msg
    assert decode(encode(9, ack)) == (9, ack)
    wire = gt.to_wire_response(ack).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == ack
    bare = PutAck(sender=ep)  # no leader hint: Optional[Endpoint] path
    assert decode(encode(0, bare)) == (0, bare)
    wire = gt.to_wire_response(bare).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == bare and back.leader is None


def test_status_serving_fields_survive_both_wires():
    """The serving columns of ClusterStatusResponse (gRPC fields 21-25)
    round-trip through both wires; an old frame parses to the defaults."""
    r = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=9,
        membership_size=3, serving_gets=4, serving_puts=7,
        serving_put_acks=11, serving_partitions=(0, 3),
        serving_leaders=("h:1", "h:2"),
    )
    assert decode(encode(4, r)) == (4, r)
    wire = gt.to_wire_response(r).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == r
    old = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=1,
        membership_size=2,
    )
    wire = gt.to_wire_response(old).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == old and back.serving_partitions == ()


# ---------------------------------------------------------------------- #
# Live engine on the virtual-time harness
# ---------------------------------------------------------------------- #

def _await(h: ClusterHarness, promise, timeout_ms: int = 600_000):
    ok = h.scheduler.run_until(promise.done, timeout_ms=timeout_ms)
    assert ok, "serving op did not complete in bounded virtual time"
    assert promise.exception() is None, promise.exception()
    return promise.peek()


def _put_until_acked(h, cluster, key, value, attempts: int = 300):
    """Client-level retry loop: the engine's internal retries give up fast
    (RETRY ack) while a failed leader is still undetected; the caller keeps
    re-issuing, which is what a real client does. Virtual time advances on
    every attempt, so detection always eventually lands."""
    for _ in range(attempts):
        ack = _await(h, cluster.serving_put(key, value))
        if ack.status == PutAck.STATUS_OK:
            return ack
    raise AssertionError(f"put {key!r} never acked in {attempts} attempts")


def _get_until_found(h, cluster, key, attempts: int = 300):
    for _ in range(attempts):
        ack = _await(h, cluster.serving_get(key))
        if ack.status == PutAck.STATUS_OK:
            return ack
    raise AssertionError(f"get {key!r} never resolved in {attempts} attempts")


def test_use_serving_requires_placement_and_handoff():
    h = ClusterHarness(seed=1)
    try:
        with pytest.raises(ValueError):
            h.start_seed(0, serving=True)
    finally:
        h.shutdown()
    h = ClusterHarness(seed=1)
    try:
        with pytest.raises(ValueError):
            h.start_seed(0, placement=PLACEMENT, serving=True)
    finally:
        h.shutdown()


def test_quorum_write_read_your_writes_across_view_change():
    """The battery headline: quorum-acked writes stay readable from every
    surviving member across a view change whose handoff sessions are still
    in flight (a delay plan keeps the transfers slow), with reads falling
    back to quorum reads during leader churn."""
    from rapid_tpu.types import HandoffRequest

    plan = FaultPlan(seed=4).delay(base_ms=300, msg_types=(HandoffRequest,))
    h = ClusterHarness(seed=3).with_faults(plan)
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant while the cluster forms
    try:
        h.start_seed(0, placement=PLACEMENT,
                     handoff=InMemoryPartitionStore(), serving=True)
        for i in (1, 2, 3):
            h.join(i, placement=PLACEMENT, handoff=InMemoryPartitionStore,
                   serving=True)
        h.wait_and_verify_agreement(4)
        writer = h.instances[h.addr(0)]
        keys = [b"rw-%02d" % i for i in range(24)]
        acked = {}
        for i, key in enumerate(keys):
            ack = _put_until_acked(h, writer, key, b"v-%d" % i)
            acked[key] = (ack.version, b"v-%d" % i)

        # a different member reads its peers' writes (routing + leader reads)
        reader = h.instances[h.addr(1)]
        for key, (version, value) in acked.items():
            ack = _get_until_found(h, reader, key)
            assert ack.value == value and ack.version >= version

        # crash a member with handoff slowed: the view change's transfer
        # sessions and the serving plane's promote-time syncs overlap
        h.nemesis.arm()
        h.fail_nodes([h.addr(3)])
        # read-your-writes THROUGH the churn window: no waiting for the
        # view to settle first -- quorum-read fallback must cover it
        for key, (version, value) in acked.items():
            ack = _get_until_found(h, reader, key)
            assert ack.value == value, f"lost acked write {key!r} mid-churn"
            assert ack.version >= version
        h.wait_and_verify_agreement(3)

        # post-view: writes land on the promoted leaders and are visible
        # from a third member
        third = h.instances[h.addr(2)]
        for key in keys[:8]:
            ack = _put_until_acked(h, writer, key, b"post-" + key)
            got = _get_until_found(h, third, key)
            assert got.value == b"post-" + key
            assert got.version >= ack.version
        gets, puts, put_acks = writer.get_serving_status()
        assert puts >= len(keys) and put_acks > 0
    finally:
        h.shutdown()


def _leader_of(h: ClusterHarness, cluster, key: bytes) -> Endpoint:
    pmap = cluster.get_placement_map()
    row = pmap.assignments[partition_of(key, len(pmap.assignments))]
    return row[0]


def _churn_plan():
    return (FaultPlan(seed=13)
            .drop(0.2, msg_types=(Put,))
            .duplicate(0.2, msg_types=(Put,))
            .reorder(0.3, max_extra_ms=25, msg_types=(Put,)))


def test_leader_failover_mid_write_under_nemesis():
    """Writes keep flowing while the leader for a hot key crashes and the
    replication wire suffers drops, duplicates, and reorders: every write
    the client saw acked reads back at >= its acked version afterwards
    (duplicated Puts are idempotent by version; dropped replication acks
    surface as RETRY, never as a false OK)."""
    h = ClusterHarness(seed=6).with_faults(_churn_plan())
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant while the cluster forms
    try:
        h.start_seed(0, placement=PLACEMENT,
                     handoff=InMemoryPartitionStore(), serving=True)
        for i in (1, 2, 3):
            h.join(i, placement=PLACEMENT, handoff=InMemoryPartitionStore,
                   serving=True)
        h.wait_and_verify_agreement(4)
        writer = h.instances[h.addr(0)]
        # a key whose leader is NOT the writer, so the routed path and the
        # failover redirect both run
        key = next(
            k for k in (b"hot-%02d" % i for i in range(64))
            if _leader_of(h, writer, k) != h.addr(0)
        )
        victim = _leader_of(h, writer, key)

        h.nemesis.arm()  # drops/duplicates/reorders bite from here on
        acked_versions = []
        for i in range(6):
            ack = _put_until_acked(h, writer, key, b"pre-%d" % i)
            acked_versions.append(ack.version)
        assert acked_versions == sorted(acked_versions)

        h.fail_nodes([victim])  # the leader dies with writes in flight
        for i in range(6):
            ack = _put_until_acked(h, writer, key, b"mid-%d" % i)
            acked_versions.append(ack.version)
        h.wait_and_verify_agreement(3)
        final = _put_until_acked(h, writer, key, b"final")
        acked_versions.append(final.version)
        # versions the client saw acked are strictly increasing: no write
        # was silently overwritten by an older one during failover
        assert acked_versions == sorted(acked_versions)
        assert len(set(acked_versions)) == len(acked_versions)
        for inst in h.instances.values():
            got = _get_until_found(h, inst, key)
            assert got.value == b"final" and got.version >= final.version
    finally:
        h.shutdown()


# ---------------------------------------------------------------------- #
# Promotion-sync quorum + map-skew gates (engine unit level)
#
# The promote-time snapshot sync must pull from the partition's PREVIOUS
# row: that row's majority is what acked every pre-view write, so only
# old-row answers intersect it. A replica that just acquired the partition
# (handoff still in flight) must abstain rather than contribute an empty
# snapshot, and replicas must reject replication Puts stamped with a map
# other than their installed one so a deposed leader cannot assemble a
# quorum during the install skew window.
# ---------------------------------------------------------------------- #

from rapid_tpu.placement import PlacementConfig, PlacementMap
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.serving.engine import ServingEngine


class _StubClient:
    """Records every send; the test completes the promises by hand."""

    def __init__(self):
        self.sent = []  # (destination, message, promise)

    def send_message(self, node, msg):
        promise = Promise()
        self.sent.append((node, msg, promise))
        return promise

    def probes(self, quorum):
        return [
            (node, msg, pr) for node, msg, pr in self.sent
            if isinstance(msg, Get) and msg.quorum == quorum
        ]


def _pmap(version: int, *rows):
    rows = tuple(tuple(r) for r in rows)
    members = []
    for row in rows:
        for node in row:
            if node not in members:
                members.append(node)
    return PlacementMap(
        config=PlacementConfig(
            partitions=len(rows),
            replicas=max((len(r) for r in rows), default=1),
        ),
        configuration_id=1, version=version,
        members=tuple(members), assignments=rows,
    )


def _eps(n):
    return tuple(Endpoint.from_parts("node", 7000 + i) for i in range(n))


def _snap_probe(sender, p: int, map_version: int) -> Get:
    return Get(sender=sender, key=p.to_bytes(8, "little"), quorum=2,
               map_version=map_version)


def test_promote_sync_pulls_from_old_row_only():
    """The review scenario: old row {A,B,C} with a write acked on a
    majority, A crashes, new row {B,C,D}. B's sync must pull from the OLD
    row (A, C) -- never from the freshly added D, whose empty pre-handoff
    snapshot must not count toward the majority -- and the churn-window
    quorum read must fan over the same old row."""
    A, B, C, D = _eps(4)
    store = InMemoryPartitionStore()
    client = _StubClient()
    eng = ServingEngine(store, B, client, None)
    eng.update_map(_pmap(101, (A, B, C)))
    # a write acked under the old map reaches B via replication
    ack = eng.handle_put(Put(
        sender=A, key=b"k", value=b"local", request_id=1, replicate=1,
        version=7, map_version=101,
    )).peek()
    assert ack.status == PutAck.STATUS_OK

    eng.update_map(_pmap(202, (B, C, D)))
    assert eng.churned_partitions() == (0,)
    sync_targets = {node for node, _, _ in client.probes(quorum=2)}
    assert sync_targets == {A, C}, "sync must pull the old row, not D"
    # majority of the old 3-row is 2; B contributes itself, so one
    # old-row snapshot suffices
    assert eng._churned[0] == ((A, C), 1)  # noqa: SLF001

    # a read during the window takes the quorum-read path over the old row
    read = eng.handle_get(Get(sender=B, key=b"k", quorum=0))
    assert not read.done()
    read_targets = {node for node, _, _ in client.probes(quorum=1)}
    assert read_targets == {A, C}, "churned reads must quorum the old row"
    for node, msg, pr in client.probes(quorum=1):
        if node == C:
            pr.set_result(PutAck(
                sender=C, status=PutAck.STATUS_OK, key=msg.key,
                value=b"acked", version=9, map_version=202,
            ))
    assert read.peek().version == 9 and read.peek().value == b"acked"

    # one old-row snapshot completes the sync and clears the churn flag
    for node, msg, pr in client.probes(quorum=2):
        if node == C:
            pr.set_result(PutAck(
                sender=C, status=PutAck.STATUS_OK, key=msg.key,
                value=encode_kv({b"k": (9, b"acked")}), map_version=202,
            ))
    assert eng.churned_partitions() == ()
    got = eng.handle_get(Get(sender=B, key=b"k", quorum=0)).peek()
    assert got.status == PutAck.STATUS_OK
    assert got.version == 9 and got.value == b"acked"


def test_promote_sync_first_map_falls_back_to_new_row():
    """A member promoted on the very first map it sees cannot know the old
    row; it best-effort syncs against the new row (responders gate empty
    answers via the acquisition check, exercised separately)."""
    B, C, D = _eps(3)
    client = _StubClient()
    eng = ServingEngine(InMemoryPartitionStore(), B, client, None)
    eng.update_map(_pmap(101, (B, C, D)))
    assert eng.churned_partitions() == (0,)
    assert {node for node, _, _ in client.probes(quorum=2)} == {C, D}


def test_snapshot_probe_abstains_until_acquisition_lands():
    """A replica whose handoff delivery for a just-acquired partition has
    not landed answers RETRY to snapshot and quorum-read probes -- an
    empty answer must never satisfy a peer's sync majority."""
    B, C, D = _eps(3)
    store = InMemoryPartitionStore()
    eng = ServingEngine(store, D, _StubClient(), None)
    eng.update_map(_pmap(202, (B, C, D)))  # D's first map: all acquired
    probe = _snap_probe(B, 0, 202)
    assert eng.handle_get(probe).peek().status == PutAck.STATUS_RETRY
    q1 = eng.handle_get(Get(sender=B, key=b"k", quorum=1)).peek()
    assert q1.status == PutAck.STATUS_RETRY
    store.put(0, encode_kv({b"k": (3, b"v")}))  # handoff delivers
    ans = eng.handle_get(probe).peek()
    assert ans.status == PutAck.STATUS_OK
    assert decode_kv(ans.value) == {b"k": (3, b"v")}
    q1 = eng.handle_get(Get(sender=B, key=b"k", quorum=1)).peek()
    assert q1.status == PutAck.STATUS_OK and q1.version == 3


def test_snapshot_probe_validates_partition_id():
    """Malformed or foreign partition ids answer RETRY and do not insert
    cache entries (unbounded growth from stale/hostile probes)."""
    A, B, C = _eps(3)
    eng = ServingEngine(InMemoryPartitionStore(), B, _StubClient(), None)
    eng.update_map(_pmap(101, (A, B), (A, C)))  # B replicates p0 only
    assert eng.handle_get(_snap_probe(A, 1, 101)).peek().status == \
        PutAck.STATUS_RETRY
    assert eng.handle_get(_snap_probe(A, 999, 101)).peek().status == \
        PutAck.STATUS_RETRY
    short = Get(sender=A, key=b"\x01", quorum=2, map_version=101)
    assert eng.handle_get(short).peek().status == PutAck.STATUS_RETRY
    assert set(eng._kv) <= {0}  # noqa: SLF001 -- no foreign cache entries


def test_retired_replica_answers_sync_probes_for_one_view():
    """A member dropped from a partition's row keeps its final blob so
    old-row syncs can still pull it after the handoff ack releases the
    store entry; the retired blob survives exactly one further view."""
    A, B, C = _eps(3)
    store = InMemoryPartitionStore()
    eng = ServingEngine(store, C, _StubClient(), None)
    eng.update_map(_pmap(101, (A, C)))
    eng.handle_put(Put(
        sender=A, key=b"k", value=b"v", request_id=1, replicate=1,
        version=5, map_version=101,
    )).peek()
    eng.update_map(_pmap(202, (A, B)))  # C dropped from the row
    store.delete(0)  # the handoff ack path releases the blob
    ans = eng.handle_get(_snap_probe(B, 0, 202)).peek()
    assert ans.status == PutAck.STATUS_OK
    assert decode_kv(ans.value)[b"k"] == (5, b"v")
    q1 = eng.handle_get(Get(sender=B, key=b"k", quorum=1)).peek()
    assert q1.status == PutAck.STATUS_OK and q1.version == 5
    # still answerable one view later (peers may sync against the old map)
    eng.update_map(_pmap(303, (A, B)))
    assert eng.handle_get(_snap_probe(B, 0, 303)).peek().status == \
        PutAck.STATUS_OK
    # two views later the retired blob is released
    eng.update_map(_pmap(404, (A, B)))
    assert eng.handle_get(_snap_probe(B, 0, 404)).peek().status == \
        PutAck.STATUS_RETRY


def test_replica_rejects_skewed_map_and_foreign_partition():
    """Replication Puts apply only under the sender's exact installed map
    and only for partitions this member replicates: a deposed leader
    racing a map install collects RETRYs (no quorum, no false ack), and a
    delayed replication Put cannot re-create a blob for a partition this
    member already dropped."""
    A, B, C = _eps(3)
    store = InMemoryPartitionStore()
    eng = ServingEngine(store, B, _StubClient(), None)
    eng.update_map(_pmap(202, (A, B), (A, C)))  # B replicates p0 only
    k0 = next(k for k in (b"pk-%d" % i for i in range(64))
              if partition_of(k, 2) == 0)
    k1 = next(k for k in (b"pk-%d" % i for i in range(64))
              if partition_of(k, 2) == 1)
    stale = Put(sender=A, key=k0, value=b"v", request_id=1, replicate=1,
                version=3, map_version=101)
    assert eng.handle_put(stale).peek().status == PutAck.STATUS_RETRY
    assert store.partitions() == ()
    foreign = Put(sender=A, key=k1, value=b"v", request_id=2, replicate=1,
                  version=3, map_version=202)
    assert eng.handle_put(foreign).peek().status == PutAck.STATUS_RETRY
    assert store.partitions() == ()
    good = Put(sender=A, key=k0, value=b"v", request_id=3, replicate=1,
               version=3, map_version=202)
    assert eng.handle_put(good).peek().status == PutAck.STATUS_OK
    assert store.partitions() == (0,)


def test_promote_sync_retries_inline_without_scheduler():
    """With scheduler=None a failed sync round must retry inline (like the
    routed-reply path) instead of silently parking the partition in the
    churned state forever."""
    A, B = _eps(2)
    client = _StubClient()
    eng = ServingEngine(InMemoryPartitionStore(), B, client, None)
    eng.update_map(_pmap(101, (A, B)))
    eng.update_map(_pmap(202, (B, A)))  # B promoted; old row (A, B)
    probes = client.probes(quorum=2)
    assert len(probes) == 1 and probes[0][0] == A
    probes[0][2].set_exception(RuntimeError("peer down"))
    probes = client.probes(quorum=2)
    assert len(probes) == 2, "failed round must re-pull inline"
    node, msg, pr = probes[1]
    pr.set_result(PutAck(sender=A, status=PutAck.STATUS_OK, key=msg.key,
                         value=encode_kv({}), map_version=202))
    assert eng.churned_partitions() == ()


# ---------------------------------------------------------------------- #
# Simulator mirror
# ---------------------------------------------------------------------- #

_SIM_METRICS = (
    "serving.gets", "serving.puts", "serving.put_acks",
    "serving.put_retries", "serving.replication_writes",
    "serving.leader_reads", "serving.quorum_reads",
    "serving.not_leader_redirects", "serving.leader_changes",
)


def _run_sim_serving(fault_plan=None, seed: int = 11):
    """Deterministic churn workload: writes, a crash (reads ride the churn
    window), the view change, then a join wave with more traffic."""
    sim = Simulator(4, capacity=5, seed=seed).ready()
    sim.enable_placement(partitions=32, replicas=3, seed=7)
    sim.enable_handoff(chunk_size=1024)
    sim.enable_serving(request_ms=1, fault_plan=fault_plan)
    history = []
    keys = [b"sim-%02d" % i for i in range(24)]

    def put(key, value):
        ack = sim.serving_put(key, value)
        history.append(("put", key, value, ack.version, ack.status))
        return ack

    def get(key):
        ack = sim.serving_get(key)
        history.append(("get", key, ack.value, ack.version, ack.status))
        return ack

    for i, key in enumerate(keys):
        put(key, b"a-%d" % i)
    sim.crash(np.array([1]))
    for key in keys:  # churn window: quorum-read fallback
        get(key)
    assert sim.run_until_decision(max_rounds=20_000) is not None
    for i, key in enumerate(keys[:12]):
        put(key, b"b-%d" % i)
    sim.request_joins(np.array([4]))
    assert sim.run_until_decision(max_rounds=20_000) is not None
    for key in keys:
        get(key)
    return sim, history


def _sim_metric_snapshot(sim: Simulator) -> dict:
    return {name: sim.metrics.get(name) for name in _SIM_METRICS}


def test_sim_serving_requires_handoff():
    sim = Simulator(3, capacity=3, seed=1)
    with pytest.raises(RuntimeError):
        sim.enable_serving()
    sim.enable_placement(partitions=8, replicas=2)
    with pytest.raises(RuntimeError):
        sim.enable_serving()
    with pytest.raises(RuntimeError):
        sim.serving_put(b"k", b"v")


def test_sim_serving_deterministic_and_lossless():
    """Two seeded runs produce identical metric trajectories, virtual
    clocks, and op histories; zero acknowledged writes are lost across the
    crash + join churn; the handoff stores carry the serving blobs (the
    state a view change moves IS the serving data)."""
    sim_a, hist_a = _run_sim_serving()
    sim_b, hist_b = _run_sim_serving()
    assert _sim_metric_snapshot(sim_a) == _sim_metric_snapshot(sim_b)
    assert sim_a.virtual_ms == sim_b.virtual_ms
    assert hist_a == hist_b
    snap = _sim_metric_snapshot(sim_a)
    assert snap["serving.puts"] > 0 and snap["serving.gets"] > 0
    assert snap["serving.leader_reads"] > 0
    assert snap["serving.quorum_reads"] > 0, "churn window never exercised"
    assert snap["serving.leader_changes"] > 0
    for key, (version, value) in sim_a.serving_acked.items():
        back = sim_a.serving_get(key)
        assert back.status == PutAck.STATUS_OK
        assert back.version >= version
        if back.version == version:
            assert back.value == value
    # the replica rows' stores hold the data as deterministic KV blobs
    assign = sim_a.placement.assign
    stores = sim_a.handoff_stores
    key = b"sim-00"
    p = partition_of(key, 32)
    holders = [int(s) for s in assign[p] if s >= 0]
    blobs = [decode_kv(stores[s].get(p)) for s in holders]
    assert all(key in kv for kv in blobs), "replica lost the serving blob"


def test_sim_serving_nemesis_replayable():
    """The same fault plan on the replication wire replays bit-identically
    and demonstrably bites (unacked writes observed) without ever losing an
    acknowledged write."""
    def plan():
        return (FaultPlan(seed=5)
                .drop(0.5, msg_types=(Put,))
                .duplicate(0.3, msg_types=(Put,)))

    sim_a, hist_a = _run_sim_serving(fault_plan=plan())
    sim_b, hist_b = _run_sim_serving(fault_plan=plan())
    assert _sim_metric_snapshot(sim_a) == _sim_metric_snapshot(sim_b)
    assert sim_a.virtual_ms == sim_b.virtual_ms
    assert hist_a == hist_b
    snap = _sim_metric_snapshot(sim_a)
    assert snap["serving.put_retries"] > 0, "nemesis never bit a write"
    for key, (version, value) in sim_a.serving_acked.items():
        back = sim_a.serving_get(key)
        assert back.status == PutAck.STATUS_OK and back.version >= version


# promoted to the nemesis-search checker module (single source of truth);
# re-exported here because this file is where the checker grew up and
# other suites import it from here
from rapid_tpu.search.checkers import check_linearizable_single_client  # noqa: E402


def test_sim_serving_history_linearizable():
    for fault_plan in (None, FaultPlan(seed=5).drop(0.5, msg_types=(Put,))):
        _, history = _run_sim_serving(fault_plan=fault_plan)
        assert history, "empty history"
        check_linearizable_single_client(history)


# ---------------------------------------------------------------------- #
# statusz surfacing
# ---------------------------------------------------------------------- #

def _load_statusz():
    spec = importlib.util.spec_from_file_location(
        "statusz", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "statusz.py")
    )
    statusz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(statusz)
    return statusz


def test_statusz_flags_serving_leader_disagreement(monkeypatch, capsys):
    """tools/statusz.py renders the serving counters, exports the
    per-partition leader map in JSON, and exits 2 when two replicas of one
    partition name different leaders (a split-brain write path)."""
    statusz = _load_statusz()
    a = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=5,
        membership_size=2, serving_gets=3, serving_puts=2,
        serving_put_acks=4, serving_partitions=(0, 1),
        serving_leaders=("h:1", "h:2"),
    )
    b = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 2), configuration_id=5,
        membership_size=2, serving_partitions=(1, 2),
        serving_leaders=("h:9", "h:2"),
    )
    text = statusz.render(a)
    assert "serving: gets=3 puts=2 acks=4 leads=1/2" in text
    blob = statusz.to_json(a)
    assert blob["serving_leaders"] == {"0": "h:1", "1": "h:2"}
    assert blob["serving_puts"] == 2
    bare = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 3), configuration_id=5,
        membership_size=2,
    )
    assert "serving:" not in statusz.render(bare)

    replies = {"h1:1": a, "h2:2": b}
    monkeypatch.setattr(
        statusz, "fetch_status",
        lambda client, target, timeout: replies[
            f"{target.hostname.decode()}:{target.port}"
        ],
    )
    rc = statusz.main(["h1:1", "h2:2"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "serving leader" in err
    assert "[1]" in err  # partition 1 is the one that diverges

    # agreeing leaders (disjoint or equal) do not trip the check
    replies["h2:2"] = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 2), configuration_id=5,
        membership_size=2, serving_partitions=(1, 2),
        serving_leaders=("h:2", "h:3"),
    )
    assert statusz.main(["h1:1", "h2:2"]) == 0
