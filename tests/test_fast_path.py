"""Equivalence of the single-dispatch fast path with the reference scan path.

run_until_decided_const is an optimization (closed-form FD + early-exiting
while_loop, engine.py); these tests pin its contract: for any constant,
deterministic fault plane it must produce *bit-identical* SimState to scanning
``step`` the same number of rounds. device_initial_state likewise must equal
the host adjacency build (MembershipView semantics) exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import (
    SimConfig,
    const_inputs,
    device_initial_state,
    run_rounds_const,
    run_until_decided_const,
)
from rapid_tpu.sim.topology import VirtualCluster, build_adjacency


def _assert_states_equal(a, b):
    for name in a.__dataclass_fields__:
        av, bv = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        if name == "rng_key":
            continue  # scan path consumes RNG per round; fast path does not
        np.testing.assert_array_equal(av, bv, err_msg=f"field {name} diverged")


def _run_both(config, state, inputs, rounds):
    scan = run_rounds_const(config, state, inputs, rounds, False)
    uniform = bool(np.asarray(inputs.deliver).all())
    fast = run_until_decided_const(config, state, inputs, jnp.int32(rounds), uniform)
    return scan, fast


def _equalize_rounds(config, fast, inputs, total_rounds):
    """The fast path stops at the decision round; replay the scan's masked
    no-op tail on it so terminal states are comparable."""
    done = int(fast.round)
    if done < total_rounds:
        fast = run_rounds_const(config, fast, inputs, total_rounds - done, False)
    return fast


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_crash_burst_matches_scan_path(seed):
    rng = np.random.default_rng(seed)
    config = SimConfig(capacity=64, k=6, h=5, l=2, fd_threshold=4)
    sim = Simulator(64, config=config, seed=seed)
    victims = rng.choice(64, size=3, replace=False)
    sim.crash(victims)
    inputs = const_inputs(config, sim.alive)
    scan, fast = _run_both(config, sim.state, inputs, 12)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 12))


def test_one_way_partition_matches_scan_path():
    config = SimConfig(capacity=32, k=5, h=4, l=2, fd_threshold=3)
    sim = Simulator(32, config=config, seed=7)
    sim.one_way_ingress_partition(np.array([4, 9]))
    inputs = const_inputs(config, sim.alive, probe_drop=sim._probe_drop_mask())
    scan, fast = _run_both(config, sim.state, inputs, 10)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 10))


def test_delivery_groups_matches_scan_path():
    config = SimConfig(capacity=32, k=5, h=4, l=2, fd_threshold=3, groups=4)
    sim = Simulator(32, config=config, seed=3)
    sim.set_delivery_groups(np.arange(32, dtype=np.int32) % 4)
    sim.crash(np.array([11]))
    sim.drop_broadcasts(2, np.array([5, 6, 7]))
    inputs = const_inputs(
        config, sim.alive, deliver=sim._deliver,
    )
    scan, fast = _run_both(config, sim.state, inputs, 10)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 10))


def test_join_reports_match_scan_path():
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=3)
    sim = Simulator(12, capacity=16, config=config, seed=5)
    sim.request_joins(np.array([12, 13]))
    join_reports = sim._arm_pending_joins()
    inputs = const_inputs(config, sim.alive, join_reports=join_reports)
    scan, fast = _run_both(config, sim.state, inputs, 8)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 8))


def test_multi_dispatch_with_revive_between():
    """Plane changes between dispatches (flip-flop): the fast path must resume
    from reconstructed fd_fail/alerted identically to the scan path."""
    config = SimConfig(capacity=24, k=5, h=4, l=2, fd_threshold=6)
    sim = Simulator(24, config=config, seed=9)
    victims = np.array([3, 17])

    state_a = state_b = sim.state
    for crash in (True, False, True):
        (sim.crash if crash else sim.revive)(victims)
        inputs = const_inputs(config, sim.alive)
        state_a = run_rounds_const(config, state_a, inputs, 3, False)
        state_b = run_until_decided_const(config, state_b, inputs, jnp.int32(3), True)
        if int(state_b.round) < int(state_a.round):
            state_b = run_rounds_const(
                config, state_b, inputs,
                int(state_a.round) - int(state_b.round), False,
            )
        _assert_states_equal(state_a, state_b)


def test_decision_state_identical_at_decision_round():
    """Up to and including the decision round, the two paths agree exactly
    (cut, winning group, decided_round)."""
    config = SimConfig(capacity=48, k=6, h=5, l=2, fd_threshold=4)
    sim = Simulator(48, config=config, seed=11)
    sim.crash(np.array([5, 6]))
    inputs = const_inputs(config, sim.alive)
    fast = run_until_decided_const(config, sim.state, inputs, jnp.int32(16), True)
    assert bool(fast.decided)
    scan = run_rounds_const(config, sim.state, inputs, int(fast.round), False)
    _assert_states_equal(scan, fast)


def test_device_initial_state_matches_host_adjacency():
    cluster = VirtualCluster.synthesize(50, k=7, seed=2)
    rng = np.random.default_rng(0)
    active = rng.random(50) < 0.7
    host_subjects, host_observers = build_adjacency(cluster, active)
    st = device_initial_state(
        SimConfig(capacity=50, k=7),
        jnp.asarray(cluster.ring_rank()),
        jnp.asarray(active),
        jnp.asarray(active),
        jnp.zeros(50, jnp.int32),
        jnp.ones(50, bool),
        jax.random.PRNGKey(0),
    )
    np.testing.assert_array_equal(np.asarray(st.subjects), host_subjects)
    np.testing.assert_array_equal(np.asarray(st.observers), host_observers)


@pytest.mark.parametrize("n_active", [0, 1, 2])
def test_device_initial_state_tiny_membership(n_active):
    cluster = VirtualCluster.synthesize(8, k=3, seed=4)
    active = np.zeros(8, dtype=bool)
    active[:n_active] = True
    host_subjects, host_observers = build_adjacency(cluster, active)
    st = device_initial_state(
        SimConfig(capacity=8, k=3),
        jnp.asarray(cluster.ring_rank()),
        jnp.asarray(active),
        jnp.asarray(active),
        jnp.zeros(8, jnp.int32),
        jnp.ones(8, bool),
        jax.random.PRNGKey(0),
    )
    np.testing.assert_array_equal(np.asarray(st.subjects), host_subjects)
    np.testing.assert_array_equal(np.asarray(st.observers), host_observers)


def test_leave_reports_match_scan_path():
    config = SimConfig(capacity=32, k=5, h=4, l=2, fd_threshold=6)
    sim = Simulator(32, config=config, seed=13)
    sim.leave(np.array([3, 28]))
    inputs = const_inputs(
        config, sim.alive, down_reports=np.asarray(sim._down_reports())
    )
    scan, fast = _run_both(config, sim.state, inputs, 8)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 8))


def test_leave_and_crash_combined_match_scan_path():
    """A leave racing a crash burst: proactive reports and FD-threshold
    alerts in the same dispatch."""
    config = SimConfig(capacity=32, k=5, h=4, l=2, fd_threshold=4)
    sim = Simulator(32, config=config, seed=14)
    sim.crash(np.array([10, 11]))
    sim.leave(np.array([20]))
    inputs = const_inputs(
        config, sim.alive, down_reports=np.asarray(sim._down_reports())
    )
    scan, fast = _run_both(config, sim.state, inputs, 10)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 10))


def test_staggered_phases_match_scan_path():
    """rounds_per_interval > 1: the closed-form probe schedule (phase-offset
    arithmetic) must be bit-identical to scanning the phase-gated step."""
    config = SimConfig(capacity=32, k=5, h=4, l=2, fd_threshold=4,
                       rounds_per_interval=4)
    sim = Simulator(32, config=config, seed=17)
    sim.crash(np.array([6, 21]))
    inputs = const_inputs(config, sim.alive)
    scan, fast = _run_both(config, sim.state, inputs, 24)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 24))


def test_staggered_phases_multi_dispatch_resume():
    """Dispatch boundaries at arbitrary rounds: the phase re-basing onto the
    dispatch's starting round must keep the probe schedule aligned."""
    config = SimConfig(capacity=24, k=5, h=4, l=2, fd_threshold=3,
                       rounds_per_interval=5)
    sim = Simulator(24, config=config, seed=18)
    sim.crash(np.array([9]))
    inputs = const_inputs(config, sim.alive)
    state_a = state_b = sim.state
    for chunk in (3, 7, 4, 9):
        state_a = run_rounds_const(config, state_a, inputs, chunk, False)
        state_b = run_until_decided_const(config, state_b, inputs, jnp.int32(chunk), True)
        if int(state_b.round) < int(state_a.round):
            state_b = run_rounds_const(
                config, state_b, inputs,
                int(state_a.round) - int(state_b.round), False,
            )
        _assert_states_equal(state_a, state_b)


@pytest.mark.parametrize("seed", [0, 1])
def test_windowed_policy_matches_scan_path(seed):
    """The windowed closed form (window recurrence stepped at trace time)
    must be bit-identical to scanning the windowed step."""
    rng = np.random.default_rng(seed)
    config = SimConfig(
        capacity=48, k=6, h=5, l=2, fd_policy="windowed",
        fd_window=6, fd_window_threshold=0.5,
    )
    sim = Simulator(48, capacity=48, config=config, seed=seed)
    victims = rng.choice(48, size=3, replace=False)
    sim.crash(victims)
    inputs = const_inputs(config, sim.alive)
    scan, fast = _run_both(config, sim.state, inputs, 14)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 14))


def test_windowed_policy_carried_window_matches_scan_path():
    """Carried-over window contents (a crash, some rounds, then a revive and
    a different crash) must reconstruct identically: the closed form starts
    from a half-full, partly-failed window, not a fresh one."""
    config = SimConfig(
        capacity=40, k=5, h=4, l=2, fd_policy="windowed",
        fd_window=8, fd_window_threshold=0.4,
    )
    sim = Simulator(40, capacity=40, config=config, seed=3)
    sim.crash(np.array([7]))
    # run 3 rounds on the scan path so fd_hist/fd_seen carry partial state
    inputs = const_inputs(config, sim.alive)
    state = run_rounds_const(config, sim.state, inputs, 3, False)
    sim.state = state
    sim.revive(np.array([7]))
    sim.crash(np.array([11, 12]))
    inputs2 = const_inputs(config, sim.alive)
    scan, fast = _run_both(config, sim.state, inputs2, 16)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs2, 16))


def test_windowed_policy_staggered_phases_matches_scan_path():
    """Windowed + rounds_per_interval > 1: probe scheduling by phase and the
    probe-index -> round mapping must agree with the scan path exactly."""
    config = SimConfig(
        capacity=32, k=4, h=3, l=2, fd_policy="windowed",
        fd_window=5, fd_window_threshold=0.4, rounds_per_interval=4,
    )
    sim = Simulator(32, capacity=32, config=config, seed=9)
    sim.crash(np.array([5, 21]))
    inputs = const_inputs(config, sim.alive)
    scan, fast = _run_both(config, sim.state, inputs, 40)
    _assert_states_equal(scan, _equalize_rounds(config, fast, inputs, 40))


def test_windowed_driver_fast_path_decides_with_exact_timing():
    """Driver-level: a windowed-policy run with no random loss takes the
    single-dispatch closed-form path (scan is only for random ingress loss)
    and decides with the exact protocol timing."""
    config = SimConfig(
        capacity=50, fd_policy="windowed", fd_window=10,
        fd_window_threshold=0.4,
    )
    sim = Simulator(50, capacity=50, config=config, seed=4)
    sim.crash(np.array([8, 9]))
    rec = sim.run_until_decision(max_rounds=64, batch=64,
                                 classic_fallback_after_rounds=None)
    assert rec is not None and set(rec.cut) == {8, 9}
    # windowed detection requires a FULL window (10 probes) before firing,
    # regardless of the 0.4 threshold: decision = 10 rounds + the
    # vote-delivery hop + the batching window
    assert rec.virtual_time_ms == 11 * 1000 + 100
    # one device dispatch settles it (the early-exit while_loop)
    assert sim.metrics.get("device_dispatches") == 1
