"""Adaptive gray-aware failure detection (ISSUE 14).

Pins the tentpole contracts layer by layer:

- scoring (monitoring/adaptive.py): a sustained RTT outlier streak or miss
  streak against an established healthy history ripens suspicion to >= 1
  and fires through the EXISTING alert path before the hard
  failure_threshold; warmup gates a fresh (or dead-on-arrival) edge onto
  the unchanged static path;
- safety under the nemesis algebra: clock skew (both directions) cannot
  masquerade as outlierness because every edge of an observer is measured
  with the same injectable probe clock;
- controllers: probe interval is RTT-proportional per tier and floored
  while the tier holds a suspect, the hard threshold keeps the static
  detection-time budget, and the alert flush window drops to the floor
  while a gray alert is ripe;
- cluster level: an adaptive cluster evicts exactly the gray node (zero
  collateral) and faster than the static budget, with the per-edge/per-tier
  telemetry exposed in ClusterStatusResponse across both wires;
- search plane: the corpus-* pinned plans and the RAPID_BUG_NEWROW_SYNC
  rediscovery stay green with adaptation enabled (the sim probe's
  fd_gray_confirm seam);
- sim plane: the gray streak mirror is bit-identical between the scan path
  and the closed-form fast path, including dispatch-boundary resume and
  staggered probe phases.
"""

import json
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from rapid_tpu import Endpoint, Settings
from rapid_tpu.faults import FaultPlan, SkewedScheduler
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import decode, encode
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.monitoring.adaptive import (
    TIER_DEFAULT,
    TIER_RACK,
    TIER_REGION,
    TIER_WAN,
    TIER_ZONE,
    AdaptivePingPongFactory,
    topology_tier_resolver,
)
from rapid_tpu.observability import Metrics, global_metrics
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.search.runner import run_probe
from rapid_tpu.settings import AdaptiveFdSettings
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import (
    SimConfig,
    const_inputs,
    run_rounds_const,
    run_until_decided_const,
)
from rapid_tpu.sim.topology import LatencyTopology
from rapid_tpu.types import ClusterStatusResponse, ProbeResponse

from harness import ClusterHarness

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = sorted((REPO / "scenarios" / "corpus").glob("*.json"))

OBSERVER = Endpoint.from_parts("10.9.0.1", 40)
SUBJECTS = tuple(
    Endpoint.from_parts("10.9.0.%d" % i, 50) for i in range(2, 6)
)


def _adaptive_settings(**overrides) -> Settings:
    return Settings(
        adaptive_fd=AdaptiveFdSettings(enabled=True, **overrides)
    )


class _Responder:
    """Per-subject scripted probe behavior: a lag in ms (delivered via the
    scheduler, like a real slow node) or None for a missed probe."""

    def __init__(self, sched: VirtualScheduler) -> None:
        self.sched = sched
        self.lag = {}

    def send_message_best_effort(self, remote, msg) -> Promise:
        p = Promise()
        lag = self.lag[remote]
        if lag is None:
            p.try_set_exception(TimeoutError(f"{remote} past the deadline"))
        else:
            self.sched.schedule(lag, lambda: p.try_set_result(ProbeResponse()))
        return p


def _edge_set(sched, metrics=None, tier_of=None, settings=None, lag_ms=10,
              subjects=SUBJECTS, clock=None):
    """A factory plus one detector per subject, all answering at lag_ms."""
    responder = _Responder(sched)
    factory = AdaptivePingPongFactory(
        OBSERVER, responder,
        settings if settings is not None else _adaptive_settings(),
        metrics=metrics, clock=clock if clock is not None else sched.now_ms,
        tier_of=tier_of,
    )
    fired = []
    detectors = {}
    for s in subjects:
        responder.lag[s] = lag_ms
        detectors[s] = factory.create_instance(s, lambda s=s: fired.append(s))
    return factory, responder, detectors, fired


def _tick(sched, detectors, settle_ms=600):
    for det in detectors.values():
        det()
    sched.run_for(settle_ms)


def _warm(sched, detectors, rounds=4):
    for _ in range(rounds):
        _tick(sched, detectors)


def _gray_alert_total() -> float:
    return sum(
        value for kind, name, _, value in global_metrics().collect()
        if kind == "counter" and name == "fd.gray_alerts"
    )


# ---------------------------------------------------------------------------
# suspicion scoring
# ---------------------------------------------------------------------------


def test_soft_gray_outlier_streak_fires_before_hard_path():
    """A node that still answers -- just far outside its tier's band --
    accrues an outlier streak and gray-alerts with the hard counter at 0."""
    sched = VirtualScheduler()
    metrics = Metrics()
    _, responder, dets, fired = _edge_set(sched, metrics=metrics)
    victim = SUBJECTS[0]
    _warm(sched, dets)
    assert all(det.suspicion() == 0.0 for det in dets.values())

    responder.lag[victim] = 500  # alive, late: tier peers sit at 10 ms
    for expect in (1 / 3, 2 / 3, 1.0):
        _tick(sched, dets)
        assert dets[victim].suspicion() == pytest.approx(expect)
    assert dets[victim].has_failed()
    assert dets[victim]._failure_count == 0  # noqa: SLF001 -- gray, not hard
    assert all(dets[s].suspicion() == 0.0 for s in SUBJECTS[1:])

    assert fired == [] and metrics.get("fd.gray_alerts") in (None, 0)
    _tick(sched, dets)  # the ripe suspicion rides the normal alert tick
    assert fired == [victim]
    assert metrics.get("fd.gray_alerts") == 1


def test_hard_gray_miss_streak_and_success_reset():
    """Misses against an established history ripen suspicion in
    gray_confirm probes; one answered probe resets the miss streak."""
    sched = VirtualScheduler()
    metrics = Metrics()
    _, responder, dets, fired = _edge_set(sched, metrics=metrics)
    victim = SUBJECTS[0]
    _warm(sched, dets)

    responder.lag[victim] = None
    _tick(sched, dets)
    _tick(sched, dets)
    assert dets[victim].suspicion() == pytest.approx(2 / 3)
    responder.lag[victim] = 10  # a healthy answer clears the streak
    _tick(sched, dets)
    assert dets[victim].suspicion() == 0.0

    responder.lag[victim] = None
    for _ in range(3):
        _tick(sched, dets)
    assert dets[victim].suspicion() >= 1.0 and dets[victim].has_failed()
    # the gray path concluded with the hard counter far from its threshold
    assert dets[victim]._failure_count == 5  # noqa: SLF001
    _tick(sched, dets)
    assert fired == [victim] and metrics.get("fd.gray_alerts") == 1


def test_warmup_gates_fresh_and_dead_on_arrival_edges():
    """Below warmup_probes samples an edge can never be gray-suspected: a
    dead-on-arrival subject takes the static hard path unchanged."""
    sched = VirtualScheduler()
    metrics = Metrics()
    _, responder, dets, fired = _edge_set(
        sched, metrics=metrics, subjects=SUBJECTS[:1], lag_ms=None
    )
    victim = SUBJECTS[0]
    for _ in range(9):  # adapted threshold == static 10 on a cold tier
        _tick(sched, dets)
        assert dets[victim].suspicion() == 0.0
        assert not dets[victim].has_failed()
    _tick(sched, dets)
    assert dets[victim].has_failed()  # hard counter reached 10
    assert dets[victim].suspicion() == 0.0
    _tick(sched, dets)  # notification tick: hard alert, not a gray one
    assert fired == [victim]
    assert metrics.get("fd.gray_alerts") in (None, 0)


def test_outliers_below_warmup_accrue_no_suspicion():
    sched = VirtualScheduler()
    _, responder, dets, _ = _edge_set(sched, subjects=SUBJECTS[:1],
                                      lag_ms=900)
    victim = SUBJECTS[0]
    for _ in range(3):  # warmup_probes=4: three huge samples stay inert
        _tick(sched, dets, settle_ms=1000)
        assert dets[victim].suspicion() == 0.0
    assert not dets[victim].has_failed()


# ---------------------------------------------------------------------------
# clock skew must not masquerade as outlierness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("offset_ms,rate", [(500, 2.0), (-200, 0.5)])
def test_skewed_probe_clock_accrues_no_suspicion(offset_ms, rate):
    """A drifted observer clock (either direction) scales every edge's
    measured RTT together and its offset cancels in the subtraction, so no
    edge outlies its tier and no suspicion accrues."""
    inner = VirtualScheduler()
    skewed = SkewedScheduler(inner, offset_ms=offset_ms, rate=rate)
    _, responder, dets, fired = _edge_set(inner, clock=skewed.now_ms)
    for _ in range(10):
        _tick(inner, dets)
    for det in dets.values():
        assert det.suspicion() == 0.0
        assert det.rtt_ms() == pytest.approx(rate * 10)
    assert fired == []


def test_adaptive_cluster_tolerates_clock_skew_without_gray_alerts():
    """test_clock_skew_cluster_converges_with_no_collateral, adaptation ON:
    a drifted-but-fast member is never suspected, never evicted."""
    n = 4
    h = ClusterHarness(seed=5, use_static_fd=False,
                       settings=_adaptive_settings())
    skewed = h.addr(1)
    h.with_faults(
        FaultPlan(seed=5).clock_skew(skewed, offset_ms=350, rate=1.25)
    )
    h.nemesis.arm()
    before = _gray_alert_total()
    try:
        h.create_cluster(n, parallel=False)
        h.wait_and_verify_agreement(n)
        h.fail_nodes([h.addr(n - 1)])
        h.wait_and_verify_agreement(n - 1)
        members = set(h.instances[h.addr(0)].get_memberlist())
        assert skewed in members  # skew alone never evicts
        assert members == {h.addr(i) for i in range(n - 1)}
    finally:
        h.shutdown()
    assert _gray_alert_total() == before


# ---------------------------------------------------------------------------
# per-tier controllers
# ---------------------------------------------------------------------------


def test_interval_is_rtt_proportional_and_clamped():
    for lag, expected in ((10, 250), (100, 800), (1000, 4000)):
        sched = VirtualScheduler()
        factory, _, dets, _ = _edge_set(sched, lag_ms=lag)
        _warm(sched, dets, rounds=5)
        assert factory.interval_ms_for(SUBJECTS[0], 1000) == expected


def test_threshold_keeps_static_detection_budget():
    # default budget: fd_failure_threshold=10 x interval 1000 ms
    for lag, interval, expected in ((100, 800, 12), (10, 250, 30),
                                    (1000, 4000, 3)):
        sched = VirtualScheduler()
        factory, _, dets, _ = _edge_set(sched, lag_ms=lag)
        _warm(sched, dets, rounds=5)
        assert factory._interval_no_metrics(SUBJECTS[0], 1000) == interval  # noqa: SLF001
        assert factory.adapted_threshold(SUBJECTS[0]) == expected


def test_suspect_tier_floors_interval_and_ripe_alert_floors_flush():
    sched = VirtualScheduler()
    factory, responder, dets, _ = _edge_set(sched, lag_ms=100)
    _warm(sched, dets, rounds=5)
    assert factory.interval_ms_for(SUBJECTS[1], 1000) == 800
    assert factory.flush_window_ms(100) == 100
    assert factory.flush_window_ms(5000) == 500  # clamped to the ceiling
    assert factory.flush_window_ms(3) == 10      # clamped to the floor

    victim = SUBJECTS[0]
    responder.lag[victim] = None
    _tick(sched, dets)  # one miss: the whole tier probes at the floor
    assert dets[victim].suspicion() == pytest.approx(1 / 3)
    assert factory.interval_ms_for(SUBJECTS[1], 1000) == 250
    assert factory.flush_window_ms(100) == 100  # suspicion not ripe yet
    _tick(sched, dets)
    _tick(sched, dets)
    assert dets[victim].suspicion() >= 1.0
    assert factory.flush_window_ms(100) == 10


def test_tier_params_separate_lan_from_wan():
    rack = SUBJECTS[:2]
    wan = SUBJECTS[2:]
    tier_of = lambda s: TIER_RACK if s in rack else TIER_WAN  # noqa: E731
    sched = VirtualScheduler()
    factory, responder, dets, _ = _edge_set(sched, tier_of=tier_of)
    for s in wan:
        responder.lag[s] = 150
    _warm(sched, dets, rounds=5)
    params = {row[0]: row[1:] for row in factory.tier_params()}
    assert params[TIER_RACK] == (250, 30, 100)
    assert params[TIER_WAN] == (1200, 8, 100)
    digest = factory.edge_digest()
    assert [row[0] for row in digest[:2]] == sorted(str(s) for s in wan)


def test_topology_tier_resolver_maps_widest_separating_boundary():
    topo = LatencyTopology(racks=8, zones=4, regions=2,
                           rack_rtt_ms=1, zone_rtt_ms=4, region_rtt_ms=20,
                           inter_region_rtt_ms=150)
    index = {SUBJECTS[0]: 8, SUBJECTS[1]: 4, SUBJECTS[2]: 2, SUBJECTS[3]: 1}
    tier_of = topology_tier_resolver(topo, 0, index.get)
    assert tier_of(SUBJECTS[0]) == TIER_RACK    # same rack as index 0
    assert tier_of(SUBJECTS[1]) == TIER_ZONE    # same zone, other rack
    assert tier_of(SUBJECTS[2]) == TIER_REGION  # same region, other zone
    assert tier_of(SUBJECTS[3]) == TIER_WAN     # other region
    assert tier_of(OBSERVER) == TIER_DEFAULT    # outside the topology


# ---------------------------------------------------------------------------
# cluster level: zero-collateral gray eviction + telemetry on the wire
# ---------------------------------------------------------------------------


def test_adaptive_cluster_evicts_gray_node_with_zero_collateral():
    n = 4
    h = ClusterHarness(seed=23, use_static_fd=False,
                       settings=_adaptive_settings())
    victim = h.addr(n - 1)
    h.with_faults(FaultPlan(seed=23).slow_node(victim, response_delay_ms=5000))
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant during bootstrap
    h.create_cluster(n, parallel=False)
    h.wait_and_verify_agreement(n)
    # gray scoring only activates on warmed-up edges (warmup_probes
    # answered samples); real gray faults hit long-running clusters
    h.scheduler.run_until(lambda: False, timeout_ms=8_000)

    status = h.instances[h.addr(0)].get_cluster_status()
    assert status.fd_subjects and len(status.fd_rtt_micros) == len(
        status.fd_subjects
    ) == len(status.fd_suspicion_milli)
    assert status.fd_tiers and len(status.fd_tier_interval_ms) == len(
        status.fd_tiers
    )

    before = _gray_alert_total()
    h.nemesis.arm()  # the victim turns gray now
    start = h.scheduler.now_ms()
    vic = h.instances.pop(victim)  # keeps running: slow, not dead
    try:
        h.wait_and_verify_agreement(n - 1)
        detect_ms = h.scheduler.now_ms() - start
        survivors = set(h.instances[h.addr(0)].get_memberlist())
        assert vic.get_membership_size() >= 1  # the gray node is alive
    finally:
        vic.shutdown()
        h.shutdown()
    assert survivors == {h.addr(i) for i in range(n - 1)}  # zero collateral
    assert _gray_alert_total() > before
    # gray_confirm misses at the static 1 s interval plus consensus: far
    # inside the static hard path's ~12.5 s detection->decision budget
    assert detect_ms <= 8_000, detect_ms


def test_status_fd_fields_survive_both_wires():
    """The fd columns of ClusterStatusResponse round-trip through the
    msgpack codec and the gRPC oneofs; an old frame parses to defaults."""
    r = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=9,
        membership_size=3,
        fd_subjects=("h:2", "h:3"), fd_rtt_micros=(1500, 0),
        fd_suspicion_milli=(333, 0), fd_tiers=("rack", "wan"),
        fd_tier_interval_ms=(250, 1200), fd_tier_threshold=(30, 8),
        fd_tier_flush_ms=(10, 100),
    )
    assert decode(encode(4, r)) == (4, r)
    wire = gt.to_wire_response(r).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == r
    old = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=1,
        membership_size=2,
    )
    wire = gt.to_wire_response(old).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == old and back.fd_subjects == () and back.fd_tiers == ()


# ---------------------------------------------------------------------------
# search plane green with adaptation enabled (sim fd_gray_confirm seam)
# ---------------------------------------------------------------------------

# the known-bug plan from tests/test_search.py, reused verbatim so the
# rediscovery runs against the same witness with adaptation switched on
BUG_PLAN = {"seed": 3, "rules": [
    {"type": "DropRule", "at": "egress", "windows": [[0, None]],
     "src": None, "dst": "node:7003", "msg_types": ["Put"],
     "probability": 1.0},
    {"type": "PartitionRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7000", "msg_types": None},
    {"type": "DropRule", "at": "egress", "windows": [[1200, None]],
     "src": None, "dst": "node:7002", "msg_types": ["Get"],
     "probability": 1.0},
]}

ADAPTATION_ON = {"fd_gray_confirm": 3, "fd_gray_warmup": 3}


class TestSearchPlaneWithAdaptation:
    @pytest.mark.parametrize("path", CORPUS, ids=[p.stem for p in CORPUS])
    def test_corpus_pins_stay_green_with_adaptation_enabled(self, path):
        artifact = json.loads(path.read_text())
        probe = {
            k: v for k, v in artifact.items()
            if k not in ("name", "description", "expect")
        }
        probe.update(ADAPTATION_ON)
        result = run_probe(probe)
        assert not result.violations, [
            v["invariant"] for v in result.violations
        ]

    def test_newrow_sync_rediscovery_with_adaptation_enabled(
        self, monkeypatch
    ):
        spec = {"harness": "engine", "n": 5, "partitions": 16, "replicas": 3,
                "horizon_ms": 4000, "ops": 40, "keys": 6, "plan": BUG_PLAN,
                **ADAPTATION_ON}
        monkeypatch.setenv("RAPID_BUG_NEWROW_SYNC", "1")
        assert {v["invariant"] for v in run_probe(spec).violations} == {
            "linearizability"
        }
        monkeypatch.delenv("RAPID_BUG_NEWROW_SYNC")
        assert not run_probe(spec).violations

    def test_sim_probe_with_gray_mirror_deterministic_and_collateral_free(
        self,
    ):
        """A pure-gray sim probe with the mirror on: the gray-collateral
        invariant holds and the probe stays bit-deterministic."""
        spec = {
            "harness": "sim", "n": 4, "capacity": 5, "horizon_ms": 20_000,
            "ops": 30, "keys": 8, **ADAPTATION_ON,
            "plan": {"seed": 5, "rules": [
                {"type": "SlowNodeRule", "at": "egress",
                 "windows": [[5000, None]], "src": None,
                 "dst": "10.0.0.3:5003", "msg_types": None,
                 "response_delay_ms": 5000},
            ]},
        }
        first = run_probe(spec)
        second = run_probe(spec)
        assert first.violations == second.violations == ()
        assert first.coverage == second.coverage
        assert first.info["view_changes"] >= 1  # the gray node was evicted


# ---------------------------------------------------------------------------
# sim plane: gray streak mirror, scan path vs closed-form fast path
# ---------------------------------------------------------------------------


def _assert_states_equal(a, b):
    for name in a.__dataclass_fields__:
        av, bv = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        if name == "rng_key":
            continue  # scan path consumes RNG per round; fast path does not
        np.testing.assert_array_equal(av, bv, err_msg=f"field {name} diverged")


def _gray_sim(config, seed=1, healthy_rounds=4, victim=5):
    """A sim with warmed-up FD histories (healthy_rounds of clean probes)
    and one node turned gray (alive, probes dropped)."""
    sim = Simulator(config.capacity, config=config, seed=seed)
    inputs = const_inputs(config, sim.alive)
    sim.state = run_rounds_const(config, sim.state, inputs, healthy_rounds,
                                 False)
    sim.one_way_ingress_partition(np.array([victim]))
    gray = const_inputs(config, sim.alive, probe_drop=sim._probe_drop_mask())  # noqa: SLF001
    return sim, gray


def test_gray_streak_path_matches_scan_path():
    config = SimConfig(capacity=8, k=3, h=3, l=2, fd_threshold=10,
                       fd_gray_confirm=3, fd_gray_warmup=2)
    sim, gray = _gray_sim(config)
    scan = run_rounds_const(config, sim.state, gray, 12, False)
    fast = run_until_decided_const(config, sim.state, gray, jnp.int32(12),
                                   True)
    if int(fast.round) < int(scan.round):
        fast = run_rounds_const(config, fast, gray,
                                int(scan.round) - int(fast.round), False)
    _assert_states_equal(scan, fast)
    assert bool(scan.decided)


def test_gray_streak_fires_before_static_threshold():
    """Same gray plane, mirror on vs off: the streak path decides several
    rounds before the cumulative counter reaches fd_threshold."""

    def decide_round(confirm):
        config = SimConfig(capacity=8, k=3, h=3, l=2, fd_threshold=10,
                           fd_gray_confirm=confirm, fd_gray_warmup=2)
        sim, gray = _gray_sim(config)
        fast = run_until_decided_const(config, sim.state, gray,
                                       jnp.int32(24), True)
        assert bool(fast.decided)
        return int(fast.round)

    assert decide_round(3) <= decide_round(0) - 5


def test_gray_streak_state_resumes_across_dispatches():
    """fd_streak/fd_ok carried over a dispatch boundary must reconstruct
    identically on the closed-form path."""
    config = SimConfig(capacity=8, k=3, h=3, l=2, fd_threshold=10,
                       fd_gray_confirm=4, fd_gray_warmup=2)
    sim, gray = _gray_sim(config)
    state_a = state_b = sim.state
    for chunk in (2, 3, 2, 5):
        state_a = run_rounds_const(config, state_a, gray, chunk, False)
        state_b = run_until_decided_const(config, state_b, gray,
                                          jnp.int32(chunk), True)
        if int(state_b.round) < int(state_a.round):
            state_b = run_rounds_const(
                config, state_b, gray,
                int(state_a.round) - int(state_b.round), False,
            )
        _assert_states_equal(state_a, state_b)


def test_gray_streak_staggered_phases_matches_scan_path():
    """rounds_per_interval > 1: only probing rounds advance the streak, in
    both lowerings identically."""
    config = SimConfig(capacity=16, k=4, h=3, l=2, fd_threshold=8,
                       fd_gray_confirm=3, fd_gray_warmup=2,
                       rounds_per_interval=4)
    sim, gray = _gray_sim(config, seed=3, healthy_rounds=12, victim=9)
    scan = run_rounds_const(config, sim.state, gray, 32, False)
    fast = run_until_decided_const(config, sim.state, gray, jnp.int32(32),
                                   True)
    if int(fast.round) < int(scan.round):
        fast = run_rounds_const(config, fast, gray,
                                int(scan.round) - int(fast.round), False)
    _assert_states_equal(scan, fast)
