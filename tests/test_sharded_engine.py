"""Sharded engine on the virtual 8-device CPU mesh: the multi-chip protocol
round must compile, execute, and agree with the single-device engine.
"""

import jax
import numpy as np
import pytest

from rapid_tpu.shard.engine import (
    make_mesh,
    make_sharded_run,
    place_inputs,
    place_state,
)
from rapid_tpu.sim.engine import SimConfig, const_inputs, initial_state, run_rounds_const
from rapid_tpu.sim.topology import VirtualCluster


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should have forced 8 CPU devices"
    return make_mesh(8)


def build(c=64, seed=21):
    cfg = SimConfig(capacity=c)
    vc = VirtualCluster.synthesize(c, cfg.k, seed=seed)
    active = np.ones(c, dtype=bool)
    return cfg, vc, active, initial_state(cfg, vc, active, seed=seed)


def test_sharded_crash_matches_single_device(mesh):
    cfg, vc, active, state = build()
    alive = active.copy()
    alive[[5, 40, 41]] = False
    inputs = const_inputs(cfg, alive)

    run = make_sharded_run(cfg, mesh, rounds=12)
    sharded_out = run(place_state(state, mesh), place_inputs(inputs, mesh))
    single_out = run_rounds_const(cfg, state, inputs, 12)

    assert bool(sharded_out.decided) and bool(single_out.decided)
    cut_sharded = set(np.flatnonzero(np.asarray(sharded_out.proposal)))
    cut_single = set(np.flatnonzero(np.asarray(single_out.proposal)))
    assert cut_sharded == cut_single == {5, 40, 41}
    assert int(sharded_out.decided_round) == int(single_out.decided_round)
    # per-edge state agrees too (deterministic when no random drops)
    np.testing.assert_array_equal(
        np.asarray(sharded_out.fd_fail), np.asarray(single_out.fd_fail)
    )


def test_sharded_state_is_actually_sharded(mesh):
    cfg, vc, active, state = build()
    placed = place_state(state, mesh)
    shards = placed.fd_fail.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape == (64 // 8, cfg.k)
    # replicated arrays present fully on every device ([G, C, K] report table)
    rep_shards = placed.reports.addressable_shards
    assert all(s.data.shape == (cfg.groups, 64, cfg.k) for s in rep_shards)


def test_sharded_no_fault_no_decision(mesh):
    cfg, vc, active, state = build(seed=22)
    inputs = const_inputs(cfg, active.copy())
    run = make_sharded_run(cfg, mesh, rounds=8)
    out = run(place_state(state, mesh), place_inputs(inputs, mesh))
    assert not bool(out.decided)
    assert int(out.round) == 8


def test_sharded_uneven_capacity_rejected(mesh):
    """Capacity must divide the mesh for row sharding; a clear error beats a
    silent wrong answer."""
    cfg = SimConfig(capacity=60)  # 60 % 8 != 0
    with pytest.raises(AssertionError, match="divide evenly"):
        make_sharded_run(cfg, mesh, rounds=2)


def test_sharded_windowed_fd_matches_single_device(mesh):
    """The windowed FD policy produces identical cuts, rounds, and per-edge
    window state on the mesh and on a single device."""
    cfg = SimConfig(capacity=64, fd_policy="windowed")
    vc = VirtualCluster.synthesize(64, cfg.k, seed=23)
    active = np.ones(64, dtype=bool)
    state = initial_state(cfg, vc, active, seed=23)
    alive = active.copy()
    alive[[9, 50]] = False
    inputs = const_inputs(cfg, alive)

    run = make_sharded_run(cfg, mesh, rounds=12)
    sharded_out = run(place_state(state, mesh), place_inputs(inputs, mesh))
    single_out = run_rounds_const(cfg, state, inputs, 12, False)

    assert bool(sharded_out.decided) and bool(single_out.decided)
    cut_sharded = set(np.flatnonzero(np.asarray(sharded_out.proposal)))
    cut_single = set(np.flatnonzero(np.asarray(single_out.proposal)))
    assert cut_sharded == cut_single == {9, 50}
    np.testing.assert_array_equal(
        np.asarray(sharded_out.fd_hist), np.asarray(single_out.fd_hist)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded_out.fd_seen), np.asarray(single_out.fd_seen)
    )


def test_2d_dcn_ici_mesh_matches_single_device():
    """A (hosts, chips) 2D mesh -- per-edge state row-sharded over both axes,
    alert reduction over ("dcn", "ici") -- produces the same decision as a
    single device (the multi-host layout, validated on 2x4 CPU devices)."""
    mesh2d = make_mesh(shape=(2, 4))
    assert mesh2d.axis_names == ("dcn", "ici")
    cfg, vc, active, state = build(c=64, seed=29)
    alive = active.copy()
    alive[[7, 33]] = False
    inputs = const_inputs(cfg, alive)

    run = make_sharded_run(cfg, mesh2d, rounds=12)
    sharded_out = run(place_state(state, mesh2d), place_inputs(inputs, mesh2d))
    single_out = run_rounds_const(cfg, state, inputs, 12, False)

    assert bool(sharded_out.decided) and bool(single_out.decided)
    cut_sharded = set(np.flatnonzero(np.asarray(sharded_out.proposal)))
    assert cut_sharded == {7, 33}
    assert int(sharded_out.decided_round) == int(single_out.decided_round)
    np.testing.assert_array_equal(
        np.asarray(sharded_out.fd_fail), np.asarray(single_out.fd_fail)
    )


def test_make_mesh_1d_shape_names_ici():
    m = make_mesh(shape=(8,))
    assert m.axis_names == ("ici",)


def test_make_multihost_mesh_rejects_uneven_rows(monkeypatch):
    """Heterogeneous per-process device counts must fail loudly at mesh
    construction (shard/engine.py row grouping), naming the widths and the
    chips_per_host escape hatch."""
    import jax

    import rapid_tpu.shard.engine as eng

    class FakeDevice:
        def __init__(self, i, proc):
            self.id = i
            self.process_index = proc

    fakes = [FakeDevice(0, 0), FakeDevice(1, 0), FakeDevice(2, 1)]
    monkeypatch.setattr(jax, "devices", lambda: fakes)
    with pytest.raises(ValueError, match="uneven devices per process"):
        eng.make_multihost_mesh()
    # chips_per_host truncates every host to a common width: accepted
    mesh = eng.make_multihost_mesh(chips_per_host=1)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (2, 1)
