"""Closing regression tests for the ADVICE.md findings fixed in this PR.

One test per finding, each constructed to fail on the pre-fix code:

1. gossip: the pushpull payload-store deque no longer grows without bound
   under age-driven dedup-table turnover (head compaction);
2. gossip: payload-ceiling eviction is oldest-first by store *generation*,
   so a re-stored id keeps its fresh payload until its own turn;
3. codec: two threads racing to pack the same large message no longer
   double-count its bytes against the body-memo budget;
4. sim: SimConfig rejects fd_threshold values the uint8 failure counter
   could never reach;
5. gateway: the liveness monitor thread only starts after the dial/delivery
   executors it dereferences are assigned.

Plus one test per concurrency finding surfaced by tools/concur.py (the
lock-graph static analyzer) and fixed in the same PR that introduced it --
see the "concur.py findings" section at the bottom.

Plus one test per device-plane finding surfaced by tools/devlint.py and the
runtime jitwatch (unbounded scan-length compile classes, per-dispatch scalar
uploads, a host sync on the extern-vote fast path, a per-call jit rebuild in
the placement builder) -- see the "devlint/jitwatch findings" section.
"""

import random
import threading

import pytest

from rapid_tpu.messaging import codec
from rapid_tpu.messaging import gossip as gossip_mod
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.types import (
    Endpoint,
    GossipEnvelope,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    ProbeMessage,
)

ME = Endpoint.from_parts("10.1.0.1", 9)
PEER = Endpoint.from_parts("10.1.0.2", 9)


class _NullClient:
    def send_message_best_effort(self, remote, msg):
        return Promise.completed(None)


def _pushpull(fanout=2):
    b = GossipBroadcaster(
        _NullClient(), ME, fanout=fanout, mode="pushpull",
        rng=random.Random(0),
    )
    b.set_membership([ME, PEER])
    return b


def _envelope(i):
    return GossipEnvelope(
        sender=PEER, gossip_id=NodeId(i, ~i), ttl=3,
        payload=ProbeMessage(sender=PEER),
    )


def test_gossip_payload_deque_bounded_under_table_turnover(monkeypatch):
    """ADVICE: age-evicted dedup entries left dead slots in _payload_keys
    forever; the deque must stay proportional to the LIVE store, not to the
    total envelope history."""
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 8)
    # negative min age: every entry is immediately old enough to evict
    monkeypatch.setattr(gossip_mod, "_SEEN_MIN_AGE_S", -1.0)
    b = _pushpull()
    for i in range(200):
        b.receive(_envelope(i))
    assert len(b._seen) <= 8
    # pre-fix: ~200 dead slots; post-fix: bounded by the live store
    assert len(b._payload_keys) <= 2 * 8
    # every remaining slot refers to a live generation
    assert all(
        b._payload_gen.get(key) == gen for key, gen in b._payload_keys
    )


def test_gossip_payload_ceiling_evicts_oldest_first_across_restores(
    monkeypatch,
):
    """ADVICE: without store generations, a re-stored id's stale deque slot
    could null its FRESH payload out of order. Eviction must consume ids
    strictly oldest-store-first."""
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 4)
    # huge min age: the dedup table never evicts, so the payload ceiling
    # (not table turnover) is what reclaims storage
    monkeypatch.setattr(gossip_mod, "_SEEN_MIN_AGE_S", 1e9)
    b = _pushpull()
    b.set_membership([ME])  # cap = max(_SEEN_CAP, 4 * |members|) = 4

    def key(i):
        return (i, ~i)

    def stored(i):
        entry = b._seen.get(key(i))
        return entry is not None and entry[2] is not None

    for i in range(1, 5):
        b.receive(_envelope(i))  # e1..e4 stored, at the ceiling
    assert all(stored(i) for i in range(1, 5))
    b.receive(_envelope(5))  # over the ceiling: e1 (oldest) is nulled
    assert not stored(1) and all(stored(i) for i in range(2, 6))
    # e1 seen again: re-stored under a NEW generation; the ceiling must now
    # take e2 (the oldest live store), not the freshly re-stored e1
    b.receive(_envelope(1))
    assert stored(1) and not stored(2)
    b.receive(_envelope(6))  # next oldest is e3
    assert not stored(3)
    assert stored(1) and stored(4) and stored(5) and stored(6)


def test_codec_body_memo_bytes_not_double_counted_on_pack_race():
    """ADVICE: two threads racing encode() on the same large message both
    packed and both added their bytes; the replaced entry's bytes must come
    off the budget. A barrier inside packb forces the lost-race interleaving
    deterministically."""
    msg = JoinResponse(
        sender=ME, status_code=JoinStatusCode.SAFE_TO_JOIN,
        configuration_id=1,
        endpoints=tuple(
            Endpoint.from_parts("10.9.%d.%d" % (i // 250, i % 250), 4000 + i)
            for i in range(4000)
        ),
        identifiers=(NodeId(1, 2),),
    )
    real_packb = codec.msgpack.packb
    barrier = threading.Barrier(2, timeout=20)

    def racing_packb(payload, **kw):
        body = real_packb(payload, **kw)
        barrier.wait()  # both threads pack before either inserts
        return body

    with codec._body_memo_lock:
        bytes_before = codec._body_memo_bytes
    errors = []
    frames = []

    def encode_once(request_no):
        try:
            frames.append(codec.encode(request_no, msg))
        except Exception as e:  # noqa: BLE001 -- surfaced via the assert below
            errors.append(e)

    codec.msgpack.packb = racing_packb
    try:
        threads = [
            threading.Thread(target=encode_once, args=(i,)) for i in (1, 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        codec.msgpack.packb = real_packb
    try:
        assert not errors, errors
        body_len = len(frames[0]) - codec.ENVELOPE.size
        assert body_len >= codec._BODY_MEMO_MIN  # the memo path actually ran
        with codec._body_memo_lock:
            # pre-fix: 2 * body_len (the loser's insert double-counted)
            assert codec._body_memo_bytes - bytes_before == body_len
    finally:
        with codec._body_memo_lock:
            entry = codec._body_memo.pop(id(msg), None)
            if entry is not None:
                codec._body_memo_bytes -= len(entry[1])


def test_sim_config_rejects_unreachable_fd_threshold():
    """ADVICE: the per-edge failure counter is uint8; a threshold past 255
    would silently never fire. Constructing such a config must fail."""
    from rapid_tpu.sim.engine import SimConfig

    SimConfig(capacity=4)  # defaults fine
    SimConfig(capacity=4, fd_threshold=255)  # inclusive upper bound
    with pytest.raises(AssertionError):
        SimConfig(capacity=4, fd_threshold=256)
    with pytest.raises(AssertionError):
        SimConfig(capacity=4, fd_threshold=0)


def test_gateway_monitor_thread_starts_after_executors(monkeypatch):
    """ADVICE: the liveness monitor was started before the dial/delivery
    executors existed; a promptly-scheduled first refresh crashed on the
    missing attributes. Run the thread body synchronously inside start()
    (the worst-case scheduling) and require the executors to be there."""
    from rapid_tpu.messaging import gateway as gw

    seen = {}

    def probe_loop(self):
        seen["dialers"] = hasattr(self, "_dialers")
        seen["delivery"] = hasattr(self, "_delivery")

    monkeypatch.setattr(gw._GatewayNetwork, "_monitor_loop", probe_loop)
    monkeypatch.setattr(threading.Thread, "start", lambda self: self.run())
    net = gw._GatewayNetwork(None, None)
    try:
        assert seen == {"dialers": True, "delivery": True}
    finally:
        net._stop.set()
        net._dialers.shutdown(wait=False)
        for lane in net._delivery:
            lane.shutdown(wait=False)


# ---------------------------------------------------------------------------
# concur.py findings: each test fails on the pre-fix code
# ---------------------------------------------------------------------------

from types import SimpleNamespace  # noqa: E402

from rapid_tpu.cluster import Cluster  # noqa: E402
from rapid_tpu.fast_paxos import FastPaxos  # noqa: E402
from rapid_tpu.messaging.gateway import SwarmGateway  # noqa: E402
from rapid_tpu.runtime.lockdep import make_lock  # noqa: E402
from rapid_tpu.service import MembershipService  # noqa: E402


class _RecordingExecutor:
    """Captures protocol_executor.execute posts without running them."""

    def __init__(self):
        self.posted = []

    def execute(self, task):
        self.posted.append(task)


def test_alert_batcher_tick_hops_onto_protocol_executor():
    """concur: the batching-window tick fires on the scheduler's timer
    thread while _enqueue_alert appends on the protocol executor; the tick
    body must run on the executor, not touch the queue in place."""
    executor = _RecordingExecutor()
    fake = SimpleNamespace(
        _resources=SimpleNamespace(protocol_executor=executor),
        _alert_batcher_flush=lambda: None,
    )
    MembershipService._alert_batcher_tick(fake)
    assert executor.posted == [fake._alert_batcher_flush]


def test_service_shutdown_cancels_detectors_on_protocol_executor():
    """concur: _failure_detector_jobs is protocol-executor confined
    (_create_failure_detectors runs there); shutdown must post the cancel
    instead of mutating the list from the caller's thread."""
    executor = _RecordingExecutor()
    client_calls = []
    fake = SimpleNamespace(
        _shut_down=False,
        _alert_batcher_job=SimpleNamespace(cancel=lambda: None),
        _hierarchy_job=None,
        _resources=SimpleNamespace(protocol_executor=executor),
        _client=SimpleNamespace(shutdown=lambda: client_calls.append(1)),
        _cancel_failure_detectors=lambda: None,
    )
    MembershipService.shutdown(fake)
    assert executor.posted == [fake._cancel_failure_detectors]
    assert client_calls == [1]
    # idempotent: a second call must not re-post or re-shutdown
    MembershipService.shutdown(fake)
    assert len(executor.posted) == 1 and client_calls == [1]


class _FakeScheduler:
    def __init__(self):
        self.scheduled = []

    def schedule(self, delay_ms, fn):
        task = SimpleNamespace(fn=fn, cancelled=False)
        task.cancel = lambda: setattr(task, "cancelled", True)
        self.scheduled.append(task)
        return task


def _fast_paxos(serialize):
    from rapid_tpu.types import Endpoint

    me = Endpoint.from_parts("10.0.0.1", 1)
    client = SimpleNamespace(
        send_message_best_effort=lambda remote, msg: None
    )
    broadcaster = SimpleNamespace(broadcast=lambda msg: None)
    sched = _FakeScheduler()
    fp = FastPaxos(
        me, configuration_id=7, membership_size=4, client=client,
        broadcaster=broadcaster, scheduler=sched,
        on_decide=lambda hosts: None, serialize=serialize,
    )
    return fp, sched, me


def test_fast_paxos_fallback_reenters_through_serializer():
    """concur: the classic-round fallback fires on the timer thread; it must
    hop through the injected serializer before touching consensus state, not
    call start_classic_paxos_round in place."""
    posted = []
    fp, sched, me = _fast_paxos(serialize=posted.append)
    fp.propose([me], recovery_delay_ms=5)
    assert len(sched.scheduled) == 1
    sched.scheduled[0].fn()  # the timer firing
    # nothing ran yet: the round start is parked on the serializer
    assert posted == [fp.start_classic_paxos_round]


def test_fast_paxos_default_serializer_is_direct_call():
    """The single-threaded virtual plane passes no serializer; the fallback
    must still reach the classic round synchronously."""
    fp, sched, me = _fast_paxos(serialize=None)
    started = []
    fp.start_classic_paxos_round = lambda: started.append(1)
    fp._classic_round_fallback()
    assert started == [1]


def test_gateway_warn_once_is_thread_safe():
    """concur: the warn-once set is hit by the probe reader thread and the
    protocol thread; exactly one of N concurrent callers may win."""
    from rapid_tpu.types import Endpoint

    fake = SimpleNamespace(
        _warned_lock=make_lock("test.SwarmGateway._warned_lock"),
        _warned_unowned=set(),
    )
    dst = Endpoint.from_parts("10.0.0.9", 9)
    wins = []
    barrier = threading.Barrier(8, timeout=20)

    def race():
        barrier.wait()
        if SwarmGateway._warn_unowned_once(fake, dst):
            wins.append(1)

    threads = [threading.Thread(target=race, daemon=True) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert len(wins) == 1
    # a different endpoint warns independently
    other = Endpoint.from_parts("10.0.0.10", 9)
    assert SwarmGateway._warn_unowned_once(fake, other)
    assert not SwarmGateway._warn_unowned_once(fake, other)


def test_tcp_dial_happens_outside_the_connection_cache_lock(monkeypatch):
    """concur: connect() can block for seconds on a dead peer; dialing under
    _conn_lock stalls every sender on the node. The dial must run with the
    lock released."""
    from rapid_tpu.messaging import tcp as tcp_mod
    from rapid_tpu.types import Endpoint

    cs = tcp_mod.TcpClientServer(Endpoint.from_parts("127.0.0.1", 0))
    held_during_dial = []

    class _FakeConn:
        def __init__(self, remote, timeout_s, **kwargs):
            held_during_dial.append(cs._conn_lock.locked())
            self.closed = False

        def close(self):
            self.closed = True

    monkeypatch.setattr(tcp_mod, "_Connection", _FakeConn)
    remote = Endpoint.from_parts("10.0.0.2", 4)
    conn = cs._connection(remote)
    assert held_during_dial == [False]
    assert cs._connection(remote) is conn  # cached: no second dial
    assert held_during_dial == [False]


def test_tcp_dial_race_loser_closes_its_fresh_connection(monkeypatch):
    """Two threads dialing the same remote: the loser must adopt the winner's
    established connection and close its own, never clobber the cache."""
    from rapid_tpu.messaging import tcp as tcp_mod
    from rapid_tpu.types import Endpoint

    cs = tcp_mod.TcpClientServer(Endpoint.from_parts("127.0.0.1", 0))
    remote = Endpoint.from_parts("10.0.0.3", 4)
    winner = SimpleNamespace(closed=False, close=lambda: None)
    fresh_conns = []

    class _RacingConn:
        def __init__(self, r, timeout_s, **kwargs):
            # while this thread was dialing, another thread won the race
            cs._connections[remote] = winner
            self.closed = False
            fresh_conns.append(self)

        def close(self):
            self.closed = True

    monkeypatch.setattr(tcp_mod, "_Connection", _RacingConn)
    got = cs._connection(remote)
    assert got is winner
    assert cs._connections[remote] is winner  # cache not clobbered
    # the loser's fresh socket was closed, not leaked
    assert len(fresh_conns) == 1 and fresh_conns[0].closed


def test_tcp_failed_dial_gates_redials_behind_jittered_backoff(monkeypatch):
    """A refused dial must open a per-peer backoff gate: until the window
    (drawn from the decorrelated-jitter RetryPolicy) expires, further
    ``_connection`` calls fail fast with ConnectionError -- no socket work,
    no retry storm against a dead peer -- and ``msg.dial_backoffs`` counts
    each shed attempt. Success clears the gate entirely."""
    from rapid_tpu.messaging import tcp as tcp_mod
    from rapid_tpu.types import Endpoint

    cs = tcp_mod.TcpClientServer(Endpoint.from_parts("127.0.0.1", 0))
    remote = Endpoint.from_parts("10.0.0.4", 4)
    dials = []

    class _RefusedConn:
        def __init__(self, r, timeout_s, **kwargs):
            dials.append(r)
            raise ConnectionRefusedError("refused")

    monkeypatch.setattr(tcp_mod, "_Connection", _RefusedConn)
    with pytest.raises(ConnectionRefusedError):
        cs._connection(remote)
    assert dials == [remote]
    # inside the window: shed without dialing
    with pytest.raises(ConnectionError) as shed:
        cs._connection(remote)
    assert "backoff" in str(shed.value)
    assert dials == [remote]  # the socket was never touched again
    assert cs.metrics.snapshot().get("msg.dial_backoffs") == 1
    # the drawn delay obeys the policy bounds [base, cap]
    gate = cs._dial_gate[remote]
    assert (
        cs._settings.dial_backoff_base_ms
        <= gate["prev"]
        <= cs._settings.dial_backoff_max_ms
    )
    # window expiry lets a real dial through again (which fails and re-arms)
    gate["until"] = 0.0
    with pytest.raises(ConnectionRefusedError):
        cs._connection(remote)
    assert dials == [remote, remote]
    assert cs._dial_gate[remote]["until"] > 0.0
    # an eventual success clears the gate: the next dial is immediate
    cs._dial_outcome(remote, True)
    assert remote not in cs._dial_gate


def test_cluster_shutdown_runs_teardown_exactly_once_under_races():
    """concur: shutdown() races leave_gracefully_async's completion callback
    against user-thread calls; exactly one caller may run the (blocking)
    teardown, and it must run outside the flag lock."""
    calls = {"server": 0, "service": 0, "resources": 0}
    fake = SimpleNamespace(
        _shutdown_lock=make_lock("test.Cluster._shutdown_lock"),
        _has_shutdown=False,
        _server=SimpleNamespace(
            shutdown=lambda: calls.__setitem__("server", calls["server"] + 1)
        ),
        _membership_service=SimpleNamespace(
            shutdown=lambda: calls.__setitem__("service", calls["service"] + 1),
            handoff_engine=lambda: None,
        ),
        _resources=SimpleNamespace(
            shutdown=lambda: calls.__setitem__(
                "resources", calls["resources"] + 1
            )
        ),
    )
    barrier = threading.Barrier(6, timeout=20)

    def caller():
        barrier.wait()
        Cluster.shutdown(fake)

    threads = [threading.Thread(target=caller, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert calls == {"server": 1, "service": 1, "resources": 1}


# ---------------------------------------------------------------------------
# devlint/jitwatch findings: each test fails on the pre-fix code
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402

from rapid_tpu.runtime import jitwatch  # noqa: E402
from rapid_tpu.sim.driver import Simulator, _pow2_chunks  # noqa: E402


def test_pow2_chunks_bounds_the_compile_classes():
    """devlint recompile-hazard: the random-loss path scanned an arbitrary
    remainder length, giving every distinct max_rounds its own jit cache
    entry. Chunk lengths must come from {batch} + powers of two below it, so
    the cache holds at most log2(batch)+1 entries regardless of caller."""
    assert _pow2_chunks(37, 8) == [8, 8, 8, 8, 4, 1]
    assert _pow2_chunks(8, 8) == [8]
    assert _pow2_chunks(1, 8) == [1]
    assert _pow2_chunks(40, 16) == [16, 16, 8]
    for n in range(1, 200):
        chunks = _pow2_chunks(n, 16)
        assert sum(chunks) == n
        assert set(chunks) <= {16, 8, 4, 2, 1}  # the bounded class alphabet


def test_driver_uploads_each_round_budget_once():
    """devlint host-sync: run_until_decision materialized jnp.int32(n) per
    dispatch -- a per-call host->device transfer inside the hot loop. The
    scalar must be uploaded through the audited seam once per distinct
    value, then served from the cache."""
    sim = Simulator(16, seed=3).ready()
    before = jitwatch.sync_counts().get("sim.batch_budget", 0)
    a = sim._i32(16)
    b = sim._i32(16)
    c = sim._i32(16)
    assert a is b is c  # cached device scalar, not re-uploaded
    assert jitwatch.sync_counts().get("sim.batch_budget", 0) == before + 1
    sim._i32(8)  # a new value is one more audited upload
    assert jitwatch.sync_counts().get("sim.batch_budget", 0) == before + 2


def test_extern_vote_fast_path_does_not_sync_host():
    """devlint host-sync: register_extern_vote fetched the slot's classic
    round rank on EVERY registration, but the rank can only exceed the fast
    rank after a classic fallback has run. Until then the fetch is pure
    overhead -- the fast path must do zero device->host syncs."""
    from rapid_tpu.sim.engine import SimConfig

    sim = Simulator(
        16, config=SimConfig(capacity=16, extern_proposals=1), seed=3
    ).ready()
    assert sim._classic_attempts == 0
    before = jitwatch.sync_counts().get("sim.extern_vote_rank", 0)
    assert sim.register_extern_vote(5, np.array([2]))
    assert jitwatch.sync_counts().get("sim.extern_vote_rank", 0) == before


def test_placement_builder_jit_is_cached_per_shape():
    """devlint recompile-hazard: build_jit created a fresh make_jit (fresh
    jax cache) on every call, recompiling the whole map builder per
    rebalance. The jitted object must be cached by (n_instances, replicas)
    and reused."""
    from rapid_tpu.placement import device as pdev

    first = pdev._builder(4, 2)
    assert pdev._builder(4, 2) is first  # same object, same jit cache
    assert pdev._builder(4, 3) is not first  # distinct shape class

    # dispatching the cached builder twice with same-shaped inputs compiles
    # at most once more (the second call is a pure cache hit)
    p32 = np.arange(8, dtype=np.uint32)
    inst = np.arange(4 * 6, dtype=np.uint32).reshape(4, 6)
    w = np.full(6, 4, dtype=np.uint32)
    act = np.ones(6, dtype=bool)
    first(p32, inst, w, act)
    n_compiles = jitwatch.compile_count("placement.build_jit")
    first(p32, inst, w, act)
    assert jitwatch.compile_count("placement.build_jit") == n_compiles


def test_warmed_decision_loop_is_steady_state_clean():
    """The headline property the whole suite defends: a warmed simulator
    reaches a decision inside a declared timed window -- zero recompiles,
    zero unaudited host transfers -- with the decided cut intact."""
    sim = Simulator(64, seed=5).ready()
    sim.crash(np.array([3]))
    record = sim.run_until_decision(max_rounds=40)  # warmup decision
    assert record is not None

    sim2 = Simulator(64, seed=5).ready()  # same shapes: fully warm
    sim2.crash(np.array([3]))
    before = jitwatch.stats()
    with jitwatch.timed_window("test.steady_decision"):
        record2 = sim2.run_until_decision(max_rounds=40)
    after = jitwatch.stats()
    assert record2 is not None
    assert 3 in record2.cut
    assert after["compiles"] == before["compiles"]
    assert jitwatch.violations() == []


# ---------------------------------------------------------------------------
# PR 14: RTT EWMA cold-start bias
# ---------------------------------------------------------------------------


def test_rtt_variance_seeds_from_first_k_samples_not_a_point_estimate():
    """One slow first probe on a fresh WAN edge must not pin the deviation
    estimate: rtt_var_ms stays None until RTT_SEED_SAMPLES answered probes,
    then seeds from the window's mean absolute deviation (TCP's single-sample
    R/2 point estimate would have locked in 200 ms here and flagged every
    normal probe as an outlier for many EWMA half-lives). The srtt EWMA
    itself is unchanged."""
    from rapid_tpu.monitoring.pingpong import (
        RTT_SEED_SAMPLES,
        PingPongFailureDetector,
    )
    from rapid_tpu.runtime.scheduler import VirtualScheduler
    from rapid_tpu.types import ProbeResponse

    sched = VirtualScheduler()
    lags = iter([400, 100, 100, 100, 100])

    class _Lagged:
        def send_message_best_effort(self, remote, msg):
            p = Promise()
            sched.schedule(next(lags), lambda: p.try_set_result(ProbeResponse()))
            return p

    fd = PingPongFailureDetector(
        Endpoint.from_parts("a", 1), Endpoint.from_parts("b", 2), _Lagged(),
        notifier=lambda: None, clock=sched.now_ms,
    )
    assert RTT_SEED_SAMPLES == 4
    srtt = None
    for i in range(4):
        fd()
        sched.run_for(401)
        lag = 400 if i == 0 else 100
        srtt = float(lag) if srtt is None else 0.875 * srtt + 0.125 * lag
        assert fd.rtt_ms() == pytest.approx(srtt)  # EWMA path untouched
        if i < 3:
            assert fd.rtt_var_ms() is None  # seeding, not a point estimate
    # seeded from the window's spread: mean 175, MAD (225 + 3*75) / 4
    assert fd.rtt_var_ms() == pytest.approx(112.5)
    # from the 5th sample on, the classic RTTVAR EWMA takes over
    srtt_before = fd.rtt_ms()
    fd()
    sched.run_for(401)
    assert fd.rtt_var_ms() == pytest.approx(
        0.75 * 112.5 + 0.25 * abs(100 - srtt_before)
    )


# ---------------------------------------------------------------------------
# PR 16: durability-plane findings, each test fails on the pre-fix code
# ---------------------------------------------------------------------------


def test_cluster_shutdown_checkpoints_the_wal_before_resources_die():
    """durability: a clean shutdown left the WAL tail unflushed (and, under
    FSYNC_NEVER, possibly only in the page cache) with no snapshot marker,
    so every restart after a GRACEFUL stop paid a full log replay. shutdown()
    must run the store's checkpoint() -- flush + snapshot + marker -- after
    the membership service stops mutating the store but before the shared
    resources are torn down. The in-memory store (no checkpoint()) must be
    left untouched by the same duck-typed seam."""
    order = []

    class _CheckpointingStore:
        def checkpoint(self):
            order.append("checkpoint")

    def _fake(store):
        engine = SimpleNamespace(store=store)
        return SimpleNamespace(
            _shutdown_lock=make_lock("test.Cluster._shutdown_lock16"),
            _has_shutdown=False,
            _server=SimpleNamespace(shutdown=lambda: order.append("server")),
            _membership_service=SimpleNamespace(
                shutdown=lambda: order.append("service"),
                handoff_engine=lambda: engine,
            ),
            _resources=SimpleNamespace(
                shutdown=lambda: order.append("resources")
            ),
        )

    Cluster.shutdown(_fake(_CheckpointingStore()))
    assert order == ["server", "service", "checkpoint", "resources"]

    order.clear()
    Cluster.shutdown(_fake(object()))  # in-memory store: no checkpoint()
    assert order == ["server", "service", "resources"]


def test_handoff_release_syncs_the_wal_before_discarding_the_partition():
    """durability: handle_ack released the source copy the moment the
    recipient verified, but with a durable store the put that the ack
    authorizes discarding may still sit in an unfsynced WAL tail on the
    recipient -- and the SOURCE's own unsynced records could vanish with
    the deleted partition. The release path must call store.sync() before
    store.delete(), and must not touch either when the member is still a
    replica. The in-memory store (no sync()) rides the same duck-typed
    seam untouched."""
    from rapid_tpu.handoff.engine import HandoffEngine
    from rapid_tpu.types import HandoffAck

    order = []

    class _DurableStore:
        def get(self, partition):
            return b"payload"

        def sync(self):
            order.append("sync")

        def delete(self, partition):
            order.append(("delete", partition))

    engine = HandoffEngine(
        _DurableStore(), ME, client=None, scheduler=None,
    )
    ack = HandoffAck(sender=PEER, session_id=7, partition=3, fingerprint=0)
    engine.handle_ack(ack, still_replica=False)
    assert order == ["sync", ("delete", 3)]  # durable BEFORE discarded

    order.clear()
    engine.handle_ack(ack, still_replica=True)
    assert order == []  # still a replica: nothing flushed, nothing dropped

    class _MemoryStore:
        def get(self, partition):
            return b"payload"

        def delete(self, partition):
            order.append(("delete", partition))

    engine = HandoffEngine(_MemoryStore(), ME, client=None, scheduler=None)
    engine.handle_ack(ack, still_replica=False)
    assert order == [("delete", 3)]  # no sync() seam: plain release
