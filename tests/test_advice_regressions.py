"""Closing regression tests for the ADVICE.md findings fixed in this PR.

One test per finding, each constructed to fail on the pre-fix code:

1. gossip: the pushpull payload-store deque no longer grows without bound
   under age-driven dedup-table turnover (head compaction);
2. gossip: payload-ceiling eviction is oldest-first by store *generation*,
   so a re-stored id keeps its fresh payload until its own turn;
3. codec: two threads racing to pack the same large message no longer
   double-count its bytes against the body-memo budget;
4. sim: SimConfig rejects fd_threshold values the uint8 failure counter
   could never reach;
5. gateway: the liveness monitor thread only starts after the dial/delivery
   executors it dereferences are assigned.
"""

import random
import threading

import pytest

from rapid_tpu.messaging import codec
from rapid_tpu.messaging import gossip as gossip_mod
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.types import (
    Endpoint,
    GossipEnvelope,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    ProbeMessage,
)

ME = Endpoint.from_parts("10.1.0.1", 9)
PEER = Endpoint.from_parts("10.1.0.2", 9)


class _NullClient:
    def send_message_best_effort(self, remote, msg):
        return Promise.completed(None)


def _pushpull(fanout=2):
    b = GossipBroadcaster(
        _NullClient(), ME, fanout=fanout, mode="pushpull",
        rng=random.Random(0),
    )
    b.set_membership([ME, PEER])
    return b


def _envelope(i):
    return GossipEnvelope(
        sender=PEER, gossip_id=NodeId(i, ~i), ttl=3,
        payload=ProbeMessage(sender=PEER),
    )


def test_gossip_payload_deque_bounded_under_table_turnover(monkeypatch):
    """ADVICE: age-evicted dedup entries left dead slots in _payload_keys
    forever; the deque must stay proportional to the LIVE store, not to the
    total envelope history."""
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 8)
    # negative min age: every entry is immediately old enough to evict
    monkeypatch.setattr(gossip_mod, "_SEEN_MIN_AGE_S", -1.0)
    b = _pushpull()
    for i in range(200):
        b.receive(_envelope(i))
    assert len(b._seen) <= 8
    # pre-fix: ~200 dead slots; post-fix: bounded by the live store
    assert len(b._payload_keys) <= 2 * 8
    # every remaining slot refers to a live generation
    assert all(
        b._payload_gen.get(key) == gen for key, gen in b._payload_keys
    )


def test_gossip_payload_ceiling_evicts_oldest_first_across_restores(
    monkeypatch,
):
    """ADVICE: without store generations, a re-stored id's stale deque slot
    could null its FRESH payload out of order. Eviction must consume ids
    strictly oldest-store-first."""
    monkeypatch.setattr(gossip_mod, "_SEEN_CAP", 4)
    # huge min age: the dedup table never evicts, so the payload ceiling
    # (not table turnover) is what reclaims storage
    monkeypatch.setattr(gossip_mod, "_SEEN_MIN_AGE_S", 1e9)
    b = _pushpull()
    b.set_membership([ME])  # cap = max(_SEEN_CAP, 4 * |members|) = 4

    def key(i):
        return (i, ~i)

    def stored(i):
        entry = b._seen.get(key(i))
        return entry is not None and entry[2] is not None

    for i in range(1, 5):
        b.receive(_envelope(i))  # e1..e4 stored, at the ceiling
    assert all(stored(i) for i in range(1, 5))
    b.receive(_envelope(5))  # over the ceiling: e1 (oldest) is nulled
    assert not stored(1) and all(stored(i) for i in range(2, 6))
    # e1 seen again: re-stored under a NEW generation; the ceiling must now
    # take e2 (the oldest live store), not the freshly re-stored e1
    b.receive(_envelope(1))
    assert stored(1) and not stored(2)
    b.receive(_envelope(6))  # next oldest is e3
    assert not stored(3)
    assert stored(1) and stored(4) and stored(5) and stored(6)


def test_codec_body_memo_bytes_not_double_counted_on_pack_race():
    """ADVICE: two threads racing encode() on the same large message both
    packed and both added their bytes; the replaced entry's bytes must come
    off the budget. A barrier inside packb forces the lost-race interleaving
    deterministically."""
    msg = JoinResponse(
        sender=ME, status_code=JoinStatusCode.SAFE_TO_JOIN,
        configuration_id=1,
        endpoints=tuple(
            Endpoint.from_parts("10.9.%d.%d" % (i // 250, i % 250), 4000 + i)
            for i in range(4000)
        ),
        identifiers=(NodeId(1, 2),),
    )
    real_packb = codec.msgpack.packb
    barrier = threading.Barrier(2, timeout=20)

    def racing_packb(payload, **kw):
        body = real_packb(payload, **kw)
        barrier.wait()  # both threads pack before either inserts
        return body

    with codec._body_memo_lock:
        bytes_before = codec._body_memo_bytes
    errors = []
    frames = []

    def encode_once(request_no):
        try:
            frames.append(codec.encode(request_no, msg))
        except Exception as e:  # noqa: BLE001 -- surfaced via the assert below
            errors.append(e)

    codec.msgpack.packb = racing_packb
    try:
        threads = [
            threading.Thread(target=encode_once, args=(i,)) for i in (1, 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        codec.msgpack.packb = real_packb
    try:
        assert not errors, errors
        body_len = len(frames[0]) - codec.ENVELOPE.size
        assert body_len >= codec._BODY_MEMO_MIN  # the memo path actually ran
        with codec._body_memo_lock:
            # pre-fix: 2 * body_len (the loser's insert double-counted)
            assert codec._body_memo_bytes - bytes_before == body_len
    finally:
        with codec._body_memo_lock:
            entry = codec._body_memo.pop(id(msg), None)
            if entry is not None:
                codec._body_memo_bytes -= len(entry[1])


def test_sim_config_rejects_unreachable_fd_threshold():
    """ADVICE: the per-edge failure counter is uint8; a threshold past 255
    would silently never fire. Constructing such a config must fail."""
    from rapid_tpu.sim.engine import SimConfig

    SimConfig(capacity=4)  # defaults fine
    SimConfig(capacity=4, fd_threshold=255)  # inclusive upper bound
    with pytest.raises(AssertionError):
        SimConfig(capacity=4, fd_threshold=256)
    with pytest.raises(AssertionError):
        SimConfig(capacity=4, fd_threshold=0)


def test_gateway_monitor_thread_starts_after_executors(monkeypatch):
    """ADVICE: the liveness monitor was started before the dial/delivery
    executors existed; a promptly-scheduled first refresh crashed on the
    missing attributes. Run the thread body synchronously inside start()
    (the worst-case scheduling) and require the executors to be there."""
    from rapid_tpu.messaging import gateway as gw

    seen = {}

    def probe_loop(self):
        seen["dialers"] = hasattr(self, "_dialers")
        seen["delivery"] = hasattr(self, "_delivery")

    monkeypatch.setattr(gw._GatewayNetwork, "_monitor_loop", probe_loop)
    monkeypatch.setattr(threading.Thread, "start", lambda self: self.run())
    net = gw._GatewayNetwork(None, None)
    try:
        assert seen == {"dialers": True, "delivery": True}
    finally:
        net._stop.set()
        net._dialers.shutdown(wait=False)
        for lane in net._delivery:
            lane.shutdown(wait=False)
