"""Event subscription semantics, mirroring SubscriptionsTest.java (264 LoC):
callback counts and ordering on join and failure, metadata delivery in DOWN
notifications, and KICKED self-eviction.
"""

import pytest

from rapid_tpu import ClusterEvents, EdgeStatus

from harness import ClusterHarness


@pytest.fixture
def harness():
    h = ClusterHarness(seed=99)
    yield h
    h.shutdown()


def collect(events):
    def cb(configuration_id, changes):
        events.append((configuration_id, list(changes)))

    return cb


def test_initial_view_change_on_start(harness):
    """Start fires one VIEW_CHANGE with the node itself UP
    (MembershipService.java:162-165)."""
    events = []
    harness.start_seed(0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(events))])
    assert len(events) == 1
    _, changes = events[0]
    assert len(changes) == 1
    assert changes[0].status == EdgeStatus.UP


def test_view_change_on_each_join(harness):
    events = []
    harness.start_seed(0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(events))])
    for i in range(1, 5):
        harness.join(i)
        harness.wait_and_verify_agreement(i + 1)
    # 1 initial + 4 joins
    assert len(events) == 5
    for idx, (_, changes) in enumerate(events[1:], start=1):
        assert all(c.status == EdgeStatus.UP for c in changes)
    # configuration ids strictly change
    config_ids = [cid for cid, _ in events]
    assert len(set(config_ids)) == len(config_ids)


def test_proposal_and_view_change_on_failure(harness):
    proposals = []
    view_changes = []
    harness.start_seed(
        0,
        subscriptions=[
            (ClusterEvents.VIEW_CHANGE_PROPOSAL, collect(proposals)),
            (ClusterEvents.VIEW_CHANGE, collect(view_changes)),
        ],
    )
    for i in range(1, 6):
        harness.join(i)
    harness.wait_and_verify_agreement(6)
    n_proposals = len(proposals)
    victim = harness.addr(5)
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(5)
    assert len(proposals) > n_proposals
    _, changes = proposals[-1]
    assert [c.endpoint for c in changes] == [victim]
    assert changes[0].status == EdgeStatus.DOWN
    _, vc = view_changes[-1]
    assert [c.endpoint for c in vc] == [victim]


def test_metadata_in_down_notification(harness):
    """Metadata tags survive to the DOWN notification
    (SubscriptionsTest.java:158-247)."""
    down_events = []
    harness.start_seed(
        0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(down_events))]
    )
    harness.join(1, metadata={"role": b"backend"})
    for i in range(2, 5):
        harness.join(i)
    harness.wait_and_verify_agreement(5)
    victim = harness.addr(1)
    # metadata visible cluster-wide after the join
    assert dict(harness.instances[harness.addr(0)].get_cluster_metadata()[victim]) == {
        "role": b"backend"
    }
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(4)
    _, changes = down_events[-1]
    assert changes[0].endpoint == victim
    assert changes[0].status == EdgeStatus.DOWN
    assert dict(changes[0].metadata) == {"role": b"backend"}


def test_capacity_metadata_weights_placement(harness):
    """A joiner advertising ``capacity`` in its metadata owns proportionally
    more partitions: the metadata plane is the placement plane's weight
    input (placement/engine.py weight_of)."""
    placement = {"partitions": 1024, "replicas": 1, "seed": 3}
    harness.start_seed(0, placement=placement)
    harness.join(1, placement=placement, metadata={"capacity": b"4"})
    for i in range(2, 6):
        harness.join(i, placement=placement)
    harness.wait_and_verify_agreement(6)
    heavy = harness.addr(1)
    fair = 1024 / (5 + 4)  # five weight-1 nodes + one weight-4 node
    for inst in harness.instances.values():
        pmap = inst.get_placement_map()
        counts = pmap.counts()
        assert counts[heavy] > 2.5 * fair  # ~4x fair share, generous slack
        assert max(
            counts.get(harness.addr(i), 0) for i in range(6) if i != 1
        ) < 2.0 * fair


def test_capacity_weight_survives_join_snapshot(harness):
    """A late joiner learns existing members' weights from the join
    snapshot's metadata: its locally-derived map is identical (same
    version) to the ones computed by nodes that watched the heavy node
    join live."""
    placement = {"partitions": 256, "replicas": 2, "seed": 5}
    harness.start_seed(0, placement=placement, metadata={"capacity": b"4"})
    harness.join(1, placement=placement)
    harness.wait_and_verify_agreement(2)
    # node 2 never saw node 0's join; its weight table comes from the
    # snapshot alone
    harness.join(2, placement=placement)
    harness.wait_and_verify_agreement(3)
    maps = [inst.get_placement_map() for inst in harness.instances.values()]
    assert len({m.version for m in maps}) == 1
    heavy = harness.addr(0)
    counts = maps[0].counts()
    # weight 4 vs 1,1: the heavy node must dominate ownership everywhere
    assert counts[heavy] > counts.get(harness.addr(1), 0)
    assert counts[heavy] > counts.get(harness.addr(2), 0)


def test_kicked_event_on_removed_node(harness):
    """A node that is cut from the view fires KICKED locally
    (MembershipService.java:424-429)."""
    kicked = []
    harness.start_seed(0)
    for i in range(1, 5):
        if i == 4:
            harness.join(i, subscriptions=[(ClusterEvents.KICKED, collect(kicked))])
        else:
            harness.join(i)
    harness.wait_and_verify_agreement(5)
    victim = harness.addr(4)
    victim_cluster = harness.instances.pop(victim)
    # Blacklist it for the others but keep its process "running" so it can
    # observe its own removal.
    harness.blacklist.add(victim)
    harness.wait_and_verify_agreement(4)
    ok = harness.scheduler.run_until(lambda: len(kicked) > 0, timeout_ms=300_000)
    assert ok, "victim never observed its own removal"
    victim_cluster.shutdown()
