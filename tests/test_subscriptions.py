"""Event subscription semantics, mirroring SubscriptionsTest.java (264 LoC):
callback counts and ordering on join and failure, metadata delivery in DOWN
notifications, and KICKED self-eviction.
"""

import pytest

from rapid_tpu import ClusterEvents, EdgeStatus

from harness import ClusterHarness


@pytest.fixture
def harness():
    h = ClusterHarness(seed=99)
    yield h
    h.shutdown()


def collect(events):
    def cb(configuration_id, changes):
        events.append((configuration_id, list(changes)))

    return cb


def test_initial_view_change_on_start(harness):
    """Start fires one VIEW_CHANGE with the node itself UP
    (MembershipService.java:162-165)."""
    events = []
    harness.start_seed(0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(events))])
    assert len(events) == 1
    _, changes = events[0]
    assert len(changes) == 1
    assert changes[0].status == EdgeStatus.UP


def test_view_change_on_each_join(harness):
    events = []
    harness.start_seed(0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(events))])
    for i in range(1, 5):
        harness.join(i)
        harness.wait_and_verify_agreement(i + 1)
    # 1 initial + 4 joins
    assert len(events) == 5
    for idx, (_, changes) in enumerate(events[1:], start=1):
        assert all(c.status == EdgeStatus.UP for c in changes)
    # configuration ids strictly change
    config_ids = [cid for cid, _ in events]
    assert len(set(config_ids)) == len(config_ids)


def test_proposal_and_view_change_on_failure(harness):
    proposals = []
    view_changes = []
    harness.start_seed(
        0,
        subscriptions=[
            (ClusterEvents.VIEW_CHANGE_PROPOSAL, collect(proposals)),
            (ClusterEvents.VIEW_CHANGE, collect(view_changes)),
        ],
    )
    for i in range(1, 6):
        harness.join(i)
    harness.wait_and_verify_agreement(6)
    n_proposals = len(proposals)
    victim = harness.addr(5)
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(5)
    assert len(proposals) > n_proposals
    _, changes = proposals[-1]
    assert [c.endpoint for c in changes] == [victim]
    assert changes[0].status == EdgeStatus.DOWN
    _, vc = view_changes[-1]
    assert [c.endpoint for c in vc] == [victim]


def test_metadata_in_down_notification(harness):
    """Metadata tags survive to the DOWN notification
    (SubscriptionsTest.java:158-247)."""
    down_events = []
    harness.start_seed(
        0, subscriptions=[(ClusterEvents.VIEW_CHANGE, collect(down_events))]
    )
    harness.join(1, metadata={"role": b"backend"})
    for i in range(2, 5):
        harness.join(i)
    harness.wait_and_verify_agreement(5)
    victim = harness.addr(1)
    # metadata visible cluster-wide after the join
    assert dict(harness.instances[harness.addr(0)].get_cluster_metadata()[victim]) == {
        "role": b"backend"
    }
    harness.fail_nodes([victim])
    harness.wait_and_verify_agreement(4)
    _, changes = down_events[-1]
    assert changes[0].endpoint == victim
    assert changes[0].status == EdgeStatus.DOWN
    assert dict(changes[0].metadata) == {"role": b"backend"}


def test_kicked_event_on_removed_node(harness):
    """A node that is cut from the view fires KICKED locally
    (MembershipService.java:424-429)."""
    kicked = []
    harness.start_seed(0)
    for i in range(1, 5):
        if i == 4:
            harness.join(i, subscriptions=[(ClusterEvents.KICKED, collect(kicked))])
        else:
            harness.join(i)
    harness.wait_and_verify_agreement(5)
    victim = harness.addr(4)
    victim_cluster = harness.instances.pop(victim)
    # Blacklist it for the others but keep its process "running" so it can
    # observe its own removal.
    harness.blacklist.add(victim)
    harness.wait_and_verify_agreement(4)
    ok = harness.scheduler.run_until(lambda: len(kicked) > 0, timeout_ms=300_000)
    assert ok, "victim never observed its own removal"
    victim_cluster.shutdown()
