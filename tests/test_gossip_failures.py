"""Gossip dissemination under the paper's headline failure scenarios.

The reference anticipates gossip as a first-class broadcast alternative
(IBroadcaster.java:24-26); the paper's evaluation (§7 Figs. 9-10, iptables
INPUT faults) is what makes Rapid's membership *stable* where SWIM-style
systems oscillate. A broadcaster is only a real alternative if the protocol
still removes EXACTLY the faulty set under those same faults while riding
it -- so both gossip modes run the full battery at N=128 on the virtual-time
cluster with the real (cumulative PingPong) failure detectors:

- one-way ingress partition (victims receive nothing, their egress flows),
- 80 % ingress loss,
- 20 s on / 20 s off flip-flop reachability.

One cluster bootstraps per mode (the expensive part) and the scenarios run
sequentially against it, like the paper's steady-state cluster."""

import random

import pytest

from harness import ClusterHarness
from rapid_tpu.messaging.gossip import GossipBroadcaster

N = 128
FD_MS = 1000  # reference default probe cadence (MembershipService.java:75)


def _harness(mode: str, seed: int) -> ClusterHarness:
    h = ClusterHarness(seed=seed, use_static_fd=False)
    h.broadcaster_factory = lambda client, rng: GossipBroadcaster(
        client, client.address, fanout=4, rng=rng, mode=mode
    )
    h.create_cluster(N, parallel=True)
    h.wait_and_verify_agreement(N)
    return h


def _survivors(h: ClusterHarness, victims) -> list:
    return [c for ep, c in h.instances.items() if ep not in victims]


def _wait_survivor_agreement(h, victims, size, timeout_ms=900_000):
    """Victims are unreachable (ingress faults), so they stay on stale
    views by design; agreement is asserted over the survivors."""
    survivors = _survivors(h, victims)

    def settled() -> bool:
        lists = [c.get_memberlist() for c in survivors]
        return all(
            len(lst) == size and lst == lists[0] for lst in lists
        )

    assert h.scheduler.run_until(settled, timeout_ms=timeout_ms), (
        f"survivors did not agree on size {size}: sizes="
        f"{sorted({len(c.get_memberlist()) for c in survivors})}"
    )
    member_list = survivors[0].get_memberlist()
    assert all(v not in member_list for v in victims), "cut is not exact"
    configs = {c.get_current_configuration_id() for c in survivors}
    assert len(configs) == 1, f"diverging configs: {configs}"
    # retire the faulted instances: they are out of the membership now
    for v in victims:
        cluster = h.instances.pop(v, None)
        if cluster is not None:
            cluster.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["eager", "pushpull"])
def test_gossip_survives_paper_failure_battery(mode):
    h = _harness(mode, seed=101 if mode == "eager" else 102)
    size = N
    rng = random.Random(991)

    # -- scenario 1: one-way ingress partition (paper Fig. 9) -------------
    victims = {h.addr(17), h.addr(63)}
    lift = h.network.add_filter(lambda s, d, m: d not in victims)
    _wait_survivor_agreement(h, victims, size - 2)
    size -= 2
    lift()

    # -- scenario 2: 80 % ingress loss (paper Fig. 10) --------------------
    victims = {h.addr(5), h.addr(90)}
    lift = h.network.add_filter(
        lambda s, d, m: d not in victims or rng.random() >= 0.8
    )
    _wait_survivor_agreement(h, victims, size - 2)
    size -= 2
    lift()

    # -- scenario 3: flip-flop, 20 s on / 20 s off (paper Fig. 10) --------
    # The cumulative FD (never reset on success,
    # PingPongFailureDetector.java:116-118) accumulates failures across
    # the reachable phases -- the design choice that makes Rapid remove
    # flip-flopping nodes where heartbeat systems oscillate forever.
    victims = {h.addr(33), h.addr(112)}
    start = h.scheduler.now_ms()
    lift = h.network.add_filter(
        lambda s, d, m: d not in victims
        or ((h.scheduler.now_ms() - start) // 20_000) % 2 == 1
    )
    _wait_survivor_agreement(h, victims, size - 2)
    size -= 2
    lift()

    # the cluster is stable afterwards: no spurious cuts, one configuration
    survivors = _survivors(h, set())
    h.scheduler.run_for(30_000)
    assert all(len(c.get_memberlist()) == size for c in survivors)
    assert len({c.get_current_configuration_id() for c in survivors}) == 1
    h.shutdown()
